"""Proxy: request orchestration in front of the query stack
(ref: src/proxy — Proxy::handle_*, Context, limiter.rs, the slow-query log
in read.rs:177-183, and hotspot tracking).

The proxy is a workload manager, not just a router: every SQL statement
passes through the ``wlm`` subsystem — per-tenant/per-table quotas and
the block-list (wlm/quota), cost-based admission control with weighted
slots + bounded wait queues (wlm/admission), and single-flight dedup of
identical in-flight SELECTs (wlm/dedup) — before it reaches the
priority runtime and the executor. Request ids, per-request
timing/metrics, the slow-query log, and LRU-bounded hotspot tracking
ride the same path.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ..db import Connection
from ..query.interpreters import AffectedRows, Output
from ..query.plan import InsertPlan, QueryPlan
from ..utils.metrics import REGISTRY
from ..utils.runtime import PriorityRuntime
from ..wlm.admission import CLASSES as ADMISSION_CLASSES
from ..wlm import (
    BlockedError,
    COST_HISTORY,
    OverloadedError,
    QuotaExceededError,
    WorkloadManager,
    classify_plan,
    lane_for,
    normalize_shape,
)

__all__ = [
    "BlockedError",
    "OverloadedError",
    "QuotaExceededError",
    "Hotspot",
    "Proxy",
    "RequestContext",
]

logger = logging.getLogger("horaedb_tpu.proxy")

# Per-admission-class end-to-end SELECT latency, eagerly registered (one
# labeled histogram per class so the series — and their samples-table
# history — exist from the first scrape). This is the SLO plane's
# canonical indicator: "cheap-class p99 stays flat during an
# expensive-scan storm" is only measurable when latency is bucketed by
# the class admission chose. Declared + linted like the other family
# registries (tests/test_observability.TestSloRegistryLint).
QUERY_CLASS_METRIC_FAMILIES = ("horaedb_query_class_duration_seconds",)

_M_CLASS_LATENCY = {
    c: REGISTRY.histogram(
        "horaedb_query_class_duration_seconds",
        "end-to-end SELECT latency by admission class (queue wait included)",
        labels={"class": c},
    )
    for c in ADMISSION_CLASSES
}


@dataclass
class RequestContext:
    request_id: int
    sql: str
    start: float = field(default_factory=time.perf_counter)


class _LruTally:
    """Bounded most-recently-bumped tally (the LRU half of
    hotspot_lru.rs): at most ``capacity`` keys; bumping revives a key,
    overflow evicts the least-recently-bumped one."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._counts: "OrderedDict[str, float]" = OrderedDict()

    def bump(self, key: str, n: float = 1.0) -> None:
        self._counts[key] = self._counts.get(key, 0.0) + n
        self._counts.move_to_end(key)
        while len(self._counts) > self.capacity:
            self._counts.popitem(last=False)

    def decay(self, factor: float) -> None:
        for k in list(self._counts):
            v = self._counts[k] * factor
            if v < 1.0:
                del self._counts[k]
            else:
                self._counts[k] = v

    def most_common(self, n: int) -> list[tuple[str, int]]:
        top = sorted(self._counts.items(), key=lambda kv: kv[1], reverse=True)
        return [(k, int(v)) for k, v in top[:n]]

    def __len__(self) -> int:
        return len(self._counts)


class Hotspot:
    """Per-table op tallies, LRU-bounded with periodic decay (ref:
    proxy/src/hotspot_lru.rs — the reference caps the map and ages
    counts so high-cardinality table names can't grow it forever and a
    burst from last week doesn't read as hot today)."""

    def __init__(
        self,
        capacity: int = 512,
        decay_interval_s: float = 60.0,
        decay_factor: float = 0.5,
    ) -> None:
        self.reads = _LruTally(capacity)
        self.writes = _LruTally(capacity)
        self.decay_interval_s = decay_interval_s
        self.decay_factor = decay_factor
        self._last_decay = time.monotonic()
        self._lock = threading.Lock()

    def record(self, table: str, is_write: bool) -> None:
        with self._lock:
            now = time.monotonic()
            if now - self._last_decay >= self.decay_interval_s:
                self.reads.decay(self.decay_factor)
                self.writes.decay(self.decay_factor)
                self._last_decay = now
            (self.writes if is_write else self.reads).bump(table)

    def top(self, n: int = 10) -> dict:
        with self._lock:
            return {
                "reads": dict(self.reads.most_common(n)),
                "writes": dict(self.writes.most_common(n)),
            }


class Proxy:
    def __init__(
        self,
        conn: Connection,
        slow_threshold_s: float = 1.0,
        limits=None,
        persist_path: Optional[str] = None,
        batch_cfg=None,
    ) -> None:
        self.conn = conn
        if persist_path is None:
            # operator-applied block/quota state survives a restart when
            # the node has a data dir to keep it in
            import os

            root = getattr(conn.store, "root", None)
            if root:
                persist_path = os.path.join(root, "wlm_state.json")
        self.wlm = WorkloadManager.from_limits(
            limits, persist_path=persist_path, batch_cfg=batch_cfg
        )
        # default per-query time budget ([limits] query_timeout; 0 =
        # unbounded) — the gateway's header/session knobs override it
        # per request by passing an explicit Deadline
        self.default_timeout_ms: float = (
            getattr(limits, "query_timeout_s", 60.0) if limits is not None
            else 60.0
        ) * 1000.0
        # the old Limiter surface (block/unblock/blocked/check) lives on,
        # served by the quota manager that subsumed it
        self.limiter = self.wlm.quota
        self.hotspot = Hotspot()
        self.slow_threshold_s = slow_threshold_s
        # Expensive (long-range / history-proven-slow) queries run on the
        # small low-priority pool (ref: SelectInterpreter spawning on the
        # priority runtime); the lane now follows the ADMISSION class.
        self.runtime = PriorityRuntime()
        # Recent per-query metric trees (ref: trace_metric; surfaced at
        # /debug/queries).
        self.recent_queries: deque = deque(maxlen=64)
        # Slow-query ring (ref: the slow log + SlowTimer, read.rs:177-183)
        # — persists across requests, surfaced at /debug/slow_log.
        self.slow_queries: deque = deque(maxlen=128)
        self._req_ids = itertools.count(1)
        self._m_queries = REGISTRY.counter("horaedb_queries_total", "SQL statements handled")
        self._m_errors = REGISTRY.counter("horaedb_query_errors_total", "SQL statements failed")
        self._m_latency = REGISTRY.histogram(
            "horaedb_query_duration_seconds", "SQL statement latency"
        )
        self._m_class_latency = _M_CLASS_LATENCY

    @property
    def slow_threshold_s(self) -> float:
        return self._slow_threshold_s

    @slow_threshold_s.setter
    def slow_threshold_s(self, seconds: float) -> None:
        """The live slow-log threshold also drives the device plane's
        always-time rule (obs/device): a query about to be slow-logged
        must carry a measured device_ms whatever threshold the operator
        dialed in at PUT /debug/slow_threshold — a sampled-out dispatch
        would render the misleading ``device_ms=0`` this field exists
        to prevent."""
        self._slow_threshold_s = seconds
        from ..obs.device import set_slow_candidate_s

        set_slow_candidate_s(seconds)

    def close(self) -> None:
        self.runtime.shutdown()
        self.wlm.close()

    def handle_sql(
        self, sql: str, tenant: str = "default", deadline=None
    ) -> Output:
        ctx = RequestContext(next(self._req_ids), sql)
        self._m_queries.inc()
        # The span tree travels by context: priority-pool threads run the
        # executor inside a COPY of this context, and remote calls ship
        # (trace_id, parent_span_id) in their wire spec (utils/tracectx).
        import contextvars

        from ..utils.deadline import (
            QUERY_REGISTRY,
            Deadline,
            DeadlineExceeded,
            QueryCancelled,
            deadline_scope,
            observe_budget,
        )
        from ..utils.querystats import finish_ledger, start_ledger
        from ..utils.tracectx import finish_trace, span, start_trace, tag_trace

        # The time budget opens HERE, at ingress, and rides the same
        # ContextVar discipline as the trace/ledger — every layer below
        # (admission, executor checkpoints, remote RPC envelopes,
        # forwarding hops, store waits) charges the one object. The
        # gateway installs its Deadline (header/session knob, a
        # forwarded hop's remaining budget) into the calling context
        # (utils/deadline.bind) so handle_sql keeps its historical
        # signature; embedded callers get the [limits] query_timeout
        # default.
        if deadline is None:
            from ..utils.deadline import current_deadline

            deadline = current_deadline()
        if deadline is None:
            deadline = Deadline(self.default_timeout_ms)
        observe_budget(deadline.budget_ms)
        trace, handle = start_trace(ctx.request_id, "sql", sql=sql[:200])
        # The cost ledger rides the same context: every stage the request
        # touches (scans, cache, kernels, remote fan-out) accounts into
        # it, and finalization feeds system.public.query_stats + the
        # horaedb_query_* metric families (utils/querystats).
        ledger, ltoken = start_ledger(ctx.request_id, sql)
        ledger.add(deadline_ms=deadline.budget_ms or 0)
        dtoken = None
        live = QUERY_REGISTRY.register(
            ctx.request_id, sql, tenant, deadline,
            protocol=getattr(deadline, "proto", "sql"),
        )
        shape = None  # set for executed SELECTs; feeds the EWMA history
        exec_elapsed: list = [None]  # leader execution seconds (EWMA input)
        admission_class = None  # set for executed SELECTs (class latency)
        adm_decision = 0  # decision-plane id for the est_cost_s admit
        ok = False
        try:
            dtoken = deadline_scope(deadline)
            dtoken.__enter__()
            # refuse already-expired work before doing ANY of it (a
            # forwarded hop may arrive with <= 0 remaining)
            deadline.check("ingress")
            # The plan cache is what makes repeated dashboard text cheap
            # at serving latency — the gateway is its target workload.
            with span("parse_plan"):
                plan = self.conn._cached_plan(sql)
            table = getattr(plan, "table", None)
            ledger.set_table(table)
            # Profile-plane dimensions (obs/profile): the serving plane
            # and — for SELECTs, below — the normalized plan-key class.
            if isinstance(plan, InsertPlan):
                tag_trace(route="ingest", shape=f"insert {plan.table}")
            elif isinstance(plan, QueryPlan):
                tag_trace(route="query")
            else:
                tag_trace(route="ddl")
            self.limiter.check(table)
            if table:
                self.hotspot.record(table, isinstance(plan, InsertPlan))
            if isinstance(plan, InsertPlan):
                self.wlm.quota.charge_write(tenant, plan.table, len(plan.rows))
            if isinstance(plan, QueryPlan):
                self.wlm.quota.charge_read(tenant, plan.table)
                shape = normalize_shape(sql)
                tag_trace(shape=shape[:160])
                admission_class, est_ms = classify_plan(plan, shape=shape)
                live.admission_class = admission_class
                lane = lane_for(admission_class)
                est_cost_s = (est_ms / 1000.0) if est_ms else None
                if est_cost_s is not None:
                    # Decision plane: the classifier predicted this
                    # shape's cost and admission will act on it; the
                    # finally below grades the prediction against the
                    # leader's realized execution seconds (the same
                    # sample the cost EWMA learns from).
                    from ..obs.decisions import record_decision

                    adm_decision = record_decision(
                        "admission",
                        key=shape,
                        choice=admission_class,
                        features={
                            "est_ms": round(est_ms, 3),
                            "budget_ms": int(deadline.budget_ms or 0),
                        },
                        predicted=est_cost_s,
                    )

                def run_leader():
                    # admission wraps only the LEADER: followers coalesce
                    # onto its slot instead of taking their own; the
                    # queue wait charges the time budget, and a budget
                    # that cannot fit the shape's expected cost sheds
                    # immediately (utils/deadline)
                    with self.wlm.admission.admit(
                        admission_class, est_cost_s=est_cost_s, shape=shape
                    ):
                        with span(
                            "execute", priority=lane, admission=admission_class
                        ):
                            cctx = contextvars.copy_context()
                            t0 = time.perf_counter()
                            try:
                                return self.runtime.run(
                                    lane,
                                    lambda: cctx.run(
                                        self.conn.interpreters.execute, plan
                                    ),
                                )
                            finally:
                                exec_elapsed[0] = time.perf_counter() - t0

                def run_solo():
                    return self.wlm.dedup.run(sql.strip(), run_leader)

                batcher = self.wlm.batch
                if batcher.enabled and batcher.eligible(plan, shape):
                    # Cohort batching (wlm/batch): shape-identical
                    # in-flight SELECTs with differing literals gather
                    # for the micro-batching window and serve from ONE
                    # fused device dispatch. The key carries the dedup
                    # write epoch — a write landing mid-window fences
                    # later members into a fresh cohort (read-your-
                    # writes, same contract as the flight table).
                    from ..wlm import batch_plan_key

                    out = batcher.run(
                        key=(self.wlm.dedup.epoch(), batch_plan_key(plan)),
                        sql=sql.strip(),
                        plan=plan,
                        solo=run_solo,
                        cohort_exec=lambda members: self._execute_cohort(
                            members, admission_class, exec_elapsed
                        ),
                    )
                else:
                    out = run_solo()
                self.recent_queries.append(
                    {
                        "request_id": ctx.request_id,
                        "sql": sql[:200],
                        "priority": plan.priority.value,
                        "admission": admission_class,
                        **(getattr(out, "metrics", None) or {}),
                    }
                )
                ok = True
                return out
            # any non-SELECT may change visible state: later identical
            # reads must start a fresh single-flight execution. Bump
            # AFTER the statement runs (in the finally, so a failed
            # attempt still invalidates conservatively): bumping before
            # would let a SELECT issued after this write COMMITS join a
            # pre-write flight opened in the new epoch.
            try:
                with span("execute"):
                    out = self.conn.interpreters.execute(plan)
                    ok = True
                    return out
            finally:
                self.wlm.dedup.bump_epoch()
        except DeadlineExceeded as e:
            # the ledger marks + typed journal event ARE the audit trail
            # the tenantsim gates read from the database's own tables
            ledger.add(timed_out=1)
            from ..utils.events import record_event

            record_event(
                "query_timeout",
                table=ledger.table_name or None,
                stage=e.stage,
                budget_ms=int(deadline.budget_ms or 0),
            )
            self._m_errors.inc()
            raise
        except QueryCancelled as e:
            ledger.add(cancelled=1)
            from ..utils.events import record_event

            record_event(
                "query_cancelled",
                table=ledger.table_name or None,
                source=e.source,
                query_id=live.query_id,
            )
            self._m_errors.inc()
            raise
        except Exception:
            self._m_errors.inc()
            raise
        finally:
            QUERY_REGISTRY.deregister(live)
            if dtoken is not None:
                dtoken.__exit__(None, None, None)
            elapsed = time.perf_counter() - ctx.start
            self._m_latency.observe(elapsed)
            if ok and admission_class is not None:
                # end-to-end latency AS THE TENANT SEES IT (queue wait
                # included), bucketed by admission class — the SLO
                # plane's "cheap p99 stays flat under an expensive
                # storm" indicator reads this family's history
                self._m_class_latency[admission_class].observe(elapsed)
            # Follower-served statement (gateway replica path): the route
            # truth is "follower" whatever executor path ran underneath,
            # and the watermark lag rides the ledger so query_stats
            # carries it on every wire.
            from ..cluster.replica import replica_context

            rc = replica_context()
            if rc is not None:
                ledger.set_route("follower")
                ledger.add(replica_lag_ms=rc["lag_ms"])
            if ok and shape is not None and exec_elapsed[0] is not None:
                # the EWMA only learns from completed LEADER executions —
                # failures/sheds would teach it queries are "fast", and
                # queue or follower wait would teach cheap shapes they
                # are "slow" under load (a self-sustaining demotion)
                COST_HISTORY.observe(shape, exec_elapsed[0])
                from ..obs.decisions import DECISION_JOURNAL, resolve_decision

                resolve_decision(
                    adm_decision, actual=exec_elapsed[0], outcome="ok",
                    loop="admission",
                )
                # a completed same-shape execution grades any pending
                # deadline_budget sheds of this shape: the shed was
                # "doomed" if the realized cost really would not have
                # fit the budget remaining at shed time, else premature
                DECISION_JOURNAL.resolve_matching(
                    "deadline",
                    shape,
                    actual=exec_elapsed[0],
                    outcome=lambda e: (
                        "doomed"
                        if exec_elapsed[0]
                        >= e["features"].get("remaining_s", 0.0)
                        else "premature"
                    ),
                )
            elif adm_decision:
                # shed/failed/timed out before a leader execution
                # completed: close the decision ungraded — a realized
                # cost never arrived, so there is nothing to grade the
                # estimator against (and "fast because it died" would
                # poison the calibration the same way it would poison
                # the EWMA)
                from ..obs.decisions import resolve_decision

                resolve_decision(
                    adm_decision,
                    outcome="failed" if exec_elapsed[0] is None else "aborted",
                    loop="admission",
                    calibrate=False,
                )
            slow = elapsed >= self.slow_threshold_s
            finish_trace(handle, slow=slow)
            finish_ledger(ledger, ltoken, elapsed)
            if slow:
                # device-plane facts at a glance: a compile-stall query
                # (compile_hit>0, device_ms small) reads differently
                # from a slow scan without opening the full ledger
                device_ms = round(ledger.counts.get("device_ms", 0.0), 3)
                compile_hit = int(ledger.counts.get("compile_hit", 0))
                logger.warning(
                    "slow query (request %d, %.3fs, device_ms=%s"
                    " compile_hit=%d): %s",
                    ctx.request_id, elapsed, device_ms, compile_hit,
                    sql[:500],
                )
                self.slow_queries.append(
                    {
                        "request_id": ctx.request_id,
                        "elapsed_s": round(elapsed, 4),
                        "sql": sql[:500],
                        "at": time.time(),
                        "device_ms": device_ms,
                        "compile_hit": compile_hit,
                        # the request's whole span tree rides with the
                        # slow-log entry (ref: SlowTimer + trace_metric)
                        "trace": trace.to_dict(),
                        # ...and its cost ledger (route + nonzero costs)
                        "ledger": ledger.to_dict(),
                    }
                )

    def _execute_cohort(
        self, members: list, admission_class: str, exec_elapsed=None
    ) -> list:
        """Execute a gathered cohort (wlm/batch) under ONE admission slot
        — members coalesce onto the leader's slot exactly like dedup
        followers — on the leader's priority lane. Returns one
        Output-or-exception per member, positionally (the interpreter
        isolates member failures). ``exec_elapsed[0]`` gets the
        AMORTIZED per-member execution seconds so the leader's shape
        keeps feeding the admission cost EWMA (the fused dispatch serves
        B queries in one execution; per-member cost is what classifies
        one query of the shape)."""
        import contextvars

        from ..utils.tracectx import span

        lane = lane_for(admission_class)
        plans = [plan for _, plan in members]
        with self.wlm.admission.admit(admission_class):
            with span(
                "execute_cohort",
                priority=lane,
                admission=admission_class,
                cohort=len(members),
            ):
                cctx = contextvars.copy_context()
                t0 = time.perf_counter()
                try:
                    return self.runtime.run(
                        lane,
                        lambda: cctx.run(
                            self.conn.interpreters.execute_cohort, plans
                        ),
                    )
                finally:
                    if exec_elapsed is not None:
                        exec_elapsed[0] = (
                            time.perf_counter() - t0
                        ) / max(len(members), 1)
