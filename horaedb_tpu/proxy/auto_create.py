"""Auto-create / auto-evolve tables on write
(ref: proxy/src/write.rs:176-263 — the write path creates missing tables
and adds missing columns before executing the insert plan).

Shared by the InfluxDB and OpenTSDB write handlers: given the observed
tags/fields of a batch, ensure a table exists whose schema covers them.
"""

from __future__ import annotations

import threading
from typing import Mapping

from ..catalog import Catalog
from ..common_types.datum import DatumKind
from ..common_types.schema import ColumnSchema, Schema
from ..engine.options import TableOptions
from ..table_engine.table import Table

_ddl_lock = threading.Lock()


def _kind_of_value(v) -> DatumKind:
    if isinstance(v, bool):
        return DatumKind.BOOLEAN
    if isinstance(v, int):
        return DatumKind.INT64
    if isinstance(v, float):
        return DatumKind.DOUBLE
    if isinstance(v, bytes):
        return DatumKind.VARBINARY
    return DatumKind.STRING


def ensure_table(
    catalog: Catalog,
    name: str,
    tag_names: list[str],
    field_samples: Mapping[str, object],
    timestamp_column: str,
) -> Table:
    """Open ``name``, creating it or adding missing field columns.

    Field kinds are inferred from sample values (ints arriving in a double
    column stay double — widening only happens at creation time here).
    """
    with _ddl_lock:
        table = catalog.open(name)
        if table is None:
            cols = [ColumnSchema(t, DatumKind.STRING, is_tag=True) for t in tag_names]
            for f, v in field_samples.items():
                kind = _kind_of_value(v)
                if kind is DatumKind.INT64:
                    kind = DatumKind.DOUBLE  # numeric fields default to double
                cols.append(ColumnSchema(f, kind))
            cols.append(ColumnSchema(timestamp_column, DatumKind.TIMESTAMP))
            schema = Schema.build(cols, timestamp_column=timestamp_column)
            return catalog.create_table(name, schema, TableOptions())

        schema = table.schema
        missing_tags = [t for t in tag_names if not schema.has_column(t)]
        if missing_tags:
            raise ValueError(
                f"table {name!r} exists without tag column(s) {missing_tags}; "
                "tags cannot be added after creation"
            )
        new_schema = schema
        for f, v in field_samples.items():
            if not new_schema.has_column(f):
                kind = _kind_of_value(v)
                if kind is DatumKind.INT64:
                    kind = DatumKind.DOUBLE
                new_schema = new_schema.with_added_column(ColumnSchema(f, kind))
        if new_schema is not schema:
            table.alter_schema(new_schema)
        return table
