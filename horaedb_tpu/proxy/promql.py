"""PromQL subset: parse + translate to the SQL engine
(ref: query_frontend/src/promql/{convert,pushdown}.rs — the reference
translates PromQL into DataFusion plans; here PromQL translates into the
same Plan/executor pipeline SQL uses, so prom queries ride the fused
device kernels).

Supported grammar:

    expr     := cmpexpr
    cmpexpr  := addexpr (('>' | '<' | '>=' | '<=' | '==' | '!=') addexpr)*
    addexpr  := mulexpr (('+' | '-') mulexpr)*
    mulexpr  := unary (('*' | '/' | '%') unary)*
    unary    := number | '(' expr ')' | vector
    vector   := agg [mod] '(' [param ','] expr ')' [mod]
              | func '(' [phi ','] (selector | subquery) ')'
              | vfunc '(' ... )'            -- per-function signature
              | selector
              | subquery
    subquery := expr '[' duration ':' [duration] ']'
                ( 'offset' duration | '@' unix )*
                -- inner expr instant-evaluates at step-aligned times
                -- within (t-range, t]; must feed a range function
    mod      := ('by' | 'without') '(' labels ')'
    agg      := sum | avg | min | max | count | stddev | stdvar
              | topk | bottomk | quantile   -- the last three take a param
    func     := rate | increase | delta | irate | idelta
              | changes | resets
              | avg_over_time | min_over_time | max_over_time
              | sum_over_time | count_over_time
              | quantile_over_time | stddev_over_time | last_over_time
    vfunc    := histogram_quantile(phi, expr)
              | label_replace(expr, dst, repl, src, regex)
              | label_join(expr, dst, sep, src...)
              | abs | ceil | floor | round | clamp_min | clamp_max
    selector := metric [ '{' matcher (',' matcher)* '}' ]
                [ '[' duration ']' ] ( 'offset' duration | '@' unix )*
    matcher  := label ('=' | '!=' | '=~' | '!~') 'value'

Aggregations nest (max(sum by (h) (m)) works) and accept both prefix and
suffix by/without placement, like prom.

Binary expressions follow prom's arithmetic semantics: scalar/scalar,
vector/scalar (applied per sample), and vector/vector one-to-one
matching on identical label sets (samples without a partner drop out;
``__name__`` is dropped from arithmetic results, like prom).
Comparison operators (> < >= <= == !=) follow prom's FILTER semantics
over vectors — samples for which the comparison is false drop out, the
surviving samples keep their values (what alert rules are made of:
``rate(errors_total[1m]) > 5`` yields the offending series). A
scalar/scalar comparison yields 1.0/0.0 (the ``bool`` modifier is
implied — this subset has no unmodified scalar comparison error).

Semantics notes:
- the metric name maps to a table; its single DOUBLE field (or a column
  literally named ``value``) is the sample value, the timestamp key is
  the sample time — exactly the shape OpenTSDB/Influx ingestion creates;
- equality matchers push into the scan; regex matchers (fully anchored,
  like prom) post-filter the series set host-side;
- ``rate``/``increase`` fold consecutive raw samples with counter-reset
  correction (a drop restarts the counter near zero), each delta
  attributed to the later sample's step bucket;
- ``offset`` evaluates a window shifted into the past and stamps results
  back at the requested times;
- range queries evaluate per aligned ``step`` bucket; instant queries use
  a 5m lookback window.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from ..engine.options import parse_duration_ms

AGG_FUNCS = {"sum", "avg", "min", "max", "count", "stddev", "stdvar"}
PARAM_AGGS = {"topk", "bottomk", "quantile"}  # aggregators with a scalar param
# Range-function families — ONE place; the parser's range requirement,
# the exact-window instant routing and the range dispatch all derive
# from these (hand-maintained parallel lists drifted once already).
_COUNTER_FUNCS = frozenset({"rate", "increase"})
# raw per-window folds: order statistics, gauge deltas, instant
# variants (last two samples), change/reset counts
_RAW_FOLD_FUNCS = frozenset({
    "quantile_over_time", "stddev_over_time", "last_over_time",
    "delta", "irate", "idelta", "changes", "resets",
})
# folds that push into the SQL kernel per step bucket
_SQL_FOLD_FUNCS = frozenset({
    "avg_over_time", "min_over_time", "max_over_time",
    "sum_over_time", "count_over_time",
})
RANGE_FUNCS = _COUNTER_FUNCS | _RAW_FOLD_FUNCS | _SQL_FOLD_FUNCS
# these three accept a missing [range] (they fold the default lookback)
_OPTIONAL_RANGE_FUNCS = frozenset(
    {"avg_over_time", "min_over_time", "max_over_time"}
)
# comparison/filter binary operators (prom semantics: false samples
# drop out of the vector; the alert evaluator's threshold surface)
COMPARE_OPS = frozenset({">", "<", ">=", "<=", "==", "!="})
# funcs over a full evaluated vector (ref surface: promql/udf.rs:50-97 +
# the IOx function table the reference inherits)
VECTOR_FUNCS = {
    "histogram_quantile", "label_replace", "label_join",
    "abs", "ceil", "floor", "round", "clamp_min", "clamp_max",
}


class PromQLError(ValueError):
    pass


@dataclass
class PromQuery:
    metric: str
    matchers: list[tuple[str, str, str]] = field(default_factory=list)  # (label, op, value)
    range_ms: Optional[int] = None
    func: Optional[str] = None  # RANGE_FUNCS
    offset_ms: int = 0  # `offset 1h` shifts the evaluated window back
    at_ms: Optional[int] = None  # `@ <unix>` pins the evaluation time
    param: Optional[float] = None  # quantile_over_time's φ


@dataclass
class PromScalar:
    """A number literal in an expression (e.g. the 100 in x * 100)."""

    value: float


@dataclass
class PromSubquery:
    """``expr[range:step]`` — the inner expression instant-evaluates at
    step-aligned times within (t-range, t]; the samples feed the
    enclosing range function (max_over_time(rate(x[1m])[5m:1m]))."""

    expr: "PromExpr"
    range_ms: int
    step_ms: Optional[int] = None  # None -> DEFAULT_SUBQUERY_STEP_MS
    func: Optional[str] = None  # the enclosing RANGE_FUNC
    param: Optional[float] = None
    offset_ms: int = 0
    at_ms: Optional[int] = None


@dataclass
class PromBin:
    """Arithmetic or comparison over sub-expressions: vector/scalar
    applies per sample, vector/vector matches one-to-one on identical
    label sets. COMPARE_OPS members filter (false samples drop out)."""

    op: str  # + - * / % or COMPARE_OPS
    lhs: "PromExpr"
    rhs: "PromExpr"


@dataclass
class PromAgg:
    """Cross-series aggregation over a full sub-expression: sum/avg/min/
    max/count/stddev/stdvar, parameterized quantile/topk/bottomk, with
    ``by`` (keep listed labels) or ``without`` (drop listed labels)."""

    op: str
    arg: "PromExpr"
    param: Optional[float] = None
    by_labels: Optional[list[str]] = None
    without_labels: Optional[list[str]] = None


@dataclass
class PromCall:
    """Vector-transform function: histogram_quantile, label_replace,
    label_join, and the per-sample math funcs (abs/ceil/floor/round/
    clamp_min/clamp_max)."""

    name: str
    arg: "PromExpr"
    params: tuple = ()  # scalars/strings, meaning depends on name


PromExpr = PromQuery | PromScalar | PromBin | PromAgg | PromCall | PromSubquery


_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:.]*"
_TOKENS = re.compile(
    rf"""\s*(?:
      (?P<name>{_NAME})
    | (?P<dur>\d+(?:ms|s|m|h|d))
    | (?P<number>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<string>'(?:[^'])*'|"(?:[^"])*")
    | (?P<op>!=|=~|!~|>=|<=|==|[<>={{}}()\[\],+\-*/%@])
    )""",
    re.VERBOSE,
)


def _tokenize(q: str):
    out, i = [], 0
    while i < len(q):
        m = _TOKENS.match(q, i)
        if not m:
            if q[i:].strip() == "":
                break
            raise PromQLError(f"unexpected character {q[i]!r} at {i}")
        if m.lastgroup:
            out.append((m.lastgroup, m.group().strip()))
        i = m.end()
    return out


class _Parser:
    def __init__(self, q: str) -> None:
        self.q = q
        self.toks = _tokenize(q)
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        if t[0] is None:
            raise PromQLError(f"unexpected end of query: {self.q!r}")
        self.i += 1
        return t

    def expect(self, text: str):
        kind, tok = self.next()
        if tok != text:
            raise PromQLError(f"expected {text!r}, found {tok!r} in {self.q!r}")

    def parse(self) -> PromExpr:
        pq = self.cmpexpr()
        if self.peek()[0] is not None:
            raise PromQLError(f"trailing input after query: {self.q!r}")
        return pq

    # precedence climbing: * / % bind tighter than + -, which bind
    # tighter than the comparison/filter operators (prom's ladder)
    def cmpexpr(self) -> PromExpr:
        node = self.addexpr()
        while self.peek()[0] == "op" and self.peek()[1] in COMPARE_OPS:
            op = self.next()[1]
            node = PromBin(op, node, self.addexpr())
        return node

    def addexpr(self) -> PromExpr:
        node = self.mulexpr()
        while self.peek() == ("op", "+") or self.peek() == ("op", "-"):
            op = self.next()[1]
            node = PromBin(op, node, self.mulexpr())
        return node

    def mulexpr(self) -> PromExpr:
        node = self.unary()
        while self.peek()[1] in ("*", "/", "%") and self.peek()[0] == "op":
            op = self.next()[1]
            node = PromBin(op, node, self.unary())
        return node

    def unary(self) -> PromExpr:
        kind, tok = self.peek()
        if kind == "number":
            self.next()
            return PromScalar(float(tok))
        if (kind, tok) == ("op", "-"):
            self.next()
            inner = self.unary()
            if isinstance(inner, PromScalar):
                return PromScalar(-inner.value)
            return PromBin("*", PromScalar(-1.0), inner)
        if (kind, tok) == ("op", "("):
            self.next()
            node = self.cmpexpr()
            self.expect(")")
            return self._maybe_subquery(node)
        return self._maybe_subquery(self.expr())

    def _maybe_subquery(self, node: PromExpr) -> PromExpr:
        """Trailing ``[range:step]`` turns any expression into a
        subquery (a bare metric's subquery is handled inside selector(),
        which owns its '[' — this covers functions and parens)."""
        while self.peek() == ("op", "[") and not (
            # a RAW range selector (cpu[5m]) takes no second range; a
            # range FUNCTION result (rate(cpu[5m])) does — that's the
            # subquery form
            isinstance(node, PromQuery)
            and node.range_ms is not None
            and node.func is None
        ):
            self.next()
            kind, dur = self.next()
            if kind != "dur":
                raise PromQLError(f"expected a duration, found {dur!r}")
            rng = parse_duration_ms(dur)
            step = self._subquery_step()
            self.expect("]")
            node = PromSubquery(node, rng, step)
            self._selector_modifiers(node)
        return node

    def _subquery_step(self) -> Optional[int]:
        """The ':step' tail of a subquery range. The tokenizer fuses
        ':1m' into one name token (prom metric names may contain colons);
        a spaced ': 1m' arrives as ':' then a duration."""
        k, t = self.peek()
        if k != "name" or not t.startswith(":"):
            raise PromQLError("expected ':' in subquery range [range:step]")
        self.next()
        if len(t) > 1:
            return parse_duration_ms(t[1:])
        if self.peek()[0] == "dur":
            return parse_duration_ms(self.next()[1])
        return None

    def _selector_modifiers(self, node) -> None:
        """offset/@ suffixes, shared by selectors and subqueries."""
        while True:
            if self.peek() == ("name", "offset"):
                self.next()
                kind, dur = self.next()
                if kind != "dur":
                    raise PromQLError(f"offset expects a duration, found {dur!r}")
                node.offset_ms = parse_duration_ms(dur)
                continue
            if self.peek() == ("op", "@"):
                self.next()
                kind, num = self.next()
                if kind != "number":
                    raise PromQLError(f"@ expects a unix timestamp, found {num!r}")
                node.at_ms = int(float(num) * 1000)
                continue
            break

    def _label_list(self) -> list[str]:
        self.expect("(")
        out = []
        if self.peek()[1] != ")":
            out.append(self._ident())
            while self.peek()[1] == ",":
                self.next()
                out.append(self._ident())
        self.expect(")")
        return out

    def _number(self) -> float:
        neg = False
        if self.peek() == ("op", "-"):
            self.next()
            neg = True
        kind, tok = self.next()
        if kind != "number":
            raise PromQLError(f"expected a number, found {tok!r}")
        return -float(tok) if neg else float(tok)

    def _string(self) -> str:
        kind, tok = self.next()
        if kind != "string":
            raise PromQLError(f"expected a quoted string, found {tok!r}")
        return tok[1:-1]

    def expr(self) -> PromExpr:
        kind, tok = self.peek()
        if kind == "name" and (tok in AGG_FUNCS or tok in PARAM_AGGS):
            self.next()
            by = without = None
            k2, t2 = self.peek()
            if (k2, t2) == ("name", "by"):
                self.next()
                by = self._label_list()
            elif (k2, t2) == ("name", "without"):
                self.next()
                without = self._label_list()
            self.expect("(")
            param = None
            if tok in PARAM_AGGS:
                param = self._number()
                self.expect(",")
            inner = self.cmpexpr()
            self.expect(")")
            # suffix form: sum(...) by (x) / without (x)
            if by is None and without is None:
                k2, t2 = self.peek()
                if (k2, t2) == ("name", "by"):
                    self.next()
                    by = self._label_list()
                elif (k2, t2) == ("name", "without"):
                    self.next()
                    without = self._label_list()
            if tok in ("topk", "bottomk") and (
                param is None or param != int(param) or param < 1
            ):
                raise PromQLError(f"{tok} expects a positive integer k")
            return PromAgg(
                tok, inner, param=param, by_labels=by, without_labels=without
            )
        if kind == "name" and tok in RANGE_FUNCS:
            self.next()
            self.expect("(")
            param = None
            if tok == "quantile_over_time":
                param = self._number()
                self.expect(",")
            inner = self.unary()
            self.expect(")")
            if not isinstance(inner, (PromQuery, PromSubquery)):
                raise PromQLError(
                    f"{tok}() expects a range selector or subquery argument"
                )
            if inner.func is not None:
                # rate(cpu[1m]) is already consumed by rate — silently
                # overwriting would drop the inner fold. The composable
                # form is a subquery: max_over_time(rate(cpu[1m])[5m:1m]).
                raise PromQLError(
                    f"{tok}() over {inner.func}(...) needs a subquery "
                    f"range, e.g. {tok}({inner.func}(...)[5m:1m])"
                )
            needs_range = tok not in _OPTIONAL_RANGE_FUNCS
            if needs_range and inner.range_ms is None:
                raise PromQLError(f"{tok}() requires a range selector like [5m]")
            inner.func = tok
            inner.param = param
            return inner
        if kind == "name" and tok in VECTOR_FUNCS:
            return self._vector_func(tok)
        return self.selector()

    def _vector_func(self, name: str) -> PromCall:
        self.next()
        self.expect("(")
        params: list = []
        if name == "histogram_quantile":
            params.append(self._number())
            self.expect(",")
            arg = self.cmpexpr()
        elif name == "label_replace":
            arg = self.cmpexpr()
            for _ in range(4):  # dst, replacement, src, regex
                self.expect(",")
                params.append(self._string())
            try:
                compiled = re.compile(params[3])
            except re.error as e:
                raise PromQLError(f"bad regex {params[3]!r}: {e}")
            # numeric $N refs must name a real capture group (parse-time
            # 400, not an evaluation-time 500)
            for m in _DOLLAR_REF.finditer(params[1]):
                ref = m.group(1).strip("{}")
                if ref.isdigit() and int(ref) > compiled.groups:
                    raise PromQLError(
                        f"label_replace replacement references group "
                        f"${ref} but the regex has {compiled.groups}"
                    )
        elif name == "label_join":
            arg = self.cmpexpr()
            self.expect(",")
            params.append(self._string())  # dst
            self.expect(",")
            params.append(self._string())  # separator
            while self.peek()[1] == ",":
                self.next()
                params.append(self._string())  # source labels
        elif name in ("clamp_min", "clamp_max"):
            arg = self.cmpexpr()
            self.expect(",")
            params.append(self._number())
        elif name == "round":
            arg = self.cmpexpr()
            if self.peek()[1] == ",":
                self.next()
                params.append(self._number())
        else:  # abs / ceil / floor
            arg = self.cmpexpr()
        self.expect(")")
        return PromCall(name, arg, tuple(params))

    def _ident(self) -> str:
        kind, tok = self.next()
        if kind != "name":
            raise PromQLError(f"expected identifier, found {tok!r}")
        return tok

    def selector(self) -> PromQuery:
        metric = self._ident()
        if metric in AGG_FUNCS or metric in RANGE_FUNCS:
            raise PromQLError(f"{metric!r} used as a metric name")
        pq = PromQuery(metric=metric)
        if self.peek()[1] == "{":
            self.next()
            while True:
                label = self._ident()
                kind, op = self.next()
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"unsupported matcher op {op!r}")
                skind, sval = self.next()
                if skind != "string":
                    raise PromQLError(f"matcher value must be quoted: {sval!r}")
                value = sval[1:-1]
                if op in ("=~", "!~"):
                    try:
                        re.compile(value)
                    except re.error as e:
                        raise PromQLError(f"bad regex {value!r}: {e}")
                pq.matchers.append((label, op, value))
                kind, tok = self.next()
                if tok == "}":
                    break
                if tok != ",":
                    raise PromQLError(f"expected ',' or '}}', found {tok!r}")
        sub = None
        if self.peek()[1] == "[":
            self.next()
            kind, dur = self.next()
            if kind != "dur":
                raise PromQLError(f"expected a duration like 5m, found {dur!r}")
            rng = parse_duration_ms(dur)
            k2, t2 = self.peek()
            if k2 == "name" and t2.startswith(":"):
                # bare-metric subquery: cpu_usage[5m:1m]
                step = self._subquery_step()
                self.expect("]")
                sub = PromSubquery(pq, rng, step)
            else:
                pq.range_ms = rng
                self.expect("]")
        node = sub if sub is not None else pq
        self._selector_modifiers(node)
        return node


def parse_promql(query: str) -> PromExpr:
    return _Parser(query).parse()


# ---- evaluation ---------------------------------------------------------


def sql_str_literal(v: str) -> str:
    """Quote a string for SQL interpolation (doubling embedded quotes) —
    EVERY protocol front end that builds WHERE clauses from client data
    must use this, or apostrophes break the query (and worse)."""
    return "'" + str(v).replace("'", "''") + "'"


def resolves_to_samples(conn, metric: str) -> bool:
    """True when a selector on ``metric`` will evaluate against the
    self-monitoring history table — exported so HTTP prom routing uses
    the SAME predicate as evaluation (``_metric_table``) and the two
    can't drift on where a metric resolves."""
    from ..engine.metrics_recorder import SAMPLES_TABLE

    return (
        conn.catalog.open(metric) is None
        and conn.catalog.open(SAMPLES_TABLE) is not None
    )


def _metric_table(conn, pq: PromQuery):
    """Resolve a selector's metric to a table: the table of that name
    when one exists, else the self-monitoring history table
    ``system_metrics.samples`` with a pushed ``name = <metric>`` matcher
    (engine/metrics_recorder) — so ``rate(horaedb_flush_rows_total[5m])``
    works over the node's own stored telemetry even though no table named
    ``horaedb_flush_rows_total`` exists. Returns ``(pq, table, inner,
    folded)`` — ``pq`` rewritten when the fallback applied — with
    ``table=None`` when neither resolves. ``inner`` holds the caller's
    matchers on the ORIGINAL family's labels (e.g. ``{protocol="http"}``),
    which a samples-shaped table folds into its ``labels`` string tag:
    they must post-filter series via ``_inner_match``, not push into the
    scan. ``folded`` is True whenever the table stores series labels that
    way — the samples fallback AND recording-rule output tables (rules/)
    — telling callers to lift the folded labels back into first-class
    keys via ``_expand_folded_keys``."""
    import dataclasses

    table = conn.catalog.open(pq.metric)
    if table is not None:
        tags = set(table.schema.tag_names)
        # The EXACT samples shape only (a recording rule's output, or
        # the samples table addressed by name): a user table that merely
        # HAS a tag called "labels" alongside its own tags must keep
        # plain-tag semantics — lifting would rewrite its series
        # identity and silently collapse distinct series.
        if "labels" in tags and tags <= {"name", "labels", "node"}:
            # matchers on the result series' own (folded) labels
            # post-filter after lifting
            inner = [m for m in pq.matchers if m[0] not in tags]
            if inner:
                pq = dataclasses.replace(
                    pq,
                    matchers=[m for m in pq.matchers if m[0] in tags],
                )
            return pq, table, inner, True
        return pq, table, [], False
    from ..engine.metrics_recorder import SAMPLES_TABLE

    samples = conn.catalog.open(SAMPLES_TABLE)
    if samples is None:
        return pq, None, [], False
    sample_tags = set(samples.schema.tag_names)
    inner = [m for m in pq.matchers if m[0] not in sample_tags]
    pq = dataclasses.replace(
        pq,
        metric=SAMPLES_TABLE,
        matchers=[m for m in pq.matchers if m[0] in sample_tags]
        + [("name", "=", pq.metric)],
    )
    return pq, samples, inner, True


def _parse_rendered_labels(s: str) -> dict:
    """Inverse of utils.metrics._render_labels for the samples table's
    folded ``labels`` tag: ``''`` or ``{k="v",...}`` with backslash,
    quote, and newline escaped inside values."""
    out: dict = {}
    for m in re.finditer(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"', s or ""):
        # single-pass unescape: ordered str.replace would mis-decode a
        # literal backslash before 'n' (\\n -> backslash+LF)
        out[m.group(1)] = re.sub(
            r"\\(.)",
            lambda e: "\n" if e.group(1) == "n" else e.group(1),
            m.group(2),
        )
    return out


def _expand_folded_keys(per_series: dict) -> dict:
    """Samples-table fallback: lift each series' folded ``labels``
    string into first-class key labels (dropping the redundant ``name``
    — ``__name__`` already carries it), so downstream machinery —
    aggregation BY an original label, binary-op join matching,
    ``_histogram_quantile``'s ``le`` pop — sees the family's own labels
    exactly as it would over a live scrape."""
    out = {}
    for key, pts in per_series.items():
        kd = dict(key)
        folded = _parse_rendered_labels(kd.pop("labels", ""))
        kd.pop("name", None)
        for k, v in folded.items():
            kd.setdefault(k, v)  # the samples node label wins a collision
        out[tuple(sorted(kd.items()))] = pts
    return out


def _inner_match(labels: dict, matchers: list[tuple[str, str, str]]) -> bool:
    """Prom matcher semantics over a series' expanded label dict: an
    absent label is the empty string (so ``{k=""}`` matches series
    WITHOUT ``k``, and ``!=``/``!~`` pass on absent labels)."""
    for label, op, val in matchers:
        current = str(labels.get(label, ""))
        if op == "=" and current != val:
            return False
        if op == "!=" and current == val:
            return False
        if op == "=~" and re.fullmatch(val, current) is None:
            return False
        if op == "!~" and re.fullmatch(val, current) is not None:
            return False
    return True


def _value_column(schema) -> str:
    if schema.has_column("value"):
        return "value"
    fields = [schema.columns[i] for i in schema.field_indexes]
    doubles = [c.name for c in fields if c.kind.value in ("double", "float")]
    if len(doubles) == 1:
        return doubles[0]
    raise PromQLError(
        f"metric table needs a 'value' column or exactly one double field; "
        f"found {doubles}"
    )


_QUOTE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _q(name: str) -> str:
    return name if _QUOTE.match(name) else f'"{name}"'


def evaluate_range(
    conn,
    pq: PromQuery,
    start_ms: int,
    end_ms: int,
    step_ms: int,
) -> list[dict]:
    """-> prom 'matrix' result list for [start, end] at step resolution."""
    combined = _range_series(conn, pq, start_ms, end_ms, step_ms)
    out = []
    for key, points in sorted(combined.items()):
        out.append(
            {
                "metric": {"__name__": pq.metric, **{l: v for l, v in key}},
                "values": [
                    # repr = shortest round-trip form (full precision,
                    # like prom's Go 'g' formatting)
                    [b / 1000.0, repr(float(points[b]))]
                    for b in sorted(points)
                ],
            }
        )
    return out


def _range_series(
    conn,
    pq: PromQuery,
    start_ms: int,
    end_ms: int,
    step_ms: int,
) -> dict[tuple, dict[int, float]]:
    """Per-series step-bucket values in REQUESTED-time space (offset
    already stamped back), keyed by ((label, value), ...)."""
    if pq.at_ms is not None:
        return _at_series(conn, pq, start_ms, end_ms, step_ms)
    pq, table, inner_matchers, fallback = _metric_table(conn, pq)
    if table is None:
        return {}
    schema = table.schema
    value_col = _value_column(schema)
    tag_names = list(schema.tag_names)

    for label, _, _ in pq.matchers:
        if label not in tag_names:
            raise PromQLError(f"unknown label {label!r} on metric {pq.metric!r}")
    # offset: evaluate a window shifted into the past, then stamp results
    # back at the requested times (prom's `offset` modifier).
    start_ms -= pq.offset_ms
    end_ms -= pq.offset_ms
    # Equality matchers push into the scan; regex matchers post-filter the
    # (small) series set host-side.
    push_matchers = [m for m in pq.matchers if m[1] in ("=", "!=")]
    regex_matchers = [m for m in pq.matchers if m[1] in ("=~", "!~")]
    # Per-SERIES temporal aggregation per step bucket — always at full tag
    # granularity, exactly prom's model (cross-series combine is PromAgg's
    # job, _combine_agg).
    group_labels = tag_names

    # Inner temporal aggregation per step bucket.
    func = pq.func
    if func == "min_over_time":
        sel = f"min({_q(value_col)}) AS v"
    elif func == "max_over_time":
        sel = f"max({_q(value_col)}) AS v"
    elif func == "sum_over_time":
        sel = f"sum({_q(value_col)}) AS v"
    elif func == "count_over_time":
        sel = f"count({_q(value_col)}) AS v"
    else:  # raw selector / avg_over_time: average within the bucket
        sel = f"avg({_q(value_col)}) AS v"

    where = [f"{_q(schema.timestamp_name)} >= {start_ms}",
             f"{_q(schema.timestamp_name)} <= {end_ms}"]
    for label, op, val in push_matchers:
        sval = str(val).replace("'", "''")  # keep in sync w/ sql_str_literal
        where.append(f"{_q(label)} {'=' if op == '=' else '!='} '{sval}'")

    if func in _COUNTER_FUNCS:
        # Counter semantics need consecutive samples (reset detection) —
        # scan raw rows and fold host-side (samples per window are small
        # next to the table; the fused path keeps serving the rest).
        per_series = _counter_series(
            conn, pq, where, schema, value_col, group_labels, step_ms, func,
            table=table, start_ms=start_ms, end_ms=end_ms,
        )
    elif func in _RAW_FOLD_FUNCS:
        # Raw folds evaluate per step over the SLIDING left-open
        # (b-range, b] window (prom semantics) — the scan must reach back
        # one window before the first step (the >= here only over-fetches
        # the one boundary row the fold then excludes).
        window = pq.range_ms or DEFAULT_LOOKBACK_MS
        raw_where = [f"{_q(schema.timestamp_name)} >= {start_ms - window}"] + where[1:]
        per_series = _raw_window_series(
            conn, pq, raw_where, schema, value_col, group_labels,
            start_ms, end_ms, step_ms, window, func, pq.param,
        )
    else:
        keys = [f"time_bucket({_q(schema.timestamp_name)}, '{step_ms}ms')"] + [
            _q(l) for l in group_labels
        ]
        label_sel = ", ".join(_q(l) for l in group_labels)
        sql = (
            f"SELECT {keys[0]} AS bucket"
            + (f", {label_sel}" if group_labels else "")
            + f", {sel} FROM {_q(pq.metric)} WHERE {' AND '.join(where)} "
            + f"GROUP BY {', '.join(keys)}"
        )
        rows = conn.execute(sql).to_pylist()

        # per-series value per bucket; keys CANONICAL (label-sorted) so
        # binary-op matching and label-transform outputs line up across
        # metrics regardless of tag declaration order
        per_series = {}
        for r in rows:
            key = tuple(sorted((l, r[l]) for l in group_labels))
            per_series.setdefault(key, {})[r["bucket"]] = r["v"]

    if regex_matchers:
        per_series = {
            key: pts
            for key, pts in per_series.items()
            if _regex_match(dict(key), regex_matchers)
        }
    if fallback:
        # Lift the folded labels into real key labels, then apply the
        # matchers on the original family's own labels.
        per_series = _expand_folded_keys(per_series)
        if inner_matchers:
            per_series = {
                key: pts
                for key, pts in per_series.items()
                if _inner_match(dict(key), inner_matchers)
            }
    combined = per_series

    if pq.offset_ms:
        # offset stamps the shifted window back at the requested times
        combined = {
            key: {b + pq.offset_ms: v for b, v in points.items()}
            for key, points in combined.items()
        }
    return combined


def _at_series(
    conn, pq: PromQuery, start_ms: int, end_ms: int, step_ms: int
) -> dict[tuple, dict[int, float]]:
    """``metric @ t``: the value is pinned at ``t`` — one evaluation
    there, replicated across every requested step (prom's @ modifier
    semantics: the same sample answers every step)."""
    import dataclasses

    fixed = dataclasses.replace(pq, at_ms=None, offset_ms=0)
    at = pq.at_ms - pq.offset_ms  # offset still shifts the pinned time
    window = pq.range_ms or DEFAULT_LOOKBACK_MS
    inner_step = window if pq.func is not None else min(window, 60_000)
    pts = _range_series(conn, fixed, at - window, at, inner_step)
    # the SAME floor-aligned grid _range_series derives from data
    # ((ts//step)*step): a ceil-aligned grid would miss the other side's
    # first bucket in binary expressions when start isn't step-aligned
    first = (start_ms // step_ms) * step_ms
    buckets = list(range(first, end_ms + 1, step_ms))
    out = {}
    for key, series in pts.items():
        if not series:
            continue
        v = series[max(series)]  # latest resolvable value at the pin
        out[key] = {b: v for b in buckets}
    return out


def _regex_match(labels: dict, matchers: list[tuple[str, str, str]]) -> bool:
    """Prom regex matchers are fully anchored."""
    for label, op, pattern in matchers:
        current = str(labels.get(label) or "")  # NULL tag == absent label
        hit = re.fullmatch(pattern, current) is not None
        if op == "=~" and not hit:
            return False
        if op == "!~" and hit:
            return False
    return True


def _counter_series(
    conn, pq: PromQuery, where: list, schema, value_col: str,
    group_labels: list, step_ms: int, func: str,
    table=None, start_ms=None, end_ms=None,
) -> dict:
    """Reset-aware rate/increase: fold raw samples per series.

    Prom counters only move up; a drop means the process restarted and
    the counter began again near zero. increase = Σ over consecutive
    in-bucket samples of (vᵢ - vᵢ₋₁), with a reset contributing vᵢ (the
    counter re-accumulated from 0). rate = increase / step_seconds —
    min/max-based deltas would silently UNDERCOUNT across resets.

    When live window state (state/livewindow) holds the open tail, the
    resident complete buckets read write-time folded increments instead
    of raw: the scan shrinks to the head ``ts < serve_lo`` plus the
    partial-bucket tail ``ts >= tail_lo``, and the chain is stitched at
    both boundaries — a boundary delta counts only when the raw side
    has samples for the series, exactly the in-range pair rule above.
    """
    state_part = None
    if table is not None and start_ms is not None and end_ms is not None:
        from ..state.livewindow import try_livewindow_counter

        push = [m for m in pq.matchers if m[1] in ("=", "!=")]
        state_part = try_livewindow_counter(
            pq.metric, table, value_col, start_ms, end_ms, step_ms, push
        )
    scan_where = where
    serve_lo = None
    if state_part is not None:
        serve_lo = state_part["serve_lo"]
        tail_lo = state_part["tail_lo"]
        ts_q = _q(schema.timestamp_name)
        if tail_lo <= end_ms:
            scan_where = where + [f"({ts_q} < {serve_lo} OR {ts_q} >= {tail_lo})"]
        else:
            scan_where = where + [f"{ts_q} < {serve_lo}"]
    samples = _series_scan(
        conn, pq, scan_where, schema, value_col, group_labels
    )
    st_series = state_part["series"] if state_part else {}
    out: dict[tuple, dict[int, float]] = {}
    for key in set(samples) | set(st_series):
        pts = sorted(samples.get(key, ()))
        buckets: dict[int, float] = {}
        prev_v = None

        def _fold(seq):
            nonlocal prev_v
            for ts, v in seq:
                if prev_v is not None:
                    delta = v - prev_v
                    if delta < 0:
                        delta = v  # counter reset: it restarted from ~0
                    # every consecutive-sample delta counts ONCE,
                    # attributed to the later sample's bucket — a delta
                    # straddling a bucket boundary must not vanish
                    # (scrape intervals rarely align with steps). A
                    # single-sample bucket emits no point, like prom
                    # (two samples make an increase).
                    b = (ts // step_ms) * step_ms
                    buckets[b] = buckets.get(b, 0.0) + delta
                prev_v = v

        st = st_series.get(key)
        head = pts if serve_lo is None else [p for p in pts if p[0] < serve_lo]
        _fold(head)
        if st is not None:
            # head->state boundary pair, then the write-time folded
            # increments, then the chain continues from the state's
            # last sample into the partial-bucket tail
            _fold([st["first"]])
            for b, d in st["buckets"].items():
                buckets[b] = buckets.get(b, 0.0) + d
            prev_v = st["last"][1]
        if serve_lo is not None:
            _fold([p for p in pts if p[0] >= serve_lo])
        if func == "rate":
            buckets = {b: d / (step_ms / 1000.0) for b, d in buckets.items()}
        out[key] = buckets
    return out


def _raw_window_series(
    conn, pq: PromQuery, where: list, schema, value_col: str,
    group_labels: list, start_ms: int, end_ms: int, step_ms: int,
    window_ms: int, func: str, param,
) -> dict:
    """Raw-fold functions (order statistics, gauge deltas, instant
    variants, change counts): at every aligned step b the fold sees the
    SLIDING window (b-window, b] — prom's semantics. Step-sized buckets
    would show each step only its own slice (irate at a step finer than
    the scrape interval would see < 2 samples and vanish)."""
    series = _series_scan(conn, pq, where, schema, value_col, group_labels)
    first = (start_ms // step_ms) * step_ms
    if first < start_ms:
        first += step_ms
    steps = list(range(first, end_ms + 1, step_ms))
    out: dict[tuple, dict[int, float]] = {}
    for key, tv_list in series.items():
        tv_list.sort()
        ts_arr = [t for t, _ in tv_list]
        import bisect

        folded: dict[int, float] = {}
        for b in steps:
            # LEFT-OPEN window (b-window, b], Prometheus's convention — a
            # sample landing exactly on a boundary belongs to one window
            # only. The instant path (_instant_over_time) uses the same
            # open left bound so instant/range answers agree.
            lo = bisect.bisect_right(ts_arr, b - window_ms)
            hi = bisect.bisect_right(ts_arr, b)
            if lo >= hi:
                continue
            v = _fold_window(func, param, tv_list[lo:hi])
            if v is not None:
                folded[b] = v
        out[key] = folded
    return out


def _series_scan(
    conn, pq: PromQuery, where: list, schema, value_col: str, group_labels: list
) -> dict[tuple, list]:
    """Raw (ts, value) samples per CANONICAL (label-sorted) series key —
    the single scan both counter folds and order-statistic folds use."""
    label_sel = ", ".join(_q(l) for l in group_labels)
    sql = (
        f"SELECT {label_sel + ', ' if group_labels else ''}"
        f"{_q(schema.timestamp_name)} AS __ts, {_q(value_col)} AS __v "
        f"FROM {_q(pq.metric)} WHERE {' AND '.join(where)}"
    )
    rows = conn.execute(sql).to_pylist()
    samples: dict[tuple, list] = {}
    for r in rows:
        key = tuple(sorted((l, r[l]) for l in group_labels))
        samples.setdefault(key, []).append((r["__ts"], r["__v"]))
    return samples


def _fold_window(func: str, param, tv: list) -> float:
    """One window's worth of raw (ts, value) samples -> one value."""
    import math

    vals = [v for _, v in tv]
    if func == "delta":
        # gauge delta: newest minus oldest sample in the window (no
        # counter-reset folding — deltas of gauges go down legitimately).
        # <2 samples -> None: NO sample, like prom (a NaN would poison
        # downstream min/max folds).
        if len(tv) < 2:
            return None
        s = sorted(tv)
        return s[-1][1] - s[0][1]
    if func in ("irate", "idelta"):
        # instant variants: the LAST TWO samples only
        if len(tv) < 2:
            return None
        s = sorted(tv)
        (t0, v0), (t1, v1) = s[-2], s[-1]
        if t1 == t0:
            return None
        d = v1 - v0
        if func == "idelta":
            return d
        if d < 0:
            d = v1  # counter reset between the two samples
        return d / ((t1 - t0) / 1000.0)
    if func == "changes":
        # prom compares bit patterns: NaN -> NaN is NO change, NaN <-> x is
        # one (Python NaN != NaN would count every NaN pair)
        s = sorted(tv)
        n = 0
        for i in range(1, len(s)):
            a, b = s[i - 1][1], s[i][1]
            a_nan, b_nan = a != a, b != b
            if (a_nan and b_nan) or (not a_nan and not b_nan and a == b):
                continue
            n += 1
        return float(n)
    if func == "resets":
        s = sorted(tv)
        return float(sum(
            1
            for i in range(1, len(s))
            if s[i][1] == s[i][1] and s[i - 1][1] == s[i - 1][1]
            and s[i][1] < s[i - 1][1]
        ))
    if func == "last_over_time":
        return max(tv)[1]
    if func == "stddev_over_time":
        mean = sum(vals) / len(vals)
        return math.sqrt(sum((v - mean) ** 2 for v in vals) / len(vals))
    if func == "quantile_over_time":
        return _quantile(param, vals)
    if func == "sum_over_time":
        return float(sum(vals))
    if func == "count_over_time":
        return float(len(vals))
    if func == "avg_over_time":
        return sum(vals) / len(vals)
    if func == "min_over_time":
        return min(vals)
    if func == "max_over_time":
        return max(vals)
    raise PromQLError(f"unknown window function {func!r}")


DEFAULT_SUBQUERY_STEP_MS = 60_000  # prom's default evaluation interval


def _subquery_points(
    conn, node: "PromSubquery", time_ms: int, instant_cache: Optional[dict] = None
) -> dict:
    """-> {label_key: [(t, value), ...]} — the inner expression
    instant-evaluated at step-aligned times within (t-range, t].

    ``instant_cache`` memoizes per aligned instant across calls: a range
    evaluation's consecutive windows share all but one instant, and
    re-running the inner expression (>= one SQL scan each) per overlap
    would multiply the work ~range/step times."""
    t_eval = (node.at_ms if node.at_ms is not None else time_ms) - node.offset_ms
    step = node.step_ms or DEFAULT_SUBQUERY_STEP_MS
    start = t_eval - node.range_ms
    t = (start // step + 1) * step  # first aligned instant AFTER start
    out: dict = {}
    while t <= t_eval:
        vec = instant_cache.get(t) if instant_cache is not None else None
        if vec is None:
            vec = {}
            for s in evaluate_expr_instant(conn, node.expr, t):
                key = tuple(
                    sorted((k, v) for k, v in s["metric"].items() if k != "__name__")
                )
                vec[key] = float(s["value"][1])
            if instant_cache is not None:
                instant_cache[t] = vec
        for key, v in vec.items():
            out.setdefault(key, []).append((t, v))
        t += step
    return out


def _fold_subquery(func: str, param, tv: list) -> Optional[float]:
    """Fold one series' subquery samples; None -> no output sample.
    rate/increase over subquery output get counter semantics over the
    sampled points (resets folded like prom's extrapolation-free core);
    delta gets gauge semantics; *_over_time delegates to the shared
    window fold."""
    if not tv:
        return None
    if func in ("rate", "increase", "delta"):
        if len(tv) < 2:
            return None
        tv = sorted(tv)
        t0, v0 = tv[0]
        t1, _ = tv[-1]
        if t1 == t0:
            return None
        if func == "delta":
            return tv[-1][1] - v0  # gauge semantics, no reset folding
        inc = 0.0
        prev = v0
        for _, v in tv[1:]:
            inc += (v - prev) if v >= prev else v  # counter reset
            prev = v
        if func == "increase":
            return inc
        return inc / ((t1 - t0) / 1000.0)
    return _fold_window(func, param, tv)


def _subquery_vector(
    conn, node: "PromSubquery", time_ms: int, instant_cache: Optional[dict] = None
) -> dict:
    if node.func is None:
        raise PromQLError(
            "a subquery result must be consumed by a range function "
            "(e.g. max_over_time(expr[5m:1m]))"
        )
    out = {}
    for key, tv in _subquery_points(conn, node, time_ms, instant_cache).items():
        v = _fold_subquery(node.func, node.param, tv)
        if v is not None:
            out[key] = v
    return out


def _quantile(phi: float, vals: list) -> float:
    """Prom's φ-quantile: linear interpolation between closest ranks;
    φ outside [0,1] yields ∓/±Inf like prom."""
    import math

    if phi < 0:
        return -math.inf
    if phi > 1:
        return math.inf
    s = sorted(vals)
    if not s:
        return math.nan
    rank = phi * (len(s) - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (rank - lo)


# ---- binary expressions --------------------------------------------------


def _apply_cmp(op: str, a: float, b: float) -> bool:
    """One comparison (filter) operator over two sample values."""
    if op == ">":
        return a > b
    if op == "<":
        return a < b
    if op == ">=":
        return a >= b
    if op == "<=":
        return a <= b
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    raise PromQLError(f"unsupported comparison {op!r}")


def _compare_series(op: str, lk, lv, rk, rv):
    """Prom filter semantics for ('scalar'|'vector') operand pairs in
    RANGE space ({key: {bucket: value}}): the surviving samples keep the
    LEFT side's values (vector OP scalar and vector OP vector), or the
    right vector's values for scalar OP vector; empty series drop out."""
    if lk == "scalar" and rk == "scalar":
        return "scalar", 1.0 if _apply_cmp(op, lv, rv) else 0.0
    if lk == "vector" and rk == "scalar":
        out = {
            key: {b: v for b, v in pts.items() if _apply_cmp(op, v, rv)}
            for key, pts in lv.items()
        }
        return "vector", {k: p for k, p in out.items() if p}
    if lk == "scalar" and rk == "vector":
        out = {
            key: {b: v for b, v in pts.items() if _apply_cmp(op, lv, v)}
            for key, pts in rv.items()
        }
        return "vector", {k: p for k, p in out.items() if p}
    out: dict = {}
    for key, lpts in lv.items():
        rpts = rv.get(key)
        if rpts is None:
            continue
        pts = {
            b: v
            for b, v in lpts.items()
            if b in rpts and _apply_cmp(op, v, rpts[b])
        }
        if pts:
            out[key] = pts
    return "vector", out


def _apply_op(op: str, a: float, b: float) -> float:
    import math

    try:
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            if b == 0:
                return math.nan  # prom: x % 0 -> NaN (fmod would raise)
            return math.fmod(a, b)
    except ZeroDivisionError:
        # prom arithmetic: x/0 -> ±Inf, 0/0 -> NaN (never an error)
        if a > 0:
            return math.inf
        if a < 0:
            return -math.inf
        return math.nan
    raise PromQLError(f"unsupported operator {op!r}")


def _eval_series(conn, node: PromExpr, start_ms: int, end_ms: int, step_ms: int):
    """-> ('scalar', float) or ('vector', {key: {bucket: value}})."""
    if isinstance(node, PromScalar):
        return "scalar", node.value
    if isinstance(node, PromSubquery):
        first = (start_ms // step_ms) * step_ms
        if first < start_ms:
            first += step_ms
        vec: dict = {}
        instant_cache: dict = {}  # consecutive windows share instants
        for b in range(first, end_ms + 1, step_ms):
            for key, v in _subquery_vector(conn, node, b, instant_cache).items():
                vec.setdefault(key, {})[b] = v
        return "vector", vec
    if isinstance(node, PromQuery):
        return "vector", _range_series(conn, node, start_ms, end_ms, step_ms)
    if isinstance(node, PromAgg):
        k, vec = _eval_series(conn, node.arg, start_ms, end_ms, step_ms)
        if k != "vector":
            raise PromQLError(f"{node.op}() expects a vector argument")
        return "vector", _combine_agg(node, vec)
    if isinstance(node, PromCall):
        k, vec = _eval_series(conn, node.arg, start_ms, end_ms, step_ms)
        if k != "vector":
            raise PromQLError(f"{node.name}() expects a vector argument")
        return "vector", _apply_call(node, vec)
    lk, lv = _eval_series(conn, node.lhs, start_ms, end_ms, step_ms)
    rk, rv = _eval_series(conn, node.rhs, start_ms, end_ms, step_ms)
    op = node.op
    if op in COMPARE_OPS:
        return _compare_series(op, lk, lv, rk, rv)
    if lk == "scalar" and rk == "scalar":
        return "scalar", _apply_op(op, lv, rv)
    if rk == "scalar":
        return "vector", {
            key: {b: _apply_op(op, v, rv) for b, v in pts.items()}
            for key, pts in lv.items()
        }
    if lk == "scalar":
        return "vector", {
            key: {b: _apply_op(op, lv, v) for b, v in pts.items()}
            for key, pts in rv.items()
        }
    # vector/vector: one-to-one on identical label sets; samples without
    # a partner (either side) drop out, matching prom's default matching
    out: dict[tuple, dict[int, float]] = {}
    for key, lpts in lv.items():
        rpts = rv.get(key)
        if rpts is None:
            continue
        pts = {
            b: _apply_op(op, v, rpts[b]) for b, v in lpts.items() if b in rpts
        }
        if pts:
            out[key] = pts
    return "vector", out


def leaf_metrics(node: PromExpr) -> list[str]:
    """Metric names referenced by an expression, left to right."""
    if isinstance(node, PromQuery):
        return [node.metric]
    if isinstance(node, PromBin):
        return leaf_metrics(node.lhs) + leaf_metrics(node.rhs)
    if isinstance(node, (PromAgg, PromCall)):
        return leaf_metrics(node.arg)
    if isinstance(node, PromSubquery):
        return leaf_metrics(node.expr)
    return []


def _combine_agg(node: PromAgg, vec: dict) -> dict:
    """Cross-series combine of {key: {bucket: v}} (ref surface: prom's
    aggregation operators via the IOx planner the reference forks).

    ``by`` keeps listed labels, ``without`` drops listed labels, neither
    collapses everything. topk/bottomk differ: they SELECT input series
    (full original labels survive), per bucket, within each group.
    """
    import math

    def out_key(key: tuple) -> tuple:
        if node.without_labels is not None:
            drop = set(node.without_labels)
            return tuple((l, v) for l, v in key if l not in drop)
        if node.by_labels is not None:
            keep = set(node.by_labels)
            return tuple((l, v) for l, v in key if l in keep)
        return ()

    if node.op in ("topk", "bottomk"):
        k = int(node.param)
        largest = node.op == "topk"
        # group -> bucket -> [(value, key)]
        ranked: dict[tuple, dict[int, list]] = {}
        for key, pts in vec.items():
            g = out_key(key)
            for b, v in pts.items():
                ranked.setdefault(g, {}).setdefault(b, []).append((v, key))
        out: dict[tuple, dict[int, float]] = {}
        for g, buckets in ranked.items():
            for b, pairs in buckets.items():
                pairs.sort(key=lambda t: t[0], reverse=largest)
                for v, key in pairs[:k]:
                    out.setdefault(key, {})[b] = v
        return out

    grouped: dict[tuple, dict[int, list]] = {}
    for key, pts in vec.items():
        g = out_key(key)
        dst = grouped.setdefault(g, {})
        for b, v in pts.items():
            dst.setdefault(b, []).append(v)

    def fn(vs: list) -> float:
        if node.op == "sum":
            return sum(vs)
        if node.op == "avg":
            return sum(vs) / len(vs)
        if node.op == "min":
            return min(vs)
        if node.op == "max":
            return max(vs)
        if node.op == "count":
            return float(len(vs))
        if node.op in ("stddev", "stdvar"):
            mean = sum(vs) / len(vs)
            var = sum((v - mean) ** 2 for v in vs) / len(vs)
            return var if node.op == "stdvar" else math.sqrt(var)
        if node.op == "quantile":
            return _quantile(node.param, vs)
        raise PromQLError(f"unknown aggregator {node.op!r}")

    return {
        g: {b: fn(vs) for b, vs in buckets.items()}
        for g, buckets in grouped.items()
    }


_DOLLAR_REF = re.compile(r"\$(\d+|\{\w+\})")


def _apply_call(node: PromCall, vec: dict) -> dict:
    """histogram_quantile / label manipulation / per-sample math."""
    import math

    name = node.name
    if name == "histogram_quantile":
        return _histogram_quantile(node.params[0], vec)
    if name in ("label_replace", "label_join"):
        out: dict = {}
        for key, pts in vec.items():
            labels = dict(key)
            if name == "label_replace":
                dst, repl, src, pattern = node.params
                current = str(labels.get(src) or "")
                m = re.fullmatch(pattern, current)
                if m is not None:
                    def _ref(g, _m=m):
                        ref = g.group(1).strip("{}")
                        try:
                            got = _m.group(int(ref) if ref.isdigit() else ref)
                        except (IndexError, re.error):
                            raise PromQLError(
                                f"label_replace: no capture group ${ref}"
                            )
                        return got or ""

                    new = _DOLLAR_REF.sub(_ref, repl)
                    if new:
                        labels[dst] = new
                    else:
                        labels.pop(dst, None)
            else:
                dst, sep, *srcs = node.params
                new = sep.join(str(labels.get(s) or "") for s in srcs)
                if new:
                    labels[dst] = new
                else:
                    labels.pop(dst, None)
            new_key = tuple(sorted(labels.items()))
            if new_key in out:
                raise PromQLError(
                    f"{name} produced duplicate series for labels {labels}"
                )
            out[new_key] = pts
        return out

    # per-sample math
    p = node.params[0] if node.params else None
    if name == "abs":
        f = abs
    elif name == "ceil":
        f = math.ceil
    elif name == "floor":
        f = math.floor
    elif name == "round":
        nearest = p if p else 1.0
        f = lambda v: math.floor(v / nearest + 0.5) * nearest
    elif name == "clamp_min":
        f = lambda v: max(v, p)
    elif name == "clamp_max":
        f = lambda v: min(v, p)
    else:
        raise PromQLError(f"unknown function {name!r}")
    return {
        key: {b: float(f(v)) for b, v in pts.items()} for key, pts in vec.items()
    }


def _histogram_quantile(phi: float, vec: dict) -> dict:
    """Prom's histogram_quantile over conventional `_bucket` series:
    groups by labels-minus-`le`, linear interpolation inside the target
    bucket, +Inf bucket answers with the highest finite bound. Bucket
    counts are made monotone first (float scrapes can jitter)."""
    import math

    groups: dict[tuple, dict[int, list]] = {}
    for key, pts in vec.items():
        labels = dict(key)
        le = labels.pop("le", None)
        if le is None:
            continue  # not a histogram series
        try:
            bound = math.inf if str(le) in ("+Inf", "Inf", "inf") else float(le)
        except ValueError:
            continue
        g = tuple(sorted(labels.items()))
        for b, v in pts.items():
            groups.setdefault(g, {}).setdefault(b, []).append((bound, v))
    out: dict[tuple, dict[int, float]] = {}
    for g, buckets in groups.items():
        pts = {}
        for b, pairs in buckets.items():
            q = _hq_one(phi, pairs)
            if q is not None:
                pts[b] = q
        if pts:
            out[g] = pts
    return out


def _hq_one(phi: float, pairs: list) -> "float | None":
    import math

    if phi < 0:
        return -math.inf
    if phi > 1:
        return math.inf
    pairs.sort()
    if len(pairs) < 2 or not math.isinf(pairs[-1][0]):
        return None  # prom requires an +Inf bucket
    # enforce monotone cumulative counts
    mono = []
    prev = 0.0
    for le, c in pairs:
        prev = max(prev, c)
        mono.append((le, prev))
    total = mono[-1][1]
    if total == 0:
        return None
    rank = phi * total
    for i, (le, c) in enumerate(mono):
        if c >= rank:
            if math.isinf(le):
                # quantile in the +Inf bucket: highest finite bound
                return mono[i - 1][0]
            lower_le = mono[i - 1][0] if i > 0 else 0.0
            lower_c = mono[i - 1][1] if i > 0 else 0.0
            if c == lower_c:
                return le
            return lower_le + (le - lower_le) * (rank - lower_c) / (c - lower_c)
    return None


def evaluate_expr_range(
    conn, node: PromExpr, start_ms: int, end_ms: int, step_ms: int
) -> list[dict]:
    """Range-evaluate any expression -> prom 'matrix'. Leaf queries keep
    their metric name; arithmetic results drop __name__ (like prom)."""
    if isinstance(node, PromQuery):
        return evaluate_range(conn, node, start_ms, end_ms, step_ms)
    kind, val = _eval_series(conn, node, start_ms, end_ms, step_ms)
    if kind == "scalar":
        # a constant series sampled at each aligned step
        first = (start_ms // step_ms) * step_ms
        if first < start_ms:
            first += step_ms
        buckets = list(range(first, end_ms + 1, step_ms))
        return [
            {
                "metric": {},
                "values": [[b / 1000.0, repr(float(val))] for b in buckets],
            }
        ]
    out = []
    for key, points in sorted(val.items()):
        out.append(
            {
                "metric": {l: v for l, v in key},
                "values": [
                    [b / 1000.0, repr(float(points[b]))] for b in sorted(points)
                ],
            }
        )
    return out


def _instant_value(conn, node: PromExpr, time_ms: int):
    """-> ('scalar', float) or ('vector', {label_key: float}).

    Every metric leaf evaluates with ITS OWN instant semantics (its own
    range window; rate folds its whole range, raw selectors take the
    latest sample) — mixing rate(x[4m]) with a raw selector never shrinks
    the rate's window. Keys exclude __name__, matching prom's one-to-one
    rule that arithmetic ignores the metric name."""
    if isinstance(node, PromScalar):
        return "scalar", node.value
    if isinstance(node, PromSubquery):
        return "vector", _subquery_vector(conn, node, time_ms)
    if isinstance(node, PromQuery):
        vec = {}
        for s in evaluate_instant(conn, node, time_ms):
            key = tuple(
                sorted((k, v) for k, v in s["metric"].items() if k != "__name__")
            )
            vec[key] = float(s["value"][1])
        return "vector", vec
    if isinstance(node, (PromAgg, PromCall)):
        k, vec = _instant_value(conn, node.arg, time_ms)
        if k != "vector":
            raise PromQLError("vector argument expected")
        # reuse the range combinators through a single synthetic bucket
        as_pts = {key: {0: v} for key, v in vec.items()}
        combined = (
            _combine_agg(node, as_pts)
            if isinstance(node, PromAgg)
            else _apply_call(node, as_pts)
        )
        return "vector", {
            key: pts[0] for key, pts in combined.items() if 0 in pts
        }
    lk, lv = _instant_value(conn, node.lhs, time_ms)
    rk, rv = _instant_value(conn, node.rhs, time_ms)
    op = node.op
    if op in COMPARE_OPS:
        # reuse the range-space filter through a single synthetic bucket
        as_pts = lambda vec: {key: {0: v} for key, v in vec.items()}
        kind, out = _compare_series(
            op,
            lk, as_pts(lv) if lk == "vector" else lv,
            rk, as_pts(rv) if rk == "vector" else rv,
        )
        if kind == "scalar":
            return "scalar", out
        return "vector", {key: pts[0] for key, pts in out.items()}
    if lk == "scalar" and rk == "scalar":
        return "scalar", _apply_op(op, lv, rv)
    if rk == "scalar":
        return "vector", {k: _apply_op(op, v, rv) for k, v in lv.items()}
    if lk == "scalar":
        return "vector", {k: _apply_op(op, lv, v) for k, v in rv.items()}
    return "vector", {
        k: _apply_op(op, v, rv[k]) for k, v in lv.items() if k in rv
    }


def evaluate_expr_instant(conn, node: PromExpr, time_ms: int) -> list[dict]:
    """Instant-evaluate any expression -> prom 'vector'."""
    if isinstance(node, PromQuery):
        return evaluate_instant(conn, node, time_ms)
    kind, val = _instant_value(conn, node, time_ms)
    if kind == "scalar":
        return [{"metric": {}, "value": [time_ms / 1000.0, repr(float(val))]}]
    return [
        {"metric": dict(key), "value": [time_ms / 1000.0, repr(float(v))]}
        for key, v in sorted(val.items())
    ]


DEFAULT_LOOKBACK_MS = 5 * 60_000  # prom's 5m instant lookback


_OVER_TIME_FUNCS = frozenset(
    f for f in RANGE_FUNCS if f.endswith("_over_time")
)
# Functions that must fold the EXACT (t-range, t] window at instant
# evaluation (epoch-aligned buckets cover only a fraction of the window
# whenever t isn't step-aligned): the *_over_time family plus delta.
_EXACT_WINDOW_FUNCS = _OVER_TIME_FUNCS | _RAW_FOLD_FUNCS


def evaluate_instant(conn, pq: PromQuery, time_ms: int) -> list[dict]:
    """-> prom 'vector': latest resolvable value per series in the lookback
    (steps at scrape-ish resolution so 'latest' means latest, not a
    whole-window average). ``*_over_time`` functions fold their EXACT
    left-open window (t-range, t] (not an epoch-aligned bucket containing
    t — an aligned bucket would cover a fraction of the window whenever t
    isn't step-aligned)."""
    if pq.func in _EXACT_WINDOW_FUNCS:
        return _instant_over_time(conn, pq, time_ms)
    window = pq.range_ms or DEFAULT_LOOKBACK_MS
    # rate/increase aggregate over their whole window; only a raw selector
    # walks in scrape-resolution steps to find the latest sample.
    step = window if pq.func is not None else min(window, 60_000)
    matrix = evaluate_range(conn, pq, time_ms - window, time_ms, step)
    out = []
    for series in matrix:
        if not series["values"]:
            continue
        ts, val = series["values"][-1]
        out.append({"metric": series["metric"], "value": [time_ms / 1000.0, val]})
    return out


def _instant_over_time(conn, pq: PromQuery, time_ms: int) -> list[dict]:
    """One raw fold per series over exactly (t-range, t] (after @/offset) —
    Prometheus's left-open window, matching _raw_window_series."""
    orig_metric = pq.metric  # the fallback rewrite must not leak into __name__
    pq, table, inner_matchers, fallback = _metric_table(conn, pq)
    if table is None:
        return []
    schema = table.schema
    value_col = _value_column(schema)
    tag_names = list(schema.tag_names)
    for label, _, _ in pq.matchers:
        if label not in tag_names:
            raise PromQLError(f"unknown label {label!r} on metric {pq.metric!r}")
    t_eval = (pq.at_ms if pq.at_ms is not None else time_ms) - pq.offset_ms
    window = pq.range_ms or DEFAULT_LOOKBACK_MS
    where = [
        f"{_q(schema.timestamp_name)} > {t_eval - window}",
        f"{_q(schema.timestamp_name)} <= {t_eval}",
    ]
    for label, op, val in pq.matchers:
        if op in ("=", "!="):
            sval = str(val).replace("'", "''")
            where.append(f"{_q(label)} {'=' if op == '=' else '!='} '{sval}'")
    regex_matchers = [m for m in pq.matchers if m[1] in ("=~", "!~")]
    series = _series_scan(conn, pq, where, schema, value_col, tag_names)
    if regex_matchers:
        series = {
            key: tv for key, tv in series.items()
            if _regex_match(dict(key), regex_matchers)
        }
    if fallback:
        series = _expand_folded_keys(series)
        if inner_matchers:
            series = {
                key: tv for key, tv in series.items()
                if _inner_match(dict(key), inner_matchers)
            }
    out = []
    for key, tv in sorted(series.items()):
        v = _fold_window(pq.func, pq.param, tv)
        if v is None:
            continue  # e.g. delta over a single sample: no output point
        out.append(
            {
                "metric": {"__name__": orig_metric, **{l: x for l, x in key}},
                "value": [time_ms / 1000.0, repr(float(v))],
            }
        )
    return out
