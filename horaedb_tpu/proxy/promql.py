"""PromQL subset: parse + translate to the SQL engine
(ref: query_frontend/src/promql/{convert,pushdown}.rs — the reference
translates PromQL into DataFusion plans; here PromQL translates into the
same Plan/executor pipeline SQL uses, so prom queries ride the fused
device kernels).

Supported grammar (the TSBS/dashboard workhorse subset):

    expr     := addexpr
    addexpr  := mulexpr (('+' | '-') mulexpr)*
    mulexpr  := unary (('*' | '/' | '%') unary)*
    unary    := number | '(' expr ')' | vector
    vector   := agg 'by' '(' labels ')' '(' vector ')'
              | agg '(' vector ')'          -- agg arg is a vector, not
              | func '(' selector ')'       -- arithmetic: sum(a*2) is
              | selector                    -- written sum(a) * 2
    agg      := sum | avg | min | max | count
    func     := rate | increase | avg_over_time | min_over_time | max_over_time
    selector := metric [ '{' matcher (',' matcher)* '}' ]
                [ '[' duration ']' ] ( 'offset' duration | '@' unix )*
    matcher  := label ('=' | '!=' | '=~' | '!~') 'value'

Binary expressions follow prom's arithmetic semantics: scalar/scalar,
vector/scalar (applied per sample), and vector/vector one-to-one
matching on identical label sets (samples without a partner drop out;
``__name__`` is dropped from arithmetic results, like prom).

Semantics notes:
- the metric name maps to a table; its single DOUBLE field (or a column
  literally named ``value``) is the sample value, the timestamp key is
  the sample time — exactly the shape OpenTSDB/Influx ingestion creates;
- equality matchers push into the scan; regex matchers (fully anchored,
  like prom) post-filter the series set host-side;
- ``rate``/``increase`` fold consecutive raw samples with counter-reset
  correction (a drop restarts the counter near zero), each delta
  attributed to the later sample's step bucket;
- ``offset`` evaluates a window shifted into the past and stamps results
  back at the requested times;
- range queries evaluate per aligned ``step`` bucket; instant queries use
  a 5m lookback window.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from ..engine.options import parse_duration_ms

AGG_FUNCS = {"sum", "avg", "min", "max", "count"}
RANGE_FUNCS = {"rate", "increase", "avg_over_time", "min_over_time", "max_over_time"}


class PromQLError(ValueError):
    pass


@dataclass
class PromQuery:
    metric: str
    matchers: list[tuple[str, str, str]] = field(default_factory=list)  # (label, op, value)
    range_ms: Optional[int] = None
    func: Optional[str] = None  # RANGE_FUNCS
    agg: Optional[str] = None  # AGG_FUNCS
    by_labels: Optional[list[str]] = None  # None = per-series
    offset_ms: int = 0  # `offset 1h` shifts the evaluated window back
    at_ms: Optional[int] = None  # `@ <unix>` pins the evaluation time


@dataclass
class PromScalar:
    """A number literal in an expression (e.g. the 100 in x * 100)."""

    value: float


@dataclass
class PromBin:
    """Arithmetic over sub-expressions: vector/scalar applies per sample,
    vector/vector matches one-to-one on identical label sets."""

    op: str  # + - * / %
    lhs: "PromExpr"
    rhs: "PromExpr"


PromExpr = PromQuery | PromScalar | PromBin


_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:.]*"
_TOKENS = re.compile(
    rf"""\s*(?:
      (?P<name>{_NAME})
    | (?P<dur>\d+(?:ms|s|m|h|d))
    | (?P<number>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
    | (?P<string>'(?:[^'])*'|"(?:[^"])*")
    | (?P<op>!=|=~|!~|[={{}}()\[\],+\-*/%@])
    )""",
    re.VERBOSE,
)


def _tokenize(q: str):
    out, i = [], 0
    while i < len(q):
        m = _TOKENS.match(q, i)
        if not m:
            if q[i:].strip() == "":
                break
            raise PromQLError(f"unexpected character {q[i]!r} at {i}")
        if m.lastgroup:
            out.append((m.lastgroup, m.group().strip()))
        i = m.end()
    return out


class _Parser:
    def __init__(self, q: str) -> None:
        self.q = q
        self.toks = _tokenize(q)
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        if t[0] is None:
            raise PromQLError(f"unexpected end of query: {self.q!r}")
        self.i += 1
        return t

    def expect(self, text: str):
        kind, tok = self.next()
        if tok != text:
            raise PromQLError(f"expected {text!r}, found {tok!r} in {self.q!r}")

    def parse(self) -> PromExpr:
        pq = self.addexpr()
        if self.peek()[0] is not None:
            raise PromQLError(f"trailing input after query: {self.q!r}")
        return pq

    # precedence climbing: * / % bind tighter than + -
    def addexpr(self) -> PromExpr:
        node = self.mulexpr()
        while self.peek() == ("op", "+") or self.peek() == ("op", "-"):
            op = self.next()[1]
            node = PromBin(op, node, self.mulexpr())
        return node

    def mulexpr(self) -> PromExpr:
        node = self.unary()
        while self.peek()[1] in ("*", "/", "%") and self.peek()[0] == "op":
            op = self.next()[1]
            node = PromBin(op, node, self.unary())
        return node

    def unary(self) -> PromExpr:
        kind, tok = self.peek()
        if kind == "number":
            self.next()
            return PromScalar(float(tok))
        if (kind, tok) == ("op", "-"):
            self.next()
            inner = self.unary()
            if isinstance(inner, PromScalar):
                return PromScalar(-inner.value)
            return PromBin("*", PromScalar(-1.0), inner)
        if (kind, tok) == ("op", "("):
            self.next()
            node = self.addexpr()
            self.expect(")")
            return node
        return self.expr()

    def expr(self) -> PromQuery:
        kind, tok = self.peek()
        if kind == "name" and tok in AGG_FUNCS:
            self.next()
            by = None
            k2, t2 = self.peek()
            if k2 == "name" and t2 == "by":
                self.next()
                self.expect("(")
                by = [self._ident()]
                while self.peek()[1] == ",":
                    self.next()
                    by.append(self._ident())
                self.expect(")")
            self.expect("(")
            inner = self.expr()
            self.expect(")")
            if inner.agg is not None:
                raise PromQLError("nested aggregations are not supported")
            inner.agg = tok
            inner.by_labels = by
            return inner
        if kind == "name" and tok in RANGE_FUNCS:
            self.next()
            self.expect("(")
            inner = self.selector()
            self.expect(")")
            if tok in ("rate", "increase") and inner.range_ms is None:
                raise PromQLError(f"{tok}() requires a range selector like [5m]")
            inner.func = tok
            return inner
        return self.selector()

    def _ident(self) -> str:
        kind, tok = self.next()
        if kind != "name":
            raise PromQLError(f"expected identifier, found {tok!r}")
        return tok

    def selector(self) -> PromQuery:
        metric = self._ident()
        if metric in AGG_FUNCS or metric in RANGE_FUNCS:
            raise PromQLError(f"{metric!r} used as a metric name")
        pq = PromQuery(metric=metric)
        if self.peek()[1] == "{":
            self.next()
            while True:
                label = self._ident()
                kind, op = self.next()
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"unsupported matcher op {op!r}")
                skind, sval = self.next()
                if skind != "string":
                    raise PromQLError(f"matcher value must be quoted: {sval!r}")
                value = sval[1:-1]
                if op in ("=~", "!~"):
                    try:
                        re.compile(value)
                    except re.error as e:
                        raise PromQLError(f"bad regex {value!r}: {e}")
                pq.matchers.append((label, op, value))
                kind, tok = self.next()
                if tok == "}":
                    break
                if tok != ",":
                    raise PromQLError(f"expected ',' or '}}', found {tok!r}")
        if self.peek()[1] == "[":
            self.next()
            kind, dur = self.next()
            if kind != "dur":
                raise PromQLError(f"expected a duration like 5m, found {dur!r}")
            pq.range_ms = parse_duration_ms(dur)
            self.expect("]")
        while True:
            if self.peek() == ("name", "offset"):
                self.next()
                kind, dur = self.next()
                if kind != "dur":
                    raise PromQLError(f"offset expects a duration, found {dur!r}")
                pq.offset_ms = parse_duration_ms(dur)
                continue
            if self.peek() == ("op", "@"):
                self.next()
                kind, num = self.next()
                if kind != "number":
                    raise PromQLError(f"@ expects a unix timestamp, found {num!r}")
                pq.at_ms = int(float(num) * 1000)
                continue
            break
        return pq


def parse_promql(query: str) -> PromExpr:
    return _Parser(query).parse()


# ---- evaluation ---------------------------------------------------------


def sql_str_literal(v: str) -> str:
    """Quote a string for SQL interpolation (doubling embedded quotes) —
    EVERY protocol front end that builds WHERE clauses from client data
    must use this, or apostrophes break the query (and worse)."""
    return "'" + str(v).replace("'", "''") + "'"


def _value_column(schema) -> str:
    if schema.has_column("value"):
        return "value"
    fields = [schema.columns[i] for i in schema.field_indexes]
    doubles = [c.name for c in fields if c.kind.value in ("double", "float")]
    if len(doubles) == 1:
        return doubles[0]
    raise PromQLError(
        f"metric table needs a 'value' column or exactly one double field; "
        f"found {doubles}"
    )


_QUOTE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _q(name: str) -> str:
    return name if _QUOTE.match(name) else f'"{name}"'


def evaluate_range(
    conn,
    pq: PromQuery,
    start_ms: int,
    end_ms: int,
    step_ms: int,
) -> list[dict]:
    """-> prom 'matrix' result list for [start, end] at step resolution."""
    combined = _range_series(conn, pq, start_ms, end_ms, step_ms)
    out = []
    for key, points in sorted(combined.items()):
        out.append(
            {
                "metric": {"__name__": pq.metric, **{l: v for l, v in key}},
                "values": [
                    # repr = shortest round-trip form (full precision,
                    # like prom's Go 'g' formatting)
                    [b / 1000.0, repr(float(points[b]))]
                    for b in sorted(points)
                ],
            }
        )
    return out


def _range_series(
    conn,
    pq: PromQuery,
    start_ms: int,
    end_ms: int,
    step_ms: int,
) -> dict[tuple, dict[int, float]]:
    """Per-series step-bucket values in REQUESTED-time space (offset
    already stamped back), keyed by ((label, value), ...)."""
    if pq.at_ms is not None:
        return _at_series(conn, pq, start_ms, end_ms, step_ms)
    table = conn.catalog.open(pq.metric)
    if table is None:
        return {}
    schema = table.schema
    value_col = _value_column(schema)
    tag_names = list(schema.tag_names)

    for label, _, _ in pq.matchers:
        if label not in tag_names:
            raise PromQLError(f"unknown label {label!r} on metric {pq.metric!r}")
    # offset: evaluate a window shifted into the past, then stamp results
    # back at the requested times (prom's `offset` modifier).
    start_ms -= pq.offset_ms
    end_ms -= pq.offset_ms
    # Equality matchers push into the scan; regex matchers post-filter the
    # (small) series set host-side.
    push_matchers = [m for m in pq.matchers if m[1] in ("=", "!=")]
    regex_matchers = [m for m in pq.matchers if m[1] in ("=~", "!~")]
    # Stage 1 (SQL, device kernels): per-SERIES temporal aggregation per
    # step bucket — always at full tag granularity, exactly prom's model.
    # Stage 2 (host, tiny): cross-series combine onto the by-labels.
    if pq.by_labels is not None:
        out_labels = list(pq.by_labels)
    elif pq.agg is not None:
        out_labels = []  # bare sum(...)/avg(...) collapses every label
    else:
        out_labels = tag_names
    for lbl in out_labels:
        if lbl not in tag_names:
            raise PromQLError(f"unknown grouping label {lbl!r}")
    group_labels = tag_names  # stage-1 grouping

    # Inner temporal aggregation per step bucket.
    func = pq.func
    agg = pq.agg
    if func == "min_over_time":
        sel = f"min({_q(value_col)}) AS v"
    elif func == "max_over_time":
        sel = f"max({_q(value_col)}) AS v"
    else:  # raw selector / avg_over_time: average within the bucket
        sel = f"avg({_q(value_col)}) AS v"

    where = [f"{_q(schema.timestamp_name)} >= {start_ms}",
             f"{_q(schema.timestamp_name)} <= {end_ms}"]
    for label, op, val in push_matchers:
        sval = str(val).replace("'", "''")  # keep in sync w/ sql_str_literal
        where.append(f"{_q(label)} {'=' if op == '=' else '!='} '{sval}'")

    if func in ("rate", "increase"):
        # Counter semantics need consecutive samples (reset detection) —
        # scan raw rows and fold host-side (samples per window are small
        # next to the table; the fused path keeps serving the rest).
        per_series = _counter_series(
            conn, pq, where, schema, value_col, group_labels, step_ms, func
        )
    else:
        keys = [f"time_bucket({_q(schema.timestamp_name)}, '{step_ms}ms')"] + [
            _q(l) for l in group_labels
        ]
        label_sel = ", ".join(_q(l) for l in group_labels)
        sql = (
            f"SELECT {keys[0]} AS bucket"
            + (f", {label_sel}" if group_labels else "")
            + f", {sel} FROM {_q(pq.metric)} WHERE {' AND '.join(where)} "
            + f"GROUP BY {', '.join(keys)}"
        )
        rows = conn.execute(sql).to_pylist()

        # Stage 1 results: per-series value per bucket.
        per_series = {}
        for r in rows:
            key = tuple((l, r[l]) for l in group_labels)
            per_series.setdefault(key, {})[r["bucket"]] = r["v"]

    if regex_matchers:
        per_series = {
            key: pts
            for key, pts in per_series.items()
            if _regex_match(dict(key), regex_matchers)
        }

    # Stage 2: combine series sharing the same by-label subset.
    if agg is None and pq.by_labels is None:
        combined = per_series
    else:
        combined = {}
        bucketed: dict[tuple, dict[int, list[float]]] = {}
        for key, points in per_series.items():
            sub = tuple((l, v) for l, v in key if l in out_labels)
            dst = bucketed.setdefault(sub, {})
            for b, v in points.items():
                dst.setdefault(b, []).append(v)
        fn = {
            None: lambda vs: sum(vs) / len(vs),  # bare by-less func: avg
            "sum": sum,
            "avg": lambda vs: sum(vs) / len(vs),
            "min": min,
            "max": max,
            "count": len,
        }[agg]
        for sub, buckets in bucketed.items():
            combined[sub] = {b: fn(vs) for b, vs in buckets.items()}

    if pq.offset_ms:
        # offset stamps the shifted window back at the requested times
        combined = {
            key: {b + pq.offset_ms: v for b, v in points.items()}
            for key, points in combined.items()
        }
    return combined


def _at_series(
    conn, pq: PromQuery, start_ms: int, end_ms: int, step_ms: int
) -> dict[tuple, dict[int, float]]:
    """``metric @ t``: the value is pinned at ``t`` — one evaluation
    there, replicated across every requested step (prom's @ modifier
    semantics: the same sample answers every step)."""
    import dataclasses

    fixed = dataclasses.replace(pq, at_ms=None, offset_ms=0)
    at = pq.at_ms - pq.offset_ms  # offset still shifts the pinned time
    window = pq.range_ms or DEFAULT_LOOKBACK_MS
    inner_step = window if pq.func is not None else min(window, 60_000)
    pts = _range_series(conn, fixed, at - window, at, inner_step)
    # the SAME floor-aligned grid _range_series derives from data
    # ((ts//step)*step): a ceil-aligned grid would miss the other side's
    # first bucket in binary expressions when start isn't step-aligned
    first = (start_ms // step_ms) * step_ms
    buckets = list(range(first, end_ms + 1, step_ms))
    out = {}
    for key, series in pts.items():
        if not series:
            continue
        v = series[max(series)]  # latest resolvable value at the pin
        out[key] = {b: v for b in buckets}
    return out


def _regex_match(labels: dict, matchers: list[tuple[str, str, str]]) -> bool:
    """Prom regex matchers are fully anchored."""
    for label, op, pattern in matchers:
        current = str(labels.get(label) or "")  # NULL tag == absent label
        hit = re.fullmatch(pattern, current) is not None
        if op == "=~" and not hit:
            return False
        if op == "!~" and hit:
            return False
    return True


def _counter_series(
    conn, pq: PromQuery, where: list, schema, value_col: str,
    group_labels: list, step_ms: int, func: str,
) -> dict:
    """Reset-aware rate/increase: fold raw samples per series.

    Prom counters only move up; a drop means the process restarted and
    the counter began again near zero. increase = Σ over consecutive
    in-bucket samples of (vᵢ - vᵢ₋₁), with a reset contributing vᵢ (the
    counter re-accumulated from 0). rate = increase / step_seconds —
    min/max-based deltas would silently UNDERCOUNT across resets.
    """
    label_sel = ", ".join(_q(l) for l in group_labels)
    sql = (
        f"SELECT {label_sel + ', ' if group_labels else ''}"
        f"{_q(schema.timestamp_name)} AS __ts, {_q(value_col)} AS __v "
        f"FROM {_q(pq.metric)} WHERE {' AND '.join(where)}"
    )
    rows = conn.execute(sql).to_pylist()
    samples: dict[tuple, list] = {}
    for r in rows:
        key = tuple((l, r[l]) for l in group_labels)
        samples.setdefault(key, []).append((r["__ts"], r["__v"]))
    out: dict[tuple, dict[int, float]] = {}
    for key, pts in samples.items():
        pts.sort()
        buckets: dict[int, float] = {}
        prev_v = None
        for ts, v in pts:
            if prev_v is not None:
                delta = v - prev_v
                if delta < 0:
                    delta = v  # counter reset: it restarted from ~0
                # every consecutive-sample delta counts ONCE, attributed
                # to the later sample's bucket — a delta straddling a
                # bucket boundary must not vanish (scrape intervals
                # rarely align with steps). A single-sample bucket emits
                # no point, like prom (two samples make an increase).
                b = (ts // step_ms) * step_ms
                buckets[b] = buckets.get(b, 0.0) + delta
            prev_v = v
        if func == "rate":
            buckets = {b: d / (step_ms / 1000.0) for b, d in buckets.items()}
        out[key] = buckets
    return out


# ---- binary expressions --------------------------------------------------


def _apply_op(op: str, a: float, b: float) -> float:
    import math

    try:
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            if b == 0:
                return math.nan  # prom: x % 0 -> NaN (fmod would raise)
            return math.fmod(a, b)
    except ZeroDivisionError:
        # prom arithmetic: x/0 -> ±Inf, 0/0 -> NaN (never an error)
        if a > 0:
            return math.inf
        if a < 0:
            return -math.inf
        return math.nan
    raise PromQLError(f"unsupported operator {op!r}")


def _eval_series(conn, node: PromExpr, start_ms: int, end_ms: int, step_ms: int):
    """-> ('scalar', float) or ('vector', {key: {bucket: value}})."""
    if isinstance(node, PromScalar):
        return "scalar", node.value
    if isinstance(node, PromQuery):
        return "vector", _range_series(conn, node, start_ms, end_ms, step_ms)
    lk, lv = _eval_series(conn, node.lhs, start_ms, end_ms, step_ms)
    rk, rv = _eval_series(conn, node.rhs, start_ms, end_ms, step_ms)
    op = node.op
    if lk == "scalar" and rk == "scalar":
        return "scalar", _apply_op(op, lv, rv)
    if rk == "scalar":
        return "vector", {
            key: {b: _apply_op(op, v, rv) for b, v in pts.items()}
            for key, pts in lv.items()
        }
    if lk == "scalar":
        return "vector", {
            key: {b: _apply_op(op, lv, v) for b, v in pts.items()}
            for key, pts in rv.items()
        }
    # vector/vector: one-to-one on identical label sets; samples without
    # a partner (either side) drop out, matching prom's default matching
    out: dict[tuple, dict[int, float]] = {}
    for key, lpts in lv.items():
        rpts = rv.get(key)
        if rpts is None:
            continue
        pts = {
            b: _apply_op(op, v, rpts[b]) for b, v in lpts.items() if b in rpts
        }
        if pts:
            out[key] = pts
    return "vector", out


def leaf_metrics(node: PromExpr) -> list[str]:
    """Metric names referenced by an expression, left to right."""
    if isinstance(node, PromQuery):
        return [node.metric]
    if isinstance(node, PromBin):
        return leaf_metrics(node.lhs) + leaf_metrics(node.rhs)
    return []


def evaluate_expr_range(
    conn, node: PromExpr, start_ms: int, end_ms: int, step_ms: int
) -> list[dict]:
    """Range-evaluate any expression -> prom 'matrix'. Leaf queries keep
    their metric name; arithmetic results drop __name__ (like prom)."""
    if isinstance(node, PromQuery):
        return evaluate_range(conn, node, start_ms, end_ms, step_ms)
    kind, val = _eval_series(conn, node, start_ms, end_ms, step_ms)
    if kind == "scalar":
        # a constant series sampled at each aligned step
        first = (start_ms // step_ms) * step_ms
        if first < start_ms:
            first += step_ms
        buckets = list(range(first, end_ms + 1, step_ms))
        return [
            {
                "metric": {},
                "values": [[b / 1000.0, repr(float(val))] for b in buckets],
            }
        ]
    out = []
    for key, points in sorted(val.items()):
        out.append(
            {
                "metric": {l: v for l, v in key},
                "values": [
                    [b / 1000.0, repr(float(points[b]))] for b in sorted(points)
                ],
            }
        )
    return out


def _instant_value(conn, node: PromExpr, time_ms: int):
    """-> ('scalar', float) or ('vector', {label_key: float}).

    Every metric leaf evaluates with ITS OWN instant semantics (its own
    range window; rate folds its whole range, raw selectors take the
    latest sample) — mixing rate(x[4m]) with a raw selector never shrinks
    the rate's window. Keys exclude __name__, matching prom's one-to-one
    rule that arithmetic ignores the metric name."""
    if isinstance(node, PromScalar):
        return "scalar", node.value
    if isinstance(node, PromQuery):
        vec = {}
        for s in evaluate_instant(conn, node, time_ms):
            key = tuple(
                sorted((k, v) for k, v in s["metric"].items() if k != "__name__")
            )
            vec[key] = float(s["value"][1])
        return "vector", vec
    lk, lv = _instant_value(conn, node.lhs, time_ms)
    rk, rv = _instant_value(conn, node.rhs, time_ms)
    op = node.op
    if lk == "scalar" and rk == "scalar":
        return "scalar", _apply_op(op, lv, rv)
    if rk == "scalar":
        return "vector", {k: _apply_op(op, v, rv) for k, v in lv.items()}
    if lk == "scalar":
        return "vector", {k: _apply_op(op, lv, v) for k, v in rv.items()}
    return "vector", {
        k: _apply_op(op, v, rv[k]) for k, v in lv.items() if k in rv
    }


def evaluate_expr_instant(conn, node: PromExpr, time_ms: int) -> list[dict]:
    """Instant-evaluate any expression -> prom 'vector'."""
    if isinstance(node, PromQuery):
        return evaluate_instant(conn, node, time_ms)
    kind, val = _instant_value(conn, node, time_ms)
    if kind == "scalar":
        return [{"metric": {}, "value": [time_ms / 1000.0, repr(float(val))]}]
    return [
        {"metric": dict(key), "value": [time_ms / 1000.0, repr(float(v))]}
        for key, v in sorted(val.items())
    ]


DEFAULT_LOOKBACK_MS = 5 * 60_000  # prom's 5m instant lookback


def evaluate_instant(conn, pq: PromQuery, time_ms: int) -> list[dict]:
    """-> prom 'vector': latest resolvable value per series in the lookback
    (steps at scrape-ish resolution so 'latest' means latest, not a
    whole-window average)."""
    window = pq.range_ms or DEFAULT_LOOKBACK_MS
    # Any range function aggregates over its WHOLE window; only a raw
    # selector / cross-series agg walks in scrape-resolution steps to find
    # the latest sample.
    step = window if pq.func is not None else min(window, 60_000)
    matrix = evaluate_range(conn, pq, time_ms - window, time_ms, step)
    out = []
    for series in matrix:
        if not series["values"]:
            continue
        ts, val = series["values"][-1]
        out.append({"metric": series["metric"], "value": [time_ms / 1000.0, val]})
    return out
