"""OpenTSDB /api/put ingestion (ref: proxy/src/opentsdb/mod.rs:50-108).

Accepts the OpenTSDB JSON put format — one datapoint or an array:

    {"metric": "sys.cpu.user", "timestamp": 1356998400, "value": 42.5,
     "tags": {"host": "web01", "dc": "lga"}}

Seconds vs milliseconds timestamps are disambiguated by magnitude exactly
like OpenTSDB (values < 10^12 are seconds). Each metric maps to a table
(auto-created) with the tags as TAG columns and a single ``value`` field.
"""

from __future__ import annotations

from typing import Any

from ..catalog import Catalog
from ..common_types.row_group import RowGroup
from .auto_create import ensure_table

TIME_COLUMN = "timestamp"
VALUE_COLUMN = "value"


class OpenTsdbError(ValueError):
    pass


def _normalize_ts(ts) -> int:
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        raise OpenTsdbError(f"bad timestamp: {ts!r}")
    ts = int(ts)
    return ts * 1000 if abs(ts) < 10**12 else ts


def parse_put(body: Any) -> list[dict]:
    """Validate the decoded JSON body -> list of datapoint dicts."""
    points = body if isinstance(body, list) else [body]
    out = []
    for i, p in enumerate(points):
        if not isinstance(p, dict):
            raise OpenTsdbError(f"datapoint {i}: not an object")
        metric = p.get("metric")
        if not isinstance(metric, str) or not metric:
            raise OpenTsdbError(f"datapoint {i}: missing metric")
        value = p.get("value")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise OpenTsdbError(f"datapoint {i}: missing numeric value")
        tags = p.get("tags", {})
        if not isinstance(tags, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in tags.items()
        ):
            raise OpenTsdbError(f"datapoint {i}: tags must be string->string")
        reserved = {TIME_COLUMN, VALUE_COLUMN} & set(tags)
        if reserved:
            raise OpenTsdbError(
                f"datapoint {i}: tag name(s) {sorted(reserved)} are reserved"
            )
        out.append(
            {
                "metric": metric,
                "timestamp": _normalize_ts(p.get("timestamp")),
                "value": float(value),
                "tags": tags,
            }
        )
    return out


def write_points(catalog: Catalog, points: list[dict]) -> int:
    by_metric: dict[str, list[dict]] = {}
    for p in points:
        by_metric.setdefault(p["metric"], []).append(p)
    written = 0
    for metric, pts in by_metric.items():
        tag_names = sorted({k for p in pts for k in p["tags"]})
        table = ensure_table(
            catalog, metric, tag_names, {VALUE_COLUMN: 1.0}, TIME_COLUMN
        )
        rows = []
        for p in pts:
            row: dict[str, object] = {
                TIME_COLUMN: p["timestamp"],
                VALUE_COLUMN: p["value"],
            }
            for t in tag_names:
                row[t] = p["tags"].get(t, "")
            rows.append(row)
        table.write(RowGroup.from_rows(table.schema, rows))
        written += len(rows)
    return written
