"""OpenTSDB /api/put ingestion (ref: proxy/src/opentsdb/mod.rs:50-108).

Accepts the OpenTSDB JSON put format — one datapoint or an array:

    {"metric": "sys.cpu.user", "timestamp": 1356998400, "value": 42.5,
     "tags": {"host": "web01", "dc": "lga"}}

Seconds vs milliseconds timestamps are disambiguated by magnitude exactly
like OpenTSDB (values < 10^12 are seconds). Each metric maps to a table
(auto-created) with the tags as TAG columns and a single ``value`` field.
"""

from __future__ import annotations

from typing import Any

from ..catalog import Catalog
from ..common_types.row_group import RowGroup
from .auto_create import ensure_table
from .promql import sql_str_literal

TIME_COLUMN = "timestamp"
VALUE_COLUMN = "value"


class OpenTsdbError(ValueError):
    pass


def _normalize_ts(ts) -> int:
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        raise OpenTsdbError(f"bad timestamp: {ts!r}")
    ts = int(ts)
    return ts * 1000 if abs(ts) < 10**12 else ts


def parse_put(body: Any) -> list[dict]:
    """Validate the decoded JSON body -> list of datapoint dicts."""
    points = body if isinstance(body, list) else [body]
    out = []
    for i, p in enumerate(points):
        if not isinstance(p, dict):
            raise OpenTsdbError(f"datapoint {i}: not an object")
        metric = p.get("metric")
        if not isinstance(metric, str) or not metric:
            raise OpenTsdbError(f"datapoint {i}: missing metric")
        value = p.get("value")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise OpenTsdbError(f"datapoint {i}: missing numeric value")
        tags = p.get("tags", {})
        if not isinstance(tags, dict) or not all(
            isinstance(k, str) and isinstance(v, str) for k, v in tags.items()
        ):
            raise OpenTsdbError(f"datapoint {i}: tags must be string->string")
        reserved = {TIME_COLUMN, VALUE_COLUMN} & set(tags)
        if reserved:
            raise OpenTsdbError(
                f"datapoint {i}: tag name(s) {sorted(reserved)} are reserved"
            )
        out.append(
            {
                "metric": metric,
                "timestamp": _normalize_ts(p.get("timestamp")),
                "value": float(value),
                "tags": tags,
            }
        )
    return out


def write_points(catalog: Catalog, points: list[dict]) -> int:
    by_metric: dict[str, list[dict]] = {}
    for p in points:
        by_metric.setdefault(p["metric"], []).append(p)
    written = 0
    for metric, pts in by_metric.items():
        tag_names = sorted({k for p in pts for k in p["tags"]})
        table = ensure_table(
            catalog, metric, tag_names, {VALUE_COLUMN: 1.0}, TIME_COLUMN
        )
        rows = []
        for p in pts:
            row: dict[str, object] = {
                TIME_COLUMN: p["timestamp"],
                VALUE_COLUMN: p["value"],
            }
            for t in tag_names:
                row[t] = p["tags"].get(t, "")
            rows.append(row)
        table.write(RowGroup.from_rows(table.schema, rows))
        written += len(rows)
    return written


def evaluate_query(conn, body: Any) -> list[dict]:
    """OpenTSDB /api/query (ref: the reference's opentsdb query planner,
    query_frontend/src/opentsdb/) — POST body:

        {"start": s, "end": e, "queries": [{"metric": m,
         "aggregator": "sum|avg|max|min|count", "tags": {k: v},
         "downsample": "60s-avg"}]}

    Returns the classic response: one object per (sub)query with ``dps``
    mapping epoch-seconds -> value, aggregated across matching series.
    """
    import numpy as np

    from ..engine.options import parse_duration_ms

    if not isinstance(body, dict) or "queries" not in body:
        raise OpenTsdbError("body must be {'start':..,'queries':[...]}")
    start_ms = _normalize_ts(body.get("start", 0))
    end_ms = _normalize_ts(body["end"]) if body.get("end") is not None else None
    out = []
    for q in body["queries"]:
        metric = q.get("metric")
        if not isinstance(metric, str):
            raise OpenTsdbError("query missing 'metric'")
        agg = str(q.get("aggregator", "sum")).lower()
        if agg == "mean":
            agg = "avg"
        if agg not in ("sum", "avg", "min", "max", "count"):
            raise OpenTsdbError(f"unsupported aggregator {agg!r}")
        table = conn.catalog.open(metric)
        if table is None:
            out.append({"metric": metric, "tags": {}, "aggregateTags": [], "dps": {}})
            continue
        schema = table.schema
        tags = q.get("tags") or {}
        down = q.get("downsample")
        if down:
            span, _, dfunc = str(down).partition("-")
            width = parse_duration_ms(span)
            dfunc = dfunc or "avg"
        else:
            # dps keys are epoch SECONDS: without an explicit downsample,
            # ms-resolution data still folds per second with the query's
            # aggregator (else same-second buckets would overwrite).
            width, dfunc = 1000, agg

        conds = " AND ".join(
            f"`{k}` = {sql_str_literal(v)}" for k, v in tags.items()
        )
        time_conds = [f"`{schema.timestamp_name}` >= {start_ms}"]
        if end_ms is not None:
            time_conds.append(f"`{schema.timestamp_name}` <= {end_ms}")
        where = " AND ".join(time_conds + ([conds] if conds else []))
        sql = f"SELECT * FROM `{metric}` WHERE {where}"
        rows = conn.execute(sql).to_pylist()
        ts_name = schema.timestamp_name
        from .promql import PromQLError, _value_column

        try:
            value_col = _value_column(schema)
        except PromQLError as e:
            raise OpenTsdbError(str(e))
        ts = np.array([r[ts_name] for r in rows], dtype=np.int64)
        vals = np.array([r[value_col] for r in rows], dtype=np.float64)
        if schema.tsid_index is not None and rows:
            series = np.array(
                [r[schema.columns[schema.tsid_index].name] for r in rows],
                dtype=np.uint64,
            )
        else:
            series = np.zeros(len(rows), dtype=np.uint64)
        # Two-level semantics (opentsdb): downsample WITHIN each series'
        # time buckets first, then the aggregator merges ACROSS series.
        bucket = (ts // width) * width if width else ts

        def _apply(fn: str, sel: np.ndarray) -> float:
            if fn == "avg":
                return float(sel.mean())
            if fn == "sum":
                return float(sel.sum())
            if fn == "min":
                return float(sel.min())
            if fn == "max":
                return float(sel.max())
            return float(len(sel))  # count

        per_series: dict[int, dict[int, float]] = {}
        for s in np.unique(series):
            smask = series == s
            sb, sv = bucket[smask], vals[smask]
            per_series[int(s)] = {
                int(b): _apply(dfunc or "avg", sv[sb == b]) for b in np.unique(sb)
            }
        dps: dict[str, float] = {}
        all_buckets = sorted({b for d in per_series.values() for b in d})
        for b in all_buckets:
            xs = np.array([d[b] for d in per_series.values() if b in d])
            dps[str(b // 1000)] = _apply(agg, xs)
        tag_names = [c.name for c in schema.columns if c.is_tag]
        out.append(
            {
                "metric": metric,
                "tags": {k: str(v) for k, v in tags.items()},
                "aggregateTags": [t for t in tag_names if t not in tags],
                "dps": dps,
            }
        )
    return out


def suggest(conn, kind: str, q: str = "", max_results: int = 25) -> list[str]:
    """/api/suggest (ref: the OpenTSDB autocomplete API the reference's
    opentsdb shim targets): prefix-complete metric names, tag keys, or
    tag values across every metric table."""
    catalog = conn.catalog
    names: set[str] = set()
    if kind == "metrics":
        names.update(catalog.table_names())
    elif kind == "tagk":
        for t in catalog.table_names():
            table = catalog.open(t)
            if table is not None:
                names.update(table.schema.tag_names)
    elif kind == "tagv":
        # prefix pushed into the scan as a range ([q, q+1)) so LIMIT
        # never truncates matching values hiding past unrelated ones
        where = ""
        if q:
            hi = q[:-1] + chr(ord(q[-1]) + 1)
            where = (
                f" WHERE {{tag}} >= {sql_str_literal(q)}"
                f" AND {{tag}} < {sql_str_literal(hi)}"
            )
        for t in catalog.table_names():
            table = catalog.open(t)
            if table is None:
                continue
            for tag in table.schema.tag_names:
                rows = conn.execute(
                    f"SELECT DISTINCT `{tag}` FROM `{t}`"
                    + where.format(tag=f"`{tag}`")
                    + f" LIMIT {max_results}"
                ).to_pylist()
                names.update(
                    str(r[tag]) for r in rows if r[tag] is not None
                )
    else:
        raise OpenTsdbError(f"unknown suggest type {kind!r}")
    hits = sorted(n for n in names if n.startswith(q))
    return hits[:max_results]


def lookup(conn, metric: str, tag_filters: list[dict], limit: int = 25) -> dict:
    """/api/search/lookup: enumerate time series (tag combinations) of a
    metric, optionally filtered by tag=value pairs ('*' matches any)."""
    table = conn.catalog.open(metric)
    if table is None:
        raise OpenTsdbError(f"unknown metric {metric!r}")
    tag_names = list(table.schema.tag_names)
    for f in tag_filters:
        if f.get("key") not in tag_names:
            raise OpenTsdbError(f"unknown tag key {f.get('key')!r} on {metric!r}")
    if not tag_names:
        # a tag-less metric is exactly one series
        return {
            "type": "LOOKUP",
            "metric": metric,
            "limit": limit,
            "totalResults": 1,
            "results": [{"metric": metric, "tags": {}}],
        }
    where = []
    for f in tag_filters:
        key, value = f.get("key"), f.get("value")
        if value and value != "*":
            where.append(f"`{key}` = {sql_str_literal(value)}")
    sql = (
        "SELECT DISTINCT "
        + ", ".join(f"`{t}`" for t in tag_names)
        + f" FROM `{metric}`"
    )
    if where:
        sql += f" WHERE {' AND '.join(where)}"
    rows = conn.execute(sql).to_pylist()
    results = [
        {"metric": metric, "tags": {k: r[k] for k in tag_names if r[k] is not None}}
        for r in rows[:limit]
    ]
    return {
        "type": "LOOKUP",
        "metric": metric,
        "limit": limit,
        "totalResults": len(rows),
        "results": results,
    }
