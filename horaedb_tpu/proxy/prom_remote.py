"""Prometheus remote read (ref: src/proxy/src/grpc/prom_query.rs and the
reference's remote-read support — Prometheus federates long-term storage
through this protocol).

Wire protocol: HTTP POST, snappy-block-compressed protobuf. The messages
used (prompb/remote.proto + types.proto, stable public schema):

    ReadRequest  { repeated Query queries = 1; }
    Query        { int64 start_timestamp_ms = 1; int64 end_timestamp_ms = 2;
                   repeated LabelMatcher matchers = 3; }
    LabelMatcher { enum Type {EQ=0; NEQ=1; RE=2; NRE=3;}
                   Type type = 1; string name = 2; string value = 3; }
    ReadResponse { repeated QueryResult results = 1; }
    QueryResult  { repeated TimeSeries timeseries = 1; }
    TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
    Label        { string name = 1; string value = 2; }
    Sample       { double value = 1; int64 timestamp = 2; }

The tiny wire codec below implements exactly these fields — no protoc
needed for a fixed, frozen schema.
"""

from __future__ import annotations

import re
import struct
from typing import Any

import numpy as np

from ..utils.snappy import SnappyError, compress, decompress


class RemoteReadError(ValueError):
    pass


# ---- protobuf wire primitives --------------------------------------------


def _uvarint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        if i >= len(buf):
            raise RemoteReadError("truncated protobuf varint")
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 63:
            raise RemoteReadError("protobuf varint too long")


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _uvarint(buf, i)
        field, wt = key >> 3, key & 0x07
        if wt == 0:  # varint
            v, i = _uvarint(buf, i)
        elif wt == 1:  # 64-bit
            v = buf[i : i + 8]
            i += 8
        elif wt == 2:  # length-delimited
            ln, i = _uvarint(buf, i)
            v = buf[i : i + ln]
            i += ln
        elif wt == 5:  # 32-bit
            v = buf[i : i + 4]
            i += 4
        else:
            raise RemoteReadError(f"unsupported wire type {wt}")
        yield field, wt, v


def _emit_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _emit_field(field: int, wt: int, payload: bytes) -> bytes:
    head = _emit_varint((field << 3) | wt)
    if wt == 2:
        return head + _emit_varint(len(payload)) + payload
    return head + payload


def _zigzag_int64(v: int) -> int:
    # plain int64 varints encode negatives as 10-byte two's complement
    return v & 0xFFFFFFFFFFFFFFFF


# ---- request decode -------------------------------------------------------


def decode_read_request(raw: bytes) -> list[dict]:
    try:
        buf = decompress(raw)
    except SnappyError as e:
        raise RemoteReadError(f"bad snappy body: {e}")
    queries = []
    for field, wt, v in _fields(buf):
        if field == 1 and wt == 2:
            queries.append(_decode_query(v))
    return queries


def _decode_query(buf: bytes) -> dict:
    q = {"start_ms": 0, "end_ms": 0, "matchers": []}
    for field, wt, v in _fields(buf):
        if field == 1 and wt == 0:
            q["start_ms"] = _signed(v)
        elif field == 2 and wt == 0:
            q["end_ms"] = _signed(v)
        elif field == 3 and wt == 2:
            q["matchers"].append(_decode_matcher(v))
    return q


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


_MATCHER_OPS = {0: "=", 1: "!=", 2: "=~", 3: "!~"}


def _decode_matcher(buf: bytes) -> tuple[str, str, str]:
    op_code = 0
    name = value = ""
    for field, wt, v in _fields(buf):
        if field == 1 and wt == 0:
            op_code = v
        elif field == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif field == 3 and wt == 2:
            value = v.decode("utf-8", "replace")
    return (_MATCHER_OPS.get(op_code, "="), name, value)


# ---- response encode ------------------------------------------------------


def encode_read_response(results: list[list[dict]]) -> bytes:
    body = b"".join(
        _emit_field(1, 2, _encode_query_result(ts_list)) for ts_list in results
    )
    return compress(body)


def _encode_query_result(ts_list: list[dict]) -> bytes:
    return b"".join(_emit_field(1, 2, _encode_timeseries(ts)) for ts in ts_list)


def _encode_timeseries(ts: dict) -> bytes:
    out = bytearray()
    for name, value in sorted(ts["labels"].items()):
        label = _emit_field(1, 2, name.encode()) + _emit_field(2, 2, value.encode())
        out += _emit_field(1, 2, label)
    for t_ms, val in ts["samples"]:
        sample = _emit_field(1, 1, struct.pack("<d", float(val))) + _emit_field(
            2, 0, _emit_varint(_zigzag_int64(int(t_ms)))
        )
        out += _emit_field(2, 2, sample)
    return bytes(out)


# ---- evaluation -----------------------------------------------------------


def handle_remote_read(conn, raw: bytes) -> bytes:
    """ReadRequest bytes -> ReadResponse bytes (both snappy-framed)."""
    queries = decode_read_request(raw)
    results = []
    for q in queries:
        results.append(_run_query(conn, q))
    return encode_read_response(results)


def _run_query(conn, q: dict) -> list[dict]:
    from .promql import _value_column

    metric = None
    tag_eq: list[tuple[str, str]] = []
    post: list[tuple[str, str, str]] = []
    for op, name, value in q["matchers"]:
        if name == "__name__" and op == "=":
            metric = value
        elif op == "=":
            tag_eq.append((name, value))
        else:
            post.append((op, name, value))
    if metric is None:
        raise RemoteReadError("only __name__ equality selection is supported")
    table = conn.catalog.open(metric)
    if table is None:
        return []
    schema = table.schema
    ts_name = schema.timestamp_name
    value_col = _value_column(schema)
    conds = [f"`{ts_name}` >= {q['start_ms']}", f"`{ts_name}` <= {q['end_ms']}"]
    for name, value in tag_eq:
        if schema.has_column(name):
            from .promql import sql_str_literal

            conds.append(f"`{name}` = {sql_str_literal(value)}")
        elif value != "":
            # Prometheus semantics: an equality matcher on a label the
            # series does not carry matches only the EMPTY value — a
            # non-empty match against a missing label matches nothing.
            return []
    rows = conn.execute(
        f"SELECT * FROM `{metric}` WHERE {' AND '.join(conds)}"
    ).to_pylist()

    tag_names = [c.name for c in schema.columns if c.is_tag]
    series: dict[tuple, dict] = {}
    for r in rows:
        labels = {t: str(r.get(t)) for t in tag_names if r.get(t) is not None}
        if not _post_match(labels, post):
            continue
        key = tuple(sorted(labels.items()))
        s = series.setdefault(
            key, {"labels": {"__name__": metric, **labels}, "samples": []}
        )
        s["samples"].append((r[ts_name], r[value_col]))
    for s in series.values():
        s["samples"].sort(key=lambda kv: kv[0])
    return [series[k] for k in sorted(series)]


def _post_match(labels: dict, post: list[tuple[str, str, str]]) -> bool:
    for op, name, value in post:
        current = labels.get(name, "")
        if op == "!=" and current == value:
            return False
        if op == "=~" and re.fullmatch(value, current) is None:
            return False
        if op == "!~" and re.fullmatch(value, current) is not None:
            return False
    return True
