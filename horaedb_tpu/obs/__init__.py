"""Device-plane observability (obs/device): HBM occupancy, kernel
timing, and compile accounting as first-class observables."""
