"""Decision plane — every adaptive loop journals its choice, its
features-at-decision-time, and the realized outcome; the database grades
its own predictions.

The engine runs five feedback loops (docs/OBSERVABILITY.md §Decision
plane): the kernel router's per-shape impl EWMA, admission's cost-EWMA
`est_cost_s`, the elastic controller's scale/move/hold rounds, the scan
cache's layout auto-tuner, and the deadline-budget shed check. Each one
predicts something, acts on it, and — before this module — discarded the
prediction, so there was no way to tell a well-calibrated loop from a
guessing one, and nothing for ROADMAP item 4's learned control plane to
learn from. "Fine-tune the data structure to the observed mix"
(PAPERS.md 2112.13099) applies to *measurement* first: you cannot tune
a loop whose error you never computed.

Two verbs, journal discipline identical to utils/events.py:

    ``record_decision(loop, key, choice, features, predicted) -> id``
    ``resolve_decision(id, actual, outcome, loop=...)``

Entries live in a bounded ring (``[observability] decision_ring`` knob,
drop-accounted — an evicted UNRESOLVED entry is counted expired, never
silently lost), and every loop's accounting reconciles exactly:

    issued == resolved + expired + unresolved_live

A resolve whose id already rolled off is a counted **miss**, not a
KeyError; an unresolved decision past ``HORAEDB_DECISION_EXPIRE_MS`` is
a counted **expiry** — a leaked resolve is an observable, not a silent
gap. Calibration is graded per loop with the SLO plane's incremental-
window discipline (slo/evaluator._Window): signed/abs relative-error
EWMA plus fast/slow sliding windows, O(1) amortized, never a rescan.
Sustained abs error over threshold in BOTH windows emits a typed
``loop_miscalibrated`` event; resolutions sample into typed
``decision_resolved`` events (1-in-N per loop so the high-rate loops
cannot flood the event ring).

Surfaces: ``system.public.decisions`` + ``system.public.calibration``
(all three wires), ``/debug/decisions``, ``horaectl decisions``, an
EXPLAIN ANALYZE ``Decision:`` line, and the registry-linted
``horaedb_decision_*`` / ``horaedb_calibration_*`` families.
``HORAEDB_DECISIONS=0`` turns the plane off (record returns 0,
resolve(0) is a no-op); the ``BENCH_CONFIG=decisions`` gate pins
journal-on within 2% of off on the flood shape.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Optional, Union

from ..utils.env import env_float
from ..utils.metrics import REGISTRY

# The instrumented loops — the label set of every horaedb_decision_*
# / horaedb_calibration_* family (eagerly registered, lint-pinned like
# DEVICE_KERNEL_KINDS).
DECISION_LOOPS = (
    "kernel_router",  # per-(plan shape, n_seg bucket) segment-impl EWMA
    "admission",      # est_cost_s admit/shed classification
    "elastic",        # scale/move/hold control rounds
    "layout_tuner",   # scan-cache per-column layouts (bf16/dict/delta),
                      # absorbing the former dtype_tuner promotion loop
    "deadline",       # reason=deadline_budget sheds (provably doomed?)
    "livewindow",     # live-window state promotions (predicted vs realized hits)
)

DECISION_METRIC_FAMILIES = (
    "horaedb_decision_recorded_total",
    "horaedb_decision_resolved_total",
    "horaedb_decision_expired_total",
    "horaedb_decision_miss_total",
    "horaedb_decision_dropped_total",
)

CALIBRATION_METRIC_FAMILIES = (
    "horaedb_calibration_error_ratio",
    "horaedb_calibration_samples_total",
    "horaedb_calibration_miscalibrated_total",
)

CALIBRATION_WINDOWS = ("fast", "slow", "ewma")
CALIBRATION_ERROR_KINDS = ("signed", "abs")

_M_RECORDED = {
    loop: REGISTRY.counter(
        "horaedb_decision_recorded_total",
        "adaptive-loop decisions journaled, by loop",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}
_M_RESOLVED = {
    loop: REGISTRY.counter(
        "horaedb_decision_resolved_total",
        "journaled decisions whose realized outcome arrived, by loop",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}
_M_EXPIRED = {
    loop: REGISTRY.counter(
        "horaedb_decision_expired_total",
        "decisions that aged out or were evicted unresolved, by loop",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}
_M_MISS = {
    loop: REGISTRY.counter(
        "horaedb_decision_miss_total",
        "resolves whose decision id had already expired or rolled off",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}
_M_DROPPED = REGISTRY.counter(
    "horaedb_decision_dropped_total",
    "journal entries discarded by the bounded ring (oldest-first)",
)
_M_CAL_ERROR = {
    (loop, window, kind): REGISTRY.gauge(
        "horaedb_calibration_error_ratio",
        "relative prediction error ((actual-predicted)/|predicted|), "
        "by loop, window, and error kind",
        labels={"loop": loop, "window": window, "kind": kind},
    )
    for loop in DECISION_LOOPS
    for window in CALIBRATION_WINDOWS
    for kind in CALIBRATION_ERROR_KINDS
}
_M_CAL_SAMPLES = {
    loop: REGISTRY.counter(
        "horaedb_calibration_samples_total",
        "resolved decisions graded into the calibration windows, by loop",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}
_M_CAL_MISCAL = {
    loop: REGISTRY.counter(
        "horaedb_calibration_miscalibrated_total",
        "transitions of a loop into the miscalibrated state",
        labels={"loop": loop},
    )
    for loop in DECISION_LOOPS
}


def decisions_enabled() -> bool:
    """HORAEDB_DECISIONS=0 turns the whole plane off — record returns 0
    and resolve(0) is a no-op (the bench A/B's off arm)."""
    import os

    return os.environ.get("HORAEDB_DECISIONS", "1") not in ("0", "off", "false")


def _expire_ms() -> float:
    """Unresolved decisions older than this are counted expired
    (HORAEDB_DECISION_EXPIRE_MS, default 10 minutes)."""
    return max(0.0, env_float("HORAEDB_DECISION_EXPIRE_MS", 600_000.0))


# decision_resolved events are SAMPLED per loop (1-in-N) — the kernel
# router resolves on every aggregation dispatch and would otherwise own
# the 512-entry event ring; the low-rate loops journal every resolution.
_EVENT_SAMPLE = {
    "kernel_router": 64,
    "admission": 16,
    "elastic": 1,
    "layout_tuner": 1,
    "deadline": 1,
    "livewindow": 1,
}

# miscalibration verdict: both windows' mean |relative error| over the
# threshold, with at least MIN_SAMPLES in the fast window
_MISCAL_THRESHOLD = 0.5
_MISCAL_MIN_SAMPLES = 8


class _ErrWindow:
    """Sliding time window over (signed, abs) relative errors — the SLO
    evaluator's running-sums discipline: push + lazy head eviction, O(1)
    amortized, never a rescan of the deque."""

    __slots__ = ("span_ms", "samples", "signed_sum", "abs_sum")

    def __init__(self, span_ms: float) -> None:
        self.span_ms = float(span_ms)
        self.samples: "deque[tuple[float, float, float]]" = deque()
        self.signed_sum = 0.0
        self.abs_sum = 0.0

    def _evict(self, now_ms: float) -> None:
        cutoff = now_ms - self.span_ms
        q = self.samples
        while q and q[0][0] <= cutoff:
            _, s, a = q.popleft()
            self.signed_sum -= s
            self.abs_sum -= a

    def push(self, now_ms: float, signed: float, abs_err: float) -> None:
        self._evict(now_ms)
        self.samples.append((now_ms, signed, abs_err))
        self.signed_sum += signed
        self.abs_sum += abs_err

    def means(self, now_ms: float) -> tuple[Optional[float], Optional[float], int]:
        """(signed mean, abs mean, n) over the live span; None means when
        empty."""
        self._evict(now_ms)
        n = len(self.samples)
        if n == 0:
            return None, None, 0
        return self.signed_sum / n, self.abs_sum / n, n


class _LoopCalibration:
    """Per-loop grading state: signed/abs EWMA + fast/slow windows +
    the miscalibration state machine."""

    __slots__ = (
        "loop", "alpha", "fast", "slow",
        "ewma_signed", "ewma_abs", "samples", "miscalibrated",
    )

    def __init__(self, loop: str, fast_ms: float, slow_ms: float,
                 alpha: float = 0.3) -> None:
        self.loop = loop
        self.alpha = alpha
        self.fast = _ErrWindow(fast_ms)
        self.slow = _ErrWindow(slow_ms)
        self.ewma_signed: Optional[float] = None
        self.ewma_abs: Optional[float] = None
        self.samples = 0
        self.miscalibrated = False

    def push(self, now_ms: float, signed: float) -> Optional[dict]:
        """Fold one graded resolution in; returns miscalibration-event
        attrs when this sample TRANSITIONS the loop into the state."""
        abs_err = abs(signed)
        self.samples += 1
        a = self.alpha
        self.ewma_signed = (
            signed if self.ewma_signed is None
            else (1 - a) * self.ewma_signed + a * signed
        )
        self.ewma_abs = (
            abs_err if self.ewma_abs is None
            else (1 - a) * self.ewma_abs + a * abs_err
        )
        self.fast.push(now_ms, signed, abs_err)
        self.slow.push(now_ms, signed, abs_err)
        _, fast_abs, fast_n = self.fast.means(now_ms)
        _, slow_abs, _ = self.slow.means(now_ms)
        bad = (
            fast_n >= _MISCAL_MIN_SAMPLES
            and fast_abs is not None and fast_abs > _MISCAL_THRESHOLD
            and slow_abs is not None and slow_abs > _MISCAL_THRESHOLD
        )
        fired = None
        if bad and not self.miscalibrated:
            self.miscalibrated = True
            fired = {
                "loop": self.loop,
                "fast_abs_error": round(fast_abs, 4),
                "slow_abs_error": round(slow_abs, 4),
                "fast_samples": fast_n,
            }
        elif self.miscalibrated and fast_abs is not None and not bad:
            # recover on the fast window clearing (or draining empty)
            self.miscalibrated = False
        return fired

    def snapshot(self, now_ms: float) -> dict:
        fast_signed, fast_abs, fast_n = self.fast.means(now_ms)
        slow_signed, slow_abs, slow_n = self.slow.means(now_ms)
        return {
            "loop": self.loop,
            "samples": self.samples,
            "ewma_signed": self.ewma_signed,
            "ewma_abs": self.ewma_abs,
            "fast_signed": fast_signed,
            "fast_abs": fast_abs,
            "fast_n": fast_n,
            "slow_signed": slow_signed,
            "slow_abs": slow_abs,
            "slow_n": slow_n,
            "miscalibrated": self.miscalibrated,
        }


class DecisionJournal:
    """Bounded ring of decision entries + per-loop calibration. One per
    process, like EVENT_STORE / TRACE_STORE / STATS_STORE.

    Accounting contract (the tenantsim reconciliation gate reads it from
    ``system.public.calibration``): for every loop, at any instant,
    ``issued == resolved + expired + unresolved`` — a decision is always
    in exactly one of those states; late resolves of expired/rolled-off
    ids are counted misses and consume nothing.
    """

    DEFAULT_CAPACITY = 1024

    def __init__(
        self,
        maxlen: int = DEFAULT_CAPACITY,
        fast_window_s: Optional[float] = None,
        slow_window_s: Optional[float] = None,
    ) -> None:
        self._ring: "deque[dict]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._issued = 0
        self.dropped = 0  # ring evictions of RESOLVED/EXPIRED entries
        # id -> live unresolved entry (insertion order == id order, so
        # TTL expiry pops from the front, O(1) amortized)
        self._pending: dict[int, dict] = {}
        # (loop, key) -> ids awaiting a keyed resolve_matching
        self._pending_by_key: dict[tuple, list[int]] = {}
        self._counts = {
            loop: {"issued": 0, "resolved": 0, "expired": 0, "missed": 0}
            for loop in DECISION_LOOPS
        }
        fast_ms = (
            fast_window_s if fast_window_s is not None
            else env_float("HORAEDB_CALIBRATION_FAST_S", 300.0)
        ) * 1000.0
        slow_ms = (
            slow_window_s if slow_window_s is not None
            else env_float("HORAEDB_CALIBRATION_SLOW_S", 3600.0)
        ) * 1000.0
        self._calibration = {
            loop: _LoopCalibration(loop, fast_ms, slow_ms)
            for loop in DECISION_LOOPS
        }
        self._event_counts: dict[str, int] = {}

    # ---- verbs -----------------------------------------------------------

    def record(
        self,
        loop: str,
        key: str,
        choice: str,
        features: Optional[dict] = None,
        predicted: Optional[float] = None,
    ) -> int:
        """Journal one adaptive decision; returns its id (0 when the
        plane is disabled — resolve(0) is a no-op)."""
        if loop not in DECISION_LOOPS:
            raise ValueError(
                f"undeclared decision loop {loop!r}: add it to "
                "horaedb_tpu.obs.decisions.DECISION_LOOPS (and document it)"
            )
        if not decisions_enabled():
            return 0
        from ..utils.tracectx import get_request_id

        now_ms = time.time() * 1000.0
        entry = {
            "timestamp": int(now_ms),
            "loop": loop,
            "key": str(key),
            "choice": str(choice),
            "features": dict(features) if features else {},
            "predicted": None if predicted is None else float(predicted),
            "resolved": False,
            "resolved_at": 0,
            "actual": None,
            "outcome": "",
            "error": None,
            "trace_id": get_request_id(),
        }
        with self._lock:
            self._expire_locked(now_ms)
            did = entry["id"] = self._issued = next(self._seq)
            self._counts[loop]["issued"] += 1
            if len(self._ring) == self._ring.maxlen:
                self._evict_oldest_locked()
            self._ring.append(entry)
            self._pending[did] = entry
            self._pending_by_key.setdefault((loop, entry["key"]), []).append(did)
        _M_RECORDED[loop].inc()
        return did

    def resolve(
        self,
        decision_id: int,
        actual: Optional[float] = None,
        outcome: str = "ok",
        loop: Optional[str] = None,
        calibrate: bool = True,
    ) -> bool:
        """Attach the realized outcome to a journaled decision.

        Returns False (and counts a miss against ``loop``) when the id
        already expired or rolled off the ring — a late resolve is an
        observable, never a KeyError. ``calibrate=False`` resolves
        without grading (compile-tainted samples, failed queries)."""
        if decision_id <= 0:
            return False  # disabled-plane ids resolve to nothing, silently
        now_ms = time.time() * 1000.0
        fired = None
        with self._lock:
            self._expire_locked(now_ms)
            entry = self._pending.pop(decision_id, None)
            if entry is None:
                miss_loop = loop if loop in DECISION_LOOPS else None
                if miss_loop is not None:
                    self._counts[miss_loop]["missed"] += 1
                    counter = _M_MISS[miss_loop]
                else:
                    counter = None
                if counter is not None:
                    counter.inc()
                return False
            self._unindex_locked(entry)
            entry["resolved"] = True
            entry["resolved_at"] = int(now_ms)
            entry["actual"] = None if actual is None else float(actual)
            entry["outcome"] = str(outcome)
            self._counts[entry["loop"]]["resolved"] += 1
            fired = self._grade_locked(entry, now_ms, calibrate)
        _M_RESOLVED[entry["loop"]].inc()
        self._emit_events(entry, fired)
        return True

    def resolve_matching(
        self,
        loop: str,
        key: str,
        actual: Optional[float] = None,
        outcome: Union[str, Callable[[dict], str]] = "ok",
        calibrate: bool = True,
        limit: int = 0,
    ) -> int:
        """Resolve pending decisions of ``(loop, key)`` oldest-first —
        the keyed form for loops whose outcome arrives detached from the
        id (a deadline shed graded by a later same-shape completion, a
        dtype promotion graded at the f32 re-upload). ``outcome`` may be
        a callable receiving the entry (so the caller can grade doomed
        vs premature from the features it recorded). ``limit=0`` means
        all pending matches. Returns how many resolved; zero matches is
        NOT a miss — nothing was issued for this completion."""
        now_ms = time.time() * 1000.0
        resolved: list[tuple[dict, Optional[dict]]] = []
        with self._lock:
            self._expire_locked(now_ms)
            ids = list(self._pending_by_key.get((loop, str(key)), ()))
            if limit > 0:
                ids = ids[:limit]
            for did in ids:
                entry = self._pending.pop(did, None)
                if entry is None:
                    continue
                self._unindex_locked(entry)
                entry["resolved"] = True
                entry["resolved_at"] = int(now_ms)
                entry["actual"] = None if actual is None else float(actual)
                entry["outcome"] = str(
                    outcome(entry) if callable(outcome) else outcome
                )
                self._counts[loop]["resolved"] += 1
                fired = self._grade_locked(entry, now_ms, calibrate)
                resolved.append((entry, fired))
        for entry, fired in resolved:
            _M_RESOLVED[loop].inc()
            self._emit_events(entry, fired)
        return len(resolved)

    # ---- internals -------------------------------------------------------

    def _unindex_locked(self, entry: dict) -> None:
        k = (entry["loop"], entry["key"])
        ids = self._pending_by_key.get(k)
        if ids is not None:
            try:
                ids.remove(entry["id"])
            except ValueError:
                pass
            if not ids:
                self._pending_by_key.pop(k, None)

    def _expire_one_locked(self, entry: dict, now_ms: float) -> None:
        self._pending.pop(entry["id"], None)
        self._unindex_locked(entry)
        entry["resolved"] = False
        entry["outcome"] = "expired"
        entry["resolved_at"] = int(now_ms)
        self._counts[entry["loop"]]["expired"] += 1
        _M_EXPIRED[entry["loop"]].inc()

    def _expire_locked(self, now_ms: float) -> None:
        """Lazily age out unresolved decisions (pending is id-ordered, so
        only the head can be expired — O(1) amortized)."""
        ttl = _expire_ms()
        if ttl <= 0:
            return
        cutoff = now_ms - ttl
        while self._pending:
            first_id = next(iter(self._pending))
            entry = self._pending[first_id]
            if entry["timestamp"] > cutoff:
                break
            self._expire_one_locked(entry, now_ms)

    def _evict_oldest_locked(self) -> None:
        """The ring is full: deque(maxlen) would evict silently; the
        journal must not — an evicted UNRESOLVED entry is accounted
        expired (its resolve, should it ever come, is a counted miss),
        and every eviction ticks the dropped counter."""
        victim = self._ring[0]
        if not victim["resolved"] and victim["id"] in self._pending:
            self._expire_one_locked(victim, time.time() * 1000.0)
        self.dropped += 1
        _M_DROPPED.inc()

    def _grade_locked(self, entry: dict, now_ms: float,
                      calibrate: bool) -> Optional[dict]:
        """Compute the relative error and fold it into the loop's
        calibration; returns loop_miscalibrated attrs on transition."""
        predicted, actual = entry["predicted"], entry["actual"]
        if not calibrate or predicted is None or actual is None:
            return None
        signed = (actual - predicted) / max(abs(predicted), 1e-9)
        entry["error"] = signed
        cal = self._calibration[entry["loop"]]
        fired = cal.push(now_ms, signed)
        _M_CAL_SAMPLES[entry["loop"]].inc()
        self._export_gauges_locked(entry["loop"], now_ms)
        if fired is not None:
            _M_CAL_MISCAL[entry["loop"]].inc()
        return fired

    def _export_gauges_locked(self, loop: str, now_ms: float) -> None:
        cal = self._calibration[loop]
        snap = cal.snapshot(now_ms)
        for window, (signed, abs_err) in (
            ("ewma", (snap["ewma_signed"], snap["ewma_abs"])),
            ("fast", (snap["fast_signed"], snap["fast_abs"])),
            ("slow", (snap["slow_signed"], snap["slow_abs"])),
        ):
            if signed is not None:
                _M_CAL_ERROR[(loop, window, "signed")].set(float(signed))
            if abs_err is not None:
                _M_CAL_ERROR[(loop, window, "abs")].set(float(abs_err))

    def _emit_events(self, entry: dict, fired: Optional[dict]) -> None:
        """Typed journal events, outside the lock (record_event takes the
        event ring's own lock)."""
        from ..utils.events import record_event

        loop = entry["loop"]
        n = self._event_counts.get(loop, 0)
        self._event_counts[loop] = n + 1
        if n % _EVENT_SAMPLE.get(loop, 1) == 0:
            attrs = {
                "loop": loop,
                "decision_key": entry["key"],
                "choice": entry["choice"],
                "outcome": entry["outcome"],
            }
            if entry["predicted"] is not None:
                attrs["predicted"] = round(entry["predicted"], 6)
            if entry["actual"] is not None:
                attrs["actual"] = round(entry["actual"], 6)
            if entry["error"] is not None:
                attrs["error"] = round(entry["error"], 4)
            record_event("decision_resolved", **attrs)
        if fired is not None:
            record_event("loop_miscalibrated", **fired)

    # ---- reads -----------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def resize(self, maxlen: int) -> None:
        """Re-bound the ring ([observability] decision_ring). Shrinking
        discards oldest-first with the same accounting as overflow."""
        maxlen = max(1, int(maxlen))
        with self._lock:
            if maxlen == self._ring.maxlen:
                return
            old = list(self._ring)
            cut = max(0, len(old) - maxlen)
            for victim in old[:cut]:
                if not victim["resolved"] and victim["id"] in self._pending:
                    self._expire_one_locked(victim, time.time() * 1000.0)
                self.dropped += 1
                _M_DROPPED.inc()
            self._ring = deque(old[cut:], maxlen=maxlen)

    def list(
        self,
        loop: Optional[str] = None,
        limit: Optional[int] = None,
        resolved: Optional[bool] = None,
    ) -> list[dict]:
        """Oldest-first snapshot of entry COPIES (entries mutate on
        resolve; readers must never race a live mutation), optionally
        filtered by loop/resolution and tailed to the newest ``limit``."""
        now_ms = time.time() * 1000.0
        with self._lock:
            self._expire_locked(now_ms)
            out = [dict(e) for e in self._ring]
        if loop is not None:
            out = [e for e in out if e["loop"] == loop]
        if resolved is not None:
            out = [e for e in out if e["resolved"] == resolved]
        if limit is not None:
            # 0 means zero entries, never "no limit" (the events contract)
            out = out[-limit:] if limit > 0 else []
        return out

    def stats(self) -> dict:
        """One consistent snapshot: ring accounting + the per-loop
        issued/resolved/expired/missed/unresolved ledger the
        reconciliation gate checks."""
        now_ms = time.time() * 1000.0
        with self._lock:
            self._expire_locked(now_ms)
            pending_by_loop = {loop: 0 for loop in DECISION_LOOPS}
            for e in self._pending.values():
                pending_by_loop[e["loop"]] += 1
            loops = {}
            for loop in DECISION_LOOPS:
                c = self._counts[loop]
                loops[loop] = {
                    **c,
                    "unresolved": pending_by_loop[loop],
                }
            return {
                "capacity": self.capacity,
                "size": len(self._ring),
                "dropped": self.dropped,
                "issued": self._issued,
                "pending": len(self._pending),
                "loops": loops,
            }

    def calibration(self) -> list[dict]:
        """Per-loop calibration rows (the ``system.public.calibration``
        materialization): window means + the accounting ledger."""
        now_ms = time.time() * 1000.0
        with self._lock:
            self._expire_locked(now_ms)
            pending_by_loop = {loop: 0 for loop in DECISION_LOOPS}
            for e in self._pending.values():
                pending_by_loop[e["loop"]] += 1
            rows = []
            for loop in DECISION_LOOPS:
                snap = self._calibration[loop].snapshot(now_ms)
                snap.update(self._counts[loop])
                snap["unresolved"] = pending_by_loop[loop]
                rows.append(snap)
            return rows

    def clear(self) -> None:
        """Drop entries + calibration state but keep the issued/drop
        accounting (the EventStore.clear contract) — live pending
        entries are expired, not forgotten."""
        now_ms = time.time() * 1000.0
        with self._lock:
            for e in list(self._pending.values()):
                self._expire_one_locked(e, now_ms)
            self._ring.clear()
            self._calibration = {
                loop: _LoopCalibration(
                    loop, cal.fast.span_ms, cal.slow.span_ms, cal.alpha
                )
                for loop, cal in self._calibration.items()
            }


DECISION_JOURNAL = DecisionJournal()


def record_decision(
    loop: str,
    key: str,
    choice: str,
    features: Optional[dict] = None,
    predicted: Optional[float] = None,
) -> int:
    """Journal one adaptive decision on the process-global journal."""
    return DECISION_JOURNAL.record(loop, key, choice, features, predicted)


def resolve_decision(
    decision_id: int,
    actual: Optional[float] = None,
    outcome: str = "ok",
    loop: Optional[str] = None,
    calibrate: bool = True,
) -> bool:
    """Attach the realized outcome on the process-global journal; pass
    ``loop`` so a late resolve is miss-attributed to the right loop."""
    return DECISION_JOURNAL.resolve(
        decision_id, actual, outcome, loop=loop, calibrate=calibrate
    )
