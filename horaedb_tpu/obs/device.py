"""Device telemetry plane — HBM occupancy, kernel timing, and compile
accounting as first-class observables.

The host-side observability stack (docs/OBSERVABILITY.md) answers *when*
(span trees) and *what it cost* (the query ledger), but the device plane
was dark: nothing reported what HBM is spent on, how long dispatches
actually run on-device, or when/why XLA recompiles. StreamBox-HBM
(PAPERS.md) treats HBM residency as a first-class managed resource, and
"Fine-Tuning Data Structures for Analytical Query Processing" argues
layout/route decisions are only tunable when their cost counters are
first-class — the compressed-storage auto-tuner and incremental-window
eviction (ROADMAP items 2 and 4) read the usage map this module serves.

Four legs:

1. **HBM occupancy** — a per-(table, column, dtype) residency inventory
   derived from the scan cache's own ``device_bytes`` accounting (plus
   session/stack uploads, and any future partial-agg/window state via
   ``register_occupancy_provider``), served as ``system.public.device``
   and ``/debug/device`` with bytes, rows, dtype, last-hit age, and
   eviction counts.
2. **Kernel timing** — ``timed_dispatch(kind, fn)`` wraps every device
   dispatch point (cached agg packed/dist/cohort, raw top-k/selection,
   the fused direct/partial kernel). Timing is SAMPLED (default 1-in-N,
   ``HORAEDB_DEVICE_SAMPLE``): a sampled dispatch pays one
   ``block_until_ready`` so the measured wall is honest on-device time,
   an unsampled one stays fully async. Slow-log candidates (elapsed so
   far over ``HORAEDB_DEVICE_SLOW_MS``) and EXPLAIN ANALYZE runs are
   always timed — diagnostics want the number, not the pipeline.
   Results land in the ledger (``device_ms``, ``device_dispatches``)
   and the per-kernel ``horaedb_device_dispatch_seconds`` histograms.
3. **Compile accounting** — ``utils/querystats.note_kernel_dispatch``
   routes first-seen static shapes here: a typed ``kernel_compile``
   event (kind, shape bucket, wall ms, XLA ``cost_analysis``
   flops/bytes where available) lands in the journal, the per-kernel
   compile histogram/counters tick, and the ledger's ``compile_hit``
   marks the query that paid the stall.
4. **Surfaces** — ``/debug/device`` (server/http.py), ``horaectl
   device`` (tools/ctl.py), ``system.public.device``
   (table_engine/system.py); the ``horaedb_device_*`` families ride the
   self-monitoring recorder into ``system_metrics.samples`` like every
   other family.

``HORAEDB_DEVICE_TELEMETRY=0`` turns the whole plane off (dispatch
wrappers become bare calls); the overhead budget with it ON is <2% on
the groupby/rawscan benches (``BENCH_CONFIG=devicetel`` gates it).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Optional

from ..utils.env import env_float, env_int
from ..utils.metrics import REGISTRY

# Every device-dispatch point declares its kernel kind here — the label
# set of the horaedb_device_* families (eagerly registered, lint-pinned
# like SEGMENT_KERNEL_LABELS / RAW_SCAN_PATHS).
DEVICE_KERNEL_KINDS = (
    "cached_packed",   # RTT-minimized packed cached agg (single device)
    "cached_dist",     # shard_map cached agg over the serving mesh
    "cached_cohort",   # vmapped fused cohort dispatch (wlm/batch)
    "fused",           # direct/partial fused scan-agg (ops/scan_agg)
    "fused_dist",      # its shard_map form (parallel/dist_agg)
    "raw_topk",        # raw read: bisection top-k (ops/scan_topk)
    "raw_select",      # raw read: bounded selection
    "raw_topk_dist",   # sharded raw variants (parallel/dist_raw)
    "raw_select_dist",
    "state_fold",      # live-window ring fold/gather (ops/livewindow)
)

# Occupancy row components: "column" rows sum to the scan cache's own
# device_bytes accounting (the acceptance invariant); "session"/"stack"
# are the content-keyed query-shape uploads and stacked value views the
# cache keeps beside the columns; "evicted" rows carry eviction counts
# for tables no longer resident.
OCCUPANCY_COMPONENTS = ("column", "session", "stack", "evicted", "state")

# Registry discipline (lint-enforced like the agg-kernel/raw families):
# declared here, registered eagerly, documented in docs/OBSERVABILITY.md,
# and no stray horaedb_device_* family may exist outside this tuple.
DEVICE_METRIC_FAMILIES = (
    "horaedb_device_dispatch_total",
    "horaedb_device_dispatch_seconds",
    "horaedb_device_compile_total",
    "horaedb_device_compile_seconds",
    "horaedb_device_resident_bytes",
    "horaedb_device_evictions_total",
)

# Device dispatches are sub-ms..s on real chips; the default bucket
# ladder starts at 1ms and would fold the whole fast path into one
# bucket.
_DISPATCH_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
)

_M_DISPATCH = {
    k: REGISTRY.counter(
        "horaedb_device_dispatch_total",
        "device kernel dispatches, by kernel kind",
        labels={"kernel": k},
    )
    for k in DEVICE_KERNEL_KINDS
}
_M_DISPATCH_SECONDS = {
    k: REGISTRY.histogram(
        "horaedb_device_dispatch_seconds",
        "sampled on-device dispatch wall seconds (block_until_ready)",
        buckets=_DISPATCH_BUCKETS,
        labels={"kernel": k},
    )
    for k in DEVICE_KERNEL_KINDS
}
_M_COMPILE_SECONDS = {
    k: REGISTRY.histogram(
        "horaedb_device_compile_seconds",
        "wall seconds of first-time XLA compiles, by kernel kind",
        labels={"kernel": k},
    )
    for k in DEVICE_KERNEL_KINDS
}
_M_COMPILE = {
    (k, outcome): REGISTRY.counter(
        "horaedb_device_compile_total",
        "compile-cache outcomes per device dispatch shape, by kernel kind",
        labels={"kernel": k, "outcome": outcome},
    )
    for k in DEVICE_KERNEL_KINDS
    for outcome in ("compile", "hit")
}
_M_RESIDENT = {
    c: REGISTRY.gauge(
        "horaedb_device_resident_bytes",
        "HBM-resident bytes by component (scan-cache columns/sessions/stacks)",
        labels={"component": c},
    )
    for c in ("column", "session", "stack")
}
_M_EVICTIONS = REGISTRY.counter(
    "horaedb_device_evictions_total",
    "scan-cache entries evicted under the HBM byte/entry budget",
)


# ---- knobs -----------------------------------------------------------------


def device_telemetry_enabled() -> bool:
    """HORAEDB_DEVICE_TELEMETRY=0 turns the plane off entirely (the
    dispatch wrappers become bare calls — the bench A/B's off arm)."""
    import os

    return os.environ.get("HORAEDB_DEVICE_TELEMETRY", "1") != "0"


def sample_every() -> int:
    """Time 1 in N dispatches (HORAEDB_DEVICE_SAMPLE, default 8; <=1
    times every dispatch). Sampling exists so the async dispatch
    pipeline is not serialized: a timed dispatch blocks until the device
    answers, an untimed one overlaps host work as before."""
    return max(1, env_int("HORAEDB_DEVICE_SAMPLE", 8))


# The proxy's live slow-log threshold overrides the env default (see
# set_slow_candidate_s): a query that will be slow-logged must carry a
# device_ms whatever threshold the operator dialed in at runtime.
_slow_override: Optional[float] = None


def set_slow_candidate_s(seconds: float) -> None:
    """Couple the always-time threshold to the slow-log threshold — the
    proxy calls this whenever ``slow_threshold_s`` changes (init and the
    PUT /debug/slow_threshold endpoint), so a slow-logged query's
    dispatches are always timed. Process-global like the slow log's
    candidate set itself; with several proxies the last setter wins."""
    global _slow_override
    _slow_override = max(0.0, float(seconds))


def _slow_candidate_s() -> float:
    """Queries already slower than this are timed ALWAYS — their
    slow-log row must say where the time went. The MIN of the env knob
    (HORAEDB_DEVICE_SLOW_MS, default 1s) and the proxy's live slow-log
    threshold: min, not override, so the documented knob keeps working
    in server deployments (Proxy.__init__ sets the override at
    construction) and a lowered threshold from either side only ever
    times MORE, never less."""
    env_s = env_float("HORAEDB_DEVICE_SLOW_MS", 1000.0) / 1000.0
    if _slow_override is not None:
        return min(_slow_override, env_s)
    return env_s


# ---- kernel timing ---------------------------------------------------------

# per-kind dispatch counters driving the 1-in-N sample choice (first
# dispatch of each kind is always sampled — compiles mostly get timed)
_sample_counts: dict[str, int] = {}
_sample_lock = threading.Lock()


def _should_time(kind: str) -> bool:
    from ..utils.querystats import current_ledger

    ledger = current_ledger()
    if ledger is not None:
        # slow-log candidate: the query has already blown the slow
        # threshold — its diagnosis needs the device number
        if time.time() - ledger.started_at >= _slow_candidate_s():
            return True
        # EXPLAIN ANALYZE is a diagnostic run: always time it so the
        # rendered ledger carries device_ms (serializing it is fine)
        if ledger.sql.lstrip()[:7].lower() == "explain":
            return True
    n = sample_every()
    if n <= 1:
        return True
    with _sample_lock:
        c = _sample_counts.get(kind, 0)
        _sample_counts[kind] = c + 1
    return c % n == 0


def timed_dispatch(kind: str, fn: Callable[[], Any]) -> Any:
    """Run one device dispatch with sampled ``block_until_ready``
    timing; returns ``fn()``'s result unchanged.

    Always (cheap): bumps ``horaedb_device_dispatch_total{kernel=}`` and
    the ledger's ``device_dispatches``. Sampled: blocks on the result,
    observes the per-kernel dispatch histogram, and adds the wall
    milliseconds to the ledger's ``device_ms``. Telemetry off: a bare
    call."""
    if not device_telemetry_enabled():
        return fn()
    from ..utils import querystats

    timed = _should_time(kind)
    t0 = time.perf_counter()
    out = fn()
    counter = _M_DISPATCH.get(kind)
    if counter is None:  # undeclared kind: account it, lint will flag
        counter = REGISTRY.counter(
            "horaedb_device_dispatch_total",
            "device kernel dispatches, by kernel kind",
            labels={"kernel": kind},
        )
    counter.inc()
    querystats.record(device_dispatches=1)
    if timed:
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:
            pass  # host-side results (numpy) have nothing to block on
        dt = time.perf_counter() - t0
        hist = _M_DISPATCH_SECONDS.get(kind)
        if hist is None:
            hist = REGISTRY.histogram(
                "horaedb_device_dispatch_seconds",
                "sampled on-device dispatch wall seconds (block_until_ready)",
                buckets=_DISPATCH_BUCKETS,
                labels={"kernel": kind},
            )
        hist.observe(dt)
        querystats.record(device_ms=dt * 1000.0)
    return out


# ---- compile accounting ----------------------------------------------------


def _shape_of(key) -> str:
    """Compact printable rendering of a static kernel key — the "shape
    bucket" a compile event names (keys are tuples of ints/strings/op
    tuples; padding already bucketed them to powers of two)."""
    s = repr(key)
    return s if len(s) <= 200 else s[:197] + "..."


def note_compile(kind: str, key, wall_s: float,
                 cost: Optional[dict] = None) -> None:
    """A never-seen static shape's first dispatch: journal the typed
    ``kernel_compile`` event (trace-linked, so EXPLAIN ANALYZE and the
    slow log can attribute the stall), tick the per-kernel compile
    histogram + counter, and mark the paying query's ledger
    (``compile_hit``). ``wall_s`` is the first call's wall time — the
    honest upper bound on the XLA compile. ``cost`` optionally carries
    ``cost_analysis`` flops/bytes (see ``cost_analysis``)."""
    if not device_telemetry_enabled():
        return
    from ..utils import querystats

    hist = _M_COMPILE_SECONDS.get(kind)
    if hist is not None:
        hist.observe(wall_s)
    counter = _M_COMPILE.get((kind, "compile"))
    if counter is not None:
        counter.inc()
    querystats.record(compile_hit=1)
    from ..utils.events import record_event

    # NB record_event's own ``kind`` arg collides (the rule_kind
    # precedent): the kernel kind ships as ``kernel``.
    attrs: dict = {
        "kernel": kind,
        "shape": _shape_of(key),
        "wall_ms": round(wall_s * 1000.0, 3),
    }
    if cost:
        attrs.update({k: v for k, v in cost.items() if v is not None})
    record_event("kernel_compile", **attrs)


def note_compile_cache_hit(kind: str) -> None:
    """A seen shape dispatched again: the compile cache served it."""
    if not device_telemetry_enabled():
        return
    counter = _M_COMPILE.get((kind, "hit"))
    if counter is not None:
        counter.inc()


def cost_analysis(jitfn, args=(), kwargs=None) -> Optional[dict]:
    """Best-effort XLA ``cost_analysis`` flops/bytes for a jit call.

    Opt-in (``HORAEDB_DEVICE_COST_ANALYSIS=1``): the AOT
    ``lower().compile()`` pays a SECOND compile of the shape, so it must
    never ride the default path — compile events carry kind/shape/wall
    regardless; flops/bytes only under the knob ("where available")."""
    import os

    if os.environ.get("HORAEDB_DEVICE_COST_ANALYSIS", "0") != "1":
        return None
    try:
        lowered = jitfn.lower(*args, **(kwargs or {}))
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None
        out = {}
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed")):
            v = ca.get(src)
            if v is not None:
                out[dst] = float(v)
        return out or None
    except Exception:
        return None


def compile_stats() -> dict:
    """Per-kernel compile/hit counts — the /debug/device compile block."""
    out = {}
    for kind in DEVICE_KERNEL_KINDS:
        compiles = _M_COMPILE[(kind, "compile")].value
        hits = _M_COMPILE[(kind, "hit")].value
        if compiles or hits:
            out[kind] = {"compiles": int(compiles), "hits": int(hits)}
    return out


def note_eviction(n: int = 1) -> None:
    """The scan cache evicted ``n`` entries under its HBM budget."""
    _M_EVICTIONS.inc(n)


# ---- HBM occupancy ---------------------------------------------------------

# Occupancy providers: anything holding device-resident state registers
# ITSELF (held weakly — a closed executor's cache drops out) and must
# expose ``snapshot_device() -> list[dict]`` (rows with table_name /
# column_name / component / dtype / bytes / rows / last_hit_age_ms /
# evictions). The scan cache registers at construction; the ROADMAP
# item-2 window state and item-4 encoded layouts plug in here.
_PROVIDERS: "weakref.WeakSet" = weakref.WeakSet()


def register_occupancy_provider(owner) -> None:
    """Track ``owner`` (weakly) as a device-residency source; it must
    expose ``snapshot_device() -> list[dict]``."""
    _PROVIDERS.add(owner)


def unregister_occupancy_provider(owner) -> None:
    """Drop ``owner`` from the inventory immediately — Connection.close
    calls this so a closed database's cache stops contributing rows the
    moment it closes instead of whenever GC collects it (the inventory
    is process-wide by design, like system.public.workload, but it must
    only merge LIVE sources). The gauges refresh forcibly afterwards:
    a close is a residency mutation like any eviction, and a parked
    gauge would report the freed bytes until the next cache serve."""
    _PROVIDERS.discard(owner)
    refresh_occupancy(force=True)


def _component_sums(rows: list[dict]) -> dict:
    """Byte totals per gauge component — THE one summing loop (the
    gauges, /debug/device totals, and the refresh fallback all use it;
    a new OCCUPANCY_COMPONENT lands in one place)."""
    sums = {c: 0 for c in ("column", "session", "stack")}
    for r in rows:
        c = r.get("component")
        if c in sums:
            sums[c] += int(r.get("bytes", 0))
    return sums


def device_inventory() -> list[dict]:
    """The full per-(table, column, dtype) residency inventory across
    every registered provider, with the resident-bytes gauges refreshed
    from what was just walked (so scrapes stay honest between queries)."""
    rows: list[dict] = []
    for p in list(_PROVIDERS):
        try:
            rows.extend(p.snapshot_device())
        except Exception:
            continue  # one sick provider must not dark the whole plane
    for c, v in _component_sums(rows).items():
        _M_RESIDENT[c].set(float(v))
    return rows


_last_refresh = 0.0


def refresh_occupancy(force: bool = False) -> None:
    """Recompute the resident-bytes gauges — the scan cache calls this
    after serving/mutations so the self-monitoring recorder scrapes
    fresh values. HOT-PATH cheap: providers exposing
    ``occupancy_bytes()`` are summed without materializing inventory
    rows, and un-forced refreshes are throttled to ~1/s (the recorder
    scrapes at 10s; per-query precision lives in the inventory reads,
    which always recompute live). Mutations that can be the LAST touch
    for a while (build, eviction, invalidate, bf16 drop) pass
    ``force=True`` so the throttle can never park a gauge on freed
    bytes forever."""
    global _last_refresh
    if not device_telemetry_enabled():
        return
    now = time.monotonic()
    if not force and now - _last_refresh < 1.0:
        return
    _last_refresh = now
    sums = {c: 0 for c in ("column", "session", "stack")}
    for p in list(_PROVIDERS):
        try:
            fast = getattr(p, "occupancy_bytes", None)
            per = fast() if fast is not None else _component_sums(
                p.snapshot_device()
            )
            for c, v in per.items():
                if c in sums:
                    sums[c] += int(v)
        except Exception:
            continue
    for c, g in _M_RESIDENT.items():
        g.set(float(sums[c]))


def occupancy_totals(rows: Optional[list[dict]] = None) -> dict:
    """Byte totals by component plus the grand total — the /debug/device
    summary block (``column`` is the scan cache's device_bytes truth)."""
    if rows is None:
        rows = device_inventory()
    out = _component_sums(rows)
    out["total"] = sum(out.values())
    return out
