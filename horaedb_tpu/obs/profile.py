"""Continuous profile plane — fleetwide wall-clock attribution from the
database's own span trees (ISSUE 20 tentpole).

The node could already show ONE trace (`utils/tracectx` span trees at
/debug/trace, a ring of 64); this module answers the question a single
trace cannot: *where does the wall-clock actually go, by stage and by
shape, over time?* Every ``finish_trace`` folds its finished tree into
the process-global streaming ``PROFILE`` aggregator — no sampling
daemon, no second timing source: the profile is derived from the exact
spans EXPLAIN ANALYZE and the slow log already show, so the two can
never disagree ("Fine-Tuning Data Structures for Analytical Query
Processing": tune from the *observed* mix, which first requires
measuring it).

Keying: ``(path, route, shape)`` where ``path`` is the slash-joined
span chain from the root (``sql/execute/dispatch``), ``route`` the
serving plane (query/ingest/ddl/flush/compaction/rules), and ``shape``
the normalized plan key class (literal-masked SQL for queries, the
target table for ingest). Each key holds count, total (inclusive) and
exclusive milliseconds, an EWMA plus fast/slow running-sum windows
(the PR-11/16 incremental-window discipline), and a last-exemplar
``trace_id`` linking back to ``/debug/trace/{id}``.

Accounting contract (the hard invariant the tests reconcile): per
folded trace

    ``root_ms == Σ non-root exclusive_ms + untracked_ms``

where a span's exclusive time is its duration minus its direct
children's, SIGNED — parallel children that overlap their parent drive
exclusive negative rather than silently clipping — and ``untracked``
(the root's own uncovered time) is a first-class row at
``<root>/(untracked)``, never absorbed. A large untracked fraction IS
the signal a plane lacks span coverage. LRU eviction under the
``[observability] profile_keys`` bound is exactly accounted: evicted
counts/totals accumulate so live rows + evicted totals always equal a
naive refold of every trace ever folded.

Surfaces: ``system.public.profile`` on all three wires,
``/debug/profile?path=&route=``, ``horaectl profile``, the EXPLAIN
ANALYZE ``Critical path:`` line, and the ``horaedb_profile_*``
families below (eagerly registered, lint-pinned). ``HORAEDB_PROFILE=0``
kills the whole plane (fold returns immediately — the bench A/B's off
arm).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

from ..utils.metrics import REGISTRY

# ---- registry discipline (lint-enforced, docs-pinned) ---------------------

PROFILE_METRIC_FAMILIES = (
    "horaedb_profile_traces_total",
    "horaedb_profile_spans_total",
    "horaedb_profile_dropped_total",
    "horaedb_profile_root_ms_total",
    "horaedb_profile_untracked_ms_total",
    "horaedb_profile_untracked_ratio",
)

_M_TRACES = REGISTRY.counter(
    "horaedb_profile_traces_total",
    "finished traces folded into the profile aggregator",
)
_M_SPANS = REGISTRY.counter(
    "horaedb_profile_spans_total",
    "span rows folded into the profile aggregator",
)
_M_DROPPED = REGISTRY.counter(
    "horaedb_profile_dropped_total",
    "profile keys LRU-evicted under the profile_keys bound",
)
_M_ROOT_MS = REGISTRY.counter(
    "horaedb_profile_root_ms_total",
    "root wall milliseconds folded (the denominator of coverage)",
)
_M_UNTRACKED_MS = REGISTRY.counter(
    "horaedb_profile_untracked_ms_total",
    "root milliseconds no child span covered (clipped at 0)",
)
_M_UNTRACKED_RATIO = REGISTRY.gauge(
    "horaedb_profile_untracked_ratio",
    "EWMA fraction of root wall time no child span covered",
)


def profile_enabled() -> bool:
    """HORAEDB_PROFILE=0 turns the whole plane off — fold is a cheap
    env-read no-op (the bench A/B's off arm). Read per call, not cached:
    the kill switch must take effect immediately."""
    try:
        return os.environ["HORAEDB_PROFILE"] not in ("0", "off", "false")
    except KeyError:
        return True


# ---- incremental windows (the PR-11/16 running-sum discipline) ------------


class _MsWindow:
    """Running-sum sliding window over ms observations, bucketed into a
    ring of ``_NB`` time slices: push is a strict O(1) — bucket index,
    two list adds — with NO per-observation storage (a deque of every
    observation made the fold the hot path's hot path; the profile plane
    runs on every finished trace, so its own cost is the first thing the
    overhead gate would flag). Eviction granularity is span/``_NB``:
    the mean covers [span, span + span/_NB) seconds of history, the same
    coarsening the metrics scrape already accepts."""

    _NB = 8

    __slots__ = ("span_s", "_bucket_s", "_sums", "_ns", "_sum", "_n",
                 "_epoch")

    def __init__(self, span_s: float) -> None:
        self.span_s = span_s
        self._bucket_s = span_s / self._NB
        self._sums = [0.0] * self._NB
        self._ns = [0] * self._NB
        self._sum = 0.0
        self._n = 0
        self._epoch = -1  # absolute bucket index of the newest slice

    def _advance(self, b: int) -> None:
        """Rotate the ring forward to absolute bucket ``b``, evicting
        the slices that fell out of the span."""
        if b <= self._epoch:
            return
        if self._epoch < 0 or b - self._epoch >= self._NB:
            # first push, or a gap longer than the whole window
            self._sums = [0.0] * self._NB
            self._ns = [0] * self._NB
            self._sum = 0.0
            self._n = 0
        else:
            for e in range(self._epoch + 1, b + 1):
                i = e % self._NB
                self._sum -= self._sums[i]
                self._n -= self._ns[i]
                self._sums[i] = 0.0
                self._ns[i] = 0
        self._epoch = b

    def push(self, now: float, ms: float) -> None:
        b = int(now / self._bucket_s)
        if b != self._epoch:
            self._advance(b)
        i = b % self._NB
        self._sums[i] += ms
        self._ns[i] += 1
        self._sum += ms
        self._n += 1

    def mean(self, now: float) -> tuple[float, int]:
        self._advance(int(now / self._bucket_s))
        return (self._sum / self._n if self._n else 0.0), self._n


# window spans, env-tunable like HORAEDB_CALIBRATION_FAST_S
def _window_spans() -> tuple[float, float]:
    import os

    try:
        fast = float(os.environ.get("HORAEDB_PROFILE_FAST_S", "60"))
        slow = float(os.environ.get("HORAEDB_PROFILE_SLOW_S", "600"))
    except ValueError:
        return 60.0, 600.0
    return max(fast, 1.0), max(slow, 1.0)


class _Key:
    """One (path, route, shape) row's streaming aggregates."""

    __slots__ = (
        "count", "total_ms", "excl_ms", "ewma_ms",
        "fast", "slow", "last_trace_id", "last_at",
    )

    def __init__(self) -> None:
        fast_s, slow_s = _window_spans()
        self.count = 0
        self.total_ms = 0.0
        self.excl_ms = 0.0
        self.ewma_ms: Optional[float] = None  # per-occurrence exclusive
        self.fast = _MsWindow(fast_s)
        self.slow = _MsWindow(slow_s)
        self.last_trace_id: Any = None
        self.last_at = 0.0


_EWMA_ALPHA = 0.3
_RATIO_ALPHA = 0.2
UNTRACKED = "(untracked)"  # first-class row suffix, never absorbed


class ProfileAggregator:
    """Bounded streaming fold of finished span trees, keyed by
    (path, route, shape). Thread-safe; every verb reconciles under one
    lock. Eviction is exactly accounted (see module docstring)."""

    def __init__(self, capacity: int = 1024) -> None:
        self.capacity = max(1, int(capacity))
        self._keys: "OrderedDict[tuple, _Key]" = OrderedDict()
        self._lock = threading.Lock()
        # fleetwide accounting — live rows + these == naive refold
        self.traces = 0
        self.spans = 0
        self.dropped = 0
        self.evicted_count = 0
        self.evicted_total_ms = 0.0
        self.evicted_excl_ms = 0.0
        self.untracked_ratio: Optional[float] = None

    # ---- fold ----------------------------------------------------------

    def fold(self, trace_id, root: dict, route: str = "",
             shape: str = "") -> None:
        """Fold one finished trace's serialized root into the profile.
        ``root`` is the snapshot dict ``Trace.to_dict()["root"]`` — the
        same object TRACE_STORE records, so the profile and /debug/trace
        can never disagree about a trace. The HORAEDB_PROFILE gate lives
        at the ``fold_trace`` entry, decided at enqueue time — a queued
        fold always lands even if the switch flips before the worker
        drains it."""
        if not isinstance(root, dict):
            return
        root_ms = root.get("duration_ms")
        if not isinstance(root_ms, (int, float)):
            return
        now = time.time()
        root_name = str(root.get("name", "request"))
        # (path, total_ms, exclusive_ms) rows; the walk is the whole cost
        rows: list[tuple[str, float, float]] = []

        def walk(node: dict, path: str) -> float:
            """-> inclusive duration; appends this node's row."""
            dur = node.get("duration_ms")
            dur = float(dur) if isinstance(dur, (int, float)) else 0.0
            child_sum = 0.0
            for c in node.get("children") or ():
                if isinstance(c, dict):
                    name = str(c.get("name", "?"))
                    child_sum += walk(c, f"{path}/{name}")
            rows.append((path, dur, dur - child_sum))
            return dur

        walk(root, root_name)
        # the root row's exclusive IS the untracked remainder — keep it a
        # first-class row so root == Σ non-root exclusive + untracked
        _, root_total, untracked = rows.pop()
        rows.append((root_name, root_total, 0.0))
        rows.append((f"{root_name}/{UNTRACKED}", untracked, untracked))

        with self._lock:
            self.traces += 1
            self.spans += len(rows)
            _M_TRACES.inc()
            _M_SPANS.inc(len(rows))
            _M_ROOT_MS.inc(max(0.0, float(root_ms)))
            _M_UNTRACKED_MS.inc(max(0.0, untracked))
            if root_ms > 0:
                frac = max(0.0, untracked) / float(root_ms)
                prev = self.untracked_ratio
                self.untracked_ratio = (
                    frac if prev is None
                    else prev + _RATIO_ALPHA * (frac - prev)
                )
                _M_UNTRACKED_RATIO.set(round(self.untracked_ratio, 6))
            for path, total, excl in rows:
                k = (path, route, shape)
                entry = self._keys.get(k)
                if entry is None:
                    entry = _Key()
                    self._keys[k] = entry
                else:
                    self._keys.move_to_end(k)  # touch at MRU end
                entry.count += 1
                entry.total_ms += total
                entry.excl_ms += excl
                entry.ewma_ms = (
                    excl if entry.ewma_ms is None
                    else entry.ewma_ms + _EWMA_ALPHA * (excl - entry.ewma_ms)
                )
                entry.fast.push(now, excl)
                entry.slow.push(now, excl)
                entry.last_trace_id = trace_id
                entry.last_at = now
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._keys) > self.capacity:
            _, victim = self._keys.popitem(last=False)
            self.dropped += 1
            self.evicted_count += victim.count
            self.evicted_total_ms += victim.total_ms
            self.evicted_excl_ms += victim.excl_ms
            _M_DROPPED.inc()

    # ---- read side -----------------------------------------------------

    def list(self, path: Optional[str] = None, route: Optional[str] = None,
             limit: int = 0) -> list[dict]:
        """Snapshot rows (exclusive-heavy first). ``path`` matches by
        prefix (``sql/execute`` covers its subtree), ``route`` exactly."""
        now = time.time()
        with self._lock:
            out = []
            for (p, r, shape), e in self._keys.items():
                if path and not p.startswith(path):
                    continue
                if route and r != route:
                    continue
                fast_ms, fast_n = e.fast.mean(now)
                slow_ms, slow_n = e.slow.mean(now)
                out.append({
                    "path": p,
                    "route": r,
                    "shape": shape,
                    "count": e.count,
                    "total_ms": round(e.total_ms, 3),
                    "exclusive_ms": round(e.excl_ms, 3),
                    "ewma_ms": round(e.ewma_ms, 4)
                    if e.ewma_ms is not None else None,
                    "fast_ms": round(fast_ms, 4),
                    "fast_n": fast_n,
                    "slow_ms": round(slow_ms, 4),
                    "slow_n": slow_n,
                    "last_trace_id": e.last_trace_id,
                    "last_at": round(e.last_at, 3),
                })
        out.sort(key=lambda r: r["exclusive_ms"], reverse=True)
        return out[:limit] if limit else out

    def stats(self) -> dict:
        """Fleetwide accounting — what the reconciliation property and
        /debug/profile's header read."""
        with self._lock:
            live_count = sum(e.count for e in self._keys.values())
            live_total = sum(e.total_ms for e in self._keys.values())
            live_excl = sum(e.excl_ms for e in self._keys.values())
            return {
                "keys": len(self._keys),
                "capacity": self.capacity,
                "traces": self.traces,
                "spans": self.spans,
                "dropped": self.dropped,
                "untracked_ratio": (
                    round(self.untracked_ratio, 6)
                    if self.untracked_ratio is not None else None
                ),
                "live": {
                    "count": live_count,
                    "total_ms": round(live_total, 3),
                    "exclusive_ms": round(live_excl, 3),
                },
                "evicted": {
                    "count": self.evicted_count,
                    "total_ms": round(self.evicted_total_ms, 3),
                    "exclusive_ms": round(self.evicted_excl_ms, 3),
                },
            }

    def resize(self, capacity: int) -> None:
        """Apply the [observability] profile_keys knob; shrinking evicts
        (and accounts) oldest keys immediately."""
        with self._lock:
            self.capacity = max(1, int(capacity))
            self._evict_locked()

    def clear(self) -> None:
        with self._lock:
            self._keys.clear()
            self.traces = 0
            self.spans = 0
            self.dropped = 0
            self.evicted_count = 0
            self.evicted_total_ms = 0.0
            self.evicted_excl_ms = 0.0
            self.untracked_ratio = None


PROFILE = ProfileAggregator()


# ---- async fold -----------------------------------------------------------
#
# The tree walk + per-row updates cost ~30us; paid on every finished
# request under one global lock, that's exactly the tax the bench
# overhead gate exists to catch. So the request thread pays only an
# enqueue — a single daemon worker does the folding. Exactness is kept
# two ways: a full queue folds INLINE (backpressure, never drop), and
# ``flush()`` is the barrier tests/gates call before reconciling.

_MAX_PENDING = 1024
_pending: "deque" = deque()
_outstanding = 0  # queued + in-flight, under _cond
_cond = threading.Condition()
_worker: Optional[threading.Thread] = None


def _drain_loop() -> None:
    global _outstanding
    while True:
        with _cond:
            while not _pending:
                _cond.wait()
            item = _pending.popleft()
        try:
            PROFILE.fold(*item)
        except Exception:
            pass
        with _cond:
            _outstanding -= 1
            if _outstanding == 0:
                _cond.notify_all()


def _ensure_worker() -> None:
    global _worker
    w = _worker
    if w is None or not w.is_alive():  # first fold, or lost to a fork
        w = threading.Thread(
            target=_drain_loop, name="profile-fold", daemon=True
        )
        _worker = w
        w.start()


def fold_trace(trace_id, root: dict, route: str = "", shape: str = "") -> None:
    """finish_trace's hook: fold one finished tree into the global
    aggregator. Never raises, and never taxes the request thread with
    the tree walk — the fold is queued for the daemon worker. The
    HORAEDB_PROFILE gate is decided HERE, at enqueue time."""
    global _outstanding
    if not isinstance(root, dict) or not profile_enabled():
        return
    try:
        inline = False
        with _cond:
            if _outstanding >= _MAX_PENDING:
                inline = True  # backpressure: exactness over latency
            else:
                _pending.append((trace_id, root, route, shape))
                _outstanding += 1
                _cond.notify()
        if inline:
            PROFILE.fold(trace_id, root, route=route, shape=shape)
        else:
            _ensure_worker()
    except Exception:
        pass


def flush(timeout: float = 5.0) -> bool:
    """Barrier: block until every queued fold has landed (tests, the
    tenantsim gate and the bench A/B reconcile AFTER a flush). False on
    timeout."""
    deadline = time.monotonic() + timeout
    with _cond:
        while _outstanding > 0:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            _cond.wait(left)
    return True


# ---- critical path (EXPLAIN ANALYZE) --------------------------------------


def critical_path(root: dict, max_hops: int = 12) -> list[dict]:
    """The max-time chain through one trace: from the root, repeatedly
    descend into the child with the greatest inclusive duration. Each
    hop carries its inclusive duration and its exclusive (self) time —
    the hop where inclusive≈exclusive is where the wall-clock actually
    went."""
    hops: list[dict] = []
    node = root
    for _ in range(max_hops):
        if not isinstance(node, dict):
            break
        dur = node.get("duration_ms")
        dur = float(dur) if isinstance(dur, (int, float)) else 0.0
        kids = [c for c in (node.get("children") or ()) if isinstance(c, dict)]
        child_sum = sum(
            float(c.get("duration_ms") or 0.0) for c in kids
        )
        hops.append({
            "name": str(node.get("name", "?")),
            "duration_ms": round(dur, 3),
            "self_ms": round(dur - child_sum, 3),
        })
        if not kids:
            break
        node = max(kids, key=lambda c: float(c.get("duration_ms") or 0.0))
    return hops


def render_critical_path(root: dict) -> str:
    """One-line rendering for EXPLAIN ANALYZE's ``Critical path:``."""
    hops = critical_path(root)
    return " -> ".join(
        f"{h['name']} {h['duration_ms']:.1f}ms (self {h['self_ms']:.1f})"
        for h in hops
    )
