"""Per-tenant / per-table quotas — token buckets + the block-list
(ref: proxy/src/limiter.rs — the reference Limiter carries both block
and quota semantics; this subsumes the old bare block-list).

Two bucket kinds, each keyed by scope:

- ``read_qps``    — SELECT statements per second
- ``write_rows``  — written rows per second

Scopes are ``("tenant", name)`` and ``("table", name)``; a request is
charged against every bucket that applies (its table's and its
tenant's). Rates are runtime-adjustable through ``/admin/quota`` and a
rejection is a typed, retryable ``QuotaExceededError`` carrying the
time until the bucket refills (HTTP 429 + Retry-After, MySQL errno
1040, PG SQLSTATE 53300).

Operator-applied state (blocked tables + quota rules) persists through
the config layer: every mutation rewrites ``persist_path`` (JSON under
the node's data dir), and a restarted node reloads it — an
``/admin/block`` survives the process.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Iterable, Optional

from ..utils.metrics import REGISTRY

logger = logging.getLogger("horaedb_tpu.wlm")

QUOTA_KINDS = ("read_qps", "write_rows")
SCOPE_KINDS = ("tenant", "table")


class BlockedError(RuntimeError):
    """Table is on the operator block-list (ref: limiter.rs). Not
    retryable — only an operator unblock clears it."""

    retryable = False


class QuotaExceededError(RuntimeError):
    """A token bucket ran dry. Retryable after ``retry_after_s``."""

    retryable = True

    def __init__(self, msg: str, scope: str, retry_after_s: float) -> None:
        super().__init__(msg)
        self.scope = scope
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Classic refill-on-demand bucket; rate 0 means 'always empty'."""

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
        self.tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def reconfigure(self, rate: float, burst: Optional[float] = None) -> None:
        with self._lock:
            self.rate = float(rate)
            self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
            # an operator changing the rate grants a fresh allowance —
            # keeping a drained bucket would delay the new rate's effect
            self.tokens = self.burst
            self._last = time.monotonic()

    def _refill_locked(self) -> None:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self._last) * self.rate)
        self._last = now

    def try_consume(self, n: float = 1.0) -> float:
        """0.0 on success; else seconds until ``n`` tokens will exist
        (inf for a zero-rate bucket)."""
        with self._lock:
            self._refill_locked()
            if self.tokens >= n:
                self.tokens -= n
                return 0.0
            if self.rate <= 0:
                return float("inf")
            return (n - self.tokens) / self.rate

    def peek(self, n: float = 1.0) -> float:
        """Like ``try_consume`` but without debiting."""
        with self._lock:
            self._refill_locked()
            if self.tokens >= n:
                return 0.0
            if self.rate <= 0:
                return float("inf")
            return (n - self.tokens) / self.rate

    def refund(self, n: float) -> None:
        with self._lock:
            self.tokens = min(self.burst, self.tokens + n)

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "burst": self.burst,
                    "tokens": round(self.tokens, 3)}


class QuotaManager:
    """Block-list + token buckets, persisted as one JSON document."""

    def __init__(self, persist_path: Optional[str] = None) -> None:
        self._blocked: set[str] = set()
        # (scope_kind, name, quota_kind) -> bucket
        self._buckets: dict[tuple[str, str, str], TokenBucket] = {}
        self._lock = threading.Lock()
        self.persist_path = persist_path
        self._m_rejected = {
            kind: REGISTRY.counter(
                "horaedb_admission_quota_rejected_total",
                "requests rejected by tenant/table token buckets",
                labels={"kind": kind},
            )
            for kind in QUOTA_KINDS
        }
        self._load()

    # ---- block-list (the old Limiter surface, unchanged) ----------------
    def block(self, tables: Iterable[str]) -> None:
        with self._lock:
            self._blocked.update(tables)
        self._save()

    def unblock(self, tables: Iterable[str]) -> None:
        with self._lock:
            self._blocked.difference_update(tables)
        self._save()

    def blocked(self) -> list[str]:
        with self._lock:
            return sorted(self._blocked)

    def check(self, table: Optional[str]) -> None:
        if table is None:
            return
        with self._lock:
            if table in self._blocked:
                raise BlockedError(f"table blocked by limiter: {table}")

    # ---- quotas ----------------------------------------------------------
    def set_quota(
        self,
        scope: str,
        name: str,
        kind: str,
        rate: float,
        burst: Optional[float] = None,
    ) -> None:
        if scope not in SCOPE_KINDS:
            raise ValueError(f"scope must be one of {SCOPE_KINDS}, got {scope!r}")
        if kind not in QUOTA_KINDS:
            raise ValueError(f"kind must be one of {QUOTA_KINDS}, got {kind!r}")
        key = (scope, name, kind)
        with self._lock:
            b = self._buckets.get(key)
            if b is None:
                self._buckets[key] = TokenBucket(rate, burst)
            else:
                b.reconfigure(rate, burst)
        self._save()

    def remove_quota(self, scope: str, name: str, kind: str) -> bool:
        with self._lock:
            removed = self._buckets.pop((scope, name, kind), None) is not None
        self._save()
        return removed

    def _consume_all(self, kind: str, charges: list) -> None:
        """Atomically-ish debit ``[(scope, name, bucket, n), ...]``: peek
        every applicable bucket before debiting ANY of them — a request
        rejected by one bucket must not drain the others (rejections
        would otherwise consume quota), and retries of a rejected batch
        must find their allowance intact."""

        def reject(scope: str, name: str, wait: float) -> QuotaExceededError:
            self._m_rejected[kind].inc()
            from ..utils.events import record_event

            record_event(
                "quota_reject",
                table=name if scope == "table" else "",
                scope=scope, name=name, quota_kind=kind,
            )
            return QuotaExceededError(
                f"{kind} quota exceeded for {scope} {name!r}; "
                f"retry in {min(wait, 60.0):.2f}s",
                scope=f"{scope}:{name}",
                retry_after_s=min(wait, 60.0) if wait != float("inf") else 1.0,
            )

        for scope, name, bucket, n in charges:
            wait = bucket.peek(n)
            if wait > 0:
                raise reject(scope, name, wait)
        taken: list = []
        for scope, name, bucket, n in charges:
            wait = bucket.try_consume(n)
            if wait > 0:
                # raced another charger between peek and consume: refund
                # what this request already took and reject
                for b, m in taken:
                    b.refund(m)
                raise reject(scope, name, wait)
            taken.append((bucket, n))

    def _charge(self, kind: str, tenant: str, table: Optional[str], n: float) -> None:
        charges = []
        with self._lock:
            for scope, name in (("tenant", tenant), ("table", table)):
                if name is None:
                    continue
                b = self._buckets.get((scope, name, kind))
                if b is not None:
                    charges.append((scope, name, b, n))
        self._consume_all(kind, charges)

    def charge_read(self, tenant: str, table: Optional[str]) -> None:
        self._charge("read_qps", tenant, table, 1.0)

    def charge_write(self, tenant: str, table: Optional[str], rows: int) -> None:
        self._charge("write_rows", tenant, table, float(rows))

    def charge_write_batch(self, tenant: str, counts: dict) -> None:
        """Charge a multi-table ingest batch (Influx line protocol,
        OpenTSDB put) as ONE all-or-nothing debit: the tenant bucket is
        peeked for the batch total and every table bucket for its share
        before anything is consumed — a rejected batch leaves every
        bucket untouched."""
        charges = []
        with self._lock:
            b = self._buckets.get(("tenant", tenant, "write_rows"))
            if b is not None:
                charges.append(
                    ("tenant", tenant, b, float(sum(counts.values())))
                )
            for table, n in counts.items():
                b = self._buckets.get(("table", table, "write_rows"))
                if b is not None:
                    charges.append(("table", table, b, float(n)))
        self._consume_all("write_rows", charges)

    # ---- persistence -----------------------------------------------------
    def _load(self) -> None:
        if not self.persist_path or not os.path.exists(self.persist_path):
            return
        try:
            with open(self.persist_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            self._blocked = set(doc.get("blocked", []))
            for q in doc.get("quotas", []):
                key = (q["scope"], q["name"], q["kind"])
                self._buckets[key] = TokenBucket(q["rate"], q.get("burst"))
        except (OSError, ValueError, KeyError, TypeError) as e:
            # TypeError included: a hand-edited state file with e.g. a
            # null rate must degrade to a warning, not block node startup
            logger.warning("could not load wlm state %s: %s", self.persist_path, e)

    def _save(self) -> None:
        if not self.persist_path:
            return
        with self._lock:
            doc = {
                "blocked": sorted(self._blocked),
                "quotas": [
                    {"scope": s, "name": n, "kind": k,
                     "rate": b.rate, "burst": b.burst}
                    for (s, n, k), b in sorted(self._buckets.items())
                ],
            }
        tmp = self.persist_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.persist_path)
        except OSError as e:
            logger.warning("could not persist wlm state %s: %s", self.persist_path, e)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "blocked": sorted(self._blocked),
                "quotas": [
                    {"scope": s, "name": n, "kind": k, **b.snapshot()}
                    for (s, n, k), b in sorted(self._buckets.items())
                ],
                "persist_path": self.persist_path,
            }
