"""Workload management: cost-based admission control, in-flight read
dedup, and per-tenant/per-table quotas — the serving-robustness layer
between the proxy and the executor (ref: the reference proxy's
Limiter/hotspot/read-dedup trio; StreamBox-HBM's capacity-aware
admission for why gating arrivals beats queueing them).

One ``WorkloadManager`` per proxy composes the three pieces
(``wlm.admission``, ``wlm.dedup``, ``wlm.quota``). Managers register in
a process-wide weak set so the SQL-queryable virtual table
``system.public.workload`` (table_engine/system.py) and the metrics lint
can observe live state without holding references.

Field-registry discipline (the PR-2 contract): every
``horaedb_admission_*`` family is declared in
``ADMISSION_METRIC_FAMILIES`` below; the lint in
tests/test_observability.py checks each one is registered live, follows
the naming convention, surfaces as rows of ``system.public.workload``,
and is documented in docs/WORKLOAD.md.
"""

from __future__ import annotations

import weakref
from typing import Optional

from .admission import (  # noqa: F401  (re-exports: the subsystem surface)
    AdmissionController,
    COST_HISTORY,
    CLASSES,
    OverloadedError,
    classify_plan,
    current_admission,
    lane_for,
    normalize_shape,
)
from .batch import (  # noqa: F401  (re-exports: the subsystem surface)
    BATCH_METRIC_FAMILIES,
    COHORT_SIZE_BUCKETS,
    CohortBatcher,
    batch_plan_key,
)
from .dedup import ReadDeduper
from .quota import BlockedError, QuotaExceededError, QuotaManager  # noqa: F401

# family -> help; the single source of truth the lint walks.
ADMISSION_METRIC_FAMILIES: dict[str, str] = {
    "horaedb_admission_admitted_total":
        "queries admitted by the workload manager, by class",
    "horaedb_admission_shed_total":
        "queries shed by admission control, by class and reason",
    "horaedb_admission_wait_seconds":
        "time queries spent waiting for an admission slot",
    "horaedb_admission_dedup_total":
        "in-flight read dedup outcomes, by role",
    "horaedb_admission_quota_rejected_total":
        "requests rejected by tenant/table token buckets",
}

# Eager registration: the families exist from the first scrape (and for
# the registry lint / system.public.workload counter rows) even before
# any WorkloadManager is constructed — same discipline as the ledger's
# horaedb_query_* families (utils/querystats).
def _register_families() -> None:
    from ..utils.metrics import REGISTRY

    for c in CLASSES:
        REGISTRY.counter(
            "horaedb_admission_admitted_total",
            ADMISSION_METRIC_FAMILIES["horaedb_admission_admitted_total"],
            labels={"class": c},
        )
        REGISTRY.counter(
            "horaedb_admission_shed_total",
            ADMISSION_METRIC_FAMILIES["horaedb_admission_shed_total"],
            labels={"class": c, "reason": "queue_full"},
        )
    REGISTRY.histogram(
        "horaedb_admission_wait_seconds",
        ADMISSION_METRIC_FAMILIES["horaedb_admission_wait_seconds"],
    )
    for role in ("leader", "follower"):
        REGISTRY.counter(
            "horaedb_admission_dedup_total",
            ADMISSION_METRIC_FAMILIES["horaedb_admission_dedup_total"],
            labels={"role": role},
        )
    for kind in ("read_qps", "write_rows"):
        REGISTRY.counter(
            "horaedb_admission_quota_rejected_total",
            ADMISSION_METRIC_FAMILIES["horaedb_admission_quota_rejected_total"],
            labels={"kind": kind},
        )


_register_families()

_MANAGERS: "weakref.WeakSet[WorkloadManager]" = weakref.WeakSet()


def registered_managers() -> list["WorkloadManager"]:
    """Live managers, for the workload system table / debug surfaces."""
    return list(_MANAGERS)


class WorkloadManager:
    """Admission + dedup + quota behind one handle (one per proxy)."""

    def __init__(
        self,
        total_units: int = 8,
        memory_budget_bytes: int = 1 << 30,
        queue_depth: int = 32,
        deadline_s: float = 5.0,
        dedup_enabled: bool = True,
        persist_path: Optional[str] = None,
        batch_cfg=None,
    ) -> None:
        self.admission = AdmissionController(
            total_units=total_units,
            memory_budget_bytes=memory_budget_bytes,
            queue_depth=queue_depth,
            deadline_s=deadline_s,
        )
        self.dedup = ReadDeduper(enabled=dedup_enabled)
        self.quota = QuotaManager(persist_path=persist_path)
        # cohort batching (wlm/batch): disabled unless [wlm.batch] says
        # otherwise — with it off the read path is exactly the old one
        self.batch = CohortBatcher.from_config(batch_cfg, deduper=self.dedup)
        _MANAGERS.add(self)

    @staticmethod
    def from_limits(
        limits, persist_path: Optional[str] = None, batch_cfg=None
    ) -> "WorkloadManager":
        """Build from a config ``[limits]`` section (utils/config
        LimitsConfig) — or defaults when ``limits`` is None — plus the
        optional ``[wlm.batch]`` section for cohort batching."""
        g = lambda k, d: getattr(limits, k, d) if limits is not None else d  # noqa: E731
        return WorkloadManager(
            total_units=g("admission_slots", 8),
            memory_budget_bytes=g("admission_memory_budget", 1 << 30),
            queue_depth=g("admission_queue_depth", 32),
            deadline_s=g("admission_deadline_s", 5.0),
            dedup_enabled=g("dedup", True),
            persist_path=persist_path,
            batch_cfg=batch_cfg,
        )

    def close(self) -> None:
        _MANAGERS.discard(self)

    def snapshot(self) -> dict:
        """The /debug/workload payload."""
        return {
            "admission": self.admission.snapshot(),
            "dedup": self.dedup.snapshot(),
            "quota": self.quota.snapshot(),
            "batch": self.batch.snapshot(),
        }
