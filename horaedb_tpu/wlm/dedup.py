"""In-flight read dedup — single-flight for identical SELECTs
(ref: proxy/src/read.rs:89,167 + components/notifier RequestNotifiers:
concurrent identical reads coalesce onto one leader execution; followers
await the leader's ``Output`` instead of re-running the scan).

This is the THREAD-level flight table used by the proxy: the HTTP
gateway keeps its own asyncio single-flight in front (one event loop),
but the proxy is also driven from wire-protocol executors, embedded
callers, and multiple gateways — this layer coalesces across all of
them. Both layers feed the same ``horaedb_admission_dedup_total``
family and the workload table.

Read-your-writes survives the dedup: the flight key carries a write
epoch the proxy bumps on every statement that can change visible state,
so a SELECT issued after a write never joins a pre-write execution.

Ledger roles: the leader's ledger records ``dedup_followers`` (how many
twins it served); each follower's records ``dedup_follower=1`` — the
roles are queryable per request in ``system.public.query_stats``.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

from ..utils.metrics import REGISTRY
from ..utils.querystats import record

T = TypeVar("T")


class _Flight:
    __slots__ = ("event", "result", "error", "followers")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.followers = 0


class ReadDeduper:
    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._inflight: dict[tuple, _Flight] = {}
        self._epoch = 0
        self._m_role = {
            role: REGISTRY.counter(
                "horaedb_admission_dedup_total",
                "in-flight read dedup outcomes, by role",
                labels={"role": role},
            )
            for role in ("leader", "follower")
        }

    def bump_epoch(self) -> None:
        """Any statement that may change visible state calls this; later
        reads start a fresh flight (conservative: bumped even when the
        statement ultimately fails)."""
        with self._lock:
            self._epoch += 1

    def epoch(self) -> int:
        """The current write epoch. Shared fencing truth for every
        coalescing layer: the cohort batcher (wlm/batch) keys forming
        cohorts by this value, so a write landing while a cohort gathers
        fences later-arriving members into a fresh cohort — the same
        read-your-writes contract the flight table gets from carrying
        the epoch in its key."""
        with self._lock:
            return self._epoch

    def run(self, sql_key: str, fn: Callable[[], T]) -> T:
        """Execute ``fn`` single-flight per (epoch, sql_key). The leader
        runs it; concurrent twins block on the leader's result (or
        re-raise its exception)."""
        if not self.enabled:
            return fn()
        with self._lock:
            key = (self._epoch, sql_key)
            flight = self._inflight.get(key)
            if flight is None:
                flight = _Flight()
                self._inflight[key] = flight
                leader = True
            else:
                flight.followers += 1
                leader = False
        if not leader:
            self._m_role["follower"].inc()
            record(dedup_follower=1)
            # the leader always resolves the flight in its finally; the
            # long timeout is a defensive bound, not a protocol step —
            # but if it ever fires, answer with a typed retryable error
            # instead of handing back a None "result". Sliced waits:
            # the follower observes ITS OWN deadline/cancel flag
            # (utils/deadline) while the leader runs — a tight-budget
            # follower unwinds with its typed error instead of riding
            # a slower leader past its deadline.
            import time as _time

            from ..utils.deadline import current_deadline

            budget = current_deadline()
            bound = _time.monotonic() + 300
            while not flight.event.wait(0.25):
                if budget is not None:
                    budget.check("executing")
                if _time.monotonic() >= bound:
                    from .admission import OverloadedError

                    raise OverloadedError(
                        "in-flight twin did not complete within 300s; retry",
                        reason="dedup_timeout",
                        retry_after_s=1.0,
                    )
            if flight.error is not None:
                raise self._follower_error(flight.error)
            return flight.result
        followers = 0
        try:
            flight.result = fn()
            return flight.result
        except BaseException as e:
            flight.error = e
            raise
        finally:
            with self._lock:
                # only new arrivals AFTER this pop start a fresh flight
                if self._inflight.get(key) is flight:
                    del self._inflight[key]
                followers = flight.followers
            flight.event.set()
            if followers:
                self._m_role["leader"].inc()
                record(dedup_followers=followers)

    @staticmethod
    def _follower_error(err: BaseException) -> BaseException:
        """The error a follower should surface for a leader-side
        failure. A leader that was CANCELLED (KILL/disconnect) or died
        to ITS deadline must not leak that personal ending to followers
        who never cancelled and carry their own budgets — they get a
        typed, retryable overload instead (a retry starts a fresh
        flight)."""
        from ..utils.deadline import DeadlineExceeded, QueryCancelled

        if isinstance(err, QueryCancelled):
            from .admission import OverloadedError

            return OverloadedError(
                "the in-flight leader serving this read was cancelled; "
                "retry starts a fresh execution",
                reason="dedup_leader_cancelled",
                retry_after_s=0.1,
            )
        if isinstance(err, DeadlineExceeded):
            from .admission import OverloadedError

            return OverloadedError(
                "the in-flight leader serving this read exceeded ITS "
                "time budget; retry starts a fresh execution",
                reason="dedup_leader_timeout",
                retry_after_s=0.1,
            )
        return err

    def note_coalesced(self, n: int = 1) -> None:
        """An upstream single-flight layer (the gateway's asyncio dedup)
        served ``n`` follower(s) — count them in the same family so the
        workload table reflects every coalesced read."""
        self._m_role["follower"].inc(n)

    def snapshot(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
            waiting = sum(f.followers for f in self._inflight.values())
            epoch = self._epoch
        return {
            "inflight_leaders": inflight,
            "waiting_followers": waiting,
            "write_epoch": epoch,
            "enabled": self.enabled,
        }
