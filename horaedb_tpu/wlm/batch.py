"""Cohort batching — the dashboard flood as ONE device dispatch.

``wlm/dedup`` single-flights *identical* SELECTs; this layer generalizes
it: in-flight queries that share a normalized plan shape but differ in
their literals (the same dashboard SELECT asked for thousands of
tenants/hosts/time windows at once) gather for a micro-batching window,
then the whole cohort is served by one fused kernel call — the packed
cached scan-agg kernel vmapped over a ``[B, ...]`` params axis
(ops/scan_agg.cached_scan_agg_cohort), each member's literals hoisted
into its row of the batched session/dyn uploads.

Correctness rails:

- **per-query demux**: every member gets its own ResultSet assembled
  from its slice of the batched kernel state — mixed LIMITs/ORDER BYs
  within one shape apply per member, after the shared dispatch;
- **error isolation**: the cohort executor returns one outcome PER
  member; a member whose execution fails raises only to its own caller
  (and a wholesale fused failure falls back to per-member solo
  execution inside the executor);
- **read-your-writes**: the cohort key carries the dedup write epoch —
  a write landing while a cohort is forming fences later-arriving
  members into a fresh cohort (wlm/dedup.ReadDeduper.epoch);
- **degenerate cohorts**: a window that gathers only one unique query
  executes through today's solo path (dedup single-flight + admission)
  with no extra dispatch;
- **identical twins**: members with the SAME sql coalesce onto one
  cohort slot (the dedup contract survives inside the batch layer; the
  twins count into the ``horaedb_admission_dedup_total`` family).

Ledger roles mirror dedup's: the leader's ledger records
``batch_leader`` (cohort size) and every participant records
``batch_cohort``; non-leader members record ``batch_member=1`` — all
queryable per request in ``system.public.query_stats``.

Field-registry discipline (the PR-2 contract): every
``horaedb_batch_*`` family is declared in ``BATCH_METRIC_FAMILIES``
below and linted in tests/test_observability.py.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils.metrics import REGISTRY
from ..utils.querystats import record

# family -> help; the single source of truth the registry lint walks.
BATCH_METRIC_FAMILIES: dict[str, str] = {
    "horaedb_batch_dispatch_total":
        "batched-serving dispatch outcomes, by kind (fused cohort vs solo)",
    "horaedb_batch_cohort_total":
        "fused cohorts served, by cohort-size bucket",
    "horaedb_batch_window_wait_seconds":
        "time queries spent gathering in the micro-batching window",
}

# cohort-size histogram as a bucket-labeled counter (the metrics lint
# reserves histogram suffixes for real units; sizes bucket cleanly)
COHORT_SIZE_BUCKETS = ("1", "2", "4", "8", "16", "32+")


def _size_bucket(n: int) -> str:
    for b in ("1", "2", "4", "8", "16"):
        if n <= int(b):
            return b
    return "32+"


def _register_families() -> None:
    for kind in ("fused", "solo"):
        REGISTRY.counter(
            "horaedb_batch_dispatch_total",
            BATCH_METRIC_FAMILIES["horaedb_batch_dispatch_total"],
            labels={"kind": kind},
        )
    for b in COHORT_SIZE_BUCKETS:
        REGISTRY.counter(
            "horaedb_batch_cohort_total",
            BATCH_METRIC_FAMILIES["horaedb_batch_cohort_total"],
            labels={"size": b},
        )
    REGISTRY.histogram(
        "horaedb_batch_window_wait_seconds",
        BATCH_METRIC_FAMILIES["horaedb_batch_window_wait_seconds"],
    )


_register_families()


def _member_error(err: BaseException) -> BaseException:
    """What non-leader members see for a wholesale cohort failure. A
    leader cancelled (KILL/disconnect) or dead to ITS deadline is a
    leader-personal ending — members who never cancelled and carry
    their own budgets get a typed retryable overload instead (a retry
    forms or joins a fresh cohort)."""
    from ..utils.deadline import DeadlineExceeded, QueryCancelled
    from .admission import OverloadedError

    if isinstance(err, QueryCancelled):
        return OverloadedError(
            "the cohort leader serving this read was cancelled; retry "
            "forms a fresh cohort",
            reason="batch_leader_cancelled",
            retry_after_s=0.1,
        )
    if isinstance(err, DeadlineExceeded):
        return OverloadedError(
            "the cohort leader serving this read exceeded ITS time "
            "budget; retry forms a fresh cohort",
            reason="batch_leader_timeout",
            retry_after_s=0.1,
        )
    return err


def batch_plan_key(plan) -> tuple:
    """Normalized plan-shape key for cohort grouping: the path router's
    literal-masked shape with LIMIT/OFFSET additionally masked (mixed
    LIMITs demux per member AFTER the shared dispatch, so they must not
    split a cohort)."""
    import dataclasses

    from ..query.path_router import _shape

    sel = dataclasses.replace(plan.select, limit=None, offset=0)
    return (plan.table, _shape(sel))


class _Member:
    """One unique SQL within a forming cohort. Identical-SQL arrivals
    share the slot (waiters beyond the first are dedup twins)."""

    __slots__ = ("sql", "plan", "event", "result", "error", "twins")

    def __init__(self, sql: str, plan) -> None:
        self.sql = sql
        self.plan = plan
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.twins = 0


class _Cohort:
    __slots__ = ("members", "closed", "full", "created", "closed_at")

    def __init__(self) -> None:
        self.members: dict[str, _Member] = {}
        self.closed = False
        self.full = threading.Event()  # set when max_cohort is reached
        self.created = time.perf_counter()
        self.closed_at = 0.0


class CohortBatcher:
    """The micro-batching window in front of the dedup/admission path.

    ``run`` is the one entry point: the first arrival for a (epoch,
    shape) key leads — it waits the window (cut short when the cohort
    fills), then either executes solo (single unique member) or hands
    the whole cohort to ``cohort_exec`` for one fused dispatch; joiners
    block on their member slot and get their own demuxed result (or
    their own error)."""

    def __init__(
        self,
        enabled: bool = False,
        window_s: float = 0.002,
        max_cohort: int = 32,
        shapes: tuple = (),
        deduper=None,
    ) -> None:
        self.enabled = enabled
        self.window_s = float(window_s)
        self.max_cohort = max(2, int(max_cohort))
        self.shapes = tuple(shapes or ())
        self.deduper = deduper
        self._lock = threading.Lock()
        self._forming: dict[tuple, _Cohort] = {}
        self._m_dispatch = {
            kind: REGISTRY.counter(
                "horaedb_batch_dispatch_total",
                BATCH_METRIC_FAMILIES["horaedb_batch_dispatch_total"],
                labels={"kind": kind},
            )
            for kind in ("fused", "solo")
        }
        self._m_cohort = {
            b: REGISTRY.counter(
                "horaedb_batch_cohort_total",
                BATCH_METRIC_FAMILIES["horaedb_batch_cohort_total"],
                labels={"size": b},
            )
            for b in COHORT_SIZE_BUCKETS
        }
        self._m_wait = REGISTRY.histogram(
            "horaedb_batch_window_wait_seconds",
            BATCH_METRIC_FAMILIES["horaedb_batch_window_wait_seconds"],
        )

    @staticmethod
    def from_config(batch_cfg, deduper=None) -> "CohortBatcher":
        """Build from a config [wlm.batch] section (utils/config
        BatchSection) — or defaults (disabled) when ``batch_cfg`` is
        None."""
        g = lambda k, d: getattr(batch_cfg, k, d) if batch_cfg is not None else d  # noqa: E731
        return CohortBatcher(
            enabled=g("enabled", False),
            window_s=g("window_s", 0.002),
            max_cohort=g("max_cohort", 32),
            shapes=tuple(g("shapes", ()) or ()),
            deduper=deduper,
        )

    def eligible(self, plan, shape_sql: str) -> bool:
        """Cheap proxy-side probe: may this SELECT gather in a cohort?
        Conservative — a wrong yes only costs the window wait (the
        executor falls back to solo execution for members it cannot
        fuse); a wrong no just skips batching."""
        if not self.enabled:
            return False
        sel = getattr(plan, "select", None)
        if sel is None or sel.join is not None or sel.ctes:
            return False
        if not getattr(plan, "is_aggregate", False):
            return False  # the fused cohort kernel serves agg shapes
        table = getattr(plan, "table", "") or ""
        if table.lower().startswith("system"):
            return False  # introspection answers about the asking moment
        if self.shapes and not any(s in shape_sql for s in self.shapes):
            return False
        return True

    def run(
        self,
        key: tuple,
        sql: str,
        plan,
        solo: Callable[[], object],
        cohort_exec: Callable[[list], list],
    ):
        """Serve one query through the batching window.

        ``key`` must already carry the write epoch (read-your-writes
        fencing). ``solo`` is today's full path (dedup single-flight +
        admission + execute); ``cohort_exec`` takes the list of unique
        ``(sql, plan)`` members and returns one Output-or-exception per
        member, positionally."""
        if not self.enabled:
            return solo()
        t_join = time.perf_counter()
        with self._lock:
            cohort = self._forming.get(key)
            if cohort is not None and not cohort.closed:
                member = cohort.members.get(sql)
                if member is not None:
                    member.twins += 1
                    joined: Optional[_Member] = member
                    twin = True
                elif len(cohort.members) < self.max_cohort:
                    member = _Member(sql, plan)
                    cohort.members[sql] = member
                    if len(cohort.members) >= self.max_cohort:
                        cohort.full.set()  # cut the leader's window short
                    joined = member
                    twin = False
                else:  # full but not yet closed: lead a fresh cohort
                    joined = None
                    twin = False
            else:
                joined = None
                twin = False
            if joined is None:
                cohort = _Cohort()
                leader_member = _Member(sql, plan)
                cohort.members[sql] = leader_member
                self._forming[key] = cohort

        if joined is not None:
            return self._await_member(cohort, joined, twin, t_join)

        # ---- leader: gather, close, dispatch ----------------------------
        cohort.full.wait(self.window_s)
        with self._lock:
            cohort.closed = True
            cohort.closed_at = time.perf_counter()
            if self._forming.get(key) is cohort:
                del self._forming[key]
            members = list(cohort.members.values())
        self._m_wait.observe(cohort.closed_at - t_join)
        n = len(members)
        if n == 1:
            # Degenerate cohort: today's path, no extra dispatch. Twins
            # (identical SQL that joined during the window) ride the
            # leader's execution exactly like dedup followers.
            self._m_dispatch["solo"].inc()
            self._m_cohort["1"].inc()
            m = members[0]
            try:
                m.result = solo()
            except BaseException as e:
                m.error = e
                raise
            finally:
                m.event.set()
                if m.twins and self.deduper is not None:
                    record(dedup_followers=m.twins)
            return m.result
        self._m_dispatch["fused"].inc()
        self._m_cohort[_size_bucket(n)].inc()
        record(batch_leader=n, batch_cohort=n)
        try:
            outcomes = cohort_exec([(m.sql, m.plan) for m in members])
        except BaseException as e:
            # wholesale failure (admission shed, runtime teardown):
            # every member sees the same error — EXCEPT a leader-
            # personal ending (its KILL, its deadline), which other
            # members must not inherit: they get the typed retryable
            # overload instead (same contract as dedup followers)
            member_err = _member_error(e)
            for m in members:
                m.error = e if m is members[0] else member_err
                m.event.set()
            raise
        for m, out in zip(members, outcomes):
            if isinstance(out, BaseException):
                m.error = out
            else:
                m.result = out
            m.event.set()
            if m.twins and self.deduper is not None:
                record(dedup_followers=m.twins)
        mine = members[0]
        if mine.error is not None:
            raise mine.error
        return mine.result

    def _await_member(self, cohort: _Cohort, member: _Member, twin: bool,
                      t_join: float):
        if twin and self.deduper is not None:
            # same contract as a dedup follower: one execution serves us
            self.deduper.note_coalesced()
            record(dedup_follower=1)
        # the leader always resolves every member in its finally; the
        # long timeout is a defensive bound, not a protocol step.
        # Sliced waits: a member observes ITS OWN deadline/cancel flag
        # while the cohort gathers/dispatches — a cancelled or expired
        # member demuxes out with its typed error and the cohort
        # SURVIVES (the leader still resolves every other slot; this
        # member's result is simply never consumed).
        from ..utils.deadline import current_deadline

        budget = current_deadline()
        bound = time.monotonic() + 300
        while not member.event.wait(0.25):
            if budget is not None:
                budget.check("executing")
            if time.monotonic() >= bound:
                from .admission import OverloadedError

                raise OverloadedError(
                    "cohort leader did not complete within 300s; retry",
                    reason="batch_timeout",
                    retry_after_s=1.0,
                )
        waited = max(0.0, (cohort.closed_at or time.perf_counter()) - t_join)
        self._m_wait.observe(waited)
        if len(cohort.members) > 1:
            record(batch_member=1, batch_cohort=len(cohort.members))
        if member.error is not None:
            # joiners (members and identical twins) never surface the
            # LEADER's personal ending (its kill, its deadline) — the
            # converter passes every other error through untouched
            raise _member_error(member.error)
        return member.result

    def snapshot(self) -> dict:
        with self._lock:
            forming = len(self._forming)
            gathering = sum(
                len(c.members) for c in self._forming.values()
            )
        return {
            "enabled": self.enabled,
            "window_s": self.window_s,
            "max_cohort": self.max_cohort,
            "shapes": list(self.shapes),
            "forming_cohorts": forming,
            "gathering_members": gathering,
        }
