"""Cost-based admission control (ref: the reference proxy's limiter /
priority runtime split, and StreamBox-HBM's capacity-aware admission —
an analytic engine only stays at hardware speed under overload when
arrivals are gated against what the hardware can actually hold).

Three pieces:

- ``classify_plan``: each ``QueryPlan`` is classified cheap / normal /
  expensive from planner shape (time-range span, aggregate-ness, the
  planner's own priority demotion) blended with an EWMA over the
  observed latency of the same *normalized SQL shape* (literals
  stripped) — the same signal ``system.public.query_stats`` records.
  Three observations of a shape outrank the static guess: a full-range
  ``count(*)`` over a tiny table stops hogging the expensive lane.

- ``AdmissionController``: weighted concurrency slots plus a memory
  budget. Each class costs a number of slot units and an estimated
  working-set size; admission blocks on a bounded per-class wait queue
  with a deadline, and sheds with a typed, retryable
  ``OverloadedError`` when the queue is full or the deadline passes.
  Non-cheap load (normal + expensive together) is additionally capped
  below the total so neither a scan storm nor a dashboard-aggregate
  storm can occupy every slot — a cheap query always has a unit to
  claim (the acceptance contract).

- Cross-node propagation: ``admit()`` publishes the admitted class in a
  ContextVar (``current_admission()``); the remote-engine client ships
  it beside the trace/ledger context so partition owners run
  PartialAgg/ExecutePlan on the matching PriorityRuntime lane and apply
  their own gate.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from contextlib import contextmanager
from typing import Optional

from ..utils.metrics import REGISTRY

CLASSES = ("cheap", "normal", "expensive")

# slot units one admitted query of each class occupies
WEIGHTS = {"cheap": 1, "normal": 2, "expensive": 3}

# working-set estimate per class, charged against the memory budget
MEM_ESTIMATES = {
    "cheap": 16 << 20,
    "normal": 64 << 20,
    "expensive": 256 << 20,
}

# EWMA thresholds: an observed shape faster than CHEAP_MS is cheap, one
# slower than EXPENSIVE_MS is expensive, regardless of static shape.
CHEAP_MS = 50.0
EXPENSIVE_MS = 500.0

# observations of a shape before the EWMA outranks the static class
HISTORY_MIN_SAMPLES = 3


# rides a gRPC RESOURCE_EXHAUSTED status detail when (and only when) a
# serving-side admission gate shed the call — the remote client maps
# marked errors back to a retryable OverloadedError, and ONLY those
# (grpc uses the same status for e.g. message-size overflow)
SHED_MARKER = "admission shed"


def lane_for(admission_class: str) -> str:
    """The PriorityRuntime lane an admission class executes on."""
    return "low" if admission_class == "expensive" else "high"


class OverloadedError(RuntimeError):
    """Admission control shed this request. Retryable by contract: the
    node is healthy, just full — clients should back off and retry
    (HTTP maps it to 503 + Retry-After, MySQL to errno 1040, PG to
    SQLSTATE 53300)."""

    retryable = True

    def __init__(self, msg: str, reason: str, retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


# ---- SQL shape normalization + EWMA cost history --------------------------

_NUM_RE = re.compile(r"\b\d+(\.\d+)?([eE][+-]?\d+)?\b")
_STR_RE = re.compile(r"'(?:[^']|'')*'")
_WS_RE = re.compile(r"\s+")


def normalize_shape(sql: str) -> str:
    """Literal-insensitive shape key: ``SELECT v FROM t WHERE ts > 5``
    and ``... ts > 9`` share one cost history entry."""
    s = _STR_RE.sub("?", sql)
    s = _NUM_RE.sub("?", s)
    return _WS_RE.sub(" ", s).strip().lower()


class CostHistory:
    """EWMA of observed latency per normalized SQL shape, bounded LRU.

    Misses bootstrap lazily from the query_stats ring (the durable-ish
    record of recent shapes), so a restarted proxy — or the EXPLAIN
    path, which never executes through the proxy — still benefits from
    whatever history the node has."""

    def __init__(self, capacity: int = 1024, alpha: float = 0.3) -> None:
        from collections import OrderedDict

        self.capacity = capacity
        self.alpha = alpha
        self._ewma: "OrderedDict[str, tuple[float, int]]" = OrderedDict()
        self._lock = threading.Lock()

    def observe(self, shape: str, elapsed_s: float) -> None:
        ms = elapsed_s * 1000.0
        with self._lock:
            prev = self._ewma.pop(shape, None)
            if prev is None or prev[1] == 0:  # fresh (or negative-cached)
                self._ewma[shape] = (ms, 1)
            else:
                est, n = prev
                self._ewma[shape] = (est + self.alpha * (ms - est), n + 1)
            while len(self._ewma) > self.capacity:
                self._ewma.popitem(last=False)

    def estimate_ms(self, shape: str) -> Optional[tuple[float, int]]:
        """(ewma_ms, samples) for the shape, or None when never seen."""
        with self._lock:
            got = self._ewma.get(shape)
            if got is not None:
                self._ewma.move_to_end(shape)
                return got if got[1] > 0 else None
        self._bootstrap(shape)
        with self._lock:
            got = self._ewma.get(shape)
            if got is None:
                # negative cache: one O(ring) bootstrap scan per shape,
                # ever — the admission hot path must not re-pay it on
                # every miss (samples=0 means "known absent")
                self._ewma[shape] = (0.0, 0)
                while len(self._ewma) > self.capacity:
                    self._ewma.popitem(last=False)
                return None
            return got if got[1] > 0 else None

    def _bootstrap(self, shape: str) -> None:
        from ..utils.querystats import STATS_STORE

        for row in STATS_STORE.list():
            sql = row.get("sql")
            if sql and normalize_shape(sql) == shape:
                self.observe(shape, float(row.get("duration_ms", 0.0)) / 1000.0)


COST_HISTORY = CostHistory()


def classify_plan(plan, shape: Optional[str] = None) -> tuple[str, Optional[float]]:
    """(admission class, ewma estimate ms or None) for a QueryPlan.

    Static shape first (the planner's long-range demotion, aggregates);
    a seasoned EWMA for the normalized shape overrides it entirely —
    history beats heuristics once there is enough of it."""
    prio = getattr(getattr(plan, "priority", None), "value", "high")
    static = "expensive" if prio == "low" else (
        "normal" if getattr(plan, "is_aggregate", False) else "cheap"
    )
    if shape is None:
        return static, None
    got = COST_HISTORY.estimate_ms(shape)
    if got is None:
        return static, None
    est_ms, samples = got
    if samples < HISTORY_MIN_SAMPLES:
        return static, est_ms
    if est_ms >= EXPENSIVE_MS:
        return "expensive", est_ms
    if est_ms < CHEAP_MS:
        return "cheap", est_ms
    return "normal", est_ms


# ---- the controller -------------------------------------------------------

_current_admission: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "horaedb_admission_class", default=None
)


def current_admission() -> Optional[str]:
    """The admission class of the currently-executing query (rides the
    context to pool threads and out over remote RPC envelopes)."""
    return _current_admission.get()


class AdmissionController:
    """Weighted slots + memory budget with bounded per-class wait queues.

    ``total_units`` is the node's concurrency capital; a query of class
    c costs WEIGHTS[c] units and MEM_ESTIMATES[c] budget bytes.
    Non-cheap load (normal + expensive together) is capped at
    ``total_units - 1`` units in use — the cheap lane can never be
    fully starved, whatever the mix — and expensive alone is held to
    the same cap so it can't crowd out normal either."""

    def __init__(
        self,
        total_units: int = 8,
        memory_budget_bytes: int = 1 << 30,
        queue_depth: int = 32,
        deadline_s: float = 5.0,
    ) -> None:
        # floor: one expensive admit plus the cheap reserve must fit, or
        # an idle controller could never admit an expensive query and a
        # small-slots config would shed them forever
        self.total_units = max(WEIGHTS["expensive"] + 1, int(total_units))
        self.memory_budget_bytes = int(memory_budget_bytes)
        self.queue_depth = int(queue_depth)
        self.deadline_s = float(deadline_s)
        # expensive can never occupy the last unit (cheap reserve)
        self.expensive_cap = self.total_units - 1
        self._cv = threading.Condition()
        self._units_in_use = 0
        self._mem_in_use = 0
        self._class_units = dict.fromkeys(CLASSES, 0)
        self._waiting = dict.fromkeys(CLASSES, 0)
        self._admitted = {
            c: REGISTRY.counter(
                "horaedb_admission_admitted_total",
                "queries admitted by the workload manager, by class",
                labels={"class": c},
            )
            for c in CLASSES
        }
        self._wait_hist = REGISTRY.histogram(
            "horaedb_admission_wait_seconds",
            "time queries spent waiting for an admission slot",
        )

    def _shed_counter(self, cls: str, reason: str):
        return REGISTRY.counter(
            "horaedb_admission_shed_total",
            "queries shed by admission control, by class and reason",
            labels={"class": cls, "reason": reason},
        )

    def _fits_locked(self, cls: str, units: int, mem: int) -> bool:
        if self._units_in_use + units > self.total_units:
            return False
        if cls != "cheap":
            # the cheap reserve holds against ALL non-cheap load (a
            # normal-class dashboard storm must not starve point
            # lookups either): non-cheap units collectively stay below
            # the total, and one cheap-sized slice of the memory budget
            # is untouchable
            noncheap = self._units_in_use - self._class_units["cheap"]
            if noncheap + units > self.total_units - 1:
                return False
            if self._mem_in_use + mem > self.memory_budget_bytes - MEM_ESTIMATES["cheap"]:
                return False
        elif self._mem_in_use + mem > self.memory_budget_bytes:
            return False
        if cls == "expensive" and self._class_units[cls] + units > self.expensive_cap:
            return False
        return True

    def _shed(self, cls: str, reason: str, msg: str) -> OverloadedError:
        self._shed_counter(cls, reason).inc()
        from ..utils.events import record_event

        record_event("admission_shed", **{"class": cls, "reason": reason})
        return OverloadedError(msg, reason=reason, retry_after_s=1.0)

    @contextmanager
    def admit(self, cls: str, deadline_s: Optional[float] = None,
              est_cost_s: Optional[float] = None,
              shape: Optional[str] = None):
        """Block until a slot frees (bounded queue + deadline), then run
        the body holding the slot. Records the queue wait into the
        current query ledger (``admission_wait_seconds``).

        The request's time budget (utils/deadline) is CHARGED here:
        queue wait never outlives the remaining budget, a budget that
        cannot fit the shape's expected cost (``est_cost_s``, the
        classifier's EWMA estimate) sheds immediately instead of
        queueing doomed work, and a KILL observed while queued unwinds
        without ever taking the slot. The slot-release invariant holds
        by construction: the slot is only held inside this context
        manager's try/finally, so a typed deadline/cancel raise from
        the body always releases it."""
        if cls not in WEIGHTS:
            cls = "normal"
        units = WEIGHTS[cls]
        mem = MEM_ESTIMATES[cls]
        deadline_s = self.deadline_s if deadline_s is None else deadline_s
        from ..utils.deadline import current_deadline

        budget = current_deadline()
        if budget is not None:
            budget.check("queued")
            rem = budget.remaining_s()
            if rem is not None:
                if est_cost_s is not None and rem < est_cost_s:
                    # the remaining budget cannot fit the expected cost:
                    # shed NOW — queueing (and then executing most of)
                    # work that is provably going to time out only
                    # burns the slot another query could use
                    from ..utils.deadline import DeadlineExceeded
                    from ..utils.events import record_event

                    self._shed_counter(cls, "deadline_budget").inc()
                    record_event(
                        "admission_shed",
                        **{"class": cls, "reason": "deadline_budget"},
                    )
                    # Decision plane: was this shed provably doomed?
                    # Journaled with the predicted cost + remaining
                    # budget; the proxy resolves it when a later
                    # same-shape query completes (actual seconds >=
                    # the remaining budget here -> "doomed", else the
                    # shed was premature and the estimator is graded
                    # by the signed error either way.
                    from ..obs.decisions import record_decision

                    record_decision(
                        "deadline",
                        key=shape if shape else cls,
                        choice="shed",
                        features={
                            "class": cls,
                            "remaining_s": round(rem, 6),
                            "budget_ms": budget.budget_ms or 0,
                        },
                        predicted=est_cost_s,
                    )
                    raise DeadlineExceeded(
                        f"remaining budget {rem * 1000:.0f}ms cannot fit "
                        f"the expected {est_cost_s * 1000:.0f}ms cost of "
                        f"this {cls} query",
                        stage="queued",
                        budget_ms=budget.budget_ms,
                    )
                deadline_s = min(deadline_s, rem)
            budget.state = "queued"
        t0 = time.perf_counter()
        deadline = t0 + deadline_s
        with self._cv:
            if not self._fits_locked(cls, units, mem):
                if self._waiting[cls] >= self.queue_depth:
                    raise self._shed(
                        cls, "queue_full",
                        f"admission queue for class {cls!r} is full "
                        f"({self.queue_depth} waiting); retry later",
                    )
                self._waiting[cls] += 1
                try:
                    while not self._fits_locked(cls, units, mem):
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            if budget is not None:
                                # the BUDGET ran out first: the typed
                                # 504, not a generic overload shed
                                budget.check("queued")
                            raise self._shed(
                                cls, "deadline",
                                f"no admission slot for class {cls!r} "
                                f"query within {deadline_s:.1f}s; "
                                "retry later",
                            )
                        # sliced waits: a KILL while queued unwinds
                        # within a checkpoint interval, not at the
                        # admission deadline
                        self._cv.wait(min(remaining, 0.25))
                        if budget is not None:
                            budget.check("queued")
                finally:
                    self._waiting[cls] -= 1
            self._units_in_use += units
            self._mem_in_use += mem
            self._class_units[cls] += units
        if budget is not None:
            budget.state = "executing"
        waited = time.perf_counter() - t0
        self._wait_hist.observe(waited)
        self._admitted[cls].inc()
        from ..utils.querystats import record

        record(admission_wait_seconds=waited)
        token = _current_admission.set(cls)
        try:
            yield
        finally:
            _current_admission.reset(token)
            with self._cv:
                self._units_in_use -= units
                self._mem_in_use -= mem
                self._class_units[cls] -= units
                self._cv.notify_all()

    def snapshot(self) -> dict:
        """Live state for /debug/workload + system.public.workload."""
        with self._cv:
            return {
                "total_units": self.total_units,
                "units_in_use": self._units_in_use,
                "memory_budget_bytes": self.memory_budget_bytes,
                "memory_in_use_bytes": self._mem_in_use,
                "expensive_cap": self.expensive_cap,
                "class_units": dict(self._class_units),
                "queue_depth": dict(self._waiting),
                "queue_limit": self.queue_depth,
                "deadline_s": self.deadline_s,
            }
