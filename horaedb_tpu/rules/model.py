"""Rule definitions: PromQL recording rules and alert rules
(ref: prometheus's rule groups — recording rules materialize an
expression as a new series under a stable name; alert rules evaluate an
expression and manage a pending->firing->resolved lifecycle per result
series. StreamBox-HBM's stance, PAPERS.md: continuous queries over the
hybrid-memory stream ARE the serving workload, not an external scraper's
job).

One ``Rule`` dataclass carries both kinds; config lines use the compact
``NAME := EXPR [for DURATION]`` form (TOML-subset-friendly inline string
arrays), the runtime ``/admin/rules`` endpoint takes the same fields as
JSON. Rule names double as output table names (recording) and alertname
labels (alerts), so they are restricted to SQL-safe identifiers — the
PromQL selector for a recording rule's output is then just its name.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..engine.options import parse_duration_ms
from ..proxy.promql import PromQLError, parse_promql

# SQL-safe so the output table needs no quoting on any wire (and so a
# remote CREATE TABLE IF NOT EXISTS forward round-trips the parser).
_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

_FOR_TAIL = re.compile(r"\s+for\s+(\d+(?:ms|s|m|h|d))\s*$")
_EVERY_TAIL = re.compile(r"\s+every\s+(\d+(?:ms|s|m|h|d))\s*$")


class RuleError(ValueError):
    pass


@dataclass
class Rule:
    """One recording or alert rule.

    ``for_s`` (alerts only): how long the expression must keep returning
    a series before that series transitions pending -> firing.
    ``every_s``: per-rule evaluation cadence — 0 means "every engine
    round" ([rules] eval_interval); a larger value makes the engine skip
    rounds until the interval elapses (an expensive daily recording rule
    must not re-run every 15s). Effective cadence is therefore
    max(eval_interval, every).
    ``source``: "config" rules reload from the config file each start and
    cannot be removed at runtime; "runtime" rules persist in the rules
    state file beside ``wlm_state.json``.
    """

    name: str
    expr: str
    kind: str = "recording"  # "recording" | "alert"
    for_s: float = 0.0
    every_s: float = 0.0
    labels: dict[str, str] = field(default_factory=dict)
    source: str = "config"  # "config" | "runtime"

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "expr": self.expr,
            "kind": self.kind,
            "for_s": self.for_s,
            "every_s": self.every_s,
            "labels": dict(self.labels),
            "source": self.source,
        }


def validate_rule(rule: Rule) -> Rule:
    """Fail loudly at load/add time, not at the first evaluation."""
    if rule.kind not in ("recording", "alert"):
        raise RuleError(f"rule {rule.name!r}: kind must be recording|alert")
    if not _NAME_RE.match(rule.name or ""):
        raise RuleError(
            f"rule name {rule.name!r} must match [A-Za-z_][A-Za-z0-9_]* "
            "(it names the output table / alertname)"
        )
    if rule.for_s < 0:
        raise RuleError(f"rule {rule.name!r}: negative for duration")
    if rule.every_s < 0:
        raise RuleError(f"rule {rule.name!r}: negative every interval")
    if rule.kind == "recording" and rule.for_s:
        raise RuleError(f"recording rule {rule.name!r} takes no for duration")
    try:
        parse_promql(rule.expr)
    except PromQLError as e:
        raise RuleError(f"rule {rule.name!r}: bad expr: {e}") from None
    if not isinstance(rule.labels, dict) or not all(
        isinstance(k, str) and isinstance(v, str)
        for k, v in rule.labels.items()
    ):
        raise RuleError(f"rule {rule.name!r}: labels must be str -> str")
    return rule


def parse_rule_line(line: str, kind: str, source: str = "config") -> Rule:
    """``NAME := EXPR [for 30s] [every 15s]`` — the ``[rules]`` config
    line form (``for`` is alert-only; ``every`` sets the per-rule
    evaluation cadence for either kind, trailing the ``for`` tail)."""
    name, sep, expr = line.partition(":=")
    if not sep:
        raise RuleError(
            f"bad rule line {line!r}: expected 'NAME := EXPR'"
        )
    name, expr = name.strip(), expr.strip()
    every_s = 0.0
    m = _EVERY_TAIL.search(expr)
    if m is not None:
        every_s = parse_duration_ms(m.group(1)) / 1000.0
        expr = expr[: m.start()].rstrip()
    for_s = 0.0
    if kind == "alert":
        m = _FOR_TAIL.search(expr)
        if m is not None:
            for_s = parse_duration_ms(m.group(1)) / 1000.0
            expr = expr[: m.start()].rstrip()
    return validate_rule(
        Rule(name, expr, kind=kind, for_s=for_s, every_s=every_s,
             source=source)
    )


def rule_from_dict(d: dict, source: str = "runtime") -> Rule:
    """The /admin/rules POST body (and the persisted state-file form)."""
    if not isinstance(d, dict):
        raise RuleError("rule must be an object")
    def _dur(key: str, alt: str) -> float:
        raw = d.get(key, d.get(alt, 0))
        if isinstance(raw, str):
            return parse_duration_ms(raw) / 1000.0
        return float(raw or 0)

    return validate_rule(
        Rule(
            name=str(d.get("name", "")),
            expr=str(d.get("expr", "")),
            kind=str(d.get("kind", "recording")),
            for_s=_dur("for", "for_s"),
            every_s=_dur("every", "every_s"),
            labels=dict(d.get("labels") or {}),
            source=source,
        )
    )
