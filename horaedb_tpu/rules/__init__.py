"""Continuous queries: PromQL recording rules, tiered rollups, and an
alerting evaluator — see rules/engine.py for the subsystem overview."""

from .engine import (
    RULES_METRIC_FAMILIES,
    RuleEngine,
    recording_schema,
    registered_engines,
)
from .model import Rule, RuleError, parse_rule_line, rule_from_dict
from .rewrite import rollup_decision_for, try_rollup_serve
from .rollup import (
    ROLLUPS,
    RollupMaintainer,
    RollupSpec,
    TIERS,
    rollup_table_name,
)

__all__ = [
    "ROLLUPS",
    "RULES_METRIC_FAMILIES",
    "Rule",
    "RuleEngine",
    "RuleError",
    "RollupMaintainer",
    "RollupSpec",
    "TIERS",
    "parse_rule_line",
    "recording_schema",
    "registered_engines",
    "rollup_decision_for",
    "rollup_table_name",
    "rule_from_dict",
    "try_rollup_serve",
]
