"""Tiered rollup tables with TTL laddering — incrementally maintained
downsampling over the mutable memtable
(ref: StreamBox-HBM's stream analytics over hybrid memory, PAPERS.md —
pre-aggregate at ingest so the dashboard-shaped range query reads the
small table; the raw/1m/1h ladder is the classic Prometheus/Influx
retention-policy shape: raw 24h -> 1m rollup 30d -> 1h rollup kept).

For a source table ``t`` (tags + one DOUBLE value column + timestamp
key), the maintainer keeps:

    t_rollup_1m   one row per (tags..., 1m bucket):  agg_sum, agg_count,
                  agg_min, agg_max   (ttl: rollup_1m_ttl, default 30d)
    t_rollup_1h   the same, folded FROM the 1m tier  (ttl: rollup_1h_ttl,
                  default 0 = kept)

and optionally applies ``rollup_raw_ttl`` (default 24h) to the source so
the ladder bounds total storage by construction. Those four partials
reconstruct every rewritable aggregate: sum == sum(agg_sum), count ==
sum(agg_count), min/max fold, avg == sum(agg_sum)/sum(agg_count).

Watermark / catch-up protocol (restarts and WAL replay can neither
double-count nor leave gaps):

- the watermark per (source, tier) is the exclusive end of COMPLETE
  buckets already rolled up; only buckets entirely older than
  ``now - grace`` close (late arrivals inside the grace window are
  captured; later ones are the documented streaming trade-off);
- each round recomputes ``[watermark, closed_end)`` FROM THE SOURCE with
  one grouped scan (memtable + SSTs — the mutable tail is included), so
  a round is a pure function of source state;
- rollup tables are ``update_mode=overwrite`` keyed (tags, bucket): a
  recomputed bucket REPLACES its previous row, so replaying a round
  (crash between write and watermark persist, WAL replay after restart)
  is idempotent;
- the watermark advances only after the rows are written (write-ahead:
  rows are WAL-durable before the state file moves), and on a cold start
  with no state file it re-derives from ``max(ts)`` of the rollup table
  itself — catch-up then recomputes forward from the last durable
  bucket, never skipping a gap.

The process-global ``ROLLUPS`` registry is how the query layer finds a
maintained rollup: the rewrite (rules/rewrite.py) consults the spec and
the live watermark to decide whether a range query's buckets can be
served from the tier, with the raw tail above the cut computed from the
source.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..common_types import ColumnSchema, DatumKind, RowGroup, Schema
from ..engine.options import TableOptions
from ..proxy.promql import _q, _value_column

logger = logging.getLogger("horaedb_tpu.rules.rollup")

# (suffix, bucket width ms), finest first. The ladder is fixed; TTLs are
# the [rules] knobs.
TIERS: tuple[tuple[str, int], ...] = (("1m", 60_000), ("1h", 3_600_000))

AGG_COLS = ("agg_sum", "agg_count", "agg_min", "agg_max")


def rollup_table_name(source: str, suffix: str) -> str:
    return f"{source}_rollup_{suffix}"


@dataclass(frozen=True)
class RollupSpec:
    """What the rewrite and the maintainer both need to know about one
    source table's ladder — derived once from the source schema."""

    source: str
    ts_col: str
    value_col: str
    tags: tuple[str, ...]
    tiers: tuple[tuple[str, int], ...] = TIERS


class RollupState:
    """Spec + live watermarks (exclusive end of completed buckets per
    tier suffix). The maintainer writes, the query rewrite reads."""

    def __init__(self, spec: RollupSpec) -> None:
        self.spec = spec
        self._lock = threading.Lock()
        self._watermarks: dict[str, int] = {}

    def watermark(self, suffix: str) -> Optional[int]:
        with self._lock:
            return self._watermarks.get(suffix)

    def set_watermark(self, suffix: str, ms: int) -> None:
        with self._lock:
            self._watermarks[suffix] = int(ms)

    def watermarks(self) -> dict[str, int]:
        with self._lock:
            return dict(self._watermarks)


class RollupRegistry:
    """Process-global source -> RollupState map (same discipline as
    EVENT_STORE / STATS_STORE: tests reset() between connections)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: dict[str, RollupState] = {}

    def register(self, state: RollupState) -> RollupState:
        with self._lock:
            self._states[state.spec.source] = state
            return state

    def get(self, source: str) -> Optional[RollupState]:
        with self._lock:
            return self._states.get(source)

    def unregister(self, source: str) -> None:
        with self._lock:
            self._states.pop(source, None)

    def reset(self) -> None:
        with self._lock:
            self._states.clear()


ROLLUPS = RollupRegistry()


def spec_for(conn, source: str) -> RollupSpec:
    """Derive the ladder spec from the source schema; raises ValueError
    for shapes the ladder cannot represent (no single value column, or a
    tag colliding with the partial-aggregate column names)."""
    schema = conn.catalog.schema_of(source)
    if schema is None:
        raise ValueError(f"rollup source table not found: {source}")
    value_col = _value_column(schema)  # raises PromQLError (a ValueError)
    tags = tuple(schema.tag_names)
    taken = set(tags) | {schema.timestamp_name, value_col}
    collide = taken & set(AGG_COLS)
    if collide:
        raise ValueError(
            f"rollup for {source!r}: column(s) {sorted(collide)} collide "
            "with the rollup partial columns"
        )
    return RollupSpec(
        source=source,
        ts_col=schema.timestamp_name,
        value_col=value_col,
        tags=tags,
    )


def rollup_schema(conn, spec: RollupSpec) -> Schema:
    """Tags copied from the source; the four partial columns DOUBLE; the
    timestamp keeps the source's name so group exprs rewrite verbatim."""
    src = conn.catalog.schema_of(spec.source)
    cols = [
        ColumnSchema(t, src.column(t).kind, is_tag=True) for t in spec.tags
    ]
    cols += [ColumnSchema(c, DatumKind.DOUBLE) for c in AGG_COLS]
    cols.append(ColumnSchema(spec.ts_col, DatumKind.TIMESTAMP, is_nullable=False))
    return Schema.build(cols, timestamp_column=spec.ts_col)


class RollupMaintainer:
    """The per-engine maintenance half: ensure tables + TTL ladder, then
    advance each tier's watermark every round. Owned by the RuleEngine
    (which provides persistence for the watermarks and the write path —
    local or forwarded to the owning node)."""

    def __init__(
        self,
        conn,
        source: str,
        grace_ms: int = 5_000,
        raw_ttl_s: float = 24 * 3600.0,
        tier_ttl_s: Optional[dict[str, float]] = None,
        write_rows=None,
        ensure_table=None,
    ) -> None:
        self.conn = conn
        self.source = source
        self.grace_ms = max(0, int(grace_ms))
        self.raw_ttl_s = float(raw_ttl_s)
        self.tier_ttl_s = dict(tier_ttl_s or {})
        # injection points for the engine's cluster forwarding; defaults
        # are the local write path
        self._write_rows = write_rows
        self._ensure_table = ensure_table
        self.spec = spec_for(conn, source)
        # a FRESH state replaces any prior registration for the source:
        # watermarks from another connection's lifetime (tests, embedded
        # + server on one process) must not leak — cold-start derivation
        # from the rollup table itself covers genuine restarts
        self.state = ROLLUPS.register(RollupState(self.spec))
        self.rows_written = 0
        self.last_error: str = ""

    # ---- tables ---------------------------------------------------------

    def ensure_tables(self) -> None:
        schema = rollup_schema(self.conn, self.spec)
        for suffix, tier_ms in self.spec.tiers:
            name = rollup_table_name(self.source, suffix)
            ttl = self.tier_ttl_s.get(suffix, 0.0)
            opts = {
                "update_mode": "overwrite",
                # coarse tiers get coarse segments: whole-SST TTL drops
                # stay cheap at 30d retention
                "segment_duration": "2h" if tier_ms < 3_600_000 else "1d",
            }
            if ttl > 0:
                opts["ttl"] = f"{max(1, int(ttl))}s"
            if self._ensure_table is not None:
                self._ensure_table(name, schema, TableOptions.from_kv(opts))
            else:
                table = self.conn.catalog.open(name)
                if table is None:
                    self.conn.catalog.create_table(
                        name, schema, TableOptions.from_kv(opts),
                        if_not_exists=True,
                    )
                else:
                    _sync_ttl(table, ttl)
        if self.raw_ttl_s > 0:
            src = self.conn.catalog.open(self.source)
            if src is not None:
                _sync_ttl(src, self.raw_ttl_s)

    # ---- one round ------------------------------------------------------

    def run_once(self, now_ms: Optional[int] = None) -> int:
        """Advance every tier; returns rollup rows written. Raises on
        write shed/failure — the engine owns backoff policy."""
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        self.ensure_tables()
        written = 0
        fine_suffix = None
        for suffix, tier_ms in self.spec.tiers:
            if fine_suffix is None:
                # finest tier folds the raw source, closed at now - grace
                closed_end = ((now_ms - self.grace_ms) // tier_ms) * tier_ms
                written += self._advance(
                    suffix, tier_ms, self.source, self.spec.value_col,
                    raw_source=True, closed_end=closed_end,
                )
            else:
                # coarser tiers fold the next-finer tier, closed at the
                # finer watermark (its buckets are final below it)
                fine_wm = self.state.watermark(fine_suffix)
                if fine_wm is None:
                    continue
                closed_end = (fine_wm // tier_ms) * tier_ms
                written += self._advance(
                    suffix, tier_ms,
                    rollup_table_name(self.source, fine_suffix),
                    None, raw_source=False, closed_end=closed_end,
                )
            fine_suffix = suffix
        self.rows_written += written
        return written

    def _advance(
        self, suffix: str, tier_ms: int, src_table: str,
        value_col: Optional[str], raw_source: bool, closed_end: int,
    ) -> int:
        wm = self.state.watermark(suffix)
        if wm is None:
            wm = self._derive_watermark(suffix, tier_ms, src_table)
            if wm is None:
                return 0  # source empty — nothing to roll yet
        if closed_end <= wm:
            return 0
        if closed_end - wm > 5 * tier_ms:
            # a normal round closes ~1 bucket; a multi-bucket advance is
            # restart catch-up or initial backfill — journal it so an
            # operator can see the recovery (and that it happened ONCE)
            from ..utils.events import record_event

            record_event(
                "rollup_catchup",
                table=rollup_table_name(self.source, suffix),
                tier=suffix,
                buckets=(closed_end - wm) // tier_ms,
                from_ms=wm, to_ms=closed_end,
            )
        ts = self.spec.ts_col
        keys = [f"time_bucket({_q(ts)}, '{tier_ms}ms')"] + [
            _q(t) for t in self.spec.tags
        ]
        if raw_source:
            v = _q(value_col)
            aggs = (
                f"sum({v}) AS agg_sum, count({v}) AS agg_count, "
                f"min({v}) AS agg_min, max({v}) AS agg_max"
            )
        else:
            aggs = (
                "sum(agg_sum) AS agg_sum, sum(agg_count) AS agg_count, "
                "min(agg_min) AS agg_min, max(agg_max) AS agg_max"
            )
        tag_sel = "".join(f", {_q(t)}" for t in self.spec.tags)
        sql = (
            f"SELECT {keys[0]} AS __bucket{tag_sel}, {aggs} "
            f"FROM {_q(src_table)} "
            f"WHERE {_q(ts)} >= {wm} AND {_q(ts)} < {closed_end} "
            f"GROUP BY {', '.join(keys)}"
        )
        out = self.conn.execute(sql).to_pylist()
        rows = []
        for r in out:
            if not r.get("agg_count"):
                # a bucket whose every value is NULL has no partials to
                # store (the rewrite serves such groups as absent —
                # documented edge; raw SQL would show NULL aggregates)
                continue
            row = {t: r[t] for t in self.spec.tags}
            row[ts] = int(r["__bucket"])
            row["agg_sum"] = float(r["agg_sum"])
            row["agg_count"] = float(r["agg_count"])
            row["agg_min"] = float(r["agg_min"])
            row["agg_max"] = float(r["agg_max"])
            rows.append(row)
        if rows:
            self._write(rollup_table_name(self.source, suffix), rows)
        self.state.set_watermark(suffix, closed_end)
        return len(rows)

    def _derive_watermark(
        self, suffix: str, tier_ms: int, src_table: str
    ) -> Optional[int]:
        """Cold start (no persisted state): resume from the last durable
        rollup bucket when the table has rows (crash recovery — never
        re-derive from 'now', that would GAP the history), else begin at
        the source's first bucket (initial backfill)."""
        name = rollup_table_name(self.source, suffix)
        ts = self.spec.ts_col
        if self.conn.catalog.open(name) is not None:
            out = self.conn.execute(
                f"SELECT max({_q(ts)}) AS m FROM {_q(name)}"
            ).to_pylist()
            if out and out[0]["m"] is not None:
                return int(out[0]["m"]) + tier_ms
        out = self.conn.execute(
            f"SELECT min({_q(ts)}) AS m FROM {_q(src_table)}"
        ).to_pylist()
        if not out or out[0]["m"] is None:
            return None
        return (int(out[0]["m"]) // tier_ms) * tier_ms

    def _write(self, table_name: str, rows: list[dict]) -> None:
        if self._write_rows is not None:
            self._write_rows(table_name, rows)
            return
        table = self.conn.catalog.open(table_name)
        rg = RowGroup.from_rows(table.schema, rows)
        from ..engine.instance import nonblocking_backpressure

        with nonblocking_backpressure():
            table.write(rg)


def _sync_ttl(table, ttl_s: float) -> None:
    """The configured ladder TTL wins over whatever the table carries
    (same contract as the self-monitoring retention knob): 0 = keep
    forever (disables enable_ttl)."""
    datas = table.physical_datas()
    if not datas:
        return
    cur = datas[0].options
    want_enable = ttl_s > 0
    want_ttl_ms = int(ttl_s * 1000) if want_enable else cur.ttl_ms
    if cur.enable_ttl == want_enable and cur.ttl_ms == want_ttl_ms:
        return
    import dataclasses

    table.alter_options(
        dataclasses.replace(cur, enable_ttl=want_enable, ttl_ms=want_ttl_ms)
    )
