"""Continuous-query engine: recording rules, tiered rollups, alerting
(ref: prometheus's rule evaluator, re-homed INSIDE the database — the
PR-5 self-monitoring recorder is the template: a ``PeriodicLoop`` that
writes through the normal ingest path under nonblocking backpressure,
node-labeled rows, and non-owner forwarding; StreamBox-HBM's continuous
queries over hybrid memory are the design stance, PAPERS.md).

One ``RuleEngine`` per node runs every ``[rules] eval_interval``:

- **rollups** — each ``rollup_tables`` entry gets a RollupMaintainer
  (rules/rollup.py): raw -> 1m -> 1h with TTL laddering and the
  watermark/catch-up protocol; the query layer transparently serves
  step-compatible range queries from the tiers (rules/rewrite.py,
  ``route=rollup``);
- **recording rules** — PromQL expressions instant-evaluated and written
  as rows of a REAL table named after the rule (labels folded into a
  ``labels`` string tag like ``system_metrics.samples``; the PromQL
  layer lifts them back so matchers on result labels keep working);
- **alert rules** — PromQL threshold expressions (the comparison
  operators: ``rate(errors[1m]) > 5``) driving a per-series
  pending -> firing -> resolved state machine with a ``for`` duration,
  journaled as typed ``alert_fired``/``alert_resolved`` events (trace
  linked) and served as ``system.public.alerts`` on every wire.

Rules come from the ``[rules]`` config section and from the runtime
``/admin/rules`` endpoint; runtime rules and rollup watermarks persist
in ``<data_dir>/rules_state.json`` beside ``wlm_state.json``. Cluster
discipline: a rule evaluates only on the node that OWNS its source
tables (eval-on-owner — every node loads the same config, exactly one
evaluates each rule); output tables that route elsewhere are forwarded
to the owner through the ordinary ``/write`` path.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Optional

from ..common_types import ColumnSchema, DatumKind, RowGroup, Schema
from ..engine.maintenance_scheduler import PeriodicLoop
from ..engine.metrics_recorder import forward_rows
from ..engine.options import TableOptions
from ..utils.events import record_event
from ..utils.metrics import REGISTRY, _render_labels
from .model import Rule, RuleError, parse_rule_line, rule_from_dict
from .rollup import ROLLUPS, RollupMaintainer, rollup_table_name

logger = logging.getLogger("horaedb_tpu.rules")

STATE_FILE = "rules_state.json"

# Declared registry of the rules/alerts metric families — the lint in
# tests/test_observability.py checks each is registered live,
# convention-clean, and documented in docs/OBSERVABILITY.md, and that no
# stray horaedb_rules_* / horaedb_alerts_* family exists outside it.
RULES_METRIC_FAMILIES = (
    "horaedb_rules_eval_total",
    "horaedb_rules_eval_failures_total",
    "horaedb_rules_eval_duration_seconds",
    "horaedb_rules_rows_total",
    "horaedb_rules_loaded_total",
    "horaedb_rules_watermark_lag_seconds",
    "horaedb_alerts_pending_total",
    "horaedb_alerts_firing_total",
    "horaedb_alerts_fired_total",
    "horaedb_alerts_resolved_total",
)

RULE_EVAL_KINDS = ("recording", "alert", "rollup")

# Eager registration: series exist from the first scrape and for the lint.
_M_EVAL = {
    k: REGISTRY.counter(
        "horaedb_rules_eval_total",
        "rule evaluations by kind (recording|alert|rollup)",
        labels={"kind": k},
    )
    for k in RULE_EVAL_KINDS
}
_M_EVAL_FAILURES = REGISTRY.counter(
    "horaedb_rules_eval_failures_total",
    "rule evaluations that raised (per rule, isolated per round)",
)
_M_EVAL_SECONDS = REGISTRY.histogram(
    "horaedb_rules_eval_duration_seconds",
    "wall time of one full rule-evaluation round",
)
_M_ROWS = REGISTRY.counter(
    "horaedb_rules_rows_total",
    "rows written by recording rules and rollup maintenance",
)
_M_LOADED = REGISTRY.gauge(
    "horaedb_rules_loaded_total",
    "rules currently loaded (config + runtime)",
)
_M_WM_LAG = REGISTRY.gauge(
    "horaedb_rules_watermark_lag_seconds",
    "worst rollup watermark lag behind now across maintained tiers",
)
_M_PENDING = REGISTRY.gauge(
    "horaedb_alerts_pending_total", "alert series currently pending"
)
_M_FIRING = REGISTRY.gauge(
    "horaedb_alerts_firing_total", "alert series currently firing"
)
_M_FIRED = REGISTRY.counter(
    "horaedb_alerts_fired_total", "pending -> firing transitions"
)
_M_RESOLVED = REGISTRY.counter(
    "horaedb_alerts_resolved_total", "firing -> resolved transitions"
)

_BACKOFF_CAP_S = 300.0

# Engines register here so system.public.alerts (table_engine/system.py)
# can materialize current alert state without a handle on the server.
_ENGINES: "weakref.WeakSet[RuleEngine]" = weakref.WeakSet()


def registered_engines() -> list["RuleEngine"]:
    return list(_ENGINES)


@dataclass
class AlertInstance:
    """One alert series' live state."""

    rule: str
    labels: dict[str, str]
    state: str  # "pending" | "firing" | "resolved"
    value: float
    active_since_ms: int
    fired_at_ms: int = 0
    resolved_at_ms: int = 0

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "labels": dict(self.labels),
            "state": self.state,
            "value": self.value,
            "active_since_ms": self.active_since_ms,
            "fired_at_ms": self.fired_at_ms,
            "resolved_at_ms": self.resolved_at_ms,
        }


def recording_schema() -> Schema:
    """A recording rule's output table: the samples-table shape minus the
    family tag (the table name IS the metric name). The folded ``labels``
    tag is what the PromQL layer lifts back into first-class labels."""
    return Schema.build(
        [
            ColumnSchema("labels", DatumKind.STRING, is_tag=True),
            ColumnSchema("node", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("ts", DatumKind.TIMESTAMP),
        ],
        timestamp_column="ts",
    )


def _recording_create_sql(name: str, ttl_s: float) -> str:
    """The forwarded-DDL form of recording_schema() — what a non-owner
    sends the owning node before forwarding rows."""
    opts = "update_mode='append', segment_duration='2h'"
    if ttl_s > 0:
        opts += f", enable_ttl='true', ttl='{max(1, int(ttl_s))}s'"
    return (
        f"CREATE TABLE IF NOT EXISTS {name} (labels string TAG, "
        "node string TAG, value double, ts timestamp NOT NULL, "
        f"TIMESTAMP KEY(ts)) ENGINE=Analytic WITH ({opts})"
    )


class RuleEngine:
    """Background continuous-query loop over a Connection."""

    def __init__(
        self,
        conn,
        section=None,
        node: str = "standalone",
        router=None,
        state_path: Optional[str] = None,
        cluster=None,
        slo=None,
    ) -> None:
        """``cluster`` (coordinator mode): output-table DDL is serialized
        through the coordinator (``cluster.meta.create_table``) instead
        of the local catalog — local creation would mint colliding table
        ids in the shared store — and ownership questions ask the live
        shard set, not the router's meta-unknown fallback.
        ``slo``: an slo.SloEvaluator ticked at the end of every round —
        the SLO plane rides THIS cadence by design (no second loop to
        drift against the rules/alerts it judges)."""
        from ..utils.config import RulesSection

        self.conn = conn
        self.section = section if section is not None else RulesSection()
        self.node = node
        self.router = router
        self.cluster = cluster
        self.slo = slo
        if state_path is None:
            root = getattr(conn.store, "root", None)
            if root:
                state_path = os.path.join(root, STATE_FILE)
        self.state_path = state_path
        self.interval_s = max(0.05, float(self.section.eval_interval_s))
        self.rules: dict[str, Rule] = {}
        self._parsed: dict[str, object] = {}  # name -> PromExpr
        # per-rule cadence bookkeeping (Rule.every_s): name -> last eval
        # wall-clock ms; a rule is due when now - last >= every_s
        self._rule_last_eval_ms: dict[str, int] = {}
        self.rollup_sources: list[str] = list(self.section.rollup_tables)
        self._maintainers: dict[str, RollupMaintainer] = {}
        self._wm_seed: dict[str, dict[str, int]] = {}  # source -> suffix -> ms
        # alert book: rule -> labelkey -> AlertInstance; recently-resolved
        # ring for the alerts table
        self._alerts: dict[str, dict[tuple, AlertInstance]] = {}
        self._resolved: deque = deque(maxlen=64)
        self._alerts_lock = threading.Lock()
        self.loaded = False
        self.rounds = 0
        self.rows_written = 0
        self.last_eval_ms = 0
        self.last_errors: dict[str, str] = {}
        self._fails = 0
        self._backoff_until = 0.0
        # remote tables whose CREATE IF NOT EXISTS already succeeded —
        # without this every round re-forwards idempotent DDL (a 10s
        # urllib round-trip per output table per eval_interval, forever)
        self._remote_ensured: set[str] = set()
        self._loop: Optional[PeriodicLoop] = None
        self._state_lock = threading.Lock()
        # rule-eval trace ids: high base so they can't collide with the
        # proxy's per-request counter in the trace store
        self._trace_ids = itertools.count((1 << 40) + (os.getpid() << 16))
        for line in self.section.recording:
            self._add(parse_rule_line(line, "recording", source="config"))
        for line in self.section.alerts:
            self._add(parse_rule_line(line, "alert", source="config"))
        _ENGINES.add(self)

    # ---- lifecycle ------------------------------------------------------

    def _add(self, rule: Rule) -> Rule:
        from ..proxy.promql import parse_promql

        self.rules[rule.name] = rule
        self._parsed[rule.name] = parse_promql(rule.expr)
        _M_LOADED.set(len(self.rules))
        return rule

    def load(self) -> "RuleEngine":
        """Load runtime rules + persisted watermarks; readiness
        (``/health?ready=1``) gates on this completing."""
        if self.state_path and os.path.exists(self.state_path):
            try:
                with open(self.state_path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                for d in data.get("rules", []):
                    try:
                        self._add(rule_from_dict(d, source="runtime"))
                    except RuleError as e:
                        logger.warning("skipping persisted rule: %s", e)
                for key, ms in (data.get("watermarks") or {}).items():
                    source, _, suffix = key.rpartition("|")
                    if source:
                        self._wm_seed.setdefault(source, {})[suffix] = int(ms)
            except (OSError, ValueError) as e:
                logger.warning(
                    "could not load rules state %s: %s", self.state_path, e
                )
        self.loaded = True
        _M_LOADED.set(len(self.rules))
        return self

    def start(self) -> "RuleEngine":
        if self._loop is not None:
            return self
        if not self.loaded:
            self.load()
        ref = weakref.WeakMethod(self.tick)

        def tick():
            fn = ref()
            if fn is None:
                return False
            fn()
            return True

        self._loop = PeriodicLoop(self.interval_s, tick, "rules-eval").start()
        return self

    def close(self) -> None:
        if self._loop is not None:
            self._loop.close()
            self._loop = None

    # ---- admin surface --------------------------------------------------

    def add_rule(self, d: dict) -> Rule:
        rule = rule_from_dict(d, source="runtime")
        existing = self.rules.get(rule.name)
        if existing is not None and existing.source == "config":
            raise RuleError(
                f"rule {rule.name!r} is config-defined; edit the [rules] "
                "section instead"
            )
        self._add(rule)
        self._save_state()
        return rule

    def remove_rule(self, name: str) -> bool:
        rule = self.rules.get(name)
        if rule is None:
            return False
        if rule.source == "config":
            raise RuleError(
                f"rule {name!r} is config-defined; remove it from the "
                "[rules] section instead"
            )
        del self.rules[name]
        self._parsed.pop(name, None)
        self._rule_last_eval_ms.pop(name, None)
        with self._alerts_lock:
            self._alerts.pop(name, None)
        self.last_errors.pop(name, None)
        _M_LOADED.set(len(self.rules))
        self._save_state()
        return True

    def list_rules(self) -> list[dict]:
        out = []
        for rule in self.rules.values():
            d = rule.to_dict()
            d["last_error"] = self.last_errors.get(rule.name, "")
            out.append(d)
        return sorted(out, key=lambda d: d["name"])

    def alerts_snapshot(self) -> list[dict]:
        """Live pending/firing instances plus the recently-resolved ring
        (newest last) — /debug/alerts and system.public.alerts."""
        with self._alerts_lock:
            live = [
                inst.to_dict()
                for book in self._alerts.values()
                for inst in book.values()
            ]
            done = [inst.to_dict() for inst in self._resolved]
        return sorted(done + live, key=lambda d: (d["rule"], sorted(d["labels"].items())))

    def stats(self) -> dict:
        with self._alerts_lock:
            pending = sum(
                1
                for book in self._alerts.values()
                for i in book.values()
                if i.state == "pending"
            )
            firing = sum(
                1
                for book in self._alerts.values()
                for i in book.values()
                if i.state == "firing"
            )
        return {
            "enabled": bool(self.section.enabled),
            "loaded": self.loaded,
            "running": self._loop is not None and self._loop.is_alive(),
            "interval_s": self.interval_s,
            "rules_loaded": len(self.rules),
            "recording": sum(1 for r in self.rules.values() if r.kind == "recording"),
            "alerts": sum(1 for r in self.rules.values() if r.kind == "alert"),
            "rollup_tables": list(self.rollup_sources),
            "rounds": self.rounds,
            "rows_written": self.rows_written,
            "last_eval_ms": self.last_eval_ms,
            "consecutive_failures": self._fails,
            "backoff_s": round(max(0.0, self._backoff_until - time.monotonic()), 2),
            "watermark_lag_s": self._watermark_lag_s(),
            "alerts_pending": pending,
            "alerts_firing": firing,
            "last_errors": dict(self.last_errors),
        }

    def _watermark_lag_s(self) -> Optional[float]:
        now_ms = time.time() * 1000
        worst = None
        for m in self._maintainers.values():
            for ms in m.state.watermarks().values():
                lag = (now_ms - ms) / 1000.0
                if worst is None or lag > worst:
                    worst = lag
        return round(worst, 3) if worst is not None else None

    # ---- one round ------------------------------------------------------

    def tick(self) -> None:
        """One periodic firing: honor failure backoff, evaluate, never
        raise (the loop keeps ticking through shed rounds)."""
        now = time.monotonic()
        if now < self._backoff_until:
            return
        from ..wlm.admission import OverloadedError

        try:
            self.run_once()
        except OverloadedError as e:
            self._note_skip("write_stall", str(e))
            return
        except Exception as e:
            self._note_skip("error", str(e))
            return
        self._fails = 0

    def _note_skip(self, reason: str, msg: str) -> None:
        self._fails += 1
        delay = min(self.interval_s * (2 ** self._fails), _BACKOFF_CAP_S)
        self._backoff_until = time.monotonic() + delay
        _M_EVAL_FAILURES.inc()
        record_event(
            "rule_eval_failed", table="",
            rule="(round)", reason=reason, error=msg[:200],
            backoff_s=round(delay, 2),
        )
        logger.warning(
            "rules eval round skipped (%s); backing off %.1fs: %s",
            reason, delay, msg,
        )

    def run_once(self, now_ms: Optional[int] = None) -> None:
        """One full evaluation round under its own trace (so the typed
        alert events cross-link to a stored span tree). Per-rule errors
        are isolated; a backpressure shed (OverloadedError) propagates —
        ``tick`` owns that backoff policy."""
        from ..utils.tracectx import finish_trace, start_trace, tag_trace
        from ..wlm.admission import OverloadedError

        t0 = time.perf_counter()
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        trace_id = next(self._trace_ids)
        _trace, handle = start_trace(trace_id, "rules-eval", node=self.node)
        tag_trace(route="rules")
        wm_dirty = False
        try:
            for source in self.rollup_sources:
                if not self._owns(source):
                    continue
                try:
                    from ..utils.tracectx import span as _span

                    m = self._maintainer(source)
                    with _span("rollup", source=source):
                        written = m.run_once(now_ms)
                    if written:
                        self.rows_written += written
                        _M_ROWS.inc(written)
                        wm_dirty = True
                    _M_EVAL["rollup"].inc()
                    self.last_errors.pop(source, None)
                except OverloadedError:
                    raise
                except Exception as e:
                    self._note_rule_error(source, "rollup", e)
            for rule in list(self.rules.values()):
                # snapshot the parsed expr: a concurrent /admin/rules
                # DELETE may race this round (skip, don't abort the
                # round — per-rule isolation must cover the lookup too)
                parsed = self._parsed.get(rule.name)
                if parsed is None:
                    continue
                try:
                    if not self._rule_due(rule, now_ms):
                        continue
                    if not self._rule_local(rule, parsed):
                        continue
                    from ..utils.tracectx import span as _span

                    with _span(rule.kind, rule=rule.name):
                        if rule.kind == "recording":
                            self._eval_recording(rule, parsed, now_ms)
                        else:
                            self._eval_alert(rule, parsed, now_ms)
                    self._rule_last_eval_ms[rule.name] = now_ms
                    _M_EVAL[rule.kind].inc()
                    self.last_errors.pop(rule.name, None)
                except OverloadedError:
                    raise
                except Exception as e:
                    self._note_rule_error(rule.name, rule.kind, e)
        finally:
            if self.slo is not None and self._owns_samples():
                # the SLO plane rides this cadence ON THE NODE OWNING the
                # samples history its indicators read (eval-on-owner, the
                # same discipline rules use — a non-owner's local view of
                # system_metrics.samples is flushed-only, stale by up to
                # the flush lag). evaluate_round only READS and isolates
                # its own per-objective errors, so it runs even on rounds
                # a rule write shed — the verdict must not pause because
                # ingest stalled (that stall is exactly what it judges)
                self.slo.evaluate_round(now_ms)
            finish_trace(handle)
            self.rounds += 1
            self.last_eval_ms = now_ms
            lag = self._watermark_lag_s()
            if lag is not None:
                _M_WM_LAG.set(lag)
            with self._alerts_lock:
                _M_PENDING.set(sum(
                    1 for b in self._alerts.values()
                    for i in b.values() if i.state == "pending"
                ))
                _M_FIRING.set(sum(
                    1 for b in self._alerts.values()
                    for i in b.values() if i.state == "firing"
                ))
            _M_EVAL_SECONDS.observe(time.perf_counter() - t0)
        if wm_dirty:
            self._save_state()

    def _rule_due(self, rule: Rule, now_ms: int) -> bool:
        """Per-rule cadence gate (Rule.every_s; 0 = every round). A tiny
        epsilon absorbs loop-tick jitter so ``every = eval_interval``
        still evaluates every round instead of every other one."""
        if rule.every_s <= 0:
            return True
        last = self._rule_last_eval_ms.get(rule.name)
        if last is None:
            return True
        return (now_ms - last) >= rule.every_s * 1000 - 50

    def _note_rule_error(self, name: str, kind: str, e: Exception) -> None:
        self.last_errors[name] = f"{type(e).__name__}: {e}"[:200]
        _M_EVAL_FAILURES.inc()
        # NB: ``kind`` is record_event's own first argument — the rule's
        # kind ships as rule_kind (the same collision quota_reject hit)
        record_event(
            "rule_eval_failed", table="",
            rule=name, rule_kind=kind, error=str(e)[:200],
        )
        logger.warning("rule %s (%s) evaluation failed: %s", name, kind, e)

    # ---- ownership (eval-on-owner) --------------------------------------

    def _owns_samples(self) -> bool:
        from ..engine.metrics_recorder import SAMPLES_TABLE

        return self._owns(SAMPLES_TABLE)

    def _owns(self, table: str) -> bool:
        if self.cluster is not None:
            # ask the live shard set, not the router: the router answers
            # is_local=True for meta-UNKNOWN tables (standalone fallback),
            # which here would make every node think it owns a
            # not-yet-created output table
            return self.cluster.owns_table(table)
        if self.router is None:
            return True
        return self.router.route(table).is_local

    def _rule_local(self, rule: Rule, parsed) -> bool:
        """A rule evaluates on the node owning ALL of its leaf source
        tables (a metric resolving to the samples fallback routes on
        where system_metrics.samples lives — the same predicate HTTP prom
        routing uses, so the evaluating node can actually read it)."""
        if self.router is None:
            return True
        from ..engine.metrics_recorder import SAMPLES_TABLE
        from ..proxy.promql import leaf_metrics, resolves_to_samples

        for m in set(leaf_metrics(parsed)):
            key = SAMPLES_TABLE if resolves_to_samples(self.conn, m) else m
            if not self._owns(key):
                return False
        return True

    # ---- rollups --------------------------------------------------------

    def _maintainer(self, source: str) -> RollupMaintainer:
        m = self._maintainers.get(source)
        if m is None:
            m = RollupMaintainer(
                self.conn,
                source,
                grace_ms=int(self.section.grace_s * 1000),
                raw_ttl_s=self.section.rollup_raw_ttl_s,
                tier_ttl_s={
                    "1m": self.section.rollup_1m_ttl_s,
                    "1h": self.section.rollup_1h_ttl_s,
                },
                write_rows=self._write_rollup_rows,
                ensure_table=self._ensure_rollup_table,
            )
            for suffix, ms in self._wm_seed.get(source, {}).items():
                # persisted watermark never overrides a LIVE registry
                # state that is already ahead (another engine round)
                cur = m.state.watermark(suffix)
                if cur is None or ms > cur:
                    m.state.set_watermark(suffix, ms)
            self._maintainers[source] = m
        return m

    def _ensure_rollup_table(self, name: str, schema, options) -> None:
        if self.cluster is not None:
            # coordinator mode: the COORDINATOR places the table and
            # allocates its id (local creation would mint colliding
            # sequential ids in the shared store — the reason rules were
            # disabled in this mode before the SLO plane needed them)
            self._ensure_meta_table(name, _create_sql_for(name, schema, options))
            if self._owns(name):
                table = self.conn.catalog.open(name)
                if table is not None:
                    from .rollup import _sync_ttl

                    _sync_ttl(
                        table,
                        (options.ttl_ms / 1000.0) if options.enable_ttl else 0.0,
                    )
            return
        if self._owns(name):
            table = self.conn.catalog.open(name)
            if table is None:
                self.conn.catalog.create_table(
                    name, schema, options, if_not_exists=True
                )
            else:
                from .rollup import _sync_ttl

                _sync_ttl(
                    table,
                    (options.ttl_ms / 1000.0) if options.enable_ttl else 0.0,
                )
            return
        # non-owner: the owning node must hold the table — forward the
        # DDL as ordinary SQL (IF NOT EXISTS makes it idempotent)
        self._forward_sql(name, _create_sql_for(name, schema, options))

    def _write_rollup_rows(self, table_name: str, rows: list[dict]) -> None:
        if self._owns(table_name):
            table = self.conn.catalog.open(table_name)
            rg = RowGroup.from_rows(table.schema, rows)
            from ..engine.instance import nonblocking_backpressure

            with nonblocking_backpressure():
                table.write(rg)
        else:
            forward_rows(
                self.router.route(table_name).endpoint, table_name, rows
            )

    # ---- recording rules ------------------------------------------------

    def _eval_recording(self, rule: Rule, parsed, now_ms: int) -> None:
        from ..proxy.promql import evaluate_expr_instant

        vec = evaluate_expr_instant(self.conn, parsed, now_ms)
        rows = []
        for s in vec:
            labels = {
                k: v for k, v in s["metric"].items() if k != "__name__"
            }
            labels.update(rule.labels)
            rows.append(
                {
                    "ts": now_ms,
                    "labels": _render_labels(labels),
                    "node": self.node,
                    "value": float(s["value"][1]),
                }
            )
        if not rows:
            return
        create_sql = _recording_create_sql(
            rule.name, self.section.recording_ttl_s
        )
        if self.cluster is not None:
            self._ensure_meta_table(rule.name, create_sql)
        if self._owns(rule.name):
            table = self.conn.catalog.open(rule.name)
            if table is None:
                if self.cluster is not None:
                    # never catalog-create here: coordinator-allocated
                    # tables must come from the meta DDL above (a local
                    # create would mint a colliding id); an open miss is
                    # a transient shard race — isolate and retry next round
                    raise RuntimeError(
                        f"recording table {rule.name!r} not open yet "
                        "(shard assignment in flight)"
                    )
                opts = {"update_mode": "append", "segment_duration": "2h"}
                if self.section.recording_ttl_s > 0:
                    opts["ttl"] = f"{max(1, int(self.section.recording_ttl_s))}s"
                table = self.conn.catalog.create_table(
                    rule.name, recording_schema(),
                    TableOptions.from_kv(opts), if_not_exists=True,
                )
            rg = RowGroup.from_rows(table.schema, rows)
            from ..engine.instance import nonblocking_backpressure

            with nonblocking_backpressure():
                table.write(rg)
        else:
            if self.cluster is None:
                self._forward_sql(rule.name, create_sql)
            forward_rows(
                self.router.route(rule.name).endpoint, rule.name, rows
            )
        self.rows_written += len(rows)
        _M_ROWS.inc(len(rows))

    def _ensure_meta_table(self, name: str, sql: str) -> None:
        from ..engine.metrics_recorder import ensure_meta_table

        ensure_meta_table(
            self.cluster, self.router, name, sql, self._remote_ensured
        )

    def _forward_sql(self, table: str, sql: str) -> None:
        """Idempotent DDL on the owning node over its /sql endpoint,
        once per engine lifetime per table (later TTL-knob changes apply
        on the owner's next restart — the ensure here is existence)."""
        if table in self._remote_ensured:
            return
        import urllib.error
        import urllib.request

        endpoint = self.router.route(table).endpoint
        req = urllib.request.Request(
            f"http://{endpoint}/sql",
            json.dumps({"query": sql}).encode(),
            {"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=10):
                pass
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")[:200]
            raise RuntimeError(
                f"rule DDL forward to {endpoint} failed ({e.code}): {body}"
            ) from None
        self._remote_ensured.add(table)

    # ---- alert rules ----------------------------------------------------

    def _eval_alert(self, rule: Rule, parsed, now_ms: int) -> None:
        from ..proxy.promql import evaluate_expr_instant

        vec = evaluate_expr_instant(self.conn, parsed, now_ms)
        active: dict[tuple, tuple[dict, float]] = {}
        for s in vec:
            labels = {
                k: v for k, v in s["metric"].items() if k != "__name__"
            }
            labels.update(rule.labels)
            labels["alertname"] = rule.name
            active[tuple(sorted(labels.items()))] = (labels, float(s["value"][1]))
        for_ms = int(rule.for_s * 1000)
        with self._alerts_lock:
            book = self._alerts.setdefault(rule.name, {})
            for key, (labels, value) in active.items():
                inst = book.get(key)
                if inst is None:
                    inst = AlertInstance(
                        rule=rule.name, labels=labels, state="pending",
                        value=value, active_since_ms=now_ms,
                    )
                    book[key] = inst
                inst.value = value
                if (
                    inst.state == "pending"
                    and now_ms - inst.active_since_ms >= for_ms
                ):
                    inst.state = "firing"
                    inst.fired_at_ms = now_ms
                    _M_FIRED.inc()
                    record_event(
                        "alert_fired", table="",
                        rule=rule.name, labels=_render_labels(labels),
                        value=value, for_s=rule.for_s,
                    )
            for key in [k for k in book if k not in active]:
                inst = book.pop(key)
                if inst.state == "firing":
                    inst.state = "resolved"
                    inst.resolved_at_ms = now_ms
                    self._resolved.append(inst)
                    _M_RESOLVED.inc()
                    record_event(
                        "alert_resolved", table="",
                        rule=rule.name, labels=_render_labels(inst.labels),
                        after_s=round((now_ms - inst.fired_at_ms) / 1000.0, 3),
                    )
                # a pending series that stopped matching simply resets

    # ---- persistence ----------------------------------------------------

    def _save_state(self) -> None:
        if not self.state_path:
            return
        with self._state_lock:
            watermarks = {}
            for source, m in self._maintainers.items():
                for suffix, ms in m.state.watermarks().items():
                    watermarks[f"{source}|{suffix}"] = ms
            data = {
                "rules": [
                    r.to_dict()
                    for r in self.rules.values()
                    if r.source == "runtime"
                ],
                "watermarks": watermarks,
            }
            tmp = self.state_path + ".tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(data, f, indent=1, sort_keys=True)
                os.replace(tmp, self.state_path)
            except OSError as e:
                logger.warning(
                    "could not persist rules state %s: %s", self.state_path, e
                )


def _create_sql_for(name: str, schema, options) -> str:
    """CREATE TABLE IF NOT EXISTS text for a rollup tier table — the
    forwarded-DDL form of rules/rollup.rollup_schema."""
    cols = []
    for c in schema.columns:
        if c.name == "tsid":
            continue
        part = f"{c.name} {c.kind.value}"
        if c.is_tag:
            part += " TAG"
        if c.name == schema.timestamp_name:
            part += " NOT NULL"
        cols.append(part)
    opts = [f"update_mode='{options.update_mode.value}'"]
    if options.segment_duration_ms:
        opts.append(f"segment_duration='{options.segment_duration_ms}ms'")
    if options.enable_ttl and options.ttl_ms:
        opts.append("enable_ttl='true'")
        opts.append(f"ttl='{options.ttl_ms}ms'")
    return (
        f"CREATE TABLE IF NOT EXISTS {name} ({', '.join(cols)}, "
        f"TIMESTAMP KEY({schema.timestamp_name})) ENGINE=Analytic "
        f"WITH ({', '.join(opts)})"
    )
