"""Transparent rollup serving — the query rewrite
(ref: materialized-view matching in every warehouse, scoped to the
dashboard shape this engine's ladder stores: ``SELECT time_bucket(ts, W),
tags..., agg(value) ... GROUP BY ...`` with W a multiple of a maintained
tier).

``rollup_decision_for`` is the ONE predicate deciding whether a plan can
be served from a rollup tier — the executor hook and EXPLAIN both call
it, so what EXPLAIN promises and what execution does cannot drift (the
``resolves_to_samples`` discipline). A decision splits the time range on
W-aligned COMPLETE-bucket boundaries (lo = start rounded UP to the step,
cut = the tier watermark rounded down):

    [start, lo)   -> raw (the partial HEAD bucket a non-aligned lower
                     bound truncates — stored whole-bucket partials
                     cannot represent it)
    [lo, cut)     -> the rollup table (partials re-aggregated: sum ==
                     sum(agg_sum), count == sum(agg_count), min/max fold,
                     avg == sum(agg_sum)/sum(agg_count))
    [cut, end)    -> raw (the still-open tail the maintainer hasn't
                     closed yet — a dashboard's 'now' edge stays fresh)

Both halves run as ordinary plans through the executor (each taking its
own best path — the rollup scan is the small one); the W-aligned cut
makes their group sets disjoint, so the results concatenate, then the
original ORDER BY / LIMIT / OFFSET apply to the combined set. The
rewrite is visible as ``route=rollup`` in the ledger/query_stats and as
a ``Rollup:`` line in EXPLAIN. ``HORAEDB_ROLLUP=0`` kills the rewrite.

Refused shapes (served raw, never wrong): a non-value aggregate column,
count(*) (the ladder stores count(value) — NULLs differ), DISTINCT
aggregates, FILTER clauses, HAVING, joins, arithmetic over aggregates,
residual WHERE on non-tag columns, a step that no tier divides, and
ORDER BY expressions that are not output columns.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from ..common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP
from ..query import ast
from ..query.plan import QueryPlan
from .rollup import ROLLUPS, AGG_COLS, RollupState, rollup_table_name

# aggregate func -> how it folds over the stored partials
_FOLDABLE = ("sum", "count", "min", "max", "avg")


def rollup_enabled() -> bool:
    return os.environ.get("HORAEDB_ROLLUP", "1") != "0"


@dataclass(frozen=True)
class RollupDecision:
    source: str
    rollup_table: str
    suffix: str
    tier_ms: int
    step_ms: int
    # W-aligned complete-bucket window: the rollup serves [lo, cut); raw
    # computes the partial HEAD [start, lo) (a non-aligned lower bound
    # truncates its first bucket — stored partials can't represent that)
    # and the still-open TAIL [cut, end)
    lo: int
    cut: int
    start: int
    end: int


def _is_bucket_expr(e: ast.Expr, ts_col: str) -> bool:
    return (
        isinstance(e, ast.FuncCall)
        and e.name in ("time_bucket", "date_trunc")
        and e.args
        and isinstance(e.args[0], ast.Column)
        and e.args[0].name == ts_col
    )


def _split_where(plan: QueryPlan, tags: set, ts_col: str):
    """-> (tag_conjuncts, ok): conjuncts usable verbatim on BOTH sides
    (tag-only), with pushed-to-storage ts range conjuncts dropped (the
    decision's [start, end) already carries them). Anything else — a
    residual value-column filter, an unpushable ts shape — refuses."""
    from ..query.planner import _as_simple_cmp, _conjuncts

    where = plan.select.where
    if where is None:
        return [], True
    from ..query.executor import _columns_of

    keep = []
    for conj in _conjuncts(where):
        cols = {c.name for c in _columns_of(conj)}
        if cols and cols <= tags:
            keep.append(conj)
            continue
        simple = _as_simple_cmp(conj)
        if simple is not None and simple[0] == ts_col and simple[1] != "!=":
            continue  # exact via the predicate time range
        if (
            isinstance(conj, ast.Between)
            and not conj.negated
            and isinstance(conj.expr, ast.Column)
            and conj.expr.name == ts_col
            and isinstance(conj.low, ast.Literal)
            and isinstance(conj.high, ast.Literal)
        ):
            continue
        return [], False
    return keep, True


def rollup_decision_for(
    catalog, plan
) -> Optional[RollupDecision]:
    """THE shared serve-from-rollup predicate (executor + EXPLAIN)."""
    if not rollup_enabled() or not isinstance(plan, QueryPlan):
        return None
    if not plan.is_aggregate or plan.agg_exprs:
        return None
    state: Optional[RollupState] = ROLLUPS.get(plan.table)
    if state is None:
        return None
    spec = state.spec
    if plan.schema.timestamp_name != spec.ts_col:
        return None
    sel = plan.select
    if (
        sel.join is not None
        or sel.joins
        or sel.distinct
        or sel.having is not None
    ):
        return None
    # group shape: exactly one time_bucket key + tag columns
    bucket_keys = [k for k in plan.group_keys if k.time_bucket_ms]
    if len(bucket_keys) != 1:
        return None
    step_ms = bucket_keys[0].time_bucket_ms
    tags = set(spec.tags)
    for k in plan.group_keys:
        if k.time_bucket_ms:
            continue
        if k.column is None or k.column not in tags:
            return None
    # aggregates: foldable funcs over THE value column only
    if not plan.aggs:
        return None
    for a in plan.aggs:
        if (
            a.func not in _FOLDABLE
            or a.distinct
            or a.filter_where is not None
            or a.column2 is not None
            or a.params
            or a.column != spec.value_col
        ):
            return None
    # select items must be group keys or plain aggs (no row arithmetic)
    out_names = []
    for item in sel.items:
        e = item.expr
        if _is_bucket_expr(e, spec.ts_col):
            pass
        elif isinstance(e, ast.Column) and e.name in tags:
            pass
        elif isinstance(e, ast.FuncCall) and e.name in _FOLDABLE:
            pass
        else:
            return None
        out_names.append(item.output_name)
    # ORDER BY must name output columns (applied after the combine)
    for o in sel.order_by:
        name = o.expr.name if isinstance(o.expr, ast.Column) else str(o.expr)
        if name not in out_names:
            return None
    _, where_ok = _split_where(plan, tags, spec.ts_col)
    if not where_ok:
        return None
    tr = plan.predicate.time_range
    start, end = tr.inclusive_start, tr.exclusive_end
    # first COMPLETE query bucket: a non-aligned start truncates its
    # bucket, which the stored whole-bucket partials cannot represent —
    # that partial head stays on the raw side
    lo = start if start == MIN_TIMESTAMP else -(-start // step_ms) * step_ms
    # coarsest tier dividing the step wins (fewest rows scanned); the
    # raw head/tail outside its window are the same either way
    for suffix, tier_ms in reversed(spec.tiers):
        if step_ms % tier_ms:
            continue
        wm = state.watermark(suffix)
        if wm is None:
            continue
        if catalog.open(rollup_table_name(spec.source, suffix)) is None:
            continue
        cut = (min(wm, end) // step_ms) * step_ms
        if cut <= lo:
            continue  # the rollup would contribute nothing
        return RollupDecision(
            source=spec.source,
            rollup_table=rollup_table_name(spec.source, suffix),
            suffix=suffix,
            tier_ms=tier_ms,
            step_ms=step_ms,
            lo=lo,
            cut=cut,
            start=start,
            end=end,
        )
    return None


def _and(conjuncts: list) -> Optional[ast.Expr]:
    out = None
    for c in conjuncts:
        out = c if out is None else ast.BinaryOp("AND", out, c)
    return out


def _map_agg_item(item: ast.SelectItem) -> ast.SelectItem:
    """One original select item -> its rollup-side form (aliased to the
    original output name so both halves align positionally)."""
    e = item.expr
    if isinstance(e, ast.FuncCall) and e.name in _FOLDABLE:
        col = {
            "sum": "agg_sum",
            "count": "agg_count",
            "min": "agg_min",
            "max": "agg_max",
        }
        if e.name == "avg":
            new: ast.Expr = ast.BinaryOp(
                "/",
                ast.FuncCall("sum", (ast.Column("agg_sum"),)),
                ast.FuncCall("sum", (ast.Column("agg_count"),)),
            )
        elif e.name in ("min", "max"):
            new = ast.FuncCall(e.name, (ast.Column(col[e.name]),))
        else:  # sum / count both fold by summing their partial
            new = ast.FuncCall("sum", (ast.Column(col[e.name]),))
        return ast.SelectItem(new, alias=item.output_name)
    return ast.SelectItem(e, alias=item.output_name)


def try_rollup_serve(factory, plan: QueryPlan):
    """Serve an eligible aggregate from the rollup ladder + raw tail;
    None when the shared predicate refuses (caller runs the normal
    path). ``factory`` is the InterpreterFactory (catalog + executor)."""
    decision = rollup_decision_for(factory.catalog, plan)
    if decision is None:
        return None
    import dataclasses

    from ..query.interpreters import _concat_results, _order_limit_result
    from ..query.planner import Planner
    from ..utils import querystats
    from ..utils.tracectx import span as _span

    state = ROLLUPS.get(plan.table)
    if state is None:  # unregistered between decision and serve
        return None
    spec = state.spec
    sel = plan.select
    tag_conjuncts, _ = _split_where(plan, set(spec.tags), spec.ts_col)
    ts = ast.Column(spec.ts_col)
    planner = Planner(factory.catalog.schema_of)

    # rollup half: the complete buckets [lo, cut) against the tier table
    roll_where = list(tag_conjuncts)
    if decision.lo > MIN_TIMESTAMP:
        roll_where.append(ast.BinaryOp(">=", ts, ast.Literal(decision.lo)))
    roll_where.append(ast.BinaryOp("<", ts, ast.Literal(decision.cut)))
    roll_select = ast.Select(
        items=tuple(_map_agg_item(i) for i in sel.items),
        table=decision.rollup_table,
        where=_and(roll_where),
        group_by=sel.group_by,
    )
    roll_plan = planner.plan(roll_select)
    roll_table = factory.catalog.open(decision.rollup_table)
    with _span("rollup_scan", table=decision.rollup_table):
        results = [factory.executor.execute(roll_plan, roll_table)]
    roll_metrics = factory.executor.last_metrics

    # raw halves against the source with the original aggregates: the
    # partial HEAD bucket [start, lo) and the still-open TAIL [cut, end)
    raw_metrics = None
    raw_ranges = []
    if decision.start < decision.lo:
        raw_ranges.append((decision.start, decision.lo))
    if decision.cut < decision.end:
        raw_ranges.append((decision.cut, decision.end))
    for r_start, r_end in raw_ranges:
        raw_where = list(tag_conjuncts)
        if r_start > MIN_TIMESTAMP:
            raw_where.append(ast.BinaryOp(">=", ts, ast.Literal(r_start)))
        if r_end < MAX_TIMESTAMP:
            raw_where.append(ast.BinaryOp("<", ts, ast.Literal(r_end)))
        raw_select = dataclasses.replace(
            sel,
            items=tuple(
                ast.SelectItem(i.expr, alias=i.output_name)
                for i in sel.items
            ),
            where=_and(raw_where),
            order_by=(),
            limit=None,
            offset=0,
        )
        raw_plan = planner.plan(raw_select)
        src_table = factory.catalog.open(plan.table)
        with _span("rollup_raw_part", table=plan.table):
            results.append(factory.executor.execute(raw_plan, src_table))
        m_part = factory.executor.last_metrics
        raw_metrics = (
            m_part if raw_metrics is None else {
                "rows_scanned": raw_metrics.get("rows_scanned", 0)
                + m_part.get("rows_scanned", 0)
            }
        )

    combined = results[0] if len(results) == 1 else _concat_results(results)
    combined = _order_limit_result(
        combined, sel.order_by, sel.limit, sel.offset
    )
    m = {
        "table": plan.table,
        "path": "rollup",
        "rollup_table": decision.rollup_table,
        "tier": decision.suffix,
        "cut": decision.cut,
        "rollup_rows": roll_metrics.get("result_rows", 0),
        "raw_tail_rows": (
            raw_metrics.get("rows_scanned", 0) if raw_metrics else 0
        ),
        "result_rows": combined.num_rows,
    }
    combined.metrics = m
    factory.executor.last_path = "rollup"
    factory.executor.last_metrics = m
    # The rewrite is a first-class route: ledger/query_stats show
    # route=rollup for the statement (set AFTER the halves so their
    # sub-executions' routes don't win).
    querystats.set_route("rollup")
    return combined
