// Native batch hashing for the ingest hot path.
//
// The reference implements its write path in Rust (row codec + hash,
// src/common_types, components/hash_ext using SeaHash/aHash). Here the
// equivalent native piece is a batch XXH64 used for series-id (tsid)
// computation and partition routing: one C call hashes a whole column
// instead of a Python-loop per row.
//
// XXH64 implemented from the public algorithm specification
// (https://github.com/Cyan4973/xxHash/blob/dev/doc/xxhash_spec.md);
// results must match python-xxhash (same spec) bit-for-bit — verified in
// tests/test_native.py.
//
// Build: g++ -O3 -shared -fPIC -o libhoraedb_native.so xxhash64.cpp

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl(acc, 31);
  return acc * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round1(0, val);
  return acc * P1 + P4;
}

uint64_t xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = round1(v1, read64(p)); p += 8;
      v2 = round1(v2, read64(p)); p += 8;
      v3 = round1(v3, read64(p)); p += 8;
      v4 = round1(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }

  h += static_cast<uint64_t>(len);

  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * P1;
    h = rotl(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl(h, 11) * P1;
    ++p;
  }

  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // namespace

extern "C" {

// Hash n variable-length items packed in `data`; item i spans
// [offsets[i], offsets[i+1]). offsets has n+1 entries.
void hash_var_xx64(const uint8_t* data, const int64_t* offsets, int64_t n,
                   uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = xxh64(data + offsets[i],
                   static_cast<size_t>(offsets[i + 1] - offsets[i]), 0);
  }
}

// Hash n fixed-width items of `itemsize` bytes each.
void hash_fixed_xx64(const uint8_t* data, int64_t itemsize, int64_t n,
                     uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = xxh64(data + i * itemsize, static_cast<size_t>(itemsize), 0);
  }
}

// FNV-1a-style column combine used by compute_tsid:
//   acc[i] = (acc[i] ^ col[i]) * 0x100000001B3
void fnv_mix(uint64_t* acc, const uint64_t* col, int64_t n) {
  constexpr uint64_t kPrime = 0x100000001B3ULL;
  for (int64_t i = 0; i < n; ++i) {
    acc[i] = (acc[i] ^ col[i]) * kPrime;
  }
}

}  // extern "C"
