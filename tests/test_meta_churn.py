"""Multi-meta soak: shard procedure churn while leaders fail over
(ref model: horaemeta HA — coordinator procedures must survive leader
kills; ROADMAP r4 item 5). Two HA metas over a shared journal, two data
nodes, a split -> migrate -> kill-leader -> restart -> merge loop, with
full data-integrity and routing checks at every step."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CPU_ENV = {
    **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http(method, url, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except Exception:
            return e.code, {}
    except (urllib.error.URLError, OSError) as e:
        return 0, {"error": str(e)}


def wait_until(fn, timeout=45.0, interval=0.2, desc="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = fn()
            if last:
                return last
        except Exception as e:  # noqa: BLE001
            last = e
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}: last={last}")


class MetaPool:
    """Issue meta ops against whichever meta currently leads, following
    421 leader hints and retrying across failovers."""

    def __init__(self, ports: list[int]) -> None:
        self.ports = ports

    def op(self, method: str, path: str, payload=None, timeout=60.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            for port in self.ports:
                s, body = http(
                    method, f"http://127.0.0.1:{port}{path}", payload,
                    timeout=30,
                )
                if s == 200 and body.get("role") != "follower":
                    return body
                last = (port, s, body)
                # 421 -> try the hinted leader next loop; 0/5xx -> retry
            time.sleep(0.3)
        raise TimeoutError(f"meta op {path} never succeeded: {last}")

    def leader(self):
        leaders = [
            p for p in self.ports
            if http("GET", f"http://127.0.0.1:{p}/health", timeout=3)[1].get("leader")
        ]
        return leaders[0] if len(leaders) == 1 else None


@pytest.fixture()
def churn_cluster(tmp_path):
    ha_dir = str(tmp_path / "ha")
    meta_ports = [free_port(), free_port()]
    node_ports = [free_port(), free_port()]
    data_dir = str(tmp_path / "shared-store")
    procs: dict[str, subprocess.Popen] = {}

    def spawn_meta(i: int) -> subprocess.Popen:
        port = meta_ports[i]
        p = subprocess.Popen(
            [
                sys.executable, "-m", "horaedb_tpu.meta",
                "--port", str(port),
                "--ha-dir", ha_dir,
                "--advertise", f"127.0.0.1:{port}",
                "--num-shards", "4",
                "--lease-ttl", "1.5",
                "--heartbeat-timeout", "2.5",
                "--election-ttl", "2.0",
                "--tick-interval", "0.25",
            ],
            env=CPU_ENV,
            stdout=open(tmp_path / f"meta{i}-{port}.log", "ab"),
            stderr=subprocess.STDOUT,
        )
        procs[f"meta{i}"] = p
        return p

    for i in range(2):
        spawn_meta(i)
    meta_eps = ", ".join(f'"127.0.0.1:{p}"' for p in meta_ports)
    for i, port in enumerate(node_ports):
        cfg = tmp_path / f"node{i}.toml"
        cfg.write_text(
            f"""
[server]
host = "127.0.0.1"
http_port = {port}

[engine]
data_dir = "{data_dir}"

[cluster]
self_endpoint = "127.0.0.1:{port}"
meta_endpoints = [{meta_eps}]
"""
        )
        procs[f"node{i}"] = subprocess.Popen(
            [sys.executable, "-m", "horaedb_tpu.server", "--config", str(cfg)],
            env=CPU_ENV,
            stdout=open(tmp_path / f"node{i}.log", "wb"),
            stderr=subprocess.STDOUT,
        )

    for port in (*meta_ports, *node_ports):
        wait_until(
            lambda p=port: http("GET", f"http://127.0.0.1:{p}/health", timeout=2)[0] == 200,
            desc=f"{port} health",
        )
    yield meta_ports, node_ports, procs, spawn_meta
    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    for p in procs.values():
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


DDL = (
    "CREATE TABLE {name} (host string TAG, v double, "
    "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
)


def sql(port, query, timeout=20.0):
    return http("POST", f"http://127.0.0.1:{port}/sql", {"query": query},
                timeout=timeout)


class TestProcedureChurnUnderFailover:
    def test_split_migrate_merge_survive_leader_kills(self, churn_cluster):
        meta_ports, node_ports, procs, spawn_meta = churn_cluster
        pool = MetaPool(meta_ports)
        wait_until(pool.leader, desc="initial leader")

        names = [f"ch{i}" for i in range(6)]
        for n in names:
            pool.op("POST", "/meta/v1/table/create",
                    {"name": n, "create_sql": DDL.format(name=n)})
        for n in names:
            def write(n=n):
                s, b = sql(
                    node_ports[0],
                    f"INSERT INTO {n} (host, v, ts) VALUES "
                    + ", ".join(f"('h{j}', {j}.5, {1000 + j})" for j in range(20)),
                )
                return s == 200
            wait_until(write, desc=f"seed {n}")

        def counts_ok():
            for port in node_ports:
                for n in names:
                    s, b = sql(port, f"SELECT count(1) AS c FROM {n}")
                    if s != 200 or b.get("rows", [{}])[0].get("c") != 20:
                        return None
            return True

        wait_until(counts_ok, desc="initial data visible everywhere")

        split_sids: list[int] = []
        for cycle in range(3):
            # 1. split the fattest shard
            shards = pool.op("GET", "/meta/v1/shards")["shards"]
            src = max(shards, key=lambda s: len(s["table_ids"]))
            out = pool.op("POST", "/meta/v1/shard/split",
                          {"shard_id": src["shard_id"]})
            new_sid = out["new_shard_id"]
            split_sids.append(new_sid)

            # 2. migrate it to whichever node doesn't hold it
            view = next(
                s for s in pool.op("GET", "/meta/v1/shards")["shards"]
                if s["shard_id"] == new_sid
            )
            target = next(
                f"127.0.0.1:{p}" for p in node_ports
                if f"127.0.0.1:{p}" != view["node"]
            )
            pool.op("POST", "/meta/v1/shard/migrate",
                    {"shard_id": new_sid, "to_node": target})

            # 3. kill the leader mid-churn; follower takes over
            lp = pool.leader()
            assert lp is not None
            idx = meta_ports.index(lp)
            victim = procs[f"meta{idx}"]
            victim.kill()
            victim.wait(timeout=10)
            other = meta_ports[1 - idx]
            wait_until(
                lambda: http("GET", f"http://127.0.0.1:{other}/health",
                             timeout=3)[1].get("leader"),
                desc=f"failover cycle {cycle}",
            )

            # 4. data must still be fully readable through the churn
            wait_until(counts_ok, desc=f"data integrity cycle {cycle}")

            # 5. merge the split shard back under the NEW leader
            shards = pool.op("GET", "/meta/v1/shards")["shards"]
            assert any(s["shard_id"] == new_sid for s in shards)
            dst = max(
                (s for s in shards if s["shard_id"] != new_sid),
                key=lambda s: len(s["table_ids"]),
            )
            pool.op("POST", "/meta/v1/shard/merge",
                    {"shard_id": new_sid, "into_shard_id": dst["shard_id"]})

            # 6. restart the killed meta: rejoins as follower
            spawn_meta(idx)
            wait_until(
                lambda p=lp: http("GET", f"http://127.0.0.1:{p}/health",
                                  timeout=3)[0] == 200,
                desc=f"meta {idx} rejoin",
            )

        # Steady state: split shards retired, every table routable with
        # all its data, exactly one leader.
        shards = pool.op("GET", "/meta/v1/shards")["shards"]
        assert not any(s["shard_id"] in split_sids for s in shards)
        for n in names:
            r = pool.op("GET", f"/meta/v1/route/{n}")
            assert r["node"], r
        wait_until(counts_ok, desc="final data integrity")
        assert pool.leader() is not None
