"""Pipelined background flush + write-stall backpressure
(engine/flush.py freeze/dump/install split, engine/flush_scheduler.py,
the maintenance-scheduler core, and the stall/shed path).

Covers the PR's acceptance scenarios: writers make progress while a slow
store flushes; the stall bound blocks then sheds with the retryable wire
codes on all three protocols; a crash between SST write and manifest
append loses no data and the orphan sweep collects the file; and
close/ALTER/drop all drain pending flushes.
"""

from __future__ import annotations

import threading
import time

import pytest

import horaedb_tpu
from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.engine.instance import EngineConfig, Instance
from horaedb_tpu.engine.options import TableOptions
from horaedb_tpu.engine.wal import LocalDiskWal
from horaedb_tpu.utils.object_store import MemoryStore


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def rows_at(t0: int, n: int, base: float = 0.0):
    return [
        {"name": "h", "value": base + float(i), "t": t0 + i} for i in range(n)
    ]


class GatedSstStore:
    """ObjectStore wrapper that blocks SST puts on an event — freezes a
    flush mid-upload so tests can assert what happens around it.
    Manifest/WAL objects pass through untouched."""

    def __init__(self, inner, gate: threading.Event) -> None:
        self._inner = inner
        self._gate = gate
        self.sst_put_started = threading.Event()
        self.sst_puts = 0

    def put(self, path, data):
        if path.endswith(".sst"):
            self.sst_put_started.set()
            assert self._gate.wait(30), "test gate never released"
            self.sst_puts += 1
        self._inner.put(path, data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class SlowSstStore:
    """ObjectStore wrapper adding a fixed delay to SST puts."""

    def __init__(self, inner, delay_s: float) -> None:
        self._inner = inner
        self._delay_s = delay_s

    def put(self, path, data):
        if path.endswith(".sst"):
            time.sleep(self._delay_s)
        self._inner.put(path, data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def make_instance(store, wal=None, **cfg):
    defaults = dict(
        background_flush=True,
        compaction_l0_trigger=10**9,  # isolate flush behavior
        compaction_interval_s=0,
    )
    defaults.update(cfg)
    return Instance(store, EngineConfig(**defaults), wal=wal)


def create_demo(inst, **opts):
    return inst.create_table(
        0, 1, "demo", demo_schema(),
        TableOptions.from_kv({"segment_duration": "1h", **opts}),
    )


class TestWritersProgressDuringFlush:
    def test_writes_commit_while_dump_blocked_on_upload(self):
        """The tentpole property: with the dump frozen mid-upload,
        writers keep committing into the fresh mutable memtable."""
        gate = threading.Event()
        store = GatedSstStore(MemoryStore(), gate)
        inst = make_instance(store)
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 50)))
            inst.request_flush(t)  # dump starts, blocks inside store.put
            assert store.sst_put_started.wait(10)

            # The flush is mid-upload. Writes must still complete fast.
            done = threading.Event()

            def write_more():
                for k in range(5):
                    inst.write(
                        t,
                        RowGroup.from_rows(
                            t.schema, rows_at(2000 + 100 * k, 20, base=100.0)
                        ),
                    )
                done.set()

            w = threading.Thread(target=write_more)
            w.start()
            assert done.wait(10), "writers blocked behind the SST upload"
            assert not gate.is_set()  # the upload genuinely never finished
            gate.set()
            w.join()
            res = inst.flush_table(t)
            assert res is not None
            out = inst.read(t)
            assert len(out) == 50 + 5 * 20
        finally:
            gate.set()
            inst.close()

    def test_concurrent_writers_all_land_with_slow_store(self):
        store = SlowSstStore(MemoryStore(), 0.02)
        inst = make_instance(store)
        t = create_demo(inst, write_buffer_size="64kb")
        errors = []

        def writer(w):
            try:
                for b in range(5):
                    inst.write(
                        t,
                        RowGroup.from_rows(
                            t.schema,
                            rows_at((w * 5 + b) * 10_000, 200, base=w * 1e4),
                        ),
                    )
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        try:
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert not errors, errors
            inst.flush_table(t)
            assert len(inst.read(t)) == 4 * 5 * 200
        finally:
            inst.close()


class TestWriteStall:
    def test_stall_blocks_then_recovers_when_flush_completes(self):
        gate = threading.Event()
        store = GatedSstStore(MemoryStore(), gate)
        inst = make_instance(
            store,
            write_stall_immutable_count=1,
            write_stall_immutable_bytes=1,
            write_stall_deadline_s=10.0,
        )
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            t.version.switch_memtable()  # one frozen memtable: at the bound
            inst.request_flush(t)
            assert store.sst_put_started.wait(10)

            # Next write stalls on the bound; releasing the gate lets the
            # flush retire the frozen memtable and the write completes.
            seq = []
            w = threading.Thread(
                target=lambda: seq.append(
                    inst.write(t, RowGroup.from_rows(t.schema, rows_at(2000, 1)))
                )
            )
            w.start()
            time.sleep(0.3)
            assert not seq, "write should be stalled while frozen >= bound"
            gate.set()
            w.join(timeout=10)
            assert seq, "stalled write never completed after flush"

            from horaedb_tpu.utils.metrics import REGISTRY

            assert "horaedb_write_stall_seconds" in set(REGISTRY.families())
        finally:
            gate.set()
            inst.close()

    def test_stall_sheds_with_typed_overloaded_error(self):
        from horaedb_tpu.wlm.admission import OverloadedError

        gate = threading.Event()
        store = GatedSstStore(MemoryStore(), gate)
        inst = make_instance(
            store,
            write_stall_immutable_count=1,
            write_stall_immutable_bytes=1,
            write_stall_deadline_s=0.1,
        )
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            t.version.switch_memtable()
            with pytest.raises(OverloadedError) as ei:
                inst.write(t, RowGroup.from_rows(t.schema, rows_at(2000, 1)))
            assert ei.value.reason == "write_stall"
            assert ei.value.retry_after_s > 0
        finally:
            gate.set()
            inst.close()

    def test_inline_mode_never_stalls(self):
        # background_flush off: the flush runs on the writing thread, so
        # the backpressure path must be a no-op (it would self-deadlock).
        inst = make_instance(
            MemoryStore(),
            background_flush=False,
            write_stall_immutable_count=0,
            write_stall_immutable_bytes=0,
        )
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            inst.flush_table(t)
            assert len(inst.read(t)) == 10
        finally:
            inst.close()


class TestStallWireCodes:
    def test_shed_maps_to_retryable_codes_on_all_three_protocols(self):
        import asyncio
        import socket

        from aiohttp.test_utils import TestClient, TestServer

        from horaedb_tpu.server import create_app
        from horaedb_tpu.server.mysql import MysqlServer
        from horaedb_tpu.server.postgres import PostgresServer
        from test_wire_protocols import MyClient, PgClient
        from test_workload import _mysql_raw_error

        conn = horaedb_tpu.connect(None)
        inst = conn.instance
        gate = threading.Event()
        # Swap in the gated store BEFORE the table exists: TableData
        # captures the store reference at create time.
        inst.store = GatedSstStore(inst.store, gate)
        inst.config.background_flush = True
        inst.config.write_stall_immutable_count = 1
        inst.config.write_stall_immutable_bytes = 1
        inst.config.write_stall_deadline_s = 0.05
        conn.execute(
            "CREATE TABLE stall_w (h string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        conn.execute("INSERT INTO stall_w (h, v, ts) VALUES ('a', 1.0, 100)")
        td = next(t for t in inst.open_tables() if t.name == "stall_w")
        td.version.switch_memtable()  # frozen >= bound; the dump will block
        app = create_app(conn)
        gw = app["sql_gateway"]
        ins = "INSERT INTO stall_w (h, v, ts) VALUES ('b', 2.0, 200)"

        def my_checks(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyClient(s)
            c.handshake()
            errno, sqlstate, msg = _mysql_raw_error(c, ins)
            assert (errno, sqlstate) == (1040, "08004"), (errno, sqlstate, msg)
            assert "write stall" in msg
            s.close()

        def pg_checks(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgClient(s)
            c.startup()
            _, _, _, err = c.query(ins)
            assert err is not None and "53300" in err, err
            s.close()

        async def body():
            client = TestClient(TestServer(app))
            await client.start_server()
            my = MysqlServer(gw, port=0)
            pg = PostgresServer(gw, port=0)
            await my.start()
            await pg.start()
            loop = asyncio.get_running_loop()
            try:
                # HTTP SQL: shed -> 503 + Retry-After
                resp = await client.post("/sql", json={"query": ins})
                assert resp.status == 503, await resp.text()
                assert "Retry-After" in resp.headers
                # raw /write ingest: same retryable contract
                resp = await client.post(
                    "/write",
                    json={
                        "table": "stall_w",
                        "rows": [{"h": "c", "v": 3.0, "ts": 300}],
                    },
                )
                assert resp.status == 503, await resp.text()
                assert "Retry-After" in resp.headers
                await loop.run_in_executor(None, my_checks, my.port)
                await loop.run_in_executor(None, pg_checks, pg.port)
            finally:
                await my.stop()
                await pg.stop()
                await client.close()

        try:
            asyncio.run(body())
        finally:
            gate.set()
            conn.close()


class TestCrashSafety:
    def test_crash_between_sst_write_and_manifest_loses_nothing(self, tmp_path):
        """Data before metadata: a flush that dies after the SST upload
        but before the manifest append leaves orphans (swept at reopen)
        and the rows replay from the WAL — no data loss, no ghost files."""
        store = MemoryStore()
        inst = make_instance(store, wal=LocalDiskWal(str(tmp_path)))
        t = create_demo(inst)
        inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 25)))

        real_append = t.manifest.append_edits

        def boom(edits):
            raise RuntimeError("injected crash before manifest append")

        t.manifest.append_edits = boom
        with pytest.raises(RuntimeError, match="injected crash"):
            inst.flush_table(t)
        t.manifest.append_edits = real_append

        orphans = [p for p in store.list("0/1/") if p.endswith(".sst")]
        assert orphans, "the dump should have written SSTs before the crash"
        # WAL must NOT have been marked flushed past the failed flush.
        assert t.version.flushed_sequence == 0
        inst.close(wait=False)

        # "Reboot": fresh instance over the same store + WAL dir.
        inst2 = make_instance(store, wal=LocalDiskWal(str(tmp_path)))
        t2 = inst2.open_table(0, 1, "demo")
        try:
            out = inst2.read(t2)
            assert len(out) == 25  # replayed from WAL — nothing lost
            leftover = [p for p in store.list("0/1/") if p.endswith(".sst")]
            assert not leftover, f"orphan sweep missed: {leftover}"
            # And the table still flushes cleanly afterwards.
            res = inst2.flush_table(t2)
            assert res.rows_flushed == 25
        finally:
            inst2.close()

    def test_wait_flush_round_trips_wal_mark(self, tmp_path):
        store = MemoryStore()
        wal = LocalDiskWal(str(tmp_path))
        inst = make_instance(store, wal=wal)
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            res = inst.flush_table(t)
            assert res.rows_flushed == 10 and res.flushed_sequence > 0
            # mark_flushed happened (strictly after the manifest append):
            # nothing newer than the flushed sequence remains to replay.
            assert not list(wal.read_from(t.table_id, res.flushed_sequence + 1))
        finally:
            inst.close()


class TestDrains:
    def test_close_table_drains_pending_background_flush(self):
        store = SlowSstStore(MemoryStore(), 0.05)
        inst = make_instance(store)
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 30)))
            inst.request_flush(t)  # queued in the background
            inst.close_table(t)  # must drain + flush the rest durably
            # No WAL here: rows can only come back from flushed SSTs.
            t2 = inst.open_table(0, 1, "demo")
            assert len(inst.read(t2)) == 30
        finally:
            inst.close()

    def test_instance_close_drains_queued_flush(self):
        store = SlowSstStore(MemoryStore(), 0.05)
        inst = make_instance(store)
        t = create_demo(inst)
        inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 15)))
        inst.request_flush(t)
        inst.close(wait=True)  # drain, never abandon the queued dump
        inst2 = make_instance(store)
        try:
            t2 = inst2.open_table(0, 1, "demo")
            assert len(inst2.read(t2)) == 15
        finally:
            inst2.close()

    def test_alter_fences_on_drained_flush(self):
        gate = threading.Event()
        store = GatedSstStore(MemoryStore(), gate)
        inst = make_instance(store)
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            inst.request_flush(t)
            assert store.sst_put_started.wait(10)  # dump is mid-upload
            threading.Timer(0.2, gate.set).start()
            # ALTER must wait for the in-flight dump, flush what's left,
            # then install — never interleave old-schema rows after it.
            new_schema = t.schema.with_added_column(
                ColumnSchema("v2", DatumKind.DOUBLE)
            )
            inst.alter_schema(t, new_schema)
            assert t.schema.version == new_schema.version
            inst.write(
                t,
                RowGroup.from_rows(
                    t.schema,
                    [{"name": "h", "value": 9.0, "v2": 7.0, "t": 9000}],
                ),
            )
            out = inst.read(t)
            by_t = {r["t"]: r for r in out.to_pylist()}
            assert len(out) == 11
            assert by_t[9000]["v2"] == 7.0
            assert by_t[1000]["v2"] is None  # pre-ALTER row, NULL-filled
        finally:
            gate.set()
            inst.close()

    def test_drop_table_with_inflight_flush_leaves_no_files(self):
        gate = threading.Event()
        store = GatedSstStore(MemoryStore(), gate)
        inst = make_instance(store)
        t = create_demo(inst)
        try:
            inst.write(t, RowGroup.from_rows(t.schema, rows_at(1000, 10)))
            inst.request_flush(t)
            assert store.sst_put_started.wait(10)
            threading.Timer(0.2, gate.set).start()
            inst.drop_table(t)  # blocks on flush_lock until the dump ends
            assert t.dropped
            leftover = [p for p in store.list("0/1/") if p.endswith(".sst")]
            assert not leftover, leftover
        finally:
            gate.set()
            inst.close()


class TestSchedulerCore:
    def _metrics(self):
        from horaedb_tpu.engine.flush_scheduler import _METRICS

        return _METRICS

    def _table(self, sid=0, tid=1, name="t"):
        class T:
            space_id = sid
            table_id = tid

        T.name = name
        return T()

    def test_waiter_attaches_to_queued_entry(self):
        import concurrent.futures as cf

        from horaedb_tpu.engine.maintenance_scheduler import MaintenanceScheduler

        started = threading.Event()
        release = threading.Event()
        runs = []

        def run_fn(table):
            started.set()
            release.wait(10)
            runs.append(table.table_id)
            return len(runs)

        s = MaintenanceScheduler(run_fn, self._metrics(), workers=1)
        try:
            t = self._table()
            s.request(t)
            assert started.wait(5)
            # Worker busy: a new request queues; both waiters share it.
            f1, f2 = cf.Future(), cf.Future()
            assert s.request(t, waiter=f1) is True
            assert s.request(t, waiter=f2) is False  # deduped, attached
            release.set()
            assert f1.result(10) == f2.result(10) == 2
            assert runs == [1, 1]
        finally:
            release.set()
            s.close()

    def test_closed_scheduler_fails_waiters_typed(self):
        import concurrent.futures as cf

        from horaedb_tpu.engine.maintenance_scheduler import (
            MaintenanceScheduler,
            SchedulerClosed,
        )

        s = MaintenanceScheduler(lambda t: None, self._metrics(), workers=1)
        s.close()
        f = cf.Future()
        assert s.request(self._table(), waiter=f) is False
        with pytest.raises(SchedulerClosed):
            f.result(1)

    def test_failure_backoff_suppresses_only_waiterless_requests(self):
        import concurrent.futures as cf

        from horaedb_tpu.engine.maintenance_scheduler import MaintenanceScheduler

        def run_fn(table):
            raise RuntimeError("durable failure")

        s = MaintenanceScheduler(run_fn, self._metrics(), workers=1)
        try:
            t = self._table()
            f = cf.Future()
            s.request(t, waiter=f)
            with pytest.raises(RuntimeError):
                f.result(10)
            # Fire-and-forget is now suppressed by backoff...
            assert s.request(t) is False
            assert "0/1" in s.stats()["backoff"]
            # ...but an explicit waiter still gets its attempt...
            f2 = cf.Future()
            assert s.request(t, waiter=f2) is True
            with pytest.raises(RuntimeError):
                f2.result(10)
            # ...and so does an urgent request (a stalled writer's only
            # way out is a retried flush — backoff must not trap it).
            assert s.request(t) is False
            assert s.request(t, urgent=True) is True
        finally:
            s.close(wait=False)
