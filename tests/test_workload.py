"""Workload management (horaedb_tpu/wlm): cost-based admission control,
in-flight read dedup with ledger roles, per-tenant/per-table quotas,
wire-error mapping, and the system.public.workload surface."""

from __future__ import annotations

import asyncio
import socket
import threading
import time

import pytest

import horaedb_tpu
from horaedb_tpu.proxy import Proxy
from horaedb_tpu.server import create_app
from horaedb_tpu.wlm import WorkloadManager
from horaedb_tpu.wlm.admission import (
    AdmissionController,
    COST_HISTORY,
    OverloadedError,
    WEIGHTS,
    classify_plan,
    normalize_shape,
)
from horaedb_tpu.wlm.quota import QuotaExceededError, QuotaManager, TokenBucket


# ---- cost estimator -------------------------------------------------------


class TestCostEstimator:
    def test_normalize_shape_strips_literals(self):
        a = normalize_shape("SELECT v FROM t WHERE h = 'abc' AND ts > 100")
        b = normalize_shape("select  v  from t where h = 'zz''q' and ts > 999999")
        assert a == b
        assert "?" in a and "abc" not in a

    def test_static_classes(self, tmp_path):
        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE ce (h string TAG, v double, ts timestamp KEY)")
        cheap = conn._cached_plan("SELECT v FROM ce WHERE ts >= 0 AND ts < 1000")
        normal = conn._cached_plan(
            "SELECT h, sum(v) FROM ce WHERE ts >= 0 AND ts < 1000 GROUP BY h"
        )
        exp = conn._cached_plan("SELECT v FROM ce")  # unbounded range
        assert classify_plan(cheap)[0] == "cheap"
        assert classify_plan(normal)[0] == "normal"
        assert classify_plan(exp)[0] == "expensive"
        conn.close()

    def test_ewma_overrides_static(self):
        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE ce2 (h string TAG, v double, ts timestamp KEY)")
        sql = "SELECT count(*) AS c FROM ce2 WHERE h = 'x'"
        plan = conn._cached_plan(sql)
        shape = normalize_shape(sql)
        assert classify_plan(plan, shape=shape)[0] == "expensive"  # static
        for _ in range(3):
            COST_HISTORY.observe(shape, 0.001)  # proven fast
        cls, est = classify_plan(plan, shape=shape)
        assert cls == "cheap" and est is not None and est < 50
        for _ in range(10):
            COST_HISTORY.observe(shape, 5.0)  # now proven slow
        assert classify_plan(plan, shape=shape)[0] == "expensive"
        conn.close()


# ---- admission controller -------------------------------------------------


class TestAdmissionController:
    def _hold(self, ctrl, cls, release, entered):
        def run():
            with ctrl.admit(cls):
                entered.append(cls)
                release.wait(10)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    def test_cheap_admits_under_expensive_saturation(self):
        """The acceptance contract: a saturated expensive lane still
        admits a cheap query within its deadline."""
        ctrl = AdmissionController(total_units=8, deadline_s=5.0)
        release = threading.Event()
        entered: list = []
        n_hold = ctrl.expensive_cap // WEIGHTS["expensive"]  # fills the cap
        threads = [
            self._hold(ctrl, "expensive", release, entered) for _ in range(n_hold)
        ]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(entered) < n_hold:
            time.sleep(0.01)
        assert len(entered) == n_hold
        # the expensive lane is at its cap: one more sheds on deadline
        with pytest.raises(OverloadedError) as ei:
            with ctrl.admit("expensive", deadline_s=0.1):
                pass
        assert ei.value.reason == "deadline" and ei.value.retryable
        # ...but a cheap query still has its reserved unit
        t0 = time.perf_counter()
        with ctrl.admit("cheap", deadline_s=2.0):
            waited = time.perf_counter() - t0
        assert waited < 1.0
        release.set()
        for t in threads:
            t.join(5)

    def test_queue_full_sheds_immediately(self):
        # total_units clamps to WEIGHTS["expensive"] + 1 = 4: two normal
        # holders (2 units each) saturate it
        ctrl = AdmissionController(total_units=2, queue_depth=0, deadline_s=5.0)
        assert ctrl.total_units == 4
        release = threading.Event()
        entered: list = []
        threads = [self._hold(ctrl, "normal", release, entered) for _ in range(2)]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(entered) < 2:
            time.sleep(0.01)
        t0 = time.perf_counter()
        with pytest.raises(OverloadedError) as ei:
            with ctrl.admit("normal"):
                pass
        assert ei.value.reason == "queue_full"
        assert time.perf_counter() - t0 < 1.0  # no deadline wait
        release.set()
        for t in threads:
            t.join(5)

    def test_cheap_admits_under_normal_saturation(self):
        """A normal-class (dashboard aggregate) storm must not starve
        cheap point lookups either: non-cheap load collectively stops at
        total_units - 1."""
        ctrl = AdmissionController(total_units=8, deadline_s=5.0)
        release = threading.Event()
        entered: list = []
        # 3 normals (6 units) fill the non-cheap cap of 7; a 4th waits
        threads = [self._hold(ctrl, "normal", release, entered) for _ in range(3)]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(entered) < 3:
            time.sleep(0.01)
        with pytest.raises(OverloadedError):
            with ctrl.admit("normal", deadline_s=0.1):
                pass
        t0 = time.perf_counter()
        with ctrl.admit("cheap", deadline_s=2.0):
            waited = time.perf_counter() - t0
        assert waited < 1.0
        release.set()
        for t in threads:
            t.join(5)

    def test_small_slots_config_still_admits_expensive(self):
        # admission_slots=2 clamps up so an idle controller can always
        # admit one expensive query instead of shedding forever
        ctrl = AdmissionController(total_units=2)
        with ctrl.admit("expensive", deadline_s=0.5):
            assert ctrl.snapshot()["units_in_use"] == WEIGHTS["expensive"]

    def test_snapshot_reflects_occupancy(self):
        ctrl = AdmissionController(total_units=8)
        with ctrl.admit("normal"):
            snap = ctrl.snapshot()
            assert snap["units_in_use"] == WEIGHTS["normal"]
            assert snap["class_units"]["normal"] == WEIGHTS["normal"]
            assert snap["memory_in_use_bytes"] > 0
        assert ctrl.snapshot()["units_in_use"] == 0


# ---- proxy-level dedup with ledger roles ----------------------------------


class TestDedupLedgerRoles:
    def test_n_identical_selects_execute_once_with_roles(self):
        from horaedb_tpu.utils.querystats import STATS_STORE

        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE dd (h string TAG, v double, ts timestamp KEY)")
        conn.execute("INSERT INTO dd (h, v, ts) VALUES ('a', 1.0, 1)")
        proxy = Proxy(conn)
        calls: list = []
        gate = threading.Event()
        orig = conn.interpreters.execute

        def slow_execute(plan):
            calls.append(plan)
            gate.wait(10)  # park the leader so followers pile up
            return orig(plan)

        conn.interpreters.execute = slow_execute
        sql = "SELECT count(*) AS c FROM dd WHERE ts >= 0 AND ts < 5000"
        results: list = [None] * 4
        errors: list = []

        def run(i):
            try:
                results[i] = proxy.handle_sql(sql)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while (
            time.monotonic() < deadline
            and proxy.wlm.dedup.snapshot()["waiting_followers"] < 3
        ):
            time.sleep(0.01)
        assert proxy.wlm.dedup.snapshot()["waiting_followers"] == 3
        gate.set()
        for t in threads:
            t.join(10)
        assert not errors, errors
        assert len(calls) == 1  # exactly one executor run
        assert all(r.to_pylist() == results[0].to_pylist() for r in results)
        rows = [r for r in STATS_STORE.list() if r["sql"] == sql]
        assert len(rows) == 4
        leaders = [r for r in rows if r["dedup_followers"] == 3]
        followers = [r for r in rows if r["dedup_follower"] == 1]
        assert len(leaders) == 1 and len(followers) == 3
        proxy.close()
        conn.close()

    def test_write_bumps_epoch_no_stale_join(self):
        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE de (h string TAG, v double, ts timestamp KEY)")
        proxy = Proxy(conn)
        epoch0 = proxy.wlm.dedup.snapshot()["write_epoch"]
        proxy.handle_sql("INSERT INTO de (h, v, ts) VALUES ('a', 1.0, 1)")
        assert proxy.wlm.dedup.snapshot()["write_epoch"] == epoch0 + 1
        proxy.close()
        conn.close()


# ---- saturated lane end-to-end through the proxy --------------------------


class TestProxySaturation:
    def test_cheap_select_completes_while_expensive_lane_held(self):
        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE sat (h string TAG, v double, ts timestamp KEY)")
        conn.execute("INSERT INTO sat (h, v, ts) VALUES ('a', 1.0, 1)")
        proxy = Proxy(conn)
        ctrl = proxy.wlm.admission
        release = threading.Event()
        entered: list = []

        def hold():
            with ctrl.admit("expensive"):
                entered.append(1)
                release.wait(10)

        n_hold = ctrl.expensive_cap // WEIGHTS["expensive"]
        threads = [threading.Thread(target=hold, daemon=True) for _ in range(n_hold)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(entered) < n_hold:
            time.sleep(0.01)
        t0 = time.perf_counter()
        out = proxy.handle_sql("SELECT v FROM sat WHERE ts >= 0 AND ts < 1000")
        elapsed = time.perf_counter() - t0
        assert out.to_pylist() == [{"v": 1.0}]
        assert elapsed < ctrl.deadline_s
        release.set()
        for t in threads:
            t.join(5)
        proxy.close()
        conn.close()


# ---- quotas ---------------------------------------------------------------


class TestQuota:
    def test_token_bucket_refill_and_zero_rate(self):
        b = TokenBucket(rate=10.0, burst=2.0)
        assert b.try_consume() == 0.0
        assert b.try_consume() == 0.0
        wait = b.try_consume()
        assert 0 < wait <= 0.2
        z = TokenBucket(rate=0.0, burst=0.0)
        assert z.try_consume() == float("inf")

    def test_charge_read_and_write_scopes(self):
        q = QuotaManager()
        q.set_quota("table", "qt", "read_qps", 0.0, burst=0.0)
        with pytest.raises(QuotaExceededError) as ei:
            q.charge_read("default", "qt")
        assert ei.value.retryable and ei.value.retry_after_s > 0
        q.charge_read("default", "other")  # unlimited table passes
        q.set_quota("tenant", "acme", "write_rows", 1.0, burst=1.0)
        q.charge_write("acme", "anytable", 1)
        with pytest.raises(QuotaExceededError):
            q.charge_write("acme", "anytable", 5)
        # runtime adjust: raising the rate unblocks
        q.set_quota("table", "qt", "read_qps", 100.0)
        q.charge_read("default", "qt")

    def test_rejection_does_not_drain_other_buckets(self):
        q = QuotaManager()
        q.set_quota("tenant", "te", "read_qps", 100.0, burst=100.0)
        q.set_quota("table", "hot", "read_qps", 0.0, burst=0.0)
        for _ in range(50):
            with pytest.raises(QuotaExceededError):
                q.charge_read("te", "hot")
        # the rejected attempts must not have consumed tenant allowance
        for _ in range(100):
            q.charge_read("te", "cold")

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "wlm_state.json")
        q1 = QuotaManager(persist_path=path)
        q1.block(["cpu", "mem"])
        q1.set_quota("table", "cpu", "read_qps", 5.0, burst=7.0)
        q2 = QuotaManager(persist_path=path)
        assert q2.blocked() == ["cpu", "mem"]
        snap = q2.snapshot()
        assert any(
            e["name"] == "cpu" and e["kind"] == "read_qps" and e["rate"] == 5.0
            and e["burst"] == 7.0
            for e in snap["quotas"]
        )
        q2.unblock(["cpu"])
        q2.remove_quota("table", "cpu", "read_qps")
        q3 = QuotaManager(persist_path=path)
        assert q3.blocked() == ["mem"]
        assert not q3.snapshot()["quotas"]

    def test_proxy_persists_block_across_restart(self, tmp_path):
        conn = horaedb_tpu.connect(str(tmp_path / "d"))
        p1 = Proxy(conn)
        p1.limiter.block(["cpu"])
        p1.wlm.quota.set_quota("table", "cpu", "read_qps", 9.0)
        p1.close()
        p2 = Proxy(conn)  # fresh proxy over the same data dir
        assert p2.limiter.blocked() == ["cpu"]
        assert any(
            e["name"] == "cpu" and e["rate"] == 9.0
            for e in p2.wlm.quota.snapshot()["quotas"]
        )
        p2.close()
        conn.close()


# ---- wire-error mapping + workload table on all three wires ---------------


def _mysql_raw_error(client, sql):
    """(errno, sqlstate, msg) from a COM_QUERY error packet."""
    client.seq = 0
    client.send_packet(b"\x03" + sql.encode())
    pkt = client.read_packet()
    assert pkt[0] == 0xFF, pkt
    errno = int.from_bytes(pkt[1:3], "little")
    sqlstate = pkt[4:9].decode()
    return errno, sqlstate, pkt[9:].decode()


class TestWireErrorsAndWorkloadTable:
    def test_shed_quota_blocked_codes_and_workload_rows(self):
        from aiohttp.test_utils import TestClient, TestServer

        from horaedb_tpu.server.mysql import MysqlServer
        from horaedb_tpu.server.postgres import PostgresServer
        from test_wire_protocols import MyClient, PgClient

        conn = horaedb_tpu.connect(None)
        conn.execute(
            "CREATE TABLE ww (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        conn.execute("INSERT INTO ww (host, v, ts) VALUES ('a', 1.5, 1000)")
        app = create_app(conn)
        proxy = app["proxy"]
        gw = app["sql_gateway"]
        ctrl = proxy.wlm.admission

        def saturate():
            ctrl.total_units = 0
            ctrl.queue_depth = 0

        def restore():
            ctrl.total_units = 8
            ctrl.queue_depth = 32

        def my_checks(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyClient(s)
            c.handshake()
            # shed -> native 'too many connections' shape
            saturate()
            errno, sqlstate, msg = _mysql_raw_error(c, "SELECT v FROM ww")
            assert (errno, sqlstate) == (1040, "08004"), (errno, sqlstate, msg)
            restore()
            # quota -> same retryable shape
            proxy.wlm.quota.set_quota("table", "ww", "read_qps", 0.0, burst=0.0)
            errno, sqlstate, _ = _mysql_raw_error(c, "SELECT v FROM ww")
            assert (errno, sqlstate) == (1040, "08004")
            proxy.wlm.quota.remove_quota("table", "ww", "read_qps")
            # blocked -> access denied shape
            proxy.limiter.block(["ww"])
            errno, sqlstate, _ = _mysql_raw_error(c, "SELECT v FROM ww")
            assert (errno, sqlstate) == (1142, "42000")
            proxy.limiter.unblock(["ww"])
            # the workload table answers over the MySQL wire
            kind, names, rows = c.query(
                "SELECT name FROM system.public.workload "
                "WHERE category = 'admission'"
            )
            assert kind == "rows" and any("total_units" in r[0] for r in rows)
            s.close()

        def pg_checks(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgClient(s)
            c.startup()
            saturate()
            _, _, _, err = c.query("SELECT v FROM ww")
            assert err is not None and "53300" in err, err
            restore()
            proxy.wlm.quota.set_quota("table", "ww", "read_qps", 0.0, burst=0.0)
            _, _, _, err = c.query("SELECT v FROM ww")
            assert err is not None and "53300" in err
            proxy.wlm.quota.remove_quota("table", "ww", "read_qps")
            proxy.limiter.block(["ww"])
            _, _, _, err = c.query("SELECT v FROM ww")
            assert err is not None and "42501" in err
            proxy.limiter.unblock(["ww"])
            names, rows, _, err = c.query(
                "SELECT name, value FROM system.public.workload "
                "WHERE name = 'horaedb_admission_shed_total'"
            )
            assert err is None and rows
            assert sum(float(r[1]) for r in rows) >= 2  # both wires shed
            s.close()

        async def body():
            client = TestClient(TestServer(app))
            await client.start_server()
            my = MysqlServer(gw, port=0)
            pg = PostgresServer(gw, port=0)
            await my.start()
            await pg.start()
            loop = asyncio.get_running_loop()
            try:
                # HTTP: shed -> 503 + Retry-After
                saturate()
                resp = await client.post("/sql", json={"query": "SELECT v FROM ww"})
                assert resp.status == 503
                assert "Retry-After" in resp.headers
                restore()
                # HTTP: quota -> 429 + Retry-After
                proxy.wlm.quota.set_quota("table", "ww", "read_qps", 0.0, burst=0.0)
                resp = await client.post("/sql", json={"query": "SELECT v FROM ww"})
                assert resp.status == 429
                assert "Retry-After" in resp.headers
                proxy.wlm.quota.remove_quota("table", "ww", "read_qps")
                # HTTP: blocked stays 403
                proxy.limiter.block(["ww"])
                resp = await client.post("/sql", json={"query": "SELECT v FROM ww"})
                assert resp.status == 403
                proxy.limiter.unblock(["ww"])
                # the other wires, off the event loop
                await loop.run_in_executor(None, my_checks, my.port)
                await loop.run_in_executor(None, pg_checks, pg.port)
                # workload table over HTTP reflects the shed/dedup state
                resp = await client.post(
                    "/sql",
                    json={"query": (
                        "SELECT category, name, value "
                        "FROM system.public.workload"
                    )},
                )
                assert resp.status == 200
                rows = (await resp.json())["rows"]
                by_name = {}
                for r in rows:
                    by_name.setdefault(r["name"], 0.0)
                    by_name[r["name"]] += r["value"]
                assert by_name.get("total_units", 0) >= 8
                assert by_name.get("horaedb_admission_shed_total", 0) >= 3
                assert "horaedb_admission_dedup_total" in by_name
                assert "inflight_leaders" in by_name
            finally:
                await my.stop()
                await pg.stop()
                await client.close()

        try:
            asyncio.run(body())
        finally:
            conn.close()


# ---- cross-node admission propagation -------------------------------------


class TestRemoteAdmission:
    def test_admission_class_gates_partial_agg_on_owner(self):
        """The admission class rides the RPC envelope; the owner applies
        its own gate (and lane) around PartialAgg."""
        from horaedb_tpu.remote.client import RemoteEngineClient
        from horaedb_tpu.remote.service import GrpcServer
        from horaedb_tpu.utils.metrics import REGISTRY

        conn = horaedb_tpu.connect(None)
        conn.execute(
            "CREATE TABLE ra (h string TAG, v double, ts timestamp KEY) "
            "ENGINE=Analytic"
        )
        conn.execute("INSERT INTO ra (h, v, ts) VALUES ('a', 1.0, 1)")
        g = GrpcServer(conn, port=0)
        g.start()
        admitted = REGISTRY.counter(
            "horaedb_admission_admitted_total", labels={"class": "expensive"}
        )
        before = admitted.value
        spec = {
            "predicate": {"time_range": [0, 10**15], "filters": []},
            "exact_filters": [], "device_filters": [],
            "group_tags": ["h"], "bucket_ms": 0, "agg_cols": ["v"],
            "trace": {"request_id": 7},
        }
        try:
            client = RemoteEngineClient(f"127.0.0.1:{g.bound_port}")
            out = client._call(
                "PartialAgg", {"table": "ra", "spec": spec,
                               "admission": "expensive"},
            )
            assert out.get("ipc") is not None
            assert admitted.value == before + 1  # the owner's gate ran
            # the owner's queue wait ships home in the serving ledger
            assert "admission_wait_seconds" in out["ledger"]["counts"]
        finally:
            g.stop()
            conn.close()


# ---- HTTP admin/debug surfaces --------------------------------------------


class TestWorkloadEndpoints:
    def test_debug_workload_and_admin_quota(self):
        from aiohttp.test_utils import TestClient, TestServer

        async def body():
            conn = horaedb_tpu.connect(None)
            app = create_app(conn)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                snap = await (await client.get("/debug/workload")).json()
                assert {"admission", "dedup", "quota"} <= set(snap)
                assert snap["admission"]["total_units"] >= 2
                resp = await client.post(
                    "/admin/quota",
                    json={"scope": "table", "name": "cpu",
                          "kind": "read_qps", "rate": 50, "burst": 60},
                )
                assert resp.status == 200
                got = await (await client.get("/admin/quota")).json()
                assert any(
                    e["name"] == "cpu" and e["rate"] == 50.0
                    for e in got["quotas"]
                )
                resp = await client.delete(
                    "/admin/quota",
                    json={"scope": "table", "name": "cpu", "kind": "read_qps"},
                )
                assert (await resp.json())["removed"] is True
                resp = await client.post(
                    "/admin/quota", json={"scope": "bogus", "name": "x",
                                          "kind": "read_qps", "rate": 1},
                )
                assert resp.status == 400
                # per-tenant quota reaches the wire via the tenant header
                resp = await client.post(
                    "/admin/quota",
                    json={"scope": "tenant", "name": "acme",
                          "kind": "read_qps", "rate": 0, "burst": 0},
                )
                assert resp.status == 200
                resp = await client.post(
                    "/sql", json={"query": "SHOW TABLES"},
                )
                assert resp.status == 200  # SHOW isn't a SELECT: uncharged
                resp = await client.post(
                    "/sql",
                    json={"query": "SELECT 1 FROM system.public.tables"},
                    headers={"X-HoraeDB-Tenant": "acme"},
                )
                assert resp.status == 429
                resp = await client.post(
                    "/sql",
                    json={"query": "SELECT 1 FROM system.public.tables"},
                )
                assert resp.status == 200  # other tenants unaffected
            finally:
                await client.close()
                conn.close()

        asyncio.run(body())


# ---- hotspot LRU + decay --------------------------------------------------


class TestHotspotLru:
    def test_bounded_and_decayed(self):
        from horaedb_tpu.proxy import Hotspot

        h = Hotspot(capacity=4, decay_interval_s=0.05, decay_factor=0.25)
        for i in range(100):
            h.record(f"t{i}", False)
        assert len(h.reads) <= 4  # unbounded Counter leak is gone
        for _ in range(8):
            h.record("hot", False)
        time.sleep(0.06)
        h.record("hot", False)  # triggers the periodic decay, then bumps
        top = h.top()
        assert top["reads"]["hot"] == 3  # 8 * 0.25 -> 2, +1
        # sub-1 residues dropped entirely
        assert all(k == "hot" or v >= 1 for k, v in top["reads"].items())

    def test_writes_and_reads_separate(self):
        from horaedb_tpu.proxy import Hotspot

        h = Hotspot(capacity=8)
        h.record("a", True)
        h.record("a", False)
        h.record("a", False)
        top = h.top()
        assert top["writes"]["a"] == 1 and top["reads"]["a"] == 2


# ---- EXPLAIN surface + config knobs ---------------------------------------


class TestExplainAndConfig:
    def test_explain_carries_admission_line(self):
        conn = horaedb_tpu.connect(None)
        conn.execute("CREATE TABLE ex (h string TAG, v double, ts timestamp KEY)")
        conn.execute("INSERT INTO ex (h, v, ts) VALUES ('a', 1.0, 1)")
        lines = [
            r["plan"]
            for r in conn.execute("EXPLAIN SELECT h, sum(v) FROM ex GROUP BY h").to_pylist()
        ]
        adm = [l for l in lines if l.strip().startswith("Admission:")]
        assert adm and "class=expensive" in adm[0] and "lane=low" in adm[0]
        analyzed = [
            r["plan"]
            for r in conn.execute(
                "EXPLAIN ANALYZE SELECT h, sum(v) FROM ex "
                "WHERE ts >= 0 AND ts < 1000 GROUP BY h"
            ).to_pylist()
        ]
        assert any("Admission: class=normal lane=high" in l for l in analyzed)
        conn.close()

    def test_limits_config_knobs(self, tmp_path):
        from horaedb_tpu.utils.config import Config, ConfigError

        p = tmp_path / "c.toml"
        p.write_text(
            "[limits]\n"
            'slow_threshold = "2s"\n'
            "admission_slots = 4\n"
            "admission_queue_depth = 7\n"
            'admission_deadline = "2s"\n'
            'admission_memory_budget = "64mb"\n'
            "dedup = false\n"
        )
        cfg = Config.load(str(p))
        assert cfg.limits.admission_slots == 4
        assert cfg.limits.admission_queue_depth == 7
        assert cfg.limits.admission_deadline_s == 2.0
        assert cfg.limits.admission_memory_budget == 64 << 20
        assert cfg.limits.dedup is False
        mgr = WorkloadManager.from_limits(cfg.limits)
        try:
            assert mgr.admission.total_units == 4
            assert mgr.admission.queue_depth == 7
            assert mgr.dedup.enabled is False
        finally:
            mgr.close()
        bad = tmp_path / "bad.toml"
        bad.write_text("[limits]\nadmission_bogus = 1\n")
        with pytest.raises(ConfigError):
            Config.load(str(bad))
