"""Compaction tests (ref model: analytic_engine tests/compaction_test.rs)."""

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema, TimeRange
from horaedb_tpu.engine.compaction import Compactor, SizeTieredPicker, TimeWindowPicker
from horaedb_tpu.engine.instance import EngineConfig, Instance
from horaedb_tpu.engine.options import TableOptions
from horaedb_tpu.utils.object_store import MemoryStore

HOUR = 3_600_000


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def env(**opts):
    inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=1000))
    table = inst.create_table(
        0, 1, "demo", demo_schema(),
        TableOptions.from_kv({"segment_duration": "1h", **opts}),
    )
    return inst, table


def write_flush(inst, table, rows):
    inst.write(table, RowGroup.from_rows(table.schema, rows))
    # flush without triggering auto-compaction (trigger set high in env())
    from horaedb_tpu.engine.flush import Flusher

    Flusher(table).flush()


class TestPickers:
    def test_time_window_picks_multi_file_windows(self):
        inst, t = env()
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        write_flush(inst, t, [{"name": "h", "value": 9.0, "t": HOUR + 5}])
        tasks = TimeWindowPicker().pick(t)
        assert len(tasks) == 1  # only window 0 has >1 file
        assert len(tasks[0].inputs) == 3

    def test_time_window_includes_overlapping_l1(self):
        inst, t = env()
        for i in range(2):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        Compactor(t).compact()
        assert len(t.version.levels.files_at(1)) == 1
        # New L0 in the same window: task must pull the L1 run back in.
        write_flush(inst, t, [{"name": "h", "value": 5.0, "t": 50}])
        tasks = TimeWindowPicker().pick(t)
        assert len(tasks) == 1 and len(tasks[0].inputs) == 2

    def test_size_tiered_groups_similar_sizes(self):
        inst, t = env(compaction_strategy="size_tiered")
        for i in range(4):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        tasks = SizeTieredPicker(min_threshold=4).pick(t)
        assert len(tasks) == 1 and len(tasks[0].inputs) == 4


class TestCompaction:
    def test_merge_dedup_newest_wins(self):
        inst, t = env()
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])  # overwrite
        write_flush(inst, t, [{"name": "h", "value": 3.0, "t": 200}])
        res = Compactor(t).compact()
        assert res.tasks_run == 1
        assert res.files_removed == 3 and res.files_added == 1
        assert [h.level for h in t.version.levels.all_files()] == [1]
        out = inst.read(t)
        got = sorted((r["t"], r["value"]) for r in out.to_pylist())
        assert got == [(100, 2.0), (200, 3.0)]

    def test_append_mode_keeps_all_rows(self):
        inst, t = env(update_mode="append")
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])
        Compactor(t).compact()
        assert len(inst.read(t)) == 2

    def test_compacted_files_purged_from_store(self):
        inst, t = env()
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        paths_before = {h.path for h in t.version.levels.files_at(0)}
        Compactor(t).compact()
        for p in paths_before:
            assert not inst.store.exists(p)

    def test_survives_reopen(self):
        store = MemoryStore()
        inst = Instance(store, EngineConfig(compaction_l0_trigger=1000))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100}])
        Compactor(t).compact()
        inst2 = Instance(store)
        t2 = inst2.open_table(0, 1, "demo")
        assert [h.level for h in t2.version.levels.all_files()] == [1]
        out = inst2.read(t2)
        assert len(out) == 1  # same key overwritten 3x
        assert out.to_pylist()[0]["value"] == 2.0

    def test_ttl_drops_expired_without_rewrite(self):
        inst, t = env(ttl="1h")
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 10 * HOUR}])
        res = Compactor(t).compact(now_ms=10 * HOUR + HOUR // 2)
        assert res.expired_dropped == 1
        out = inst.read(t)
        assert [r["t"] for r in out.to_pylist()] == [10 * HOUR]

    def test_multi_window_tasks(self):
        inst, t = env()
        for w in range(2):
            for i in range(2):
                write_flush(
                    inst, t, [{"name": "h", "value": float(i), "t": w * HOUR + i}]
                )
        res = Compactor(t).compact()
        assert res.tasks_run == 2
        l1 = t.version.levels.files_at(1)
        assert len(l1) == 2
        # windows don't overlap after compaction
        assert not l1[0].time_range.overlaps(l1[1].time_range)

    def test_staged_pipeline_uploads_before_manifest_and_overlaps_tasks(self):
        """PR-10 satellite: output-SST uploads run on the io pool and a
        task's install is deferred past the NEXT task's merge — but the
        manifest must never reference an object that is not yet durable
        (data before metadata), and the final state must match what the
        serial runner produced."""
        inst, t = env()
        # two windows -> two tasks (the one-deep pipeline actually runs)
        for w in range(2):
            for i in range(3):
                write_flush(
                    inst, t,
                    [{"name": f"h{i}", "value": float(w * 10 + i),
                      "t": w * HOUR + i}],
                )
        store = inst.store
        real_put = store.put
        puts: list[str] = []
        appended_after: list[str] = []

        def spy_put(path, data):
            puts.append(path)
            return real_put(path, data)

        store.put = spy_put
        real_append = t.manifest.append_edits

        def spy_append(edits):
            from horaedb_tpu.engine.manifest import AddFile

            for e in edits:
                if isinstance(e, AddFile) and e.path not in puts:
                    appended_after.append(e.path)
            return real_append(edits)

        t.manifest.append_edits = spy_append
        try:
            res = Compactor(t).compact()
        finally:
            store.put = real_put
            t.manifest.append_edits = real_append
        assert res.tasks_run == 2
        assert not appended_after, (
            "manifest referenced an SST before its upload completed"
        )
        # every manifest-tracked file is durable and readable
        for h in t.version.levels.all_files():
            assert store.exists(h.path)
        got = sorted(
            (r["t"], r["value"]) for r in inst.read(t).to_pylist()
        )
        assert got == sorted(
            (w * HOUR + i, float(w * 10 + i))
            for w in range(2) for i in range(3)
        )

    def test_stream_writer_finalize_upload_split(self):
        """finalize() encodes without storing; upload() makes it
        durable; close() remains finalize+upload."""
        from horaedb_tpu.engine.sst.reader import SstReader
        from horaedb_tpu.engine.sst.writer import SstStreamWriter

        store = MemoryStore()
        schema = demo_schema()
        w = SstStreamWriter(store, "0/9/1.sst", 1)
        rows = RowGroup.from_rows(
            schema,
            [{"name": "h", "value": 1.0, "t": 100},
             {"name": "h", "value": 2.0, "t": 200}],
        )
        w.append(rows, max_sequence=7)
        out = w.finalize()
        assert out is not None
        meta, raw = out
        assert meta.num_rows == 2 and meta.size_bytes == len(raw)
        assert not store.exists("0/9/1.sst")  # finalize does NOT store
        w.upload(raw)
        assert store.exists("0/9/1.sst")
        back = SstReader(store, "0/9/1.sst").read(schema)
        assert len(back) == 2
        # empty writer: finalize -> None, close -> None
        w2 = SstStreamWriter(store, "0/9/2.sst", 2)
        assert w2.finalize() is None and w2.close() is None

    def test_auto_compact_triggered_by_flush_inline(self):
        """background_compaction=False keeps the deterministic mode."""
        inst = Instance(
            MemoryStore(),
            EngineConfig(compaction_l0_trigger=3, background_compaction=False),
        )
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": float(i), "t": 100 + i}]))
            inst.flush_table(t)
        assert len(t.version.levels.files_at(0)) == 0
        assert len(t.version.levels.files_at(1)) == 1

    def test_auto_compact_runs_in_background(self):
        """Default mode: flush returns with L0 intact (the writer never
        pays for the merge); the scheduler folds them shortly after,
        and close() drains whatever is still queued."""
        import time as _time

        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=3))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": float(i), "t": 100 + i}]))
            inst.flush_table(t)
        deadline = _time.monotonic() + 15
        while _time.monotonic() < deadline:
            # Both conditions: the compactor adds the L1 output before
            # removing L0 inputs, so L1==1 alone can be a torn view.
            if (len(t.version.levels.files_at(1)) == 1
                    and len(t.version.levels.files_at(0)) == 0):
                break
            _time.sleep(0.02)
        assert len(t.version.levels.files_at(1)) == 1
        assert len(t.version.levels.files_at(0)) == 0
        inst.close()

    def test_close_drains_queued_compaction(self):
        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=2))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(2):
            inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": float(i), "t": 100 + i}]))
            inst.flush_table(t)
        inst.close(wait=True)  # must not abandon the queued merge
        assert len(t.version.levels.files_at(1)) == 1

    def test_close_time_flush_cannot_resurrect_scheduler(self, tmp_path):
        """Connection.close flushes tables via the catalog, and those
        flushes may trip the compaction trigger. That request must land
        in the still-draining scheduler (catalog first, then instance
        drain) — never lazily rebirth one after close, whose zombie merge
        would race the next Connection over the same manifest (fuzz
        seed 2's referenced-SST loss)."""
        import horaedb_tpu

        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(
            "CREATE TABLE zz (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic "
            "WITH (segment_duration='1h')"
        )
        inst = conn.instance
        tbl = conn.catalog.open("zz")
        n = 0
        # Arm the trigger: enough flushed L0 runs in one window that the
        # close-time flush's maybe_compact fires.
        for i in range(inst.config.compaction_l0_trigger):
            conn.execute(
                f"INSERT INTO zz (host, v, ts) VALUES ('h', {float(i)}, {1000 + i})"
            )
            n += 1
            tbl.flush()
        # One more unflushed row so catalog.close performs a real flush.
        conn.execute(f"INSERT INTO zz (host, v, ts) VALUES ('h', 9.0, 2000)")
        n += 1
        conn.close()
        assert inst._closed and inst._compactions is None
        assert inst._compaction_scheduler() is None  # terminal, no rebirth
        conn2 = horaedb_tpu.connect(str(tmp_path / "db"))
        out = conn2.execute("SELECT count(1) AS c FROM zz").to_pylist()
        assert out[0]["c"] == n
        conn2.close()

    def test_close_table_fences_queued_compaction(self):
        """close_table retires the handle under serial_lock: a background
        merge queued by the close-time flush must bail instead of racing
        the table's next owner over the manifest (shard handover)."""
        from horaedb_tpu.engine.compaction import Compactor

        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=100))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": float(i), "t": 100 + i}]))
            inst.flush_table(t)
        inst.close_table(t, flush=False)
        assert t.retired
        result = Compactor(t).compact()  # the stale queued merge, post-close
        assert result.tasks_run == 0
        assert len(t.version.levels.files_at(0)) == 3  # untouched
        inst.close()

    def test_background_compaction_skips_dropped_table(self):
        from horaedb_tpu.engine.compaction import Compactor

        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=2))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": 1.0, "t": 100}]))
        inst.flush_table(t)
        t.dropped = True
        result = Compactor(t).compact()
        assert result.tasks_run == 0
        inst.close()

    def test_swap_files_is_atomic_to_readers(self):
        """A reader snapshotting the levels mid-compaction must see the
        merge's inputs XOR its output — never both (APPEND reads don't
        dedup; a torn view doubles rows) and never neither (rows vanish)."""
        import threading

        from horaedb_tpu.common_types.time_range import TimeRange
        from horaedb_tpu.engine.sst.manager import FileHandle, LevelsController
        from horaedb_tpu.engine.sst.meta import SstMeta

        def handle(fid, level):
            meta = SstMeta(
                file_id=fid, time_range=TimeRange(0, 1000), max_sequence=fid,
                num_rows=1, size_bytes=1, schema_version=1, column_ranges={},
            )
            return FileHandle(meta, f"p/{fid}.sst", level)

        levels = LevelsController()
        levels.add_file(0, handle(1, 0))
        levels.add_file(0, handle(2, 0))
        stop = threading.Event()
        torn: list[str] = []

        def reader():
            # The file set alternates atomically between {1,2} and {3,4};
            # any other observed combination is a torn view.
            while not stop.is_set():
                files = {h.file_id for h in levels.all_files()}
                if files not in ({1, 2}, {3, 4}):
                    torn.append(f"torn: {sorted(files)}")

        r = threading.Thread(target=reader, daemon=True)
        r.start()
        for _ in range(500):
            levels.swap_files(
                [(1, handle(3, 1)), (1, handle(4, 1))], [(0, 1), (0, 2)]
            )
            levels.swap_files(
                [(0, handle(1, 0)), (0, handle(2, 0))], [(1, 3), (1, 4)]
            )
        stop.set()
        r.join(timeout=10)
        assert not torn, torn[:3]
        levels.drain_purge_queue()

    def test_large_randomized_dedup_correctness(self):
        inst, t = env()
        rng = np.random.default_rng(11)
        expect = {}
        for run in range(6):
            rows = []
            for _ in range(500):
                ts = int(rng.integers(0, HOUR))
                name = f"h{rng.integers(0, 5)}"
                v = float(rng.random())
                rows.append({"name": name, "value": v, "t": ts})
                expect[(name, ts)] = v  # later runs overwrite
            write_flush(inst, t, rows)
        Compactor(t).compact()
        out = inst.read(t)
        got = {(r["name"], r["t"]): r["value"] for r in out.to_pylist()}
        assert got == expect

    def test_chunked_pipeline_matches_single_shot(self, monkeypatch):
        """The tsid-range chunked pipeline (big merges) must agree exactly
        with the single-shot kernel: same survivors, same order, dedup
        correct across chunk boundaries (duplicate keys share a chunk)."""
        monkeypatch.setenv("HORAEDB_MERGE_CHUNK_ROWS", "500")
        from horaedb_tpu.engine.compaction import merge_chunk_count

        inst, t = env()
        rng = np.random.default_rng(3)
        expect = {}
        for run in range(5):
            rows = []
            for _ in range(800):
                ts = int(rng.integers(0, HOUR))
                name = f"h{rng.integers(0, 7)}"  # few series: heavy overlap
                v = float(rng.random())
                rows.append({"name": name, "value": v, "t": ts})
                expect[(name, ts)] = v
            write_flush(inst, t, rows)
        assert merge_chunk_count(4000) > 1  # the env knob took effect
        Compactor(t).compact()
        out = inst.read(t)
        got = {(r["name"], r["t"]): r["value"] for r in out.to_pylist()}
        assert got == expect
        # output SSTs are globally (tsid, ts)-sorted despite per-chunk merges
        from horaedb_tpu.engine.sst.reader import SstReader

        for h in t.version.levels.files_at(1):
            rows = SstReader(t.store, h.path).read(t.schema)
            tsid = rows.columns["tsid"].astype(np.uint64)
            ts = rows.timestamps.astype(np.int64)
            comp = list(zip(tsid.tolist(), ts.tolist()))
            assert comp == sorted(comp)


class TestAdviceRegressions:
    """Regressions for the round-1 advisor findings (ADVICE.md)."""

    def test_purge_deferred_while_read_pinned(self):
        # A reader holding a view picked before compaction's version swap
        # must still find the replaced SSTs on disk (deferred purge).
        inst, t = env()
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        paths_before = {h.path for h in t.version.levels.files_at(0)}
        with t.version.levels.read_pin():
            Compactor(t).compact()
            for p in paths_before:
                assert inst.store.exists(p), "SST purged under an active read pin"
        # Pin released: the next maintenance drain deletes them.
        inst._purge(t)
        for p in paths_before:
            assert not inst.store.exists(p)

    def test_purge_drains_fully_without_readers(self):
        inst, t = env()
        for i in range(2):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        paths_before = {h.path for h in t.version.levels.files_at(0)}
        Compactor(t).compact()
        for p in paths_before:
            assert not inst.store.exists(p)

    def test_file_spanning_windows_not_double_compacted(self):
        # After segment_duration shrinks, an L1 run spanning two new windows
        # is picked into both window tasks; only one may consume it.
        import dataclasses

        inst, t = env(update_mode="append", segment_duration="2h")
        for _ in range(2):
            write_flush(
                inst,
                t,
                [
                    {"name": "h", "value": 1.0, "t": 100},
                    {"name": "h", "value": 2.0, "t": HOUR + 100},
                ],
            )
        Compactor(t).compact()
        assert len(t.version.levels.files_at(1)) == 1  # spans [0, 2h)
        t.options = dataclasses.replace(t.options, segment_duration_ms=HOUR)
        write_flush(inst, t, [{"name": "h", "value": 3.0, "t": 200}])
        write_flush(inst, t, [{"name": "h", "value": 4.0, "t": HOUR + 200}])
        Compactor(t).compact()
        out = inst.read(t)
        # APPEND mode: every written row exactly once (6 writes total);
        # double consumption would duplicate the 4 L1 rows.
        assert len(out) == 6
        ts = sorted(r["t"] for r in out.to_pylist())
        assert ts == [100, 100, 200, HOUR + 100, HOUR + 100, HOUR + 200]


class TestDedupPruningRegression:
    def test_value_filter_pruning_cannot_resurface_overwritten_row(self):
        # SST1 holds (h,100)=1.0; SST2 overwrites with 100.0. A scan whose
        # predicate has value<50 must NOT prune SST2's row group and hand
        # back the stale 1.0 (merge_read leaves value filtering to the
        # executor, so the correct result here is the newest row).
        from horaedb_tpu.table_engine.predicate import (
            ColumnFilter,
            FilterOp,
            Predicate,
        )

        inst, t = env()
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 100.0, "t": 100}])
        pred = Predicate.all_time([ColumnFilter("value", FilterOp.LT, 50.0)])
        out = inst.read(t, pred)
        vals = [r["value"] for r in out.to_pylist()]
        assert vals == [100.0], f"stale overwritten row resurfaced: {vals}"

    def test_disjoint_ssts_value_prune_and_skip_merge(self):
        """Time-DISJOINT deduped SSTs (the flushed steady state): value
        filters reach the reader (row groups prune by min/max stats) and
        the merge is skipped — results identical, fewer rows read."""
        from horaedb_tpu.engine.sst.reader import SstReader
        from horaedb_tpu.table_engine.predicate import (
            ColumnFilter,
            FilterOp,
            Predicate,
        )

        inst, t = env(num_rows_per_row_group="64")
        # Two disjoint windows (segment 1h); values such that only a few
        # row groups can contain value > 900.
        for w in range(2):
            rows = [
                {"name": f"h{i % 4}", "value": float(w * 500 + i),
                 "t": w * HOUR + i * 1000}
                for i in range(500)
            ]
            write_flush(inst, t, rows)
        read_counts = []
        orig = SstReader.read

        def spy(self, schema, predicate=None, projection=None):
            out = orig(self, schema, predicate, projection=projection)
            read_counts.append(len(out))
            return out

        SstReader.read = spy
        try:
            pred = Predicate.all_time(
                [ColumnFilter("value", FilterOp.GT, 900.0)]
            )
            out = inst.read(t, pred)
        finally:
            SstReader.read = orig
        # correctness: superset of matches at row-group granularity; the
        # true matches present
        vals = [r["value"] for r in out.to_pylist()]
        assert {v for v in vals if v > 900.0} == {
            float(500 + i) for i in range(401, 500)
        }
        # the first window (max value 499) pruned entirely
        assert read_counts[0] == 0 or read_counts[1] == 0, read_counts
        assert sum(read_counts) < 1000, read_counts

    def test_explicit_pk_without_ts_never_takes_disjoint_shortcut(self):
        """Review repro: PRIMARY KEY(name) — one key's versions live in
        DIFFERENT time windows, so time-disjoint SSTs still need the
        merge; the shortcut must gate on ts ∈ primary key."""
        from horaedb_tpu.common_types import (
            ColumnSchema, DatumKind, Schema,
        )

        schema = Schema.build(
            [
                ColumnSchema("name", DatumKind.STRING, is_tag=True),
                ColumnSchema("value", DatumKind.DOUBLE),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
            primary_key=["name"],
        )
        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=1000))
        t = inst.create_table(
            0, 1, "kv", schema,
            TableOptions.from_kv({"segment_duration": "1h"}),
        )
        write_flush(inst, t, [{"name": "a", "value": 1.0, "t": 1000}])
        write_flush(inst, t, [{"name": "a", "value": 2.0, "t": HOUR + 1000}])
        out = inst.read(t)
        assert [r["value"] for r in out.to_pylist()] == [2.0], (
            "overwritten key version resurfaced via the disjoint shortcut"
        )

    def test_overlapping_ssts_still_merge_exactly(self):
        # Same key overwritten across two OVERLAPPING SSTs: the disjoint
        # shortcut must NOT engage; newest wins.
        inst, t = env()
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])
        out = inst.read(t)
        assert [r["value"] for r in out.to_pylist()] == [2.0]

    def test_sweep_respects_purge_queue_under_pin(self):
        # Purge-queued (pin-protected) SSTs are referenced, not orphans;
        # the open-time sweep must not delete them out from under a reader.
        inst, t = env()
        for i in range(2):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        paths_before = {h.path for h in t.version.levels.files_at(0)}
        with t.version.levels.read_pin():
            Compactor(t).compact()
            inst._sweep_orphan_ssts(t)
            for p in paths_before:
                assert inst.store.exists(p), "sweep deleted a pin-protected SST"
        inst._purge(t)
        for p in paths_before:
            assert not inst.store.exists(p)

    def test_cross_window_rows_keep_their_own_sequence(self):
        # OVERWRITE table: an L1 run spanning two windows (after ALTER
        # shrank segment_duration) is compacted with window A; its window-B
        # rows must NOT get stamped with window A's newer sequence, or a
        # later window-B compaction resurrects the stale value.
        import dataclasses

        inst, t = env(segment_duration="2h")
        K = HOUR + 100  # the contested key's timestamp (window B under 1h)
        write_flush(
            inst, t,
            [{"name": "h", "value": 10.0, "t": 100},
             {"name": "h", "value": 1.0, "t": K}],
        )
        write_flush(inst, t, [{"name": "h", "value": 11.0, "t": 150}])
        Compactor(t).compact()
        assert len(t.version.levels.files_at(1)) == 1  # spans [0, 2h)
        # Newer write overwrites the contested key; stays in its own L0.
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": K}])
        t.options = dataclasses.replace(t.options, segment_duration_ms=HOUR)
        # Trigger a window-A task that consumes the spanning L1 run.
        write_flush(inst, t, [{"name": "h", "value": 12.0, "t": 200}])
        # ONE call: the re-pick loop compacts window B (skipped in the
        # first pass because window A consumed the spanning L1 run) too.
        Compactor(t).compact()
        got = {r["t"]: r["value"] for r in inst.read(t).to_pylist()}
        assert got[K] == 2.0, f"stale overwritten value resurrected: {got[K]}"

    def test_explicit_primary_key_fallback_dedup(self):
        # No-tsid table (explicit PRIMARY KEY): compaction's host lexsort
        # fallback path, with duplicate keys across runs.
        from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
        from horaedb_tpu.utils.object_store import MemoryStore

        schema = Schema.build(
            [
                ColumnSchema("name", DatumKind.STRING, is_tag=True),
                ColumnSchema("value", DatumKind.DOUBLE),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
            primary_key=["name", "t"],
        )
        assert schema.tsid_index is None
        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=1000))
        t = inst.create_table(
            0, 1, "pk", schema, TableOptions.from_kv({"segment_duration": "1h"})
        )
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])
        res = Compactor(t).compact()
        assert res.tasks_run == 1
        out = inst.read(t)
        assert [(r["t"], r["value"]) for r in out.to_pylist()] == [(100, 2.0)]

    def test_periodic_tick_expires_ttl_on_idle_table(self):
        """The scheduler's own picking loop (ref: scheduler.rs background
        loop) must expire TTL data and fold L0 on tables that stopped
        receiving writes — flush-triggered requests alone never would."""
        import time as _time

        inst = Instance(MemoryStore(), EngineConfig(compaction_interval_s=0.05))
        now = int(_time.time() * 1000)
        t = inst.create_table(
            0, 1, "demo", demo_schema(),
            TableOptions.from_kv({"segment_duration": "1h", "ttl": "1h"}),
        )
        inst.write(t, RowGroup.from_rows(
            t.schema, [{"name": "h", "value": 1.0, "t": now - 7_200_000}]
        ))
        # Assert on the flush RESULT, not the live level state: the flush
        # itself requests the TTL compaction, which can expire the file
        # on its worker before this thread wakes from the completion.
        res = inst.flush_table(t)
        assert res.files_added == 1
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline and t.version.levels.files_at(0):
            _time.sleep(0.02)
        assert not t.version.levels.files_at(0)
        inst.close()

    def test_periodic_tick_disabled_by_config(self):
        import time as _time

        inst = Instance(MemoryStore(), EngineConfig(compaction_interval_s=0))
        t = inst.create_table(
            0, 1, "demo", demo_schema(),
            TableOptions.from_kv({"segment_duration": "1h"}),
        )
        assert inst._compactions is None  # no eager scheduler, no thread
        inst.close()

    def test_size_tiered_trigger_agrees_with_picker(self):
        """needs_work must not re-request a table whose picker emits no
        task (size_tiered files that never group) — that loop would run
        a futile serial_lock-holding pass every tick forever."""
        from horaedb_tpu.engine.compaction import Compactor
        from horaedb_tpu.engine.sst.manager import FileHandle
        from horaedb_tpu.engine.sst.meta import SstMeta

        inst, t = env(compaction_strategy="size_tiered")
        # wildly different sizes in one window: picker groups nothing
        for i, size in enumerate([1_000, 50_000, 2_000_000, 80_000_000]):
            meta = SstMeta(
                file_id=100 + i, time_range=TimeRange(0, 1000),
                max_sequence=i + 1, num_rows=10, size_bytes=size,
                schema_version=1, column_ranges={},
            )
            t.version.levels.add_file(0, FileHandle(meta, f"x/{i}.sst", 0))
        assert not Compactor.needs_work(t, l0_trigger=2)
        inst.close()

    def test_scheduler_failure_backoff(self):
        from horaedb_tpu.engine.compaction_scheduler import CompactionScheduler

        calls = []

        def boom(table):
            calls.append(1)
            raise RuntimeError("x")

        class T:
            space_id, table_id, name = 0, 1, "t"

        s = CompactionScheduler(boom)
        assert s.request(T()) is True
        import time as _time

        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and not calls:
            _time.sleep(0.01)
        _time.sleep(0.05)  # let the failure register
        assert s.request(T()) is False  # suppressed by backoff
        assert len(calls) == 1
        # Dropping/retiring the table prunes its backoff entry — a
        # durably-failing table must not leave a stats() row forever.
        assert "0/1" in s.stats()["backoff"]
        s.forget((0, 1))
        assert s.stats()["backoff"] == {}
        assert s.request(T()) is True  # backoff cleared with the entry
        s.close()

    def test_abandoned_instance_periodic_thread_exits(self):
        """An Instance dropped without close() must be collectable; its
        tick thread sees the dead weakref and exits."""
        import gc
        import threading
        import time as _time

        def make():
            inst = Instance(
                MemoryStore(),
                EngineConfig(compaction_l0_trigger=1, compaction_interval_s=0.05),
            )
            t = inst.create_table(
                0, 1, "demo", demo_schema(),
                TableOptions.from_kv({"segment_duration": "1h"}),
            )
            inst.write(t, RowGroup.from_rows(
                t.schema, [{"name": "h", "value": 1.0, "t": 100}]
            ))
            inst.flush_table(t)

        before = {
            th.ident for th in threading.enumerate()
            if th.name == "compaction-tick"
        }
        make()
        gc.collect()
        # Only THIS test's thread (0.05s tick) is expected to exit within
        # the deadline — other tests' abandoned 60s-interval threads only
        # notice the dead weakref on their next tick.
        def mine():
            return [
                th for th in threading.enumerate()
                if th.name == "compaction-tick" and th.ident not in before
            ]

        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and mine():
            _time.sleep(0.05)
        assert not mine()
