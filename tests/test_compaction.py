"""Compaction tests (ref model: analytic_engine tests/compaction_test.rs)."""

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema, TimeRange
from horaedb_tpu.engine.compaction import Compactor, SizeTieredPicker, TimeWindowPicker
from horaedb_tpu.engine.instance import EngineConfig, Instance
from horaedb_tpu.engine.options import TableOptions
from horaedb_tpu.utils.object_store import MemoryStore

HOUR = 3_600_000


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def env(**opts):
    inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=1000))
    table = inst.create_table(
        0, 1, "demo", demo_schema(),
        TableOptions.from_kv({"segment_duration": "1h", **opts}),
    )
    return inst, table


def write_flush(inst, table, rows):
    inst.write(table, RowGroup.from_rows(table.schema, rows))
    # flush without triggering auto-compaction (trigger set high in env())
    from horaedb_tpu.engine.flush import Flusher

    Flusher(table).flush()


class TestPickers:
    def test_time_window_picks_multi_file_windows(self):
        inst, t = env()
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        write_flush(inst, t, [{"name": "h", "value": 9.0, "t": HOUR + 5}])
        tasks = TimeWindowPicker().pick(t)
        assert len(tasks) == 1  # only window 0 has >1 file
        assert len(tasks[0].inputs) == 3

    def test_time_window_includes_overlapping_l1(self):
        inst, t = env()
        for i in range(2):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        Compactor(t).compact()
        assert len(t.version.levels.files_at(1)) == 1
        # New L0 in the same window: task must pull the L1 run back in.
        write_flush(inst, t, [{"name": "h", "value": 5.0, "t": 50}])
        tasks = TimeWindowPicker().pick(t)
        assert len(tasks) == 1 and len(tasks[0].inputs) == 2

    def test_size_tiered_groups_similar_sizes(self):
        inst, t = env(compaction_strategy="size_tiered")
        for i in range(4):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        tasks = SizeTieredPicker(min_threshold=4).pick(t)
        assert len(tasks) == 1 and len(tasks[0].inputs) == 4


class TestCompaction:
    def test_merge_dedup_newest_wins(self):
        inst, t = env()
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])  # overwrite
        write_flush(inst, t, [{"name": "h", "value": 3.0, "t": 200}])
        res = Compactor(t).compact()
        assert res.tasks_run == 1
        assert res.files_removed == 3 and res.files_added == 1
        assert [h.level for h in t.version.levels.all_files()] == [1]
        out = inst.read(t)
        got = sorted((r["t"], r["value"]) for r in out.to_pylist())
        assert got == [(100, 2.0), (200, 3.0)]

    def test_append_mode_keeps_all_rows(self):
        inst, t = env(update_mode="append")
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 100}])
        Compactor(t).compact()
        assert len(inst.read(t)) == 2

    def test_compacted_files_purged_from_store(self):
        inst, t = env()
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100 + i}])
        paths_before = {h.path for h in t.version.levels.files_at(0)}
        Compactor(t).compact()
        for p in paths_before:
            assert not inst.store.exists(p)

    def test_survives_reopen(self):
        store = MemoryStore()
        inst = Instance(store, EngineConfig(compaction_l0_trigger=1000))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            write_flush(inst, t, [{"name": "h", "value": float(i), "t": 100}])
        Compactor(t).compact()
        inst2 = Instance(store)
        t2 = inst2.open_table(0, 1, "demo")
        assert [h.level for h in t2.version.levels.all_files()] == [1]
        out = inst2.read(t2)
        assert len(out) == 1  # same key overwritten 3x
        assert out.to_pylist()[0]["value"] == 2.0

    def test_ttl_drops_expired_without_rewrite(self):
        inst, t = env(ttl="1h")
        write_flush(inst, t, [{"name": "h", "value": 1.0, "t": 100}])
        write_flush(inst, t, [{"name": "h", "value": 2.0, "t": 10 * HOUR}])
        res = Compactor(t).compact(now_ms=10 * HOUR + HOUR // 2)
        assert res.expired_dropped == 1
        out = inst.read(t)
        assert [r["t"] for r in out.to_pylist()] == [10 * HOUR]

    def test_multi_window_tasks(self):
        inst, t = env()
        for w in range(2):
            for i in range(2):
                write_flush(
                    inst, t, [{"name": "h", "value": float(i), "t": w * HOUR + i}]
                )
        res = Compactor(t).compact()
        assert res.tasks_run == 2
        l1 = t.version.levels.files_at(1)
        assert len(l1) == 2
        # windows don't overlap after compaction
        assert not l1[0].time_range.overlaps(l1[1].time_range)

    def test_auto_compact_triggered_by_flush(self):
        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=3))
        t = inst.create_table(
            0, 1, "demo", demo_schema(), TableOptions.from_kv({"segment_duration": "1h"})
        )
        for i in range(3):
            inst.write(t, RowGroup.from_rows(t.schema, [{"name": "h", "value": float(i), "t": 100 + i}]))
            inst.flush_table(t)
        assert len(t.version.levels.files_at(0)) == 0
        assert len(t.version.levels.files_at(1)) == 1

    def test_large_randomized_dedup_correctness(self):
        inst, t = env()
        rng = np.random.default_rng(11)
        expect = {}
        for run in range(6):
            rows = []
            for _ in range(500):
                ts = int(rng.integers(0, HOUR))
                name = f"h{rng.integers(0, 5)}"
                v = float(rng.random())
                rows.append({"name": name, "value": v, "t": ts})
                expect[(name, ts)] = v  # later runs overwrite
            write_flush(inst, t, rows)
        Compactor(t).compact()
        out = inst.read(t)
        got = {(r["name"], r["t"]): r["value"] for r in out.to_pylist()}
        assert got == expect
