"""Learned aggregation-kernel routing (PR 6).

Covers: the three-segment-impl equivalence property (mxu / scatter /
hash must be indistinguishable on every input), the KernelRouter's
probe/serve/re-probe loop and cardinality seeding, the guarded env-int
satellite, the dist-agg step-cache LRU bound, the scan-cache dtype
auto-tuning, and the end-to-end kill switch + ledger surfaces.
"""

import dataclasses

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.ops.encoding import build_padded_batch, next_pow2
from horaedb_tpu.ops.scan_agg import (
    ScanAggSpec,
    mxu_max_segments,
    pinned_segment_impl,
    resolve_segment_impl,
    scan_aggregate,
)


@pytest.fixture()
def db():
    conn = horaedb_tpu.connect(None)
    yield conn
    conn.close()


@pytest.fixture(autouse=True)
def _fresh_router():
    from horaedb_tpu.query.path_router import KERNEL_ROUTER

    KERNEL_ROUTER.reset()
    yield
    KERNEL_ROUTER.reset()


def _dispatch(batch, spec, impl, slots=0, literals=()):
    return scan_aggregate(
        batch,
        dataclasses.replace(spec, segment_impl=impl, hash_slots=slots),
        list(literals),
    )


def _assert_states_equal(a, b, label):
    assert np.array_equal(np.asarray(a.counts), np.asarray(b.counts)), label
    for fa, fb, name in (
        (a.sums, b.sums, "sums"),
        (a.mins, b.mins, "mins"),
        (a.maxs, b.maxs, "maxs"),
    ):
        assert np.allclose(
            np.asarray(fa), np.asarray(fb), rtol=1e-5, atol=1e-5,
            equal_nan=True,
        ), f"{label}: {name}"


class TestKernelEquivalence:
    """Satellite: all three segment impls return identical
    counts/sums/mins/maxs over randomized specs."""

    def test_randomized_specs(self, monkeypatch):
        # keep the hash arm on-device even for tiny randomized inputs
        monkeypatch.setenv("HORAEDB_HASH_HOST_MAX_ROWS", "0")
        from horaedb_tpu.ops.hash_agg import default_hash_slots

        rng = np.random.default_rng(42)
        for trial in range(8):
            n = int(rng.integers(5, 1500))
            n_groups = int(rng.integers(2, 40))
            n_buckets = int(rng.integers(1, 5))
            n_fields = int(rng.integers(0, 3))
            # empty groups: codes drawn from a PREFIX of the domain, so
            # the tail groups exist in the spec but hold no rows
            live_groups = max(1, n_groups // 2)
            codes = rng.integers(0, live_groups, n).astype(np.int32)
            buckets = rng.integers(0, n_buckets, n).astype(np.int32)
            mask = rng.random(n) < 0.8  # masked rows
            vals = [rng.normal(size=n).astype(np.float32) for _ in range(n_fields)]
            batch = build_padded_batch(codes, buckets, mask, vals)
            spec = ScanAggSpec(
                n_groups=n_groups,
                n_buckets=n_buckets,
                n_agg_fields=n_fields,
                need_minmax=bool(trial % 2),
            ).padded()
            n_seg = spec.n_groups * spec.n_buckets
            ref = _dispatch(batch, spec, "scatter")
            _assert_states_equal(
                ref, _dispatch(batch, spec, "mxu"), f"trial {trial}: mxu"
            )
            for slots in (16, default_hash_slots(n_seg)):
                got = _dispatch(batch, spec, "hash", slots=slots)
                _assert_states_equal(
                    ref, got, f"trial {trial}: hash slots={slots}"
                )

    def test_hash_at_slot_table_boundary(self, monkeypatch):
        """n_seg == slot-count boundary: every slot needed, load factor
        1.0 — the probe budget can't place everything and the overflow
        fallback must make up the difference exactly."""
        monkeypatch.setenv("HORAEDB_HASH_HOST_MAX_ROWS", "0")
        rng = np.random.default_rng(7)
        n_groups = 16  # spec pads to pow2: n_seg == 16 == slots
        n = 600
        codes = rng.integers(0, n_groups, n).astype(np.int32)
        mask = np.ones(n, bool)
        vals = [rng.normal(size=n).astype(np.float32)]
        batch = build_padded_batch(codes, np.zeros(n, np.int32), mask, vals)
        spec = ScanAggSpec(n_groups=n_groups, n_buckets=1, n_agg_fields=1).padded()
        n_seg = spec.n_groups * spec.n_buckets
        assert n_seg == next_pow2(n_seg) == 16
        ref = _dispatch(batch, spec, "scatter")
        _assert_states_equal(
            ref, _dispatch(batch, spec, "hash", slots=16), "boundary"
        )

    def test_single_segment_bypasses_routing(self):
        """n_seg == 1 (global aggregate) resolves to the pure-reduction
        impl regardless of the requested kernel."""
        assert resolve_segment_impl(1, "auto") == "single"
        assert resolve_segment_impl(1, "hash") == "single"
        rng = np.random.default_rng(3)
        n = 300
        batch = build_padded_batch(
            np.zeros(n, np.int32), np.zeros(n, np.int32),
            np.ones(n, bool), [rng.normal(size=n).astype(np.float32)],
        )
        spec = ScanAggSpec(n_groups=1, n_buckets=1, n_agg_fields=1).padded()
        ref = _dispatch(batch, spec, "auto")
        _assert_states_equal(ref, _dispatch(batch, spec, "hash"), "single")

    def test_hash_host_fallback_is_exact(self, monkeypatch):
        """Below HORAEDB_HASH_HOST_MAX_ROWS the hash route serves from
        host numpy — same numbers as the device impls."""
        monkeypatch.delenv("HORAEDB_SEGMENT_IMPL", raising=False)
        rng = np.random.default_rng(11)
        n = 200
        codes = rng.integers(0, 6, n).astype(np.int32)
        mask = rng.random(n) < 0.9
        vals = [rng.normal(size=n).astype(np.float32)]
        batch = build_padded_batch(codes, np.zeros(n, np.int32), mask, vals)
        spec = ScanAggSpec(n_groups=8, n_buckets=1, n_agg_fields=1).padded()
        ref = _dispatch(batch, spec, "scatter")
        monkeypatch.setenv("HORAEDB_HASH_HOST_MAX_ROWS", "100000")
        _assert_states_equal(ref, _dispatch(batch, spec, "hash"), "host")

    def test_live_pin_flip_retraces_warm_shapes(self, monkeypatch):
        """Review regression: the pin used to resolve INSIDE the jitted
        body — a warm shape kept serving the stale compiled branch after
        an operator flipped HORAEDB_SEGMENT_IMPL (the bisect tool's whole
        purpose). Host-side resolution makes the concrete impl the jit
        key, so the flip must mint a new trace through the new branch."""
        from horaedb_tpu.ops import scan_agg as sa

        rng = np.random.default_rng(9)
        n = 100
        batch = build_padded_batch(
            rng.integers(0, 8, n).astype(np.int32), np.zeros(n, np.int32),
            np.ones(n, bool), [rng.normal(size=n).astype(np.float32)],
        )
        spec = ScanAggSpec(n_groups=8, n_buckets=1, n_agg_fields=1).padded()
        monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", "scatter")
        ref = _dispatch(batch, spec, "auto")  # warm: compiles scatter
        traced = []
        orig = sa._mxu_segment_agg

        def spy(*args, **kwargs):
            traced.append(1)
            return orig(*args, **kwargs)

        monkeypatch.setattr(sa, "_mxu_segment_agg", spy)
        monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", "mxu")
        got = _dispatch(batch, spec, "auto")
        assert traced, "pin flip did not re-trace the warm shape"
        _assert_states_equal(ref, got, "pin flip")

    def test_pin_disables_host_fallback(self, monkeypatch):
        """HORAEDB_SEGMENT_IMPL exists to bisect device lowerings: a
        pinned run must actually run them, even on tiny inputs."""
        monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", "hash")
        monkeypatch.setenv("HORAEDB_HASH_HOST_MAX_ROWS", "100000")
        rng = np.random.default_rng(5)
        n = 50
        batch = build_padded_batch(
            rng.integers(0, 4, n).astype(np.int32), np.zeros(n, np.int32),
            np.ones(n, bool), [rng.normal(size=n).astype(np.float32)],
        )
        spec = ScanAggSpec(n_groups=4, n_buckets=1, n_agg_fields=1).padded()
        assert pinned_segment_impl() == "hash"
        got = _dispatch(batch, spec, "auto")
        monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", "scatter")
        ref = _dispatch(batch, spec, "auto")
        _assert_states_equal(ref, got, "pinned")


class TestEnvInt:
    """Satellite: malformed env ints degrade to defaults, never raise."""

    def test_env_int_guards(self, monkeypatch):
        from horaedb_tpu.utils.env import env_float, env_int

        monkeypatch.delenv("X_LINT_INT", raising=False)
        assert env_int("X_LINT_INT", 7) == 7
        monkeypatch.setenv("X_LINT_INT", "12")
        assert env_int("X_LINT_INT", 7) == 12
        monkeypatch.setenv("X_LINT_INT", "8k")  # the operator typo
        assert env_int("X_LINT_INT", 7) == 7
        monkeypatch.setenv("X_LINT_INT", "")
        assert env_int("X_LINT_INT", 7) == 7
        monkeypatch.setenv("X_LINT_INT", "nope")
        assert env_float("X_LINT_INT", 1.5) == 1.5

    def test_malformed_mxu_threshold_does_not_abort(self, monkeypatch):
        """Regression: scan_agg read HORAEDB_MXU_MAX_SEGMENTS with a bare
        int() at import time — a typo killed the whole server."""
        monkeypatch.setenv("HORAEDB_MXU_MAX_SEGMENTS", "8k")
        assert mxu_max_segments() == 8192
        assert resolve_segment_impl(500, "auto") in ("mxu", "scatter")

    def test_other_guarded_readers(self, monkeypatch):
        from horaedb_tpu.engine.compaction import merge_chunk_count
        from horaedb_tpu.engine.merge import device_merge_min_rows
        from horaedb_tpu.parallel.mesh import dist_min_rows
        from horaedb_tpu.query.scan_cache import ScanCache

        monkeypatch.setenv("HORAEDB_MERGE_CHUNK_ROWS", "4m")
        assert merge_chunk_count(10_000_000) >= 1
        monkeypatch.setenv("HORAEDB_DIST_MIN_ROWS", "lots")
        assert dist_min_rows() > 0
        monkeypatch.setenv("HORAEDB_DEVICE_MERGE_MIN_ROWS", "???")
        assert device_merge_min_rows() > 0
        # review regression: explicit values — including negatives, which
        # force the device merge at every size — are honored, only
        # unset/malformed fall back to the backend default
        monkeypatch.setenv("HORAEDB_DEVICE_MERGE_MIN_ROWS", "-1")
        assert device_merge_min_rows() == -1
        monkeypatch.setenv("HORAEDB_CACHE_HOST_ROWS_MB", "1gb")
        assert ScanCache().max_host_rows_bytes == 256 << 20


class TestKernelRouter:
    def test_probes_then_serves_winner(self):
        from horaedb_tpu.query.path_router import KernelRouter

        r = KernelRouter()
        cands = ("scatter", "mxu", "hash")
        seen = []
        # synthetic latencies: hash fastest; first sample of each impl is
        # compile-tainted (huge) and must not poison the estimate
        lat = {"scatter": 0.05, "mxu": 0.03, "hash": 0.01}
        for i in range(2 * len(cands)):
            k = r.choose("key", "scatter", cands)
            seen.append(k)
            r.record("key", k, 5.0 if seen.count(k) == 1 else lat[k])
        assert set(seen) == set(cands)  # every candidate warmed
        assert r.choose("key", "scatter", cands) == "hash"
        r.record("key", "hash", lat["hash"])

    def test_reprobes_losers_on_cadence(self):
        from horaedb_tpu.query.path_router import PROBE_EVERY, KernelRouter

        r = KernelRouter()
        cands = ("scatter", "hash")
        for i in range(2 * len(cands)):
            k = r.choose("key", "scatter", cands)
            r.record("key", k, 0.01 if k == "scatter" else 0.05)
        picks = []
        for i in range(2 * PROBE_EVERY):
            k = r.choose("key", "scatter", cands)
            picks.append(k)
            r.record("key", k, 0.01 if k == "scatter" else 0.05)
        assert picks.count("hash") >= 1  # losers still get probed
        assert picks.count("scatter") > picks.count("hash")

    def test_lru_bound(self):
        from horaedb_tpu.query.path_router import MAX_KEYS, KernelRouter

        r = KernelRouter()
        for i in range(MAX_KEYS + 50):
            r.choose(("k", i), "scatter", ("scatter",))
        assert len(r._stats) <= MAX_KEYS

    def test_observed_segments_feedback(self):
        from horaedb_tpu.query.path_router import KernelRouter

        r = KernelRouter()
        assert r.observed_segments("key") is None
        r.note_segments("key", 100)
        assert r.observed_segments("key") == 100
        r.note_segments("key", 0)  # EWMA decays, doesn't snap
        assert 0 < r.observed_segments("key") < 100

    def test_candidate_gating(self):
        from horaedb_tpu.query.path_router import candidate_kernels

        # tiny domain: no hash (the table can't beat direct impls)
        assert "hash" not in candidate_kernels(64, 10_000)
        # dense estimate: no hash (near-full table = all overflow)
        assert "hash" not in candidate_kernels(1024, 10_000, est_distinct=1024)
        # sparse estimate: hash is worth probing
        assert "hash" in candidate_kernels(65536, 10_000, est_distinct=8)
        # scatter is always a candidate
        assert "scatter" in candidate_kernels(10**6, 10_000)

    def test_seed_kernel(self):
        from horaedb_tpu.query.path_router import seed_kernel

        assert seed_kernel(65536, 8, "tpu") == "hash"
        assert seed_kernel(65536, 8, "cpu") == "hash"
        assert seed_kernel(1024, None, "tpu") == "mxu"
        assert seed_kernel(10**6, None, "tpu") == "scatter"
        assert seed_kernel(1024, None, "cpu") == "scatter"

    def test_hash_slots_sizing(self, monkeypatch):
        from horaedb_tpu.ops.hash_agg import default_hash_slots, hash_slots_for

        assert hash_slots_for(65536, 4) == 16  # 4x headroom, pow2
        assert hash_slots_for(65536, 100) == 512
        assert hash_slots_for(65536, None) == default_hash_slots(65536)
        assert hash_slots_for(10**6, 10**6) == 4096  # cap
        monkeypatch.setenv("HORAEDB_HASH_MAX_SLOTS", "256")
        assert hash_slots_for(10**6, 10**6) == 256
        monkeypatch.setenv("HORAEDB_HASH_MAX_SLOTS", "bogus")
        assert hash_slots_for(10**6, 10**6) == 4096


class TestStepCacheLRU:
    """Satellite: the dist-agg compiled-step cache must not grow without
    bound across distinct query shapes."""

    def test_step_cache_bounded(self, monkeypatch):
        from horaedb_tpu.parallel import dist_agg
        from horaedb_tpu.parallel.mesh import serving_mesh

        mesh = serving_mesh()
        assert mesh is not None  # conftest forces the 8-device CPU mesh
        monkeypatch.setattr(
            "horaedb_tpu.query.path_router.MAX_KEYS", 8
        )
        dist_agg._STEP_CACHE.clear()
        for i in range(2, 30):
            spec = ScanAggSpec(
                n_groups=i, n_buckets=1, n_agg_fields=1
            ).padded()
            dist_agg.make_cached_dist_scan_agg(mesh, spec)
        assert len(dist_agg._STEP_CACHE) <= 8
        # LRU: the most recent shape is still resident (cache keys carry
        # the host-RESOLVED impl, not "auto" — that's what makes a live
        # env flip re-key warm shapes)
        spec = dist_agg._resolved(
            ScanAggSpec(n_groups=29, n_buckets=1, n_agg_fields=1).padded()
        )
        assert spec.segment_impl in ("mxu", "scatter")
        assert (mesh, spec, "cached") in dist_agg._STEP_CACHE
        dist_agg._STEP_CACHE.clear()


GROUP_DDL = (
    "CREATE TABLE kr (host string TAG, v double, w double, "
    "ts timestamp NOT NULL, TIMESTAMP KEY(ts))"
)


def _seed_groupby(db, n=500, hosts=20):
    db.execute(GROUP_DDL)
    rows = ", ".join(
        f"('h{i % hosts}', {float(i)}, {float(2 * i)}, {1_700_000_000_000 + i * 1000})"
        for i in range(n)
    )
    db.execute(f"INSERT INTO kr (host, v, w, ts) VALUES {rows}")


class TestRoutingEndToEnd:
    SQL = "SELECT host, count(1) AS c, sum(v) AS s, min(w) AS lo FROM kr GROUP BY host"

    def test_pinned_impls_agree_over_sql(self, db, monkeypatch):
        _seed_groupby(db)
        results = {}
        for impl in ("scatter", "mxu", "hash"):
            monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", impl)
            out = db.execute(self.SQL)
            results[impl] = sorted(
                tuple(r.values()) for r in out.to_pylist()
            )
            if out.metrics.get("path", "").startswith("device"):
                assert out.metrics.get("kernel") == impl
        assert results["scatter"] == results["mxu"] == results["hash"]

    def test_kernel_in_ledger_and_query_stats(self, db):
        # ledgers open per SQL statement at the PROXY (the wire layer's
        # shared gateway) — route through it like a real request
        from horaedb_tpu.proxy import Proxy

        proxy = Proxy(db)
        try:
            _seed_groupby(db)
            for _ in range(3):
                out = proxy.handle_sql(self.SQL)
            kernel = out.metrics.get("kernel")
            assert kernel in ("mxu", "scatter", "hash", "single", "host")
            stats = proxy.handle_sql(
                "SELECT kernel, agg_segments FROM system.public.query_stats"
            ).to_pylist()
            mine = [r for r in stats if r["kernel"] == kernel]
            assert mine, f"no query_stats row with kernel={kernel}: {stats}"
            assert max(r["agg_segments"] for r in mine) > 0
        finally:
            proxy.close()

    def test_router_disabled_matches_static(self, db, monkeypatch):
        monkeypatch.setenv("HORAEDB_KERNEL_ROUTER", "0")
        _seed_groupby(db)
        for _ in range(3):
            out = db.execute(self.SQL)
        if out.metrics.get("path", "").startswith("device"):
            import jax

            n_seg = 32  # 20 hosts padded to pow2, 1 bucket
            expect = (
                "mxu"
                if jax.default_backend() == "tpu" and n_seg <= mxu_max_segments()
                else "scatter"
            )
            assert out.metrics["kernel"] == expect

    def test_agg_kernel_counter_moves(self, db):
        from horaedb_tpu.utils.metrics import REGISTRY

        _seed_groupby(db)
        db.execute(self.SQL)
        db.execute(self.SQL)
        text = REGISTRY.expose()
        assert "horaedb_agg_kernel_total" in text

    def test_bootstrap_from_query_stats_history(self, db):
        from horaedb_tpu.proxy import Proxy
        from horaedb_tpu.query.path_router import bootstrap_observed_segments

        proxy = Proxy(db)
        try:
            _seed_groupby(db)
            for _ in range(3):
                proxy.handle_sql(self.SQL)
        finally:
            proxy.close()
        # the finalized history carries the live segment count; a fresh
        # sighting of the same normalized SQL shape seeds from it
        segs = bootstrap_observed_segments(self.SQL)
        assert segs is not None and segs > 0
        # an unrelated shape finds nothing
        assert bootstrap_observed_segments(
            "SELECT count(1) FROM never_seen_table"
        ) is None


class TestCacheDtypeAutoTune:
    def _warm_cached(self, db, sql, times=4):
        out = None
        for _ in range(times):
            out = db.execute(sql)
        return out

    def _entry(self, db, table="kr"):
        return db.interpreters.executor.scan_cache._entries.get(table)

    def test_minmax_only_column_stored_bf16(self, db, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("HORAEDB_CACHE_DTYPE", "auto")
        _seed_groupby(db)
        self._warm_cached(
            db, "SELECT host, min(w) AS lo, max(w) AS hi, sum(v) AS s "
            "FROM kr GROUP BY host",
        )
        entry = self._entry(db)
        assert entry is not None, "cache never built"
        assert entry.value_cols_dev["w"].dtype == jnp.bfloat16
        assert entry.value_cols_dev["v"].dtype == jnp.float32

    def test_promotion_on_new_sum_usage(self, db, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("HORAEDB_CACHE_DTYPE", "auto")
        _seed_groupby(db)
        self._warm_cached(
            db, "SELECT host, min(w) AS lo FROM kr GROUP BY host"
        )
        entry = self._entry(db)
        assert entry is not None
        assert entry.value_cols_dev["w"].dtype == jnp.bfloat16
        out = self._warm_cached(
            db, "SELECT host, sum(w) AS s FROM kr GROUP BY host"
        )
        entry = self._entry(db)
        assert entry.value_cols_dev["w"].dtype == jnp.float32
        # exact f32 sums after promotion (bf16 would be visibly off)
        expect = {}
        for i in range(500):
            expect.setdefault(f"h{i % 20}", 0.0)
            expect[f"h{i % 20}"] += float(2 * i)
        got = {r["host"]: r["s"] for r in out.to_pylist()}
        for h, s in expect.items():
            assert abs(got[h] - s) < 1e-6, h

    def test_filter_usage_pins_f32(self, db, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("HORAEDB_CACHE_DTYPE", "auto")
        _seed_groupby(db)
        self._warm_cached(
            db, "SELECT host, min(w) AS lo FROM kr WHERE w > 10 GROUP BY host"
        )
        entry = self._entry(db)
        assert entry is not None
        assert entry.value_cols_dev["w"].dtype == jnp.float32

    def test_default_mode_stays_f32(self, db, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.delenv("HORAEDB_CACHE_DTYPE", raising=False)
        _seed_groupby(db)
        self._warm_cached(
            db, "SELECT host, min(w) AS lo FROM kr GROUP BY host"
        )
        entry = self._entry(db)
        assert entry is not None
        assert entry.value_cols_dev["w"].dtype == jnp.float32
