"""Concurrency machinery: pending-write merging + priority runtime
(ref model: PendingWriteQueue tests + priority_runtime.rs)."""

import threading
import time

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.engine.instance import Instance
from horaedb_tpu.engine.wal import LocalDiskWal
from horaedb_tpu.utils.runtime import PriorityRuntime


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("h", DatumKind.STRING, is_tag=True),
            ColumnSchema("v", DatumKind.DOUBLE),
            ColumnSchema("ts", DatumKind.TIMESTAMP),
        ],
        timestamp_column="ts",
    )


class TestPendingWriteQueue:
    def test_concurrent_writers_all_land(self, tmp_path):
        schema = demo_schema()
        from horaedb_tpu.utils.object_store import LocalDiskStore

        wal = LocalDiskWal(str(tmp_path / "wal"))
        inst = Instance(LocalDiskStore(str(tmp_path / "store")), wal=wal)
        table = inst.create_table(0, 1, "t", schema)

        n_threads, rows_each = 16, 25
        seqs: list[int] = []
        lock = threading.Lock()

        def writer(tid):
            for i in range(rows_each):
                rg = RowGroup.from_rows(
                    schema, [{"h": f"h{tid}", "v": float(i), "ts": tid * 10_000 + i}]
                )
                seq = inst.write(table, rg)
                with lock:
                    seqs.append(seq)

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        out = inst.read(table)
        assert len(out) == n_threads * rows_each
        # batching observable: fewer WAL records than writes
        wal_records = sum(1 for _ in wal.read_from(1, 1))
        assert wal_records <= len(seqs)
        # every writer got a real sequence
        assert len(seqs) == n_threads * rows_each and all(s >= 1 for s in seqs)

        # recovery sees the same data (merged batches replay correctly)
        inst2 = Instance(LocalDiskStore(str(tmp_path / "store")),
                         wal=LocalDiskWal(str(tmp_path / "wal")))
        t2 = inst2.open_table(0, 1, "t")
        assert len(inst2.read(t2)) == n_threads * rows_each

    def test_writer_failure_propagates_only_to_its_group(self):
        # A failing group must not wedge the queue for later writers.
        from horaedb_tpu.utils.object_store import MemoryStore

        schema = demo_schema()
        inst = Instance(MemoryStore())
        table = inst.create_table(0, 1, "t", schema)
        inst.write(table, RowGroup.from_rows(schema, [{"h": "a", "v": 1.0, "ts": 1}]))
        table.dropped = True
        with pytest.raises(ValueError, match="dropped"):
            inst.write(table, RowGroup.from_rows(schema, [{"h": "a", "v": 2.0, "ts": 2}]))
        table.dropped = False
        inst.write(table, RowGroup.from_rows(schema, [{"h": "a", "v": 3.0, "ts": 3}]))
        assert len(inst.read(table)) == 2  # writes 1 and 3; write 2 rejected


class TestPriorityRuntime:
    def test_pools_and_counters(self):
        rt = PriorityRuntime(high_workers=2, low_workers=1)
        try:
            assert rt.run("high", lambda: 1 + 1) == 2
            assert rt.run("low", lambda: threading.current_thread().name).startswith(
                "query-low"
            )
            assert rt.submitted_high == 1 and rt.submitted_low == 1
        finally:
            rt.shutdown()

    def test_no_deadlock_when_called_from_own_pool(self):
        rt = PriorityRuntime(high_workers=1, low_workers=1)
        try:
            # Nested run() on the same pool must run inline, not deadlock.
            out = rt.run("high", lambda: rt.run("high", lambda: "inner"))
            assert out == "inner"
        finally:
            rt.shutdown()

    def test_sql_priority_routed(self):
        db = horaedb_tpu.connect(None)
        from horaedb_tpu.proxy import Proxy

        proxy = Proxy(db)
        db.execute("CREATE TABLE t (h string TAG, v double, ts timestamp KEY)")
        db.execute("INSERT INTO t (h, v, ts) VALUES ('a', 1.0, 1000)")
        # bounded range -> high; unbounded -> low
        proxy.handle_sql("SELECT count(*) AS c FROM t WHERE ts >= 0 AND ts < 10000")
        proxy.handle_sql("SELECT count(*) AS c FROM t")
        assert proxy.runtime.submitted_high >= 1
        assert proxy.runtime.submitted_low >= 1
        db.close()
