"""System catalog virtual tables + MySQL federated compatibility probes
(ref: src/system_catalog/src/tables.rs — system.public.tables;
src/server/src/federated.rs — connector session-probe answers)."""

from __future__ import annotations

import pytest

import horaedb_tpu
from horaedb_tpu.server.federated import SERVER_VERSION, check


@pytest.fixture()
def conn():
    c = horaedb_tpu.connect(None)
    c.execute(
        "CREATE TABLE demo (name string TAG, value double, t timestamp KEY) "
        "ENGINE=Analytic"
    )
    c.execute(
        "CREATE TABLE cpu (host string TAG, usage double, t timestamp KEY) "
        "ENGINE=Analytic"
    )
    yield c
    c.close()


class TestSystemTables:
    def test_lists_all_tables_with_reference_shape(self, conn):
        rows = conn.execute(
            "SELECT timestamp, catalog, schema, table_name, table_id, engine "
            "FROM system.public.tables"
        ).to_pylist()
        assert [r["table_name"] for r in rows] == ["cpu", "demo"]
        for r in rows:
            assert r["catalog"] == "horaedb"
            assert r["schema"] == "public"
            assert r["engine"] == "Analytic"
            assert r["table_id"] > 0

    def test_filters_and_aggregates_work(self, conn):
        out = conn.execute(
            "SELECT count(1) AS c FROM system.public.tables"
        ).to_pylist()
        assert out[0]["c"] == 2
        out = conn.execute(
            "SELECT table_name FROM system.public.tables "
            "WHERE table_name = 'demo'"
        ).to_pylist()
        assert [r["table_name"] for r in out] == ["demo"]

    def test_reflects_ddl_immediately(self, conn):
        conn.execute(
            "CREATE TABLE extra (a string TAG, v double, t timestamp KEY) "
            "ENGINE=Analytic"
        )
        names = [
            r["table_name"] for r in conn.execute(
                "SELECT table_name FROM system.public.tables"
            ).to_pylist()
        ]
        assert "extra" in names
        conn.execute("DROP TABLE extra")
        names = [
            r["table_name"] for r in conn.execute(
                "SELECT table_name FROM system.public.tables"
            ).to_pylist()
        ]
        assert "extra" not in names

    def test_read_only(self, conn):
        # INSERT doesn't even parse a dotted target (system tables are
        # unreachable from the write path); the Table guard backs it up.
        with pytest.raises(Exception, match="read-only|expected VALUES"):
            conn.execute(
                "INSERT INTO system.public.tables (table_name) VALUES ('x')"
            )
        from horaedb_tpu.table_engine.system import SystemTablesTable

        with pytest.raises(ValueError, match="read-only"):
            SystemTablesTable(conn.catalog).write(None)

    def test_unknown_system_table_is_not_found(self, conn):
        with pytest.raises(Exception, match="not found"):
            conn.execute("SELECT 1 FROM system.public.nope")

    def test_timestamp_filter_applies(self, conn):
        # The executor trusts storage for timestamp conjuncts — the
        # virtual table must actually apply them.
        out = conn.execute(
            "SELECT table_name FROM system.public.tables WHERE timestamp > 100"
        ).to_pylist()
        assert out == []
        out = conn.execute(
            "SELECT table_name FROM system.public.tables WHERE timestamp >= 0"
        ).to_pylist()
        assert len(out) == 2

    def test_dotted_user_table_name_still_reachable(self, conn):
        conn.execute(
            'CREATE TABLE `a.b` (g string TAG, v double, t timestamp KEY) '
            "ENGINE=Analytic"
        )
        conn.execute('INSERT INTO `a.b` (g, v, t) VALUES (\'x\', 1.5, 10)')
        out = conn.execute('SELECT v FROM `a.b`').to_pylist()
        assert [r["v"] for r in out] == [1.5]

    def test_join_with_qualified_table(self, conn):
        conn.execute(
            "INSERT INTO demo (name, value, t) VALUES ('a', 1.0, 10)"
        )
        conn.execute(
            "INSERT INTO cpu (host, usage, t) VALUES ('a', 9.0, 10)"
        )
        out = conn.execute(
            "SELECT demo.name, cpu.usage FROM demo "
            "INNER JOIN public.cpu ON demo.name = cpu.host"
        ).to_pylist()
        assert out == [{"name": "a", "usage": 9.0}]

    def test_schema_qualified_name_resolves(self, conn):
        out = conn.execute("SELECT count(1) AS c FROM public.demo").to_pylist()
        assert out[0]["c"] == 0
        out = conn.execute(
            "SELECT count(1) AS c FROM horaedb.public.demo"
        ).to_pylist()
        assert out[0]["c"] == 0


class TestFederatedProbes:
    def test_select_version_comment(self):
        kind, cols, rows = check("SELECT @@version_comment LIMIT 1")
        assert cols == ["@@version_comment"]
        assert rows == [["horaedb_tpu"]]

    def test_select_multiple_vars(self):
        # the mysql-connector-java opening burst shape
        kind, cols, rows = check(
            "SELECT @@session.auto_increment_increment, @@character_set_client, "
            "@@max_allowed_packet"
        )
        assert len(cols) == 3 and len(rows[0]) == 3
        assert rows[0][2] == "67108864"

    def test_select_version_and_database(self):
        assert check("SELECT version()")[2] == [[SERVER_VERSION]]
        assert check("select DATABASE()")[2] == [["public"]]

    def test_timediff_probe(self):
        kind, cols, rows = check("SELECT TIMEDIFF(NOW(), UTC_TIMESTAMP())")
        assert kind == "rows" and ":" in rows[0][0]

    def test_show_variables_like(self):
        kind, cols, rows = check("SHOW VARIABLES LIKE 'lower_case_table_names'")
        assert cols == ["Variable_name", "Value"]
        assert rows == [["lower_case_table_names", "0"]]
        kind, cols, rows = check("SHOW VARIABLES LIKE 'character_set%'")
        assert len(rows) >= 3
        kind, cols, rows = check("SHOW VARIABLES")
        assert len(rows) > 10

    def test_set_and_transaction_chatter_is_ok(self):
        for q in (
            "SET NAMES utf8mb4",
            "SET character_set_results = NULL",
            "SET autocommit=1",
            "set sql_mode='STRICT_TRANS_TABLES'",
            "BEGIN", "COMMIT", "ROLLBACK",
            "USE public",
            "/*!40101 SET NAMES utf8 */",
        ):
            assert check(q) == ("ok",), q

    def test_shape_only_probes_get_empty_sets(self):
        for q in (
            "SHOW COLLATION",
            "SHOW WARNINGS",
            "SHOW ENGINES",
            "SHOW MASTER STATUS",
            "/* ApplicationName=DBeaver */ SHOW PLUGINS",
        ):
            kind, cols, rows = check(q)
            assert kind == "rows" and rows == [], q

    def test_real_queries_pass_through(self):
        for q in (
            "SELECT * FROM demo",
            "SELECT name, avg(value) FROM demo GROUP BY name",
            "INSERT INTO demo (name) VALUES ('x')",
            "CREATE TABLE t (a string TAG, ts timestamp KEY)",
            "SHOW TABLES",
            "SETTINGS_TABLE_QUERY",  # name starting with SET must not match
            # mixing a session var with table data is a REAL query — the
            # canned answer must not hijack it
            "SELECT @@autocommit, name FROM servers",
        ):
            assert check(q) is None, q

    def test_dotted_table_not_shadowed_by_bare_sibling(self):
        c = horaedb_tpu.connect(None)
        c.execute(
            'CREATE TABLE `public.x` (g string TAG, v double, t timestamp KEY) '
            "ENGINE=Analytic"
        )
        c.execute(
            "CREATE TABLE x (g string TAG, v double, t timestamp KEY) "
            "ENGINE=Analytic"
        )
        c.execute("INSERT INTO `public.x` (g, v, t) VALUES ('dotted', 1.0, 1)")
        c.execute("INSERT INTO x (g, v, t) VALUES ('bare', 2.0, 1)")
        out = c.execute('SELECT g FROM `public.x`').to_pylist()
        assert [r["g"] for r in out] == ["dotted"]
        out = c.execute("SELECT g FROM x").to_pylist()
        assert [r["g"] for r in out] == ["bare"]
        c.close()
