"""Tests for the type/schema core (ref test model: common_types inline tests)."""

import numpy as np
import pyarrow as pa
import pytest

from horaedb_tpu.common_types import (
    ColumnSchema,
    DatumKind,
    RowGroup,
    Schema,
    TimeRange,
    TSID_COLUMN,
    compute_tsid,
)


def demo_schema() -> Schema:
    # The README demo table: CREATE TABLE demo (name string TAG,
    #   value double, t timestamp KEY) (ref README.md:55-88)
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


class TestDatumKind:
    def test_sql_round_trip(self):
        assert DatumKind.from_sql_type("double") is DatumKind.DOUBLE
        assert DatumKind.from_sql_type("VARCHAR") is DatumKind.STRING
        assert DatumKind.from_sql_type("Timestamp") is DatumKind.TIMESTAMP
        assert DatumKind.from_sql_type("bigint") is DatumKind.INT64
        with pytest.raises(ValueError):
            DatumKind.from_sql_type("blob")

    def test_key_kinds(self):
        assert DatumKind.STRING.is_key_kind
        assert DatumKind.TIMESTAMP.is_key_kind
        assert not DatumKind.DOUBLE.is_key_kind

    def test_numpy_dtypes(self):
        assert DatumKind.TIMESTAMP.numpy_dtype == np.int64
        assert DatumKind.DOUBLE.numpy_dtype == np.float64


class TestSchema:
    def test_auto_tsid_layout(self):
        s = demo_schema()
        # auto-tsid: [tsid, t, name, value]
        assert s.names()[:2] == [TSID_COLUMN, "t"]
        assert s.primary_key_indexes == (0, 1)
        assert s.timestamp_name == "t"
        assert s.tag_names == ("name",)
        assert s.column("name").is_dictionary

    def test_explicit_primary_key(self):
        s = Schema.build(
            [
                ColumnSchema("host", DatumKind.STRING, is_tag=True),
                ColumnSchema("ts", DatumKind.TIMESTAMP),
                ColumnSchema("v", DatumKind.DOUBLE),
            ],
            timestamp_column="ts",
            primary_key=["host", "ts"],
        )
        assert s.tsid_index is None
        assert [s.columns[i].name for i in s.primary_key_indexes] == ["host", "ts"]

    def test_timestamp_must_be_timestamp_kind(self):
        with pytest.raises(ValueError):
            Schema.build(
                [ColumnSchema("ts", DatumKind.INT64)],
                timestamp_column="ts",
            )

    def test_add_column_bumps_version(self):
        s = demo_schema()
        s2 = s.with_added_column(ColumnSchema("v2", DatumKind.DOUBLE))
        assert s2.version == s.version + 1
        assert s2.has_column("v2")
        with pytest.raises(ValueError):
            s2.with_added_column(ColumnSchema("v2", DatumKind.DOUBLE))

    def test_dict_round_trip(self):
        s = demo_schema()
        assert Schema.from_dict(s.to_dict()) == s

    def test_arrow_schema_tags_are_dictionary(self):
        a = demo_schema().to_arrow()
        assert pa.types.is_dictionary(a.field("name").type)


class TestTimeRange:
    def test_overlap_half_open(self):
        a = TimeRange(0, 10)
        assert a.overlaps(TimeRange(9, 20))
        assert not a.overlaps(TimeRange(10, 20))
        assert a.contains(0) and not a.contains(10)

    def test_bucket_alignment_negative(self):
        b = TimeRange.bucket_of(-1, 1000)
        assert b == TimeRange(-1000, 0)

    def test_buckets(self):
        bs = TimeRange(500, 2500).buckets(1000)
        assert [b.inclusive_start for b in bs] == [0, 1000, 2000]

    def test_intersect(self):
        assert TimeRange(0, 10).intersect(TimeRange(5, 20)) == TimeRange(5, 10)
        assert TimeRange(0, 10).intersect(TimeRange(10, 20)).is_empty()


class TestTsid:
    def test_deterministic_and_tag_sensitive(self):
        a = compute_tsid([np.array(["h1", "h2", "h1"], dtype=object)])
        assert a[0] == a[2] != a[1]
        b = compute_tsid([np.array(["h1"], dtype=object)])
        assert b[0] == a[0]

    def test_order_sensitive_across_columns(self):
        ab = compute_tsid(
            [np.array(["a"], dtype=object), np.array(["b"], dtype=object)]
        )
        ba = compute_tsid(
            [np.array(["b"], dtype=object), np.array(["a"], dtype=object)]
        )
        assert ab[0] != ba[0]


class TestRowGroup:
    def rows(self):
        return [
            {"name": "h2", "value": 2.0, "t": 2000},
            {"name": "h1", "value": 1.0, "t": 1000},
            {"name": "h1", "value": 3.0, "t": 3000},
        ]

    def test_from_rows_computes_tsid(self):
        rg = RowGroup.from_rows(demo_schema(), self.rows())
        assert len(rg) == 3
        tsid = rg.column(TSID_COLUMN)
        assert tsid[1] == tsid[2] != tsid[0]
        assert rg.time_range() == TimeRange(1000, 3001)

    def test_nulls(self):
        rg = RowGroup.from_rows(demo_schema(), [{"name": "h", "value": None, "t": 1}])
        assert not rg.valid_mask("value")[0]
        assert rg.to_pylist()[0]["value"] is None

    def test_null_in_non_nullable_rejected(self):
        with pytest.raises(ValueError):
            RowGroup.from_rows(demo_schema(), [{"name": "h", "value": 1.0, "t": None}])

    def test_sorted_by_key(self):
        rg = RowGroup.from_rows(demo_schema(), self.rows()).sorted_by_key()
        tsid = rg.column(TSID_COLUMN)
        ts = rg.timestamps
        keys = list(zip(tsid.tolist(), ts.tolist()))
        assert keys == sorted(keys)

    def test_seq_breaks_ties_newest_first(self):
        schema = demo_schema()
        rg = RowGroup.from_rows(
            schema,
            [
                {"name": "h", "value": 1.0, "t": 1000},
                {"name": "h", "value": 2.0, "t": 1000},
            ],
        )
        out = rg.sorted_by_key(seq=np.array([1, 2], dtype=np.uint64))
        assert out.column("value")[0] == 2.0

    def test_arrow_round_trip(self):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, self.rows())
        back = RowGroup.from_arrow(schema, rg.to_arrow())
        assert back.to_pylist() == rg.to_pylist()

    def test_concat_filter_slice(self):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, self.rows())
        cat = RowGroup.concat([rg, rg])
        assert len(cat) == 6
        flt = cat.filter(cat.column("value") > 1.5)
        assert len(flt) == 4
        assert len(cat.slice(1, 3)) == 2
