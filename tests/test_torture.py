"""Flush-vs-read-vs-compact-vs-write torture
(ref model: the reference guards these interleavings with ASan/MSan runs
over the engine tests, Makefile:95-114 — Python needs systematic
interleaving stress instead; VERDICT r1 called the absence out).

Invariants under concurrent chaos:
- reads NEVER observe a missing SST (deferred purge + pins) or crash;
- APPEND tables conserve every written row (no loss, no duplication);
- OVERWRITE tables expose exactly one row per key, with a value that was
  actually written for that key.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.engine.compaction import Compactor
from horaedb_tpu.engine.flush import Flusher
from horaedb_tpu.engine.instance import EngineConfig, Instance
from horaedb_tpu.engine.options import TableOptions
from horaedb_tpu.utils.object_store import MemoryStore

DURATION_S = 3.0


def schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


class _Torture:
    def __init__(self, update_mode: str):
        self.inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=10_000))
        self.table = self.inst.create_table(
            0, 1, "tt", schema(),
            TableOptions.from_kv({"segment_duration": "1h", "update_mode": update_mode}),
        )
        self.stop = threading.Event()
        self.errors: list[str] = []
        self.written_rows = 0
        self.written_lock = threading.Lock()
        # per-key set of written values (overwrite correctness oracle)
        self.key_values: dict[tuple, set] = {}

    def guard(self, fn, who: str):
        def run():
            try:
                while not self.stop.is_set():
                    fn()
            except Exception as e:  # any crash fails the test with context
                self.errors.append(f"{who}: {type(e).__name__}: {e}")
                self.stop.set()

        return threading.Thread(target=run, name=who, daemon=True)

    def writer(self, wid: int):
        rng = np.random.default_rng(wid)

        def once():
            n = int(rng.integers(1, 40))
            rows = []
            for _ in range(n):
                ts = int(rng.integers(0, 600_000))
                name = f"h{int(rng.integers(0, 8))}"
                v = float(rng.random())
                rows.append({"name": name, "value": v, "t": ts})
                with self.written_lock:
                    self.key_values.setdefault((name, ts), set()).add(v)
            self.inst.write(self.table, RowGroup.from_rows(self.table.schema, rows))
            with self.written_lock:
                self.written_rows += n

        return once

    def reader(self):
        def once():
            out = self.inst.read(self.table)
            # dedup invariant mid-flight (overwrite mode only): no key
            # appears twice in one consistent read
            if self.table.options.update_mode.value == "overwrite" and len(out):
                keys = list(zip(out.column("name"), out.timestamps.tolist()))
                assert len(keys) == len(set(keys)), "duplicate key in overwrite read"

        return once

    def flusher(self):
        def once():
            Flusher(self.table).flush()
            self.inst._purge(self.table)
            time.sleep(0.01)

        return once

    def compactor(self):
        def once():
            Compactor(self.table).compact()
            self.inst._purge(self.table)
            time.sleep(0.02)

        return once

    def run(self):
        threads = [
            self.guard(self.writer(i), f"writer-{i}") for i in range(3)
        ] + [
            self.guard(self.reader(), f"reader-{i}") for i in range(3)
        ] + [
            self.guard(self.flusher(), "flusher"),
            self.guard(self.compactor(), "compactor"),
        ]
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        self.stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not self.errors, self.errors


class TestTorture:
    def test_append_mode_conserves_rows(self):
        tor = _Torture("append")
        tor.run()
        Flusher(tor.table).flush()
        out = tor.inst.read(tor.table)
        assert len(out) == tor.written_rows, (
            f"append lost/duplicated rows: read {len(out)}, wrote {tor.written_rows}"
        )
        assert tor.written_rows > 0

    def test_overwrite_mode_dedups_to_written_values(self):
        tor = _Torture("overwrite")
        tor.run()
        Flusher(tor.table).flush()
        Compactor(tor.table).compact()
        out = tor.inst.read(tor.table)
        keys = list(zip(out.column("name"), out.timestamps.tolist()))
        assert len(keys) == len(set(keys)), "duplicate keys after compaction"
        vals = out.column("value")
        for (name, ts), v in zip(keys, vals):
            written = tor.key_values.get((str(name), int(ts)))
            assert written is not None, f"read a never-written key {(name, ts)}"
            assert float(v) in written, (
                f"key {(name, ts)} holds {v}, not among written {written}"
            )
        assert set(tor.key_values) == set(
            (str(n), int(t)) for n, t in keys
        ), "some written keys are missing"
