"""Pluggable leader-lease backends + lock-loss shard watch
(ref: horaemeta/server/member/member.go — etcd-lease election;
src/cluster/src/shard_lock_manager.rs:23-60 — lock loss freezes the
shard). The EtcdLease backend is tested against an in-process stub of
etcd's v3 HTTP/JSON gateway (the image ships no etcd binary); the stub
implements exactly the gateway surface the backend uses: lease
grant/keepalive/revoke and kv txn/range with create-revision compares
and lease-bound key expiry."""

from __future__ import annotations

import base64
import json
import threading
import time

import pytest

from horaedb_tpu.meta.lease import EtcdLease, LeaderLease, make_lease


# ---- etcd v3 gateway stub -------------------------------------------------


class EtcdStub:
    """Just enough of the v3 gateway for elections: leases with TTL, keys
    bound to leases, create-revision txn compares."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.leases: dict[str, float] = {}  # id -> expires_at
        self.kv: dict[str, tuple[str, str]] = {}  # key -> (value, lease_id)
        self._next = 1000

    def _expire(self) -> None:
        now = time.monotonic()
        dead = [i for i, exp in self.leases.items() if exp <= now]
        for i in dead:
            del self.leases[i]
            for k in [k for k, (_, lid) in self.kv.items() if lid == i]:
                del self.kv[k]

    def handle(self, path: str, body: dict) -> dict:
        with self.lock:
            self._expire()
            if path == "/v3/lease/grant":
                self._next += 1
                lid = str(self._next)
                ttl = int(body["TTL"])
                self.leases[lid] = time.monotonic() + ttl
                return {"ID": lid, "TTL": str(ttl)}
            if path == "/v3/lease/keepalive":
                lid = body["ID"]
                if lid not in self.leases:
                    return {"result": {}}
                # stub TTL: re-extend by the original grant is enough here
                self.leases[lid] = time.monotonic() + 2.0
                return {"result": {"ID": lid, "TTL": "2"}}
            if path == "/v3/lease/revoke":
                lid = body["ID"]
                self.leases.pop(lid, None)
                for k in [k for k, (_, l) in self.kv.items() if l == lid]:
                    del self.kv[k]
                return {}
            if path == "/v3/kv/range":
                key = base64.b64decode(body["key"]).decode()
                if key not in self.kv:
                    return {}
                v, _ = self.kv[key]
                return {"kvs": [{"key": body["key"],
                                 "value": base64.b64encode(v.encode()).decode()}]}
            if path == "/v3/kv/txn":
                cmp = body["compare"][0]
                key = base64.b64decode(cmp["key"]).decode()
                assert cmp["target"] == "CREATE"
                succeeded = (key not in self.kv) == (cmp["create_revision"] == "0")
                ops = body["success"] if succeeded else body["failure"]
                responses = []
                for op in ops:
                    if "request_put" in op:
                        put = op["request_put"]
                        # Real etcd rejects a put quoting a dead lease —
                        # the stub must too, or stale-lease bugs in the
                        # client hide behind it.
                        lid = put.get("lease", "")
                        if lid and lid not in self.leases:
                            raise AssertionError(
                                f"requested lease not found: {lid}"
                            )
                        self.kv[base64.b64decode(put["key"]).decode()] = (
                            base64.b64decode(put["value"]).decode(),
                            lid,
                        )
                        responses.append({"response_put": {}})
                    elif "request_range" in op:
                        k2 = base64.b64decode(op["request_range"]["key"]).decode()
                        kvs = []
                        if k2 in self.kv:
                            v, _ = self.kv[k2]
                            kvs.append({
                                "key": op["request_range"]["key"],
                                "value": base64.b64encode(v.encode()).decode(),
                            })
                        responses.append({"response_range": {"kvs": kvs}})
                return {"succeeded": succeeded, "responses": responses}
            raise AssertionError(f"unhandled path {path}")


@pytest.fixture()
def etcd():
    """(base_url, stub) — a real HTTP listener running the stub."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    stub = EtcdStub()

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            try:
                out = stub.handle(self.path, body)
            except AssertionError as e:
                self.send_response(400)
                self.end_headers()
                self.wfile.write(str(e).encode())
                return
            payload = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}", stub
    srv.shutdown()


# ---- EtcdLease election semantics ----------------------------------------


class TestEtcdLease:
    def test_single_candidate_acquires_and_renews(self, etcd):
        url, _ = etcd
        a = EtcdLease(url, "/horaedb/leader", "meta-a:1", ttl_s=2)
        assert a.try_acquire()
        assert a.verify()
        assert a.leader() == "meta-a:1"
        assert a.renew()

    def test_second_candidate_loses_then_takes_over_on_expiry(self, etcd):
        url, stub = etcd
        a = EtcdLease(url, "/horaedb/leader", "meta-a:1", ttl_s=1)
        b = EtcdLease(url, "/horaedb/leader", "meta-b:2", ttl_s=1)
        assert a.try_acquire()
        assert not b.try_acquire()
        assert b.leader() == "meta-a:1"
        # a dies (no keepalive): after the TTL, b campaigns and wins.
        with stub.lock:
            stub.leases = {i: time.monotonic() - 1 for i in stub.leases}
        assert b.try_acquire()
        assert b.verify() and not a.verify()

    def test_resign_hands_over_immediately(self, etcd):
        url, _ = etcd
        a = EtcdLease(url, "/horaedb/leader", "meta-a:1", ttl_s=5)
        b = EtcdLease(url, "/horaedb/leader", "meta-b:2", ttl_s=5)
        assert a.try_acquire()
        a.resign()
        assert a.leader() is None
        assert b.try_acquire()
        assert b.leader() == "meta-b:2"

    def test_lost_lease_forces_fresh_campaign(self, etcd):
        url, stub = etcd
        a = EtcdLease(url, "/horaedb/leader", "meta-a:1", ttl_s=1)
        assert a.try_acquire()
        with stub.lock:
            stub.leases.clear()
            stub.kv.clear()
        assert not a.renew()  # keepalive of a dead lease reports loss
        assert a.try_acquire()  # and the next campaign re-grants

    def test_unreachable_endpoint_never_claims_leadership(self):
        a = EtcdLease("http://127.0.0.1:9", "/k", "meta-a:1", ttl_s=1,
                      timeout_s=0.2)
        assert not a.try_acquire()
        assert not a.renew()
        assert not a.verify()
        assert a.leader() is None
        a.resign()  # must not raise

    def test_meta_server_election_loop_drives_etcd_backend(self, etcd):
        """The real MetaServer tick loop over the etcd-shaped backend:
        leader elected, follower rejects RPCs with a leader hint,
        failover on resign."""
        from horaedb_tpu.meta.kv import MemoryKV
        from horaedb_tpu.meta.service import MetaServer, NotLeader

        url, _ = etcd
        a = MetaServer(
            num_shards=2, election=EtcdLease(url, "/el", "a:1", ttl_s=5),
            kv_factory=MemoryKV,
        )
        b = MetaServer(
            num_shards=2, election=EtcdLease(url, "/el", "b:2", ttl_s=5),
            kv_factory=MemoryKV,
        )
        a.tick()
        b.tick()
        assert a.is_leader and not b.is_leader
        with pytest.raises(NotLeader) as e:
            b.handle_route("t")
        assert e.value.leader == "a:1"
        a.stop()  # resigns
        b.tick()
        assert b.is_leader


    def test_revoked_lease_demotes_leader_and_freezes_shards(self, etcd):
        """The full lock-loss chain against the protocol fake (VERDICT r4
        item 10; ref: shard_lock_manager.rs:23-60): the leader's etcd
        lease is revoked out from under it -> the next tick's keepalive
        reports loss and the server stands down (<= one tick, well inside
        TTL) -> heartbeats get NotLeader -> a data node whose shard-lease
        deadline stops renewing freezes the shard within its TTL. (The
        reference reacts to lock loss via etcd watch; this backend polls
        verify()/renew() each tick — same detection bound, no stream.)"""
        from horaedb_tpu.cluster.shard import ShardState
        from horaedb_tpu.meta.kv import MemoryKV
        from horaedb_tpu.meta.service import MetaServer, NotLeader

        url, stub = etcd
        a = MetaServer(
            num_shards=2, election=EtcdLease(url, "/el3", "a:1", ttl_s=1),
            kv_factory=MemoryKV,
        )
        a.tick()
        assert a.is_leader
        # Revoke server-side through the gateway protocol (an operator
        # fencing the node / the lease expiring during a partition).
        lease_ids = list(stub.leases)
        for lid in lease_ids:
            stub.handle("/v3/lease/revoke", {"ID": lid})
        a.tick()  # keepalive of the revoked lease reports loss
        assert not a.is_leader
        with pytest.raises(NotLeader):
            a.handle_route("t")
        # Data-node side: with no leader answering heartbeats, the shard
        # lease deadline lapses and the watch freezes the shard.
        impl, shard = TestLeaseWatch()._impl()
        impl._lease_deadline[7] = time.monotonic() + 0.15
        t = threading.Thread(target=impl._lease_watch_loop, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 5
            while shard.state is not ShardState.FROZEN:
                assert time.monotonic() < deadline, "never froze"
                time.sleep(0.02)
        finally:
            impl._stop.set()
            t.join(timeout=2)


class TestMakeLease:
    def test_factory_picks_backend(self, tmp_path):
        from horaedb_tpu.meta.election import FileLease

        etcd = make_lease("etcd://h:2379/custom/key", "me:1", ttl_s=3)
        assert isinstance(etcd, EtcdLease)
        assert etcd.base_url == "http://h:2379" and etcd.key == "/custom/key"
        assert isinstance(etcd, LeaderLease)
        f = make_lease(str(tmp_path / "leader.lock"), "me:1", ttl_s=3)
        assert isinstance(f, FileLease)
        assert isinstance(f, LeaderLease)


# ---- lock-loss watch: lease lapse freezes the shard -----------------------


class TestLeaseWatch:
    def _impl(self):
        from horaedb_tpu.cluster.cluster_impl import ClusterImpl
        from horaedb_tpu.cluster.shard import Shard, ShardInfo

        impl = ClusterImpl.__new__(ClusterImpl)  # no conn/meta needed
        impl._lock = threading.RLock()
        impl._stop = threading.Event()
        impl._lease_deadline = {}
        impl._last_lease_ttl = 0.2
        from horaedb_tpu.cluster.shard import ShardSet

        impl.shard_set = ShardSet()
        shard = Shard(ShardInfo(7, version=1))
        shard.begin_open()
        shard.finish_open()
        impl.shard_set.insert(shard)
        return impl, shard

    def test_lapsed_lease_freezes_then_renewal_thaws(self):
        from horaedb_tpu.cluster.shard import ShardState

        impl, shard = self._impl()
        impl._lease_deadline[7] = time.monotonic() + 0.15
        t = threading.Thread(target=impl._lease_watch_loop, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 5
            while shard.state is not ShardState.FROZEN:
                assert time.monotonic() < deadline, "never froze"
                time.sleep(0.02)
            # Renewal (as a heartbeat would apply it) thaws.
            impl._lease_deadline[7] = time.monotonic() + 10
            deadline = time.monotonic() + 5
            while shard.state is not ShardState.READY:
                assert time.monotonic() < deadline, "never thawed"
                time.sleep(0.02)
        finally:
            impl._stop.set()
            t.join(timeout=2)
