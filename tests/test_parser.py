"""SQL parser + planner tests (ref model: query_frontend inline tests)."""

import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, Schema
from horaedb_tpu.common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP
from horaedb_tpu.query import ast
from horaedb_tpu.query.parser import ParseError, parse_many, parse_sql
from horaedb_tpu.query.plan import CreateTablePlan, InsertPlan, QueryPlan, QueryPriority
from horaedb_tpu.query.planner import PlanError, Planner, extract_predicate
from horaedb_tpu.table_engine.predicate import FilterOp


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def planner():
    schemas = {"demo": demo_schema()}
    return Planner(lambda n: schemas.get(n))


class TestParser:
    def test_create_table_reference_syntax(self):
        # The README demo DDL shape (ref: README.md:55-66).
        stmt = parse_sql(
            "CREATE TABLE demo (name string TAG, value double NOT NULL, "
            "t timestamp NOT NULL, TIMESTAMP KEY(t)) "
            "ENGINE=Analytic with (enable_ttl='false')"
        )
        assert isinstance(stmt, ast.CreateTable)
        assert stmt.timestamp_key == "t"
        assert stmt.columns[0].is_tag
        assert stmt.columns[1].not_null
        assert stmt.options == {"enable_ttl": "false"}
        assert stmt.engine == "Analytic"

    def test_create_inline_timestamp_key(self):
        stmt = parse_sql("CREATE TABLE x (ts timestamp KEY, v double)")
        assert stmt.timestamp_key == "ts"

    def test_create_partition_by(self):
        stmt = parse_sql(
            "CREATE TABLE p (h string TAG, t timestamp KEY, v double) "
            "PARTITION BY KEY(h) PARTITIONS 4 ENGINE=Analytic"
        )
        assert stmt.partition_by.method == "key"
        assert stmt.partition_by.columns == ("h",)
        assert stmt.partition_by.num_partitions == 4

    def test_insert_multi_row(self):
        stmt = parse_sql(
            "INSERT INTO demo (name, value, t) VALUES ('h1', 0.5, 1000), ('h2', -2, 2000)"
        )
        assert stmt.values == (("h1", 0.5, 1000), ("h2", -2, 2000))

    def test_select_full_clause(self):
        stmt = parse_sql(
            "SELECT name, avg(value) AS a FROM demo "
            "WHERE t >= 100 AND t < 200 AND name != 'x' "
            "GROUP BY name ORDER BY a DESC LIMIT 10"
        )
        assert isinstance(stmt, ast.Select)
        assert stmt.items[1].alias == "a"
        assert len(stmt.group_by) == 1
        assert not stmt.order_by[0].ascending
        assert stmt.limit == 10

    def test_select_time_bucket(self):
        stmt = parse_sql(
            "SELECT time_bucket(t, '1h'), max(value) FROM demo GROUP BY time_bucket(t, '1h')"
        )
        fn = stmt.items[0].expr
        assert isinstance(fn, ast.FuncCall) and fn.name == "time_bucket"

    def test_operator_precedence(self):
        stmt = parse_sql("SELECT * FROM demo WHERE value > 1 + 2 * 3 OR name = 'a' AND value < 5")
        w = stmt.where
        assert isinstance(w, ast.BinaryOp) and w.op == "OR"
        left = w.left
        assert isinstance(left, ast.BinaryOp) and left.op == ">"
        assert isinstance(left.right, ast.BinaryOp) and left.right.op == "+"

    def test_in_between_is_null(self):
        stmt = parse_sql(
            "SELECT * FROM demo WHERE name IN ('a','b') AND value BETWEEN 1 AND 2 "
            "AND value IS NOT NULL AND name NOT IN ('c')"
        )
        assert stmt.where is not None

    def test_statements_split(self):
        stmts = parse_many("SHOW TABLES; DROP TABLE IF EXISTS x; DESCRIBE demo")
        assert [type(s).__name__ for s in stmts] == ["ShowTables", "DropTable", "Describe"]

    def test_errors_are_located(self):
        with pytest.raises(ParseError, match="near"):
            parse_sql("SELECT FROM demo WHERE")
        with pytest.raises(ParseError):
            parse_sql("CREATE TABLE t (a double) ENGINE =")
        with pytest.raises(ParseError, match="trailing"):
            parse_sql("SHOW TABLES garbage garbage")

    def test_quoted_identifiers_and_comments(self):
        stmt = parse_sql('SELECT `value` FROM demo -- trailing comment\nWHERE "name" = \'x\'')
        assert isinstance(stmt.items[0].expr, ast.Column)

    def test_alter(self):
        stmt = parse_sql("ALTER TABLE demo ADD COLUMN v2 double")
        assert stmt.columns[0].name == "v2"
        stmt = parse_sql("ALTER TABLE demo MODIFY SETTING write_buffer_size='1mb'")
        assert stmt.options == {"write_buffer_size": "1mb"}


class TestPlanner:
    def test_create_plan_builds_schema(self):
        plan = planner().plan(
            parse_sql(
                "CREATE TABLE demo2 (host string TAG, v double, ts timestamp KEY) "
                "WITH (segment_duration='2h', update_mode='APPEND')"
            )
        )
        assert isinstance(plan, CreateTablePlan)
        assert plan.schema.tag_names == ("host",)
        assert plan.options.segment_duration_ms == 2 * 3_600_000

    def test_create_requires_timestamp_key(self):
        with pytest.raises(PlanError, match="TIMESTAMP KEY"):
            planner().plan(parse_sql("CREATE TABLE bad (v double)"))

    def test_insert_plan_positional_columns(self):
        plan = planner().plan(parse_sql("INSERT INTO demo VALUES (1000, 'h1', 0.5)"))
        assert isinstance(plan, InsertPlan)
        # positional order is schema order minus tsid: [t, name, value]
        assert plan.rows[0] == {"t": 1000, "name": "h1", "value": 0.5}

    def test_insert_arity_checked(self):
        with pytest.raises(PlanError, match="arity"):
            planner().plan(parse_sql("INSERT INTO demo (name) VALUES ('a', 1)"))

    def test_unknown_column_rejected(self):
        with pytest.raises(PlanError, match="unknown column"):
            planner().plan(parse_sql("SELECT nope FROM demo"))

    def test_unknown_table_rejected(self):
        with pytest.raises(PlanError, match="not found"):
            planner().plan(parse_sql("SELECT * FROM missing"))

    def test_agg_shape(self):
        plan = planner().plan(
            parse_sql(
                "SELECT name, time_bucket(t, '1m'), avg(value), count(*) FROM demo "
                "GROUP BY name, time_bucket(t, '1m')"
            )
        )
        assert isinstance(plan, QueryPlan) and plan.is_aggregate
        assert [a.func for a in plan.aggs] == ["avg", "count"]
        assert plan.group_keys[0].column == "name"
        assert plan.group_keys[1].time_bucket_ms == 60_000

    def test_bare_column_outside_group_by_rejected(self):
        with pytest.raises(PlanError, match="GROUP BY"):
            planner().plan(parse_sql("SELECT value, avg(value) FROM demo GROUP BY name"))

    def test_priority_by_time_range(self):
        p1 = planner().plan(parse_sql("SELECT * FROM demo WHERE t >= 0 AND t < 1000"))
        assert p1.priority is QueryPriority.HIGH
        p2 = planner().plan(parse_sql("SELECT * FROM demo"))
        assert p2.priority is QueryPriority.LOW


class TestPredicateExtraction:
    def pred(self, where_sql):
        stmt = parse_sql(f"SELECT * FROM demo WHERE {where_sql}")
        return extract_predicate(stmt.where, demo_schema())

    def test_time_range_conjuncts(self):
        p = self.pred("t >= 100 AND t < 200")
        assert p.time_range.inclusive_start == 100
        assert p.time_range.exclusive_end == 200

    def test_time_point(self):
        p = self.pred("t = 150")
        assert (p.time_range.inclusive_start, p.time_range.exclusive_end) == (150, 151)

    def test_between_on_timestamp(self):
        p = self.pred("t BETWEEN 100 AND 200")
        assert (p.time_range.inclusive_start, p.time_range.exclusive_end) == (100, 201)

    def test_flipped_literal(self):
        p = self.pred("100 <= t AND 200 > t")
        assert (p.time_range.inclusive_start, p.time_range.exclusive_end) == (100, 200)

    def test_column_filters(self):
        p = self.pred("value > 90 AND name = 'host_5'")
        ops = {(f.column, f.op) for f in p.filters}
        assert ("value", FilterOp.GT) in ops and ("name", FilterOp.EQ) in ops

    def test_or_not_pushed(self):
        p = self.pred("t > 100 OR value > 5")
        assert p.time_range.inclusive_start == MIN_TIMESTAMP
        assert p.filters == ()

    def test_empty_range(self):
        p = self.pred("t > 200 AND t < 100")
        assert p.time_range.is_empty()

    def test_in_list_pushed(self):
        p = self.pred("name IN ('a', 'b')")
        assert p.filters[0].op is FilterOp.IN
        assert p.filters[0].value == ("a", "b")
