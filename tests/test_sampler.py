"""Primary-key sampling: low-cardinality-first key order suggested from
first-segment writes, applied at first flush, persisted via the manifest
(ref: analytic_engine/src/sampler.rs:271-360 PrimaryKeySampler;
table/version.rs:670-674 applies the suggestion on first flush)."""

from __future__ import annotations

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.engine.sampler import (
    MIN_SAMPLE_ROWS,
    SAMPLE_DISTINCT_CAP,
    PrimaryKeySampler,
)


def _schema():
    return Schema.build(
        [
            ColumnSchema("region", DatumKind.STRING, is_tag=True),
            ColumnSchema("host", DatumKind.STRING, is_tag=True),
            ColumnSchema("v", DatumKind.DOUBLE),
            ColumnSchema("ts", DatumKind.TIMESTAMP, is_nullable=False),
        ],
        timestamp_column="ts",
        primary_key=["host", "region", "ts"],
    )


def _rows(schema, n, n_hosts, n_regions, seed=0):
    rng = np.random.default_rng(seed)
    return RowGroup(
        schema,
        {
            "region": np.array(
                [f"r{i}" for i in rng.integers(0, n_regions, n)], dtype=object
            ),
            "host": np.array(
                [f"h{i}" for i in rng.integers(0, n_hosts, n)], dtype=object
            ),
            "v": rng.normal(0, 1, n),
            "ts": rng.integers(0, 3_600_000, n).astype(np.int64),
        },
    )


class TestSamplerUnit:
    def test_low_cardinality_leads(self):
        schema = _schema()
        s = PrimaryKeySampler(schema)
        assert s.has_candidates
        s.collect(_rows(schema, 2000, n_hosts=500, n_regions=4))
        out = s.suggest(schema)
        assert out is not None
        names = [out.columns[i].name for i in out.primary_key_indexes]
        # region (4 values) before host (500), timestamp stays last
        assert names == ["region", "host", "ts"]
        assert out.version == schema.version + 1

    def test_too_few_samples_suggests_nothing(self):
        schema = _schema()
        s = PrimaryKeySampler(schema)
        s.collect(_rows(schema, MIN_SAMPLE_ROWS - 1, 10, 2))
        assert s.suggest(schema) is None

    def test_equal_cardinalities_keep_declared_order(self):
        """Ties break by the user's declared PK position — a reorder with
        zero pruning benefit must not churn the schema."""
        schema = _schema()  # declared: host, region, ts
        s = PrimaryKeySampler(schema)
        s.collect(_rows(schema, 2000, n_hosts=4, n_regions=4))  # equal card
        assert s.suggest(schema) is None

    def test_writes_racing_first_flush_rewrap_not_fail(self, tmp_path):
        """A write built against schema v1 that lands after the sampler's
        first-flush reorder installed v2 must be REWRAPPED (same columns,
        metadata-only change), not rejected."""
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(
            "CREATE TABLE pk (region string TAG, host string TAG, v double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts), "
            "PRIMARY KEY(host, region, ts)) ENGINE=Analytic "
            "WITH (segment_duration='2h')"
        )
        t = conn.catalog.open("pk")
        rng = np.random.default_rng(3)
        n = 500

        def make_rows(schema):
            return RowGroup(
                schema,
                {
                    "region": np.array(
                        [f"r{i}" for i in rng.integers(0, 3, n)], dtype=object
                    ),
                    "host": np.array(
                        [f"h{i}" for i in rng.integers(0, 100, n)], dtype=object
                    ),
                    "v": rng.normal(0, 1, n),
                    "ts": rng.integers(0, 3_600_000, n).astype(np.int64),
                },
            )

        v1_schema = t.schema
        pre_built = make_rows(v1_schema)  # built BEFORE the flush
        t.write(make_rows(v1_schema))
        t.flush()  # installs the reordered v2 schema
        assert t.schema.version == v1_schema.version + 1
        t.write(pre_built)  # races: v1 rows against v2 table
        out = conn.execute("SELECT count(1) AS c FROM pk").to_pylist()
        assert out[0]["c"] == 2 * n
        conn.close()

    def test_matching_order_suggests_nothing(self):
        schema = Schema.build(
            [
                ColumnSchema("region", DatumKind.STRING, is_tag=True),
                ColumnSchema("host", DatumKind.STRING, is_tag=True),
                ColumnSchema("v", DatumKind.DOUBLE),
                ColumnSchema("ts", DatumKind.TIMESTAMP, is_nullable=False),
            ],
            timestamp_column="ts",
            primary_key=["region", "host", "ts"],  # already low-card first
        )
        s = PrimaryKeySampler(schema)
        s.collect(_rows(schema, 2000, n_hosts=500, n_regions=4))
        assert s.suggest(schema) is None

    def test_saturated_column_ranks_last(self):
        schema = _schema()
        s = PrimaryKeySampler(schema)
        n = SAMPLE_DISTINCT_CAP * 2
        rows = RowGroup(
            schema,
            {
                "region": np.array(["r0", "r1"] * (n // 2), dtype=object),
                "host": np.array([f"h{i}" for i in range(n)], dtype=object),
                "v": np.zeros(n),
                "ts": np.arange(n, dtype=np.int64),
            },
        )
        s.collect(rows)
        out = s.suggest(schema)
        names = [out.columns[i].name for i in out.primary_key_indexes]
        assert names[0] == "region"

    def test_dict_columns_count_values_not_codes(self):
        """Per-batch dict code spaces are not comparable: batch 1's code
        0 and batch 2's code 0 may be different hosts. Cardinality must
        come from the mapped VALUES."""
        from horaedb_tpu.common_types.dict_column import DictColumn

        schema = _schema()
        s = PrimaryKeySampler(schema)
        for batch in range(20):
            n = 50
            hosts = np.array(
                [f"h{batch * 10 + i}" for i in range(10)], dtype=object
            )
            rows = RowGroup(
                schema,
                {
                    # host: 10 NEW values per batch (200 total), codes 0-9
                    "host": DictColumn(
                        np.arange(n, dtype=np.int32) % 10, hosts
                    ),
                    # region: the SAME 3 values every batch
                    "region": DictColumn(
                        np.arange(n, dtype=np.int32) % 3,
                        np.array(["r0", "r1", "r2"], dtype=object),
                    ),
                    "v": np.zeros(n),
                    "ts": np.arange(n, dtype=np.int64),
                },
            )
            s.collect(rows)
        out = s.suggest(schema)
        names = [out.columns[i].name for i in out.primary_key_indexes]
        # region (3 values) must lead; code-based counting would have
        # ranked host at 10 "distinct" and broken the tie wrong
        assert names == ["region", "host", "ts"]

    def test_auto_tsid_table_has_no_candidates(self):
        schema = Schema.build(
            [
                ColumnSchema("host", DatumKind.STRING, is_tag=True),
                ColumnSchema("v", DatumKind.DOUBLE),
                ColumnSchema("ts", DatumKind.TIMESTAMP, is_nullable=False),
            ],
            timestamp_column="ts",
        )
        assert not PrimaryKeySampler(schema).has_candidates


class TestSamplerE2E:
    DDL = (
        "CREATE TABLE pk (region string TAG, host string TAG, v double, "
        "ts timestamp NOT NULL, TIMESTAMP KEY(ts), "
        "PRIMARY KEY(host, region, ts)) ENGINE=Analytic "
        "WITH (segment_duration='2h')"
    )

    def _seed(self, conn, n=1000):
        t = conn.catalog.open("pk")
        rng = np.random.default_rng(7)
        rows = RowGroup(
            t.schema,
            {
                "region": np.array(
                    [f"r{i}" for i in rng.integers(0, 3, n)], dtype=object
                ),
                "host": np.array(
                    [f"h{i}" for i in rng.integers(0, 200, n)], dtype=object
                ),
                "v": rng.normal(0, 1, n),
                "ts": rng.integers(0, 3_600_000, n).astype(np.int64),
            },
        )
        t.write(rows)
        return t

    def test_first_flush_applies_and_persists_suggestion(self, tmp_path):
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(self.DDL)
        t = self._seed(conn)
        pk_before = [
            t.schema.columns[i].name for i in t.schema.primary_key_indexes
        ]
        assert pk_before == ["host", "region", "ts"]
        t.flush()
        pk_after = [
            t.schema.columns[i].name for i in t.schema.primary_key_indexes
        ]
        assert pk_after == ["region", "host", "ts"]
        # Reads still answer correctly under the reordered schema.
        out = conn.execute("SELECT count(1) AS c FROM pk").to_pylist()
        assert out[0]["c"] == 1000
        conn.close()

        # Manifest persists the suggestion across reopen.
        conn2 = horaedb_tpu.connect(str(tmp_path / "db"))
        t2 = conn2.catalog.open("pk")
        pk_reopened = [
            t2.schema.columns[i].name for i in t2.schema.primary_key_indexes
        ]
        assert pk_reopened == ["region", "host", "ts"]
        out = conn2.execute("SELECT count(1) AS c FROM pk").to_pylist()
        assert out[0]["c"] == 1000
        conn2.close()

    def test_sst_rows_sorted_by_suggested_order(self, tmp_path):
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(self.DDL)
        t = self._seed(conn)
        t.flush()
        data = t.physical_datas()[0]
        from horaedb_tpu.engine.sst.reader import SstReader

        files = data.version.levels.all_files()
        assert files
        rows = SstReader(data.store, files[0].path).read(t.schema)
        regions = rows.columns["region"]
        vals = [regions[i] for i in range(len(rows))]
        assert vals == sorted(vals)  # region leads the sort now
        conn.close()

    def test_overwrite_dedup_correct_after_reorder(self, tmp_path):
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(self.DDL)
        t = self._seed(conn)
        t.flush()
        # Overwrite one existing key: dedup must keep the newest.
        conn.execute(
            "INSERT INTO pk (region, host, v, ts) VALUES ('r0', 'h1', 99.5, 123)"
        )
        conn.execute(
            "INSERT INTO pk (region, host, v, ts) VALUES ('r0', 'h1', 77.5, 123)"
        )
        t.flush()
        out = conn.execute(
            "SELECT v FROM pk WHERE host = 'h1' AND region = 'r0' AND ts = 123"
        ).to_pylist()
        assert [r["v"] for r in out] == [77.5]
        conn.close()

    def test_failed_flush_leaves_schema_untouched(self, tmp_path, monkeypatch):
        """A flush that dies before the manifest append must not install
        the suggested order (the table would claim a sort its data and
        manifest don't have); the retry re-suggests and applies."""
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(self.DDL)
        t = self._seed(conn)
        data = t.physical_datas()[0]
        v0 = t.schema.version

        real_append = data.manifest.append_edits
        boom = {"armed": True}

        def flaky_append(edits):
            if boom["armed"]:
                boom["armed"] = False
                raise RuntimeError("store down")
            return real_append(edits)

        monkeypatch.setattr(data.manifest, "append_edits", flaky_append)
        with pytest.raises(RuntimeError, match="store down"):
            t.flush()
        assert t.schema.version == v0  # nothing installed
        assert data.pk_sampler is not None  # sampler survives for retry
        t.flush()  # retry succeeds and applies the suggestion
        assert [
            t.schema.columns[i].name for i in t.schema.primary_key_indexes
        ] == ["region", "host", "ts"]
        out = conn.execute("SELECT count(1) AS c FROM pk").to_pylist()
        assert out[0]["c"] == 1000
        conn.close()

    def test_second_flush_does_not_resample(self, tmp_path):
        conn = horaedb_tpu.connect(str(tmp_path / "db"))
        conn.execute(self.DDL)
        t = self._seed(conn)
        t.flush()
        v1 = t.schema.version
        self._seed(conn)
        t.flush()
        assert t.schema.version == v1  # one-shot: no churn after segment 1
        conn.close()
