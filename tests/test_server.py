"""HTTP server tests via aiohttp's test utilities (ref model: the protocol
suites under integration_tests/ that drive a running server).

No async pytest plugin in the image, so each test runs its own event loop.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

import horaedb_tpu
from horaedb_tpu.server import create_app

DDL = (
    "CREATE TABLE demo (name string TAG, value double NOT NULL, "
    "t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic"
)


def with_client(coro_fn):
    """Run an async test body against a live in-memory server."""

    async def runner():
        conn = horaedb_tpu.connect(None)
        app = create_app(conn)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await coro_fn(client)
        finally:
            await client.close()
            conn.close()

    asyncio.run(runner())


async def post_sql(client, query):
    resp = await client.post("/sql", json={"query": query})
    return resp.status, await resp.json()


class TestSqlRoute:
    def test_ddl_insert_select(self):
        async def body(client):
            status, b = await post_sql(client, DDL)
            assert status == 200 and b == {"affected_rows": 0}
            _, b = await post_sql(
                client,
                "INSERT INTO demo (name, value, t) VALUES ('h1', 1.0, 1000), ('h2', 2.0, 2000)",
            )
            assert b == {"affected_rows": 2}
            status, b = await post_sql(
                client, "SELECT name, avg(value) AS a FROM demo GROUP BY name ORDER BY name"
            )
            assert status == 200
            assert b["rows"] == [{"name": "h1", "a": 1.0}, {"name": "h2", "a": 2.0}]
            assert b["names"] == ["name", "a"]

        with_client(body)

    def test_error_statuses(self):
        async def body(client):
            status, b = await post_sql(client, "SELEC 1")
            assert status == 422 and "SELEC" in b["error"]
            resp = await client.post("/sql", data=b"not json")
            assert resp.status == 400
            resp = await client.post("/sql", json={"nope": 1})
            assert resp.status == 400
            status, b = await post_sql(client, "SELECT * FROM ghost")
            assert status == 422 and "not found" in b["error"]

        with_client(body)


class TestWriteRoute:
    def test_bulk_write(self):
        async def body(client):
            await post_sql(client, DDL)
            resp = await client.post(
                "/write",
                json={"table": "demo", "rows": [
                    {"name": "h1", "value": 5.0, "t": 1000},
                    {"name": "h1", "value": 6.0, "t": 2000},
                ]},
            )
            assert (await resp.json()) == {"affected_rows": 2}
            _, b = await post_sql(client, "SELECT count(*) AS c FROM demo")
            assert b["rows"] == [{"c": 2}]
            resp = await client.post("/write", json={"table": "demo"})
            assert resp.status == 400
            resp = await client.post(
                "/write", json={"table": "ghost", "rows": [{"t": 1}]}
            )
            assert resp.status == 422

        with_client(body)


class TestAdminAndDebug:
    def test_block_body_validation(self):
        async def body(client):
            resp = await client.post("/admin/block", json={"tables": "users"})
            assert resp.status == 400  # a string must not block per-character
            resp = await client.post("/admin/block", json={"tables": 5})
            assert resp.status == 400

        with_client(body)

    def test_block_unblock(self):
        async def body(client):
            await post_sql(client, DDL)
            resp = await client.post("/admin/block", json={"tables": ["demo"]})
            assert (await resp.json())["blocked"] == ["demo"]
            status, b = await post_sql(client, "SELECT * FROM demo")
            assert status == 403 and "blocked" in b["error"]
            resp = await client.delete("/admin/block", json={"tables": ["demo"]})
            assert (await resp.json())["blocked"] == []
            status, _ = await post_sql(client, "SELECT * FROM demo")
            assert status == 200

        with_client(body)

    def test_metrics_route_health_debug(self):
        async def body(client):
            await post_sql(client, DDL)
            await post_sql(client, "INSERT INTO demo (name, value, t) VALUES ('h', 1.0, 1)")
            await post_sql(client, "SELECT * FROM demo")

            text = await (await client.get("/metrics")).text()
            assert "horaedb_queries_total" in text
            assert "horaedb_query_duration_seconds_bucket" in text

            resp = await client.get("/route/demo")
            assert (await resp.json())["routes"][0]["endpoint"] == "local"
            assert (await client.get("/route/ghost")).status == 404
            assert (await (await client.get("/health")).json()) == {"status": "ok"}

            tables = await (await client.get("/debug/tables")).json()
            assert "demo" in tables and tables["demo"]["last_sequence"] == 1
            cfg = await (await client.get("/debug/config")).json()
            assert "engine" in cfg
            hot = await (await client.get("/debug/hotspot")).json()
            assert hot["writes"].get("demo") == 1
            resp = await client.put("/debug/slow_threshold/0.5")
            assert (await resp.json())["slow_threshold_s"] == 0.5

        with_client(body)
