-- Explicit PRIMARY KEY vs auto-tsid
CREATE TABLE pk (host string TAG, v double, ts timestamp NOT NULL,
TIMESTAMP KEY(ts), PRIMARY KEY(host, ts)) ENGINE=Analytic;
DESCRIBE pk;
INSERT INTO pk (host, v, ts) VALUES ('a', 1.0, 100), ('a', 2.0, 100);
SELECT host, v FROM pk;
DROP TABLE pk;
