-- Create-table variations (ref: cases/env/local/ddl/create_tables.sql)
CREATE TABLE t1 (ts timestamp NOT NULL, v double, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE t1 (ts timestamp NOT NULL, v double, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE IF NOT EXISTS t1 (ts timestamp NOT NULL, v double, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE t2 (`ts` timestamp NOT NULL, `tag-1` string TAG, v double, TIMESTAMP KEY(ts)) ENGINE=Analytic;
SHOW TABLES;
DESCRIBE t2;
CREATE TABLE t3 (ts timestamp NOT NULL, v unknown_type, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE t4 (v double) ENGINE=Analytic;
DROP TABLE t1;
DROP TABLE t2;
