-- ALTER TABLE ADD COLUMN; old rows read back NULL-filled
-- (ref: cases/env/local/ddl/alter_table.sql)
CREATE TABLE at (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO at (host, v, ts) VALUES ('a', 1.0, 1000);
ALTER TABLE at ADD COLUMN extra double;
DESCRIBE at;
INSERT INTO at (host, v, extra, ts) VALUES ('b', 2.0, 9.5, 2000);
SELECT host, v, extra FROM at ORDER BY ts;
ALTER TABLE at ADD COLUMN v double;
DROP TABLE at;
