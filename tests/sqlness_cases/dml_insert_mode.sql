-- overwrite vs append semantics (ref: cases/common/dml/insert_mode.sql)
CREATE TABLE ow (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO ow (host, v, ts) VALUES ('a', 1.0, 100);
INSERT INTO ow (host, v, ts) VALUES ('a', 2.0, 100);
SELECT host, v FROM ow;
CREATE TABLE ap (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts))
ENGINE=Analytic WITH (update_mode='append');
INSERT INTO ap (host, v, ts) VALUES ('a', 1.0, 100);
INSERT INTO ap (host, v, ts) VALUES ('a', 2.0, 100);
SELECT host, v FROM ap ORDER BY v;
DROP TABLE ow;
DROP TABLE ap;
