-- equality-correlated scalar subqueries (decorrelated into one grouped
-- inner query + per-row lookup; ref: DataFusion scalar decorrelation)
CREATE TABLE co (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE lim (host string TAG, cap double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO co (host, v, ts) VALUES ('a', 1.0, 1), ('a', 8.0, 2), ('b', 3.0, 1), ('c', 4.0, 1);
INSERT INTO lim (host, cap, ts) VALUES ('a', 5.0, 1), ('b', 10.0, 1);
SELECT host, v FROM co WHERE v < (SELECT max(cap) FROM lim WHERE lim.host = co.host) ORDER BY host, v;
SELECT host, v, (SELECT sum(cap) FROM lim WHERE lim.host = co.host) AS s FROM co ORDER BY host, v;
SELECT host, v FROM co WHERE (SELECT count(cap) FROM lim WHERE lim.host = co.host) = 0 ORDER BY host;
SELECT host FROM co WHERE v > (SELECT cap FROM lim WHERE lim.host = co.host);
DROP TABLE co;
DROP TABLE lim;
-- non-aggregate correlated scalar: duplicates in a correlated group error
CREATE TABLE outerq (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE dup (host string TAG, cap double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO outerq (host, v, ts) VALUES ('a', 9.0, 1);
INSERT INTO dup (host, cap, ts) VALUES ('a', 1.0, 1), ('a', 2.0, 2);
SELECT host FROM outerq WHERE v > (SELECT cap FROM dup WHERE dup.host = outerq.host);
DROP TABLE outerq;
DROP TABLE dup;
