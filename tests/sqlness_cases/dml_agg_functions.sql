-- statistical aggregates, FILTER clause, arithmetic over aggregates
CREATE TABLE ag (host string TAG, v double, w double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO ag (host, v, w, ts) VALUES
  ('a', 1.0, 2.0, 1), ('a', 2.0, 4.0, 2), ('a', 3.0, 6.0, 3),
  ('b', 10.0, 5.0, 4), ('b', 20.0, 15.0, 5), ('b', 30.0, 19.0, 6);
SELECT stddev(v) AS sd, var_pop(v) AS vp FROM ag WHERE host = 'a';
SELECT host, median(v) AS m FROM ag GROUP BY host ORDER BY host;
SELECT approx_percentile_cont(v, 0.5) AS p50 FROM ag;
SELECT corr(v, w) AS c FROM ag WHERE host = 'a';
SELECT approx_distinct(host) AS hosts FROM ag;
SELECT count(*) FILTER (WHERE v >= 10) AS big, count(*) FILTER (WHERE v < 10) AS small FROM ag;
SELECT host, sum(v) FILTER (WHERE w > 4) AS s FROM ag GROUP BY host ORDER BY host;
SELECT sum(v) / count(*) AS mean, max(v) - min(v) AS spread FROM ag;
SELECT host, round(sum(w) / sum(v), 3) AS ratio FROM ag GROUP BY host ORDER BY host;
SELECT CASE WHEN sum(v) IS NULL THEN 0.0 ELSE sum(v) END AS total FROM ag WHERE v > 99;
SELECT time_bucket(ts, 2) AS b, count(*) AS c FROM ag GROUP BY b ORDER BY b;
SELECT date_trunc('second', ts) AS s, count(*) AS c FROM ag GROUP BY s ORDER BY s;
DROP TABLE ag;
