-- Partitioned tables: DDL, scatter writes, pruned reads
-- (ref: partition-table DDL, parser.rs partition extension)
CREATE TABLE pt (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts))
PARTITION BY KEY(host) PARTITIONS 4 ENGINE=Analytic;
INSERT INTO pt (host, v, ts) VALUES ('a', 1.0, 1000), ('b', 2.0, 1000), ('c', 3.0, 1000), ('d', 4.0, 1000);
SELECT host, v FROM pt ORDER BY host;
SELECT count(*) AS c FROM pt WHERE host = 'a';
SELECT host, sum(v) AS s FROM pt GROUP BY host ORDER BY host;
DROP TABLE pt;
