-- time_bucket + scalar functions in expressions
CREATE TABLE tb (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO tb (host, v, ts) VALUES
  ('a', -1.5, 0), ('a', 2.0, 30000), ('a', 3.0, 60000), ('b', -4.0, 90000);
SELECT time_bucket(ts, '1m') AS b, count(*) AS c FROM tb GROUP BY time_bucket(ts, '1m') ORDER BY b;
SELECT host, time_bucket(ts, '1m') AS b, sum(v) AS s FROM tb GROUP BY host, time_bucket(ts, '1m') ORDER BY host, b;
SELECT host, abs(v) AS av FROM tb WHERE v < 0 ORDER BY host;
SELECT host, v + 1 AS p, v * 2 AS m FROM tb WHERE host = 'b';
DROP TABLE tb;
