-- ORDER BY / LIMIT shapes (ref: cases/common/dml/select_order.sql)
CREATE TABLE o (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO o (host, v, ts) VALUES ('b', 2.0, 200), ('a', 3.0, 100), ('c', 1.0, 300);
SELECT host, v FROM o ORDER BY v;
SELECT host, v FROM o ORDER BY v DESC;
SELECT host, v FROM o ORDER BY host DESC, v;
SELECT host, v FROM o ORDER BY ts LIMIT 2;
SELECT host, v * 2 AS dbl FROM o ORDER BY dbl DESC LIMIT 1;
DROP TABLE o;
