-- HAVING (ref: cases/common/dml/select_having.sql)
CREATE TABLE h (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO h (host, v, ts) VALUES ('a', 1.0, 100), ('a', 2.0, 200), ('b', 9.0, 100), ('c', 1.0, 100);
SELECT host, count(*) AS c FROM h GROUP BY host HAVING c > 1 ORDER BY host;
SELECT host, sum(v) AS s FROM h GROUP BY host HAVING s >= 2 ORDER BY host;
SELECT host, count(*) AS c FROM h GROUP BY host HAVING host != 'a' ORDER BY host;
SELECT v FROM h HAVING v > 1;
DROP TABLE h;
