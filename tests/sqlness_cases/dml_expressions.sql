-- CASE / CAST / LIKE / OFFSET / NULLS placement / scalar functions
CREATE TABLE fx (host string TAG, lbl string, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO fx (host, lbl, v, ts) VALUES
  ('aa', 'x', 1.0, 1), ('ab', NULL, 2.0, 2), ('bc', 'y', 3.0, 3), ('bd', 'z', 4.0, 4);
SELECT CASE WHEN v > 2 THEN 'big' ELSE 'small' END AS size, v FROM fx ORDER BY v;
SELECT CASE host WHEN 'aa' THEN 1 WHEN 'ab' THEN 2 END AS code FROM fx ORDER BY code NULLS LAST;
SELECT cast(v AS bigint) AS i, cast(v AS string) AS s FROM fx ORDER BY v LIMIT 2;
SELECT host FROM fx WHERE host LIKE 'a%' ORDER BY host;
SELECT host FROM fx WHERE host NOT LIKE '%b%' ORDER BY host;
SELECT host FROM fx WHERE host ILIKE 'A_' ORDER BY host;
SELECT v FROM fx ORDER BY v LIMIT 2 OFFSET 1;
SELECT lbl FROM fx ORDER BY lbl NULLS FIRST, v;
SELECT lbl FROM fx ORDER BY lbl DESC NULLS LAST, v;
SELECT upper(host) AS u, length(host) AS n, concat(host, '-x') AS cx FROM fx ORDER BY v LIMIT 1;
SELECT coalesce(lbl, 'none') AS l FROM fx ORDER BY v;
SELECT round(v + 0.44, 1) AS r, floor(v) AS f, sqrt(v) AS s FROM fx ORDER BY v LIMIT 1;
DROP TABLE fx;
