-- aggregate function coverage incl count distinct + UDAF
CREATE TABLE ag (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO ag (host, v, ts) VALUES
  ('a', 1.0, 100), ('a', 2.0, 200), ('b', 2.0, 100), ('b', 2.0, 200), ('c', 5.0, 100);
SELECT count(*) AS c, sum(v) AS s, min(v) AS lo, max(v) AS hi, avg(v) AS a FROM ag;
SELECT count(DISTINCT host) AS hosts FROM ag;
SELECT host, count(DISTINCT v) AS dv FROM ag GROUP BY host ORDER BY host;
SELECT thetasketch_distinct(host) AS d FROM ag;
SELECT count(*) AS c FROM ag WHERE v > 100;
SELECT sum(v) AS s FROM ag WHERE v > 100;
DROP TABLE ag;
