-- filter shapes (ref: cases/common/dml/select_filter.sql)
CREATE TABLE f (host string TAG, region string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO f (host, region, v, ts) VALUES
  ('a', 'us', 1.0, 1000), ('b', 'us', 2.0, 2000), ('c', 'eu', 3.0, 3000), ('d', 'eu', 4.0, 4000);
SELECT host FROM f WHERE v > 2 ORDER BY host;
SELECT host FROM f WHERE v >= 2 AND region = 'eu' ORDER BY host;
SELECT host FROM f WHERE host IN ('a', 'd') ORDER BY host;
SELECT host FROM f WHERE host NOT IN ('a', 'd') ORDER BY host;
SELECT host FROM f WHERE v BETWEEN 2 AND 3 ORDER BY host;
SELECT host FROM f WHERE ts > 1500 AND ts < 3500 ORDER BY host;
SELECT host FROM f WHERE v > 3 OR region = 'us' ORDER BY host;
SELECT host FROM f WHERE NOT (v > 2) ORDER BY host;
DROP TABLE f;
