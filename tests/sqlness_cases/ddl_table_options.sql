-- Table options: segment_duration, TTL, update_mode, show create
CREATE TABLE opts (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts))
ENGINE=Analytic WITH (segment_duration='2h', ttl='7d', update_mode='append');
SHOW CREATE TABLE opts;
ALTER TABLE opts MODIFY SETTING segment_duration='1h';
SHOW CREATE TABLE opts;
CREATE TABLE badopt (ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic WITH (nonsense='1');
DROP TABLE opts;
