-- duplicate keys within ONE insert batch: LAST write wins (row order)
CREATE TABLE sb (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO sb (host, v, ts) VALUES ('a', 1.0, 100), ('a', 2.0, 100), ('a', 3.0, 100);
SELECT host, v FROM sb;
DROP TABLE sb;
