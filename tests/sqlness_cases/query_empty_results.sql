-- empty-result shapes: ungrouped agg yields one row, grouped yields none
CREATE TABLE e (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
SELECT count(*) AS c FROM e;
SELECT count(*) AS c, sum(v) AS s FROM e;
SELECT host, count(*) AS c FROM e GROUP BY host;
SELECT host, v FROM e;
INSERT INTO e (host, v, ts) VALUES ('a', 1.0, 100);
SELECT count(*) AS c FROM e WHERE ts > 5000;
DROP TABLE e;
