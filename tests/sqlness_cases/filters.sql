-- Predicates: time range, tags, numeric, IN/BETWEEN, NULL semantics
CREATE TABLE m (host string TAG, region string TAG, v double,
                ts timestamp NOT NULL, TIMESTAMP KEY(ts));

INSERT INTO m (host, region, v, ts) VALUES
  ('a', 'east', 1.0, 1000), ('a', 'east', 2.0, 2000),
  ('b', 'west', 3.0, 1500), ('b', 'west', NULL, 2500),
  ('c', 'east', 5.0, 3000);

SELECT host, v FROM m WHERE ts >= 1000 AND ts < 2500 ORDER BY ts;

SELECT count(*) AS c FROM m WHERE host IN ('a', 'c');

SELECT host FROM m WHERE v BETWEEN 2 AND 5 ORDER BY host;

SELECT count(v) AS non_null, count(*) AS total FROM m;

SELECT host, v FROM m WHERE v IS NULL;

SELECT count(*) AS c FROM m WHERE region = 'east' AND v > 1.5;

SELECT host, max(v) AS m FROM m WHERE ts > 0 GROUP BY host ORDER BY host;
