-- SELECT DISTINCT incl NULL keys
CREATE TABLE d (host string TAG, region string TAG, x double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO d (host, region, x, ts) VALUES
  ('a', 'us', 0.0, 1), ('b', 'us', NULL, 2), ('c', 'eu', 0.0, 3), ('d', 'eu', NULL, 4);
SELECT DISTINCT region FROM d ORDER BY region;
SELECT DISTINCT x FROM d;
SELECT DISTINCT region, count(*) AS c FROM d GROUP BY region ORDER BY region;
DROP TABLE d;
