-- SHOW / DESCRIBE / EXISTS surfaces (ref: cases/common/show, system/)
CREATE TABLE s1 (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE s2 (ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
SHOW TABLES;
SHOW CREATE TABLE s1;
DESCRIBE s1;
EXISTS TABLE s1;
EXISTS TABLE nope;
DROP TABLE s2;
SHOW TABLES;
DROP TABLE s1;
