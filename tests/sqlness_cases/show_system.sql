-- SHOW / DESCRIBE / EXISTS surfaces (ref: cases/common/show, system/)
CREATE TABLE s1 (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE s2 (ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
SHOW TABLES;
SHOW CREATE TABLE s1;
DESCRIBE s1;
EXISTS TABLE s1;
EXISTS TABLE nope;
DROP TABLE s2;
SHOW TABLES;
DROP TABLE s1;

-- system catalog virtual table (ref: system_catalog/src/tables.rs)
CREATE TABLE s3 (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
SELECT catalog, schema, table_name, engine FROM system.public.tables;
SELECT count(1) AS n FROM system.public.tables WHERE table_name = 's3';
DROP TABLE s3;
SELECT table_name FROM system.public.tables;
