CREATE TABLE win_demo (host string TAG, v double NOT NULL, t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic;

INSERT INTO win_demo (host, v, t) VALUES ('a', 1.0, 1000), ('a', 3.0, 2000), ('a', 2.0, 3000), ('b', 10.0, 1000), ('b', 10.0, 2000), ('b', 30.0, 3000);

SELECT host, t, v, row_number() OVER (PARTITION BY host ORDER BY t) AS rn FROM win_demo ORDER BY host, t;

SELECT host, t, v, lag(v) OVER (PARTITION BY host ORDER BY t) AS prev, lead(v) OVER (PARTITION BY host ORDER BY t) AS next FROM win_demo ORDER BY host, t;

SELECT host, t, v, lag(v, 2, -1.0) OVER (PARTITION BY host ORDER BY t) AS prev2 FROM win_demo ORDER BY host, t;

SELECT host, v, rank() OVER (PARTITION BY host ORDER BY v) AS rk, dense_rank() OVER (PARTITION BY host ORDER BY v) AS drk FROM win_demo ORDER BY host, v, t;

SELECT host, t, sum(v) OVER (PARTITION BY host ORDER BY t) AS running, avg(v) OVER (PARTITION BY host) AS part_avg FROM win_demo ORDER BY host, t;

SELECT host, t, first_value(v) OVER (PARTITION BY host ORDER BY t) AS fst, last_value(v) OVER (PARTITION BY host ORDER BY t) AS cur, min(v) OVER (PARTITION BY host ORDER BY t) AS run_min FROM win_demo ORDER BY host, t;

SELECT host, t, v - lag(v) OVER (PARTITION BY host ORDER BY t) AS delta FROM win_demo ORDER BY host, t;

SELECT v, row_number() OVER (ORDER BY v DESC) AS rn FROM win_demo ORDER BY rn LIMIT 3;

DROP TABLE win_demo;
