-- EXPLAIN (plan shape only; ANALYZE timings are non-deterministic)
CREATE TABLE ex (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO ex (host, v, ts) VALUES ('a', 1.0, 100);
EXPLAIN SELECT host, avg(v) AS a FROM ex WHERE ts > 50 GROUP BY host;
EXPLAIN SELECT host, v FROM ex WHERE v > 0.5 ORDER BY ts LIMIT 10;
DROP TABLE ex;
