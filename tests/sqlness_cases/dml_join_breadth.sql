-- Join breadth: RIGHT/FULL OUTER, 3-table chains, EXISTS/NOT EXISTS
-- (ref: the reference gets these from DataFusion, datafusion_impl/mod.rs:54)
CREATE TABLE jf (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO jf (host, v, ts) VALUES ('a', 1.0, 1000), ('a', 2.0, 2000), ('b', 3.0, 1000), ('c', 5.0, 1000);
CREATE TABLE jo (host string TAG, owner string TAG, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO jo (host, owner, ts) VALUES ('a', 'alice', 1), ('z', 'zoe', 1);
CREATE TABLE jt (owner string TAG, team string TAG, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO jt (owner, team, ts) VALUES ('alice', 'core', 1), ('zoe', 'infra', 1);
SELECT host, v, owner FROM jf RIGHT JOIN jo ON jf.host = jo.host ORDER BY host, v;
SELECT host, v, owner FROM jf FULL OUTER JOIN jo ON jf.host = jo.host ORDER BY host NULLS LAST, v;
SELECT host, v, owner, team FROM jf JOIN jo ON jf.host = jo.host JOIN jt ON jo.owner = jt.owner ORDER BY v;
SELECT host, owner, team FROM jf LEFT JOIN jo ON jf.host = jo.host JOIN jt ON jo.owner = jt.owner ORDER BY host;
SELECT host, v FROM jf WHERE EXISTS (SELECT * FROM jo WHERE jo.host = jf.host) ORDER BY v;
SELECT host, v FROM jf WHERE NOT EXISTS (SELECT * FROM jo WHERE jo.host = jf.host) ORDER BY v;
SELECT host, v FROM jf WHERE EXISTS (SELECT * FROM jo WHERE ts > 0) ORDER BY host, v;
SELECT host, v FROM jf WHERE EXISTS (SELECT * FROM jo WHERE ts > 100) ORDER BY host, v;
DROP TABLE jf;
DROP TABLE jo;
DROP TABLE jt;
