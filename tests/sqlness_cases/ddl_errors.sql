-- DDL edge cases and error surfaces
CREATE TABLE t1 (ts timestamp KEY, v double);

CREATE TABLE t1 (ts timestamp KEY, v double);

CREATE TABLE IF NOT EXISTS t1 (ts timestamp KEY, v double);

CREATE TABLE bad (v double);

CREATE TABLE bad (host string TAG, ts timestamp KEY)
  PARTITION BY HASH(host) PARTITIONS 2;

ALTER TABLE t1 ADD COLUMN v2 double;

ALTER TABLE t1 ADD COLUMN v2 double;

INSERT INTO t1 (ts, v, v2) VALUES (100, 1.5, 2.5);

SELECT * FROM t1;

DROP TABLE missing;

DROP TABLE IF EXISTS missing;

SELECT nope FROM t1;

SELECT sum(v) FROM t1 GROUP BY v2;
