-- INSERT validation errors
CREATE TABLE ie (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO ie (host, v) VALUES ('a', 1.0);
INSERT INTO ie (host, v, ts) VALUES ('a', 1.0);
INSERT INTO ie (host, nope, ts) VALUES ('a', 1.0, 100);
INSERT INTO nosuch (host) VALUES ('a');
SELECT count(*) AS c FROM ie;
DROP TABLE ie;
