-- Minimum end-to-end slice: DDL, insert, aggregate (ref README demo)
CREATE TABLE demo (name string TAG, value double NOT NULL,
                   t timestamp NOT NULL, TIMESTAMP KEY(t))
ENGINE=Analytic WITH (segment_duration='2h');

INSERT INTO demo (name, value, t) VALUES
  ('host1', 0.32, 1695348000000),
  ('host2', 0.61, 1695348000005),
  ('host1', 0.44, 1695348001000);

SELECT name, value, t FROM demo ORDER BY t;

SELECT name, avg(value) AS a, count(*) AS c FROM demo GROUP BY name ORDER BY name;

SHOW TABLES;

DESCRIBE demo;

EXISTS TABLE demo;

DROP TABLE demo;

SHOW TABLES;
