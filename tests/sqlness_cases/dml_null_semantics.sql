-- SQL 3-valued logic + NULL-skipping aggregates
CREATE TABLE n (host string TAG, x double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO n (host, x, ts) VALUES ('a', 1.0, 1), ('b', NULL, 2), ('c', 3.0, 3);
SELECT host FROM n WHERE x > 0 ORDER BY host;
SELECT host FROM n WHERE x IS NULL;
SELECT host FROM n WHERE x IS NOT NULL ORDER BY host;
SELECT count(*) AS all_rows, count(x) AS non_null, sum(x) AS s, avg(x) AS a FROM n;
SELECT min(x) AS lo, max(x) AS hi FROM n;
DROP TABLE n;
