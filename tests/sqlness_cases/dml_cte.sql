CREATE TABLE cte_src (host string TAG, v double NOT NULL, t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic;

INSERT INTO cte_src (host, v, t) VALUES ('a', 1.0, 1000), ('a', 2.0, 2000), ('b', 10.0, 1000), ('b', 20.0, 2000), ('c', 5.0, 1500);

WITH recent AS (SELECT host, v, t FROM cte_src WHERE t >= 1500) SELECT host, count(1) AS c FROM recent GROUP BY host ORDER BY host;

WITH per_host AS (SELECT host, avg(v) AS a FROM cte_src GROUP BY host) SELECT host, a FROM per_host WHERE a > 2 ORDER BY a DESC;

WITH per_host AS (SELECT host, avg(v) AS a FROM cte_src GROUP BY host), ranked AS (SELECT host, a, rank() OVER (ORDER BY a DESC) AS rk FROM per_host) SELECT host, rk FROM ranked ORDER BY rk;

WITH lo AS (SELECT host, v FROM cte_src WHERE v < 3), hi AS (SELECT host, v FROM cte_src WHERE v >= 10) SELECT host, v FROM lo UNION ALL SELECT host, v FROM hi ORDER BY v;

WITH w AS (SELECT host, v, t FROM cte_src) SELECT host, sum(v) OVER (PARTITION BY host ORDER BY t) AS s FROM w ORDER BY host, t;

DROP TABLE cte_src;
