-- uncorrelated subqueries: IN (SELECT ...) and scalar (SELECT ...)
CREATE TABLE sq (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE allow (host string TAG, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO sq (host, v, ts) VALUES ('a', 1.0, 1), ('b', 5.0, 2), ('c', 9.0, 3);
INSERT INTO allow (host, ts) VALUES ('a', 1), ('c', 1);
SELECT host, v FROM sq WHERE host IN (SELECT host FROM allow) ORDER BY host;
SELECT host FROM sq WHERE host NOT IN (SELECT host FROM allow);
SELECT host, v FROM sq WHERE v > (SELECT avg(v) FROM sq) ORDER BY v;
SELECT host FROM sq WHERE v > (SELECT v FROM sq);
DROP TABLE sq;
DROP TABLE allow;
