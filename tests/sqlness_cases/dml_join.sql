-- single-key inner join (host path subset)
CREATE TABLE m (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE owners (host string TAG, owner string TAG, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO m (host, v, ts) VALUES ('a', 1.0, 100), ('a', 2.0, 200), ('b', 3.0, 100), ('x', 9.0, 100);
INSERT INTO owners (host, owner, ts) VALUES ('a', 'alice', 1), ('b', 'bob', 1);
SELECT host, v, owner FROM m JOIN owners ON m.host = owners.host ORDER BY host, v;
SELECT host, v FROM m JOIN owners ON m.host = owners.host WHERE owner = 'bob';
SELECT count(*) AS c FROM m JOIN owners ON m.host = owners.host;
SELECT host, v, owner FROM m LEFT JOIN owners ON m.host = owners.host ORDER BY host, v;
SELECT host FROM m LEFT OUTER JOIN owners ON m.host = owners.host WHERE owner IS NULL;
SELECT host, owner FROM m LEFT JOIN owners ON m.host = owners.host ORDER BY owner, host;
DROP TABLE m;
DROP TABLE owners;
