-- single-key inner join (host path subset)
CREATE TABLE m (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE owners (host string TAG, owner string TAG, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO m (host, v, ts) VALUES ('a', 1.0, 100), ('a', 2.0, 200), ('b', 3.0, 100), ('x', 9.0, 100);
INSERT INTO owners (host, owner, ts) VALUES ('a', 'alice', 1), ('b', 'bob', 1);
SELECT host, v, owner FROM m JOIN owners ON m.host = owners.host ORDER BY host, v;
SELECT host, v FROM m JOIN owners ON m.host = owners.host WHERE owner = 'bob';
SELECT count(*) AS c FROM m JOIN owners ON m.host = owners.host;
SELECT host, v, owner FROM m LEFT JOIN owners ON m.host = owners.host ORDER BY host, v;
SELECT host FROM m LEFT OUTER JOIN owners ON m.host = owners.host WHERE owner IS NULL;
SELECT host, owner FROM m LEFT JOIN owners ON m.host = owners.host ORDER BY owner, host;
DROP TABLE m;
DROP TABLE owners;
-- multi-key equi-join: ON a AND b
CREATE TABLE m2 (host string TAG, region string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
CREATE TABLE caps (host string TAG, region string TAG, cap double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO m2 (host, region, v, ts) VALUES ('a', 'us', 1.0, 1), ('b', 'us', 2.0, 1), ('b', 'eu', 3.0, 1);
INSERT INTO caps (host, region, cap, ts) VALUES ('a', 'us', 10.0, 1), ('b', 'eu', 30.0, 1);
SELECT host, region, v, cap FROM m2 JOIN caps ON m2.host = caps.host AND m2.region = caps.region ORDER BY host, region;
SELECT host, region, cap FROM m2 LEFT JOIN caps ON m2.host = caps.host AND m2.region = caps.region ORDER BY host, region;
DROP TABLE m2;
DROP TABLE caps;
