CREATE TABLE u1 (host string TAG, v double NOT NULL, t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic;

CREATE TABLE u2 (host string TAG, v double NOT NULL, t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic;

INSERT INTO u1 (host, v, t) VALUES ('a', 1.0, 1000), ('b', 2.0, 2000);

INSERT INTO u2 (host, v, t) VALUES ('b', 2.0, 2000), ('c', 3.0, 3000);

SELECT host, v FROM u1 UNION ALL SELECT host, v FROM u2 ORDER BY v, host;

SELECT host, v FROM u1 UNION SELECT host, v FROM u2 ORDER BY v, host;

SELECT host, v FROM u1 UNION ALL SELECT host, v FROM u2 ORDER BY v DESC LIMIT 2;

SELECT host, avg(v) AS a FROM u1 GROUP BY host UNION ALL SELECT host, avg(v) AS a FROM u2 GROUP BY host ORDER BY host, a;

SELECT host FROM u1 UNION ALL SELECT host FROM u2 UNION ALL SELECT host FROM u1 ORDER BY host;

DROP TABLE u1;

DROP TABLE u2;
