-- multi-key grouping: tags x time buckets
CREATE TABLE g (host string TAG, region string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic;
INSERT INTO g (host, region, v, ts) VALUES
  ('a', 'us', 1.0, 0), ('a', 'us', 2.0, 60000), ('b', 'eu', 3.0, 0), ('b', 'us', 4.0, 60000);
SELECT host, region, count(*) AS c FROM g GROUP BY host, region ORDER BY host, region;
SELECT region, time_bucket(ts, '1m') AS b, sum(v) AS s FROM g GROUP BY region, time_bucket(ts, '1m') ORDER BY region, b;
DROP TABLE g;
