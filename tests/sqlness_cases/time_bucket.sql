-- time_bucket aggregation + EXPLAIN
CREATE TABLE cpu (host string TAG, usage double,
                  ts timestamp NOT NULL, TIMESTAMP KEY(ts))
WITH (segment_duration='1h');

INSERT INTO cpu (host, usage, ts) VALUES
  ('h1', 10.0, 0), ('h1', 20.0, 30000), ('h1', 30.0, 60000),
  ('h2', 5.0, 0), ('h2', 15.0, 90000);

SELECT time_bucket(ts, '1m') AS minute, count(*) AS c, sum(usage) AS s
FROM cpu GROUP BY time_bucket(ts, '1m') ORDER BY minute;

SELECT host, time_bucket(ts, '1m') AS minute, max(usage) AS peak
FROM cpu GROUP BY host, time_bucket(ts, '1m') ORDER BY host, minute;

EXPLAIN SELECT host, avg(usage) FROM cpu WHERE ts >= 0 AND ts < 60000 GROUP BY host;
