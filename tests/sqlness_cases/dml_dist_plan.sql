-- Distributed plan shipping over a partitioned table: window / top-k /
-- distinct / full-agg / residual-filter shapes execute per partition
-- owner and combine at the coordinator (ref: dist_sql_query resolver
-- execute_physical_plan; the 2-node proof lives in test_remote_engine)
CREATE TABLE dsp (host string TAG, v double, ts timestamp NOT NULL, TIMESTAMP KEY(ts))
PARTITION BY KEY(host) PARTITIONS 4 ENGINE=Analytic;
INSERT INTO dsp (host, v, ts) VALUES
  ('a', 5.0, 1000), ('a', 3.0, 2000), ('a', 9.0, 3000),
  ('b', 2.0, 1000), ('b', 8.0, 2000),
  ('c', 7.0, 1000), ('c', 1.0, 2000), ('c', 4.0, 3000);
EXPLAIN SELECT host, ts, row_number() OVER (PARTITION BY host ORDER BY ts) AS rn FROM dsp;
SELECT host, ts, row_number() OVER (PARTITION BY host ORDER BY ts) AS rn FROM dsp ORDER BY host, ts;
SELECT host, v FROM dsp ORDER BY v DESC LIMIT 3;
SELECT DISTINCT host FROM dsp ORDER BY host;
SELECT host, count(v) FILTER (WHERE v > 4) AS big FROM dsp GROUP BY host ORDER BY host;
SELECT host, v FROM dsp WHERE v * 2 > 13 ORDER BY host, v;
DROP TABLE dsp;
