-- identifier case handling (ref: cases/common/dml/case_sensitive.sql)
CREATE TABLE Cs (Host string TAG, V double, Ts timestamp NOT NULL, TIMESTAMP KEY(Ts)) ENGINE=Analytic;
INSERT INTO Cs (Host, V, Ts) VALUES ('a', 1.0, 100);
SELECT Host, V FROM Cs;
SELECT host FROM Cs;
DROP TABLE Cs;
