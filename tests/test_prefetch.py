"""Cold-read prefetch pipeline: parallel page fan-out + prefetch hints
(ref: analytic_engine/src/prefetchable_stream.rs and
num_streams_to_prefetch, lib.rs:109 — first reads overlap IO with
compute instead of serializing fetch -> decode)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from horaedb_tpu.utils.object_store import DiskCacheStore, MemoryStore


class SlowStore(MemoryStore):
    """Latency-injected inner store that records fetch concurrency."""

    def __init__(self, latency_s: float = 0.01) -> None:
        super().__init__()
        self.latency_s = latency_s
        self.range_calls = 0
        self._active = 0
        self.max_concurrent = 0
        self._l = threading.Lock()

    def get_range(self, path, start, end):
        with self._l:
            self._active += 1
            self.range_calls += 1
            self.max_concurrent = max(self.max_concurrent, self._active)
        try:
            time.sleep(self.latency_s)
            return super().get_range(path, start, end)
        finally:
            with self._l:
                self._active -= 1


PAGE = 4096


@pytest.fixture()
def slow_cache(tmp_path):
    inner = SlowStore()
    cache = DiskCacheStore(inner, str(tmp_path / "cache"), page_size=PAGE)
    return inner, cache


def test_cold_multipage_get_fans_out(slow_cache):
    inner, cache = slow_cache
    blob = np.random.default_rng(0).bytes(PAGE * 16)
    inner.put("sst/1.sst", blob)
    s = time.perf_counter()
    assert cache.get("sst/1.sst") == blob
    cold_s = time.perf_counter() - s
    # 16 cold pages must NOT serialize into 16 round trips.
    assert inner.max_concurrent > 1
    assert inner.range_calls == 16
    # Warm read comes from disk, no inner traffic.
    calls = inner.range_calls
    assert cache.get("sst/1.sst") == blob
    assert inner.range_calls == calls
    # The fan-out keeps the cold read well under the serial lower bound.
    serial_floor = 16 * inner.latency_s
    assert cold_s < serial_floor * 0.75, (cold_s, serial_floor)


def test_cold_range_read_slices_correctly(slow_cache):
    inner, cache = slow_cache
    blob = bytes(range(256)) * (PAGE // 128)  # 2 pages exactly
    inner.put("x", blob)
    # Unaligned slice spanning the page boundary, fetched cold.
    assert cache.get_range("x", 100, PAGE + 300) == blob[100:PAGE + 300]
    # Single-page read stays on the serial path.
    assert cache.get_range("x", 0, 10) == blob[:10]


def test_prefetch_warms_cache_in_background(slow_cache):
    inner, cache = slow_cache
    for i in range(4):
        inner.put(f"sst/{i}", np.random.default_rng(i).bytes(PAGE * 4))
    cache.prefetch([f"sst/{i}" for i in range(4)])
    deadline = time.monotonic() + 10
    while inner.range_calls < 16 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert inner.range_calls == 16
    # Reads after the prefetch landed are pure cache hits.
    for i in range(4):
        cache.get(f"sst/{i}")
    assert inner.range_calls == 16
    assert cache.hits >= 16


def test_prefetch_of_missing_object_is_harmless(slow_cache):
    inner, cache = slow_cache
    cache.prefetch(["does/not/exist"])  # must not raise, ever
    time.sleep(0.05)
    inner.put("later", b"x" * 10)
    assert cache.get("later") == b"x" * 10


def test_concurrent_cold_readers_dedup_fetches(slow_cache):
    inner, cache = slow_cache
    blob = np.random.default_rng(1).bytes(PAGE * 8)
    inner.put("big", blob)
    results = []
    threads = [
        threading.Thread(target=lambda: results.append(cache.get("big")))
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r == blob for r in results)
    # Leader/follower inflight dedup: each of the 8 pages fetched ONCE.
    assert inner.range_calls == 8
