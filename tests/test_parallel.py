"""Distributed aggregation tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from horaedb_tpu.ops import ScanAggSpec, scan_aggregate
from horaedb_tpu.ops.encoding import build_padded_batch
from horaedb_tpu.parallel import dist_scan_aggregate


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    assert len(devs) == 8, "conftest must provide 8 virtual devices"
    return Mesh(devs, ("shard",))


class TestDistScanAgg:
    def make_batch(self, n=8192, g=5, b=3, seed=0):
        rng = np.random.default_rng(seed)
        return build_padded_batch(
            rng.integers(0, g, n).astype(np.int32),
            rng.integers(0, b, n).astype(np.int32),
            rng.random(n) > 0.1,
            [rng.normal(size=n).astype(np.float32)],
        )

    def test_matches_single_device(self, mesh):
        batch = self.make_batch()
        spec = ScanAggSpec(n_groups=5, n_buckets=3, n_agg_fields=1).padded()
        single = scan_aggregate(batch, spec)
        dist = dist_scan_aggregate(mesh, batch, spec)
        np.testing.assert_array_equal(single.counts, dist.counts)
        np.testing.assert_allclose(single.sums, dist.sums, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(single.mins, dist.mins)
        np.testing.assert_allclose(single.maxs, dist.maxs)

    @pytest.mark.parametrize("op", ["<", "<=", ">", ">=", "=", "!="])
    def test_device_filter_in_dist(self, mesh, op):
        # Discretized values so every op differs from every other op's
        # result (a continuous distribution can't tell '>' from '>=').
        rng = np.random.default_rng(5)
        n = 8192
        batch = build_padded_batch(
            rng.integers(0, 5, n).astype(np.int32),
            rng.integers(0, 3, n).astype(np.int32),
            np.ones(n, dtype=bool),
            [rng.integers(-2, 3, n).astype(np.float32)],
        )
        spec = ScanAggSpec(
            n_groups=5, n_buckets=3, n_agg_fields=1, numeric_filters=((0, op),)
        ).padded()
        single = scan_aggregate(batch, spec, [0.0])
        dist = dist_scan_aggregate(mesh, batch, spec, [0.0])
        np.testing.assert_array_equal(single.counts, dist.counts)
        assert single.counts.sum() not in (0, n)  # filter actually selective

    def test_result_replicated_on_all_devices(self, mesh):
        from horaedb_tpu.parallel import make_dist_scan_agg
        import jax.numpy as jnp

        batch = self.make_batch(n=4096)
        spec = ScanAggSpec(n_groups=5, n_buckets=3, n_agg_fields=1).padded()
        step = make_dist_scan_agg(mesh, spec)
        counts, *_ = step(
            jnp.asarray(batch.group_codes),
            jnp.asarray(batch.bucket_ids),
            jnp.asarray(batch.mask),
            jnp.asarray(batch.values),
            jnp.zeros(0, dtype=jnp.float32),
        )
        assert counts.sharding.is_fully_replicated


class TestServingPathMesh:
    """VERDICT r1 #1: a /sql query must run the shard_map kernel when the
    batch is large enough — same code path the server and dryrun use."""

    def _db(self):
        import horaedb_tpu

        db = horaedb_tpu.connect(None)
        db.execute(
            "CREATE TABLE st (name string TAG, value double, "
            "t timestamp NOT NULL, TIMESTAMP KEY(t)) ENGINE=Analytic"
        )
        return db

    def _write(self, db, n=5000):
        from horaedb_tpu.common_types import RowGroup
        from horaedb_tpu.common_types.schema import compute_tsid

        rng = np.random.default_rng(7)
        names = np.array([f"h{i}" for i in rng.integers(0, 8, n)], dtype=object)
        t = db.catalog.open("st")
        rows = RowGroup(
            t.schema,
            {
                "tsid": compute_tsid([names]),
                "name": names,
                "value": rng.normal(10, 3, n),
                "t": rng.integers(0, 3_600_000, n).astype(np.int64),
            },
        )
        t.write(rows)
        return n

    def test_sql_query_runs_on_mesh_and_matches_host(self, mesh, monkeypatch):
        monkeypatch.setenv("HORAEDB_DIST_MIN_ROWS", "1")
        monkeypatch.setenv("HORAEDB_SCAN_CACHE", "0")
        db = self._db()
        self._write(db)
        sql = (
            "SELECT name, count(value) AS c, avg(value) AS a, "
            "min(value) AS lo, max(value) AS hi FROM st "
            "WHERE value > 4.0 GROUP BY name"
        )
        out = db.execute(sql)
        ex = db.interpreters.executor
        assert ex.last_path == "device-dist"
        assert ex.last_metrics["mesh_devices"] == 8
        dist_rows = {r["name"]: r for r in out.to_pylist()}

        # Host path on the same data must agree.
        orig = ex._device_capable
        ex._device_capable = lambda plan, rows: False
        host = db.execute(sql)
        ex._device_capable = orig
        assert ex.last_path == "host"
        host_rows = {r["name"]: r for r in host.to_pylist()}
        assert set(dist_rows) == set(host_rows)
        for k in host_rows:
            assert dist_rows[k]["c"] == host_rows[k]["c"]
            for f in ("a", "lo", "hi"):
                np.testing.assert_allclose(
                    dist_rows[k][f], host_rows[k][f], rtol=1e-4, atol=1e-5
                )

    def test_small_batch_stays_single_device(self, mesh, monkeypatch):
        monkeypatch.setenv("HORAEDB_SCAN_CACHE", "0")
        # default threshold (256k) far above 5k rows
        db = self._db()
        self._write(db, n=1000)
        db.execute("SELECT name, count(value) AS c FROM st GROUP BY name")
        assert db.interpreters.executor.last_path == "device"

    def test_non_power_of_two_mesh_pads(self):
        from jax.sharding import Mesh as JMesh

        from horaedb_tpu.ops import ScanAggSpec, scan_aggregate
        from horaedb_tpu.ops.encoding import build_padded_batch
        from horaedb_tpu.parallel import dist_scan_aggregate

        devs = np.array(jax.devices()[:6])
        m6 = JMesh(devs, ("shard",))
        rng = np.random.default_rng(3)
        n = 8192  # pow2 padded len, NOT divisible by 6
        batch = build_padded_batch(
            rng.integers(0, 5, n).astype(np.int32),
            rng.integers(0, 3, n).astype(np.int32),
            np.ones(n, dtype=bool),
            [rng.normal(size=n).astype(np.float32)],
        )
        spec = ScanAggSpec(n_groups=5, n_buckets=3, n_agg_fields=1).padded()
        single = scan_aggregate(batch, spec)
        dist = dist_scan_aggregate(m6, batch, spec)
        np.testing.assert_array_equal(single.counts, dist.counts)
        np.testing.assert_allclose(single.sums, dist.sums, rtol=1e-4, atol=1e-5)
        # Pad rows are zero-valued: a mask leak would corrupt min/max
        # (inject 0.0) before it ever showed in counts/sums.
        np.testing.assert_allclose(single.mins, dist.mins)
        np.testing.assert_allclose(single.maxs, dist.maxs)


class TestDistMergeDedup:
    """Merge-dedup under shard_map: tsid-range chunks mapped to devices,
    zero collectives, output in global key order (dryrun leg 5)."""

    def test_matches_host_oracle(self, mesh):
        from horaedb_tpu.parallel import dist_merge_dedup

        rng = np.random.default_rng(5)
        n = 5000
        tsid = rng.integers(0, 2**63, 80, dtype=np.uint64)[
            rng.integers(0, 80, n)
        ]
        ts = rng.integers(0, 500, n).astype(np.int64)
        seq = rng.integers(1, 7, n).astype(np.uint64)
        sel = dist_merge_dedup(mesh, tsid, ts, seq)
        # survivor set: one row per key, newest sequence wins
        expect: dict = {}
        for i in range(n):
            k = (int(tsid[i]), int(ts[i]))
            # same-seq ties: LAST input row wins (matches the single-chip
            # kernel's reversal + stable-sort contract)
            if k not in expect or int(seq[i]) >= int(seq[expect[k]]):
                expect[k] = i
        got = {(int(tsid[i]), int(ts[i])): i for i in sel}
        assert set(got) == set(expect)
        for k, i in got.items():
            assert int(seq[i]) == int(seq[expect[k]]), k
        merged = [(int(tsid[i]), int(ts[i])) for i in sel]
        assert merged == sorted(merged)

    def test_no_dedup_keeps_all_rows(self, mesh):
        from horaedb_tpu.parallel import dist_merge_dedup

        rng = np.random.default_rng(6)
        n = 1000
        tsid = rng.integers(0, 2**40, n).astype(np.uint64)
        ts = rng.integers(0, 100, n).astype(np.int64)
        seq = np.ones(n, dtype=np.uint64)
        sel = dist_merge_dedup(mesh, tsid, ts, seq, dedup=False)
        assert len(sel) == n
        assert np.array_equal(np.sort(sel), np.arange(n))
