"""Distributed aggregation tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from horaedb_tpu.ops import ScanAggSpec, scan_aggregate
from horaedb_tpu.ops.encoding import build_padded_batch
from horaedb_tpu.parallel import dist_scan_aggregate


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    assert len(devs) == 8, "conftest must provide 8 virtual devices"
    return Mesh(devs, ("shard",))


class TestDistScanAgg:
    def make_batch(self, n=8192, g=5, b=3, seed=0):
        rng = np.random.default_rng(seed)
        return build_padded_batch(
            rng.integers(0, g, n).astype(np.int32),
            rng.integers(0, b, n).astype(np.int32),
            rng.random(n) > 0.1,
            [rng.normal(size=n).astype(np.float32)],
        )

    def test_matches_single_device(self, mesh):
        batch = self.make_batch()
        spec = ScanAggSpec(n_groups=5, n_buckets=3, n_agg_fields=1).padded()
        single = scan_aggregate(batch, spec)
        dist = dist_scan_aggregate(mesh, batch, spec)
        np.testing.assert_array_equal(single.counts, dist.counts)
        np.testing.assert_allclose(single.sums, dist.sums, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(single.mins, dist.mins)
        np.testing.assert_allclose(single.maxs, dist.maxs)

    @pytest.mark.parametrize("op", ["<", "<=", ">", ">=", "=", "!="])
    def test_device_filter_in_dist(self, mesh, op):
        # Discretized values so every op differs from every other op's
        # result (a continuous distribution can't tell '>' from '>=').
        rng = np.random.default_rng(5)
        n = 8192
        batch = build_padded_batch(
            rng.integers(0, 5, n).astype(np.int32),
            rng.integers(0, 3, n).astype(np.int32),
            np.ones(n, dtype=bool),
            [rng.integers(-2, 3, n).astype(np.float32)],
        )
        spec = ScanAggSpec(
            n_groups=5, n_buckets=3, n_agg_fields=1, numeric_filters=((0, op),)
        ).padded()
        single = scan_aggregate(batch, spec, [0.0])
        dist = dist_scan_aggregate(mesh, batch, spec, [0.0])
        np.testing.assert_array_equal(single.counts, dist.counts)
        assert single.counts.sum() not in (0, n)  # filter actually selective

    def test_result_replicated_on_all_devices(self, mesh):
        from horaedb_tpu.parallel import make_dist_scan_agg
        import jax.numpy as jnp

        batch = self.make_batch(n=4096)
        spec = ScanAggSpec(n_groups=5, n_buckets=3, n_agg_fields=1).padded()
        step = make_dist_scan_agg(mesh, spec)
        counts, *_ = step(
            jnp.asarray(batch.group_codes),
            jnp.asarray(batch.bucket_ids),
            jnp.asarray(batch.mask),
            jnp.asarray(batch.values),
            jnp.zeros(0, dtype=jnp.float32),
        )
        assert counts.sharding.is_fully_replicated
