"""The concurrency fuzz harness as a CI target (the sanitizer-analog;
ref model: the reference's ASan/MSan engine-test builds, Makefile:95-114).
Short seeded runs here; longer soaks are `python -m horaedb_tpu.tools.fuzz
--duration 60 --reopen` by hand. The disk+reopen config is the one that
caught the manifest snapshot-truncation data-loss bug (seed 2)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_fuzz(*args: str, timeout: float = 120.0) -> dict:
    env = {
        **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    }
    p = subprocess.run(
        [sys.executable, "-m", "horaedb_tpu.tools.fuzz", *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON output; stderr tail: {p.stderr[-500:]}"
    out = json.loads(lines[-1])
    assert p.returncode == (0 if out["ok"] else 1)
    return out


class TestFuzzHarness:
    def test_memory_backend(self):
        out = run_fuzz("--seed", "11", "--duration", "3", "--threads", "4")
        assert out["ok"], out["violations"]
        assert out["ops"].get("insert", 0) > 0
        assert out["ops"].get("select", 0) > 0

    def test_disk_with_reopen_cycles(self, tmp_path):
        out = run_fuzz(
            "--seed", "2", "--duration", "4", "--threads", "4",
            "--data-dir", str(tmp_path / "fz"), "--reopen",
        )
        assert out["ok"], out["violations"]
        assert out["ops"].get("reopen", 0) >= 1

    @pytest.mark.parametrize("backend", ["object_store", "shared_log"])
    def test_alternative_wal_backends(self, tmp_path, backend):
        """Row conservation across restarts must hold on every WAL
        implementation, not just the framed local log."""
        out = run_fuzz(
            "--seed", "5", "--duration", "3", "--threads", "3",
            "--data-dir", str(tmp_path / "fz"), "--reopen",
            "--wal-backend", backend,
        )
        assert out["ok"], out["violations"]
        assert out["wal_backend"] == backend
