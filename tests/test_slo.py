"""SLO plane unit tests: objective parsing, incremental burn-rate
windows, burn/recover transitions + typed events, the system.public.slo
/ /debug/slo serving faces, the FaultInjectingStore, and event-journal
drop accounting (PR 11)."""

import time

import pytest

import horaedb_tpu
from horaedb_tpu.slo import (
    SloError,
    SloEvaluator,
    parse_objective_line,
)
from horaedb_tpu.slo.evaluator import _Window
from horaedb_tpu.utils.config import SloSection
from horaedb_tpu.utils.events import EVENT_STORE, EventStore


class TestObjectiveParsing:
    def test_full_line(self):
        o = parse_objective_line(
            "cheap_p99 := histogram_quantile(0.99, "
            'rate(horaedb_query_class_duration_seconds_bucket{class="cheap"}'
            "[1m])) <= 0.5 target 99.9%"
        )
        assert o.name == "cheap_p99"
        assert o.op == "<="
        assert o.bound == 0.5
        assert abs(o.target - 0.999) < 1e-9
        assert abs(o.budget - 0.001) < 1e-9
        assert "histogram_quantile" in o.expr

    def test_default_target_and_ops(self):
        for op in ("<=", "<", ">=", ">"):
            o = parse_objective_line(f"x := some_metric {op} 3")
            assert o.op == op and o.bound == 3.0 and o.target == 0.99

    def test_comparison_inside_braces_not_split(self):
        # a regex matcher containing '>' must not be mistaken for the
        # bound comparison
        o = parse_objective_line(
            'weird := some_metric{path=~"a>b.*"} <= 1 target 90%'
        )
        assert o.op == "<=" and o.bound == 1.0
        assert 'path=~"a>b.*"' in o.expr

    def test_rejects(self):
        with pytest.raises(SloError, match="top-level comparison"):
            parse_objective_line("x := some_metric")
        with pytest.raises(SloError, match="must be a number"):
            parse_objective_line("x := a <= b")
        with pytest.raises(SloError, match="target"):
            parse_objective_line("x := a <= 1 target 100%")
        with pytest.raises(SloError, match="target"):
            parse_objective_line("x := a <= 1 target 0%")
        with pytest.raises(SloError, match="name"):
            parse_objective_line("bad-name := a <= 1")
        with pytest.raises(SloError, match="bad expr"):
            parse_objective_line("x := ,nope, <= 1")
        with pytest.raises(SloError, match="NAME := EXPR"):
            parse_objective_line("just an expression <= 1")

    def test_config_section_validation(self):
        import os
        import tempfile

        from horaedb_tpu.utils.config import Config, ConfigError

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "c.toml")
            with open(path, "w") as f:
                f.write(
                    "[slo]\n"
                    'objectives = ["ok := up <= 1 target 99%"]\n'
                    'fast_window = "2m"\n'
                    'slow_window = "30m"\n'
                    "burn_threshold = 2.0\n"
                )
            cfg = Config.load(path)
            assert cfg.slo.objectives and cfg.slo.fast_window_s == 120.0
            assert cfg.slo.slow_window_s == 1800.0
            assert cfg.slo.burn_threshold == 2.0

            with open(path, "w") as f:
                f.write('[slo]\nobjectives = ["nope"]\n')
            with pytest.raises(ConfigError, match=r"\[slo\]"):
                Config.load(path)

            with open(path, "w") as f:
                f.write('[slo]\nfast_window = "2h"\nslow_window = "1h"\n')
            with pytest.raises(ConfigError, match="fast_window"):
                Config.load(path)

            with open(path, "w") as f:
                f.write("[observability]\nevent_ring = 0\n")
            with pytest.raises(ConfigError, match="event_ring"):
                Config.load(path)


class TestWindow:
    def test_incremental_matches_naive(self):
        """The O(1) running sums must equal a from-scratch refold at
        every step (the incremental-maintenance correctness claim)."""
        import random

        rng = random.Random(3)
        w = _Window(5_000)
        samples = []
        t = 1_000_000
        for _ in range(300):
            dt = rng.randrange(50, 900)
            t += dt
            bad = rng.random() < 0.3
            samples.append((t, dt, dt if bad else 0))
            w.push(t, dt, bad)
            kept = [s for s in samples if s[0] > t - 5_000]
            assert w.total_ms == sum(s[1] for s in kept)
            assert w.bad_ms == sum(s[2] for s in kept)

    def test_bad_fraction_empty(self):
        assert _Window(1000).bad_fraction() == 0.0


class TestEvaluator:
    def _eval(self, db, objectives, fast=2.0, slow=8.0, thr=1.0):
        return SloEvaluator(
            db,
            SloSection(
                objectives=objectives, fast_window_s=fast, slow_window_s=slow,
                burn_threshold=thr,
            ),
            node="unit",
        )

    def test_burn_and_recover_with_events(self):
        db = horaedb_tpu.connect(None)
        try:
            ev = self._eval(db, ["slo_unit_bad := 2 <= 1 target 90%"])
            before = EVENT_STORE.stats()["last_seq"]
            now = int(time.time() * 1000)
            for i in range(40):
                ev.evaluate_round(now + i * 300)
            (row,) = ev.snapshot()
            assert row["state"] == "burning"
            assert row["breaches"] == 1
            assert row["burn_fast"] == pytest.approx(10.0)
            # expression flips compliant -> the fast window drains ->
            # recovery (the slow window still remembers)
            ev._states["slo_unit_bad"].objective.bound = 5.0
            for i in range(40, 60):
                ev.evaluate_round(now + i * 300)
            (row,) = ev.snapshot()
            assert row["state"] == "ok"
            assert row["burn_fast"] == 0.0
            assert row["burn_slow"] > 0.0
            kinds = [
                e["kind"]
                for e in EVENT_STORE.list()
                if e["seq"] > before and e["kind"].startswith("slo_")
            ]
            assert kinds == ["slo_burn", "slo_recovered"]
            hist = ev.breach_history()
            assert len(hist) == 1 and hist[0]["recovered_at_ms"] > 0
        finally:
            db.close()

    def test_multiwindow_blip_does_not_burn(self):
        """A violation shorter than the slow window's budget share must
        not page — that's the whole point of the slow window."""
        db = horaedb_tpu.connect(None)
        try:
            ev = self._eval(
                db, ["slo_unit_blip := 2 <= 5 target 50%"], fast=1.0, slow=60.0
            )
            state = ev._states["slo_unit_blip"]
            now = int(time.time() * 1000)
            # 20 good rounds, then 4 bad rounds (fills the 1s fast window
            # but is a sliver of the 60s slow one)
            for i in range(20):
                ev.evaluate_round(now + i * 300)
            state.objective.bound = 1.0  # still 2 <= 1 -> bad
            for i in range(20, 24):
                ev.evaluate_round(now + i * 300)
            (row,) = ev.snapshot()
            assert row["burn_fast"] >= 1.0  # fast window saturated...
            assert row["state"] == "ok"  # ...but slow window vetoed
            assert row["breaches"] == 0
        finally:
            db.close()

    def test_no_data_state_and_error_isolation(self):
        db = horaedb_tpu.connect(None)
        try:
            ev = self._eval(
                db,
                [
                    "slo_unit_nodata := no_such_metric_xyz <= 1",
                    "slo_unit_live := 0 <= 1",
                ],
            )
            now = int(time.time() * 1000)
            for i in range(3):
                ev.evaluate_round(now + i * 300)
            rows = {r["name"]: r for r in ev.snapshot()}
            assert rows["slo_unit_nodata"]["state"] == "no_data"
            assert rows["slo_unit_nodata"]["no_data_rounds"] == 3
            assert rows["slo_unit_nodata"]["value"] is None
            assert rows["slo_unit_live"]["state"] == "ok"
            assert ev.stats()["objectives"] == 2
        finally:
            db.close()

    def test_worst_series_direction(self):
        """For an upper bound the MAX series decides; for a lower bound
        the MIN — the worst series is the verdict."""
        from horaedb_tpu.slo.model import SloObjective, complies

        assert complies("<=", 1.0, 1.0) and not complies("<", 1.0, 1.0)
        assert complies(">=", 1.0, 1.0) and not complies(">", 1.0, 1.0)
        o = SloObjective("x", "m", "<=", 1.0)
        assert o.budget == pytest.approx(0.01)

    def test_sql_and_debug_faces(self):
        """system.public.slo on the SQL wire + /debug/slo JSON, from the
        same snapshot."""
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from horaedb_tpu.server import create_app

        db = horaedb_tpu.connect(None)
        try:
            ev = self._eval(db, ["slo_unit_face := 2 <= 1 target 90%"])
            now = int(time.time() * 1000)
            for i in range(30):
                ev.evaluate_round(now + i * 300)
            out = db.execute(
                "SELECT objective, state, breaches, burn_fast FROM "
                "system.public.slo WHERE objective = 'slo_unit_face'"
            )
            (row,) = out.to_pylist()
            assert row["state"] == "burning" and row["breaches"] == 1
            assert row["burn_fast"] > 1.0

            app = create_app(db)
            app["slo"] = ev  # face an existing evaluator

            async def body():
                client = TestClient(TestServer(app))
                await client.start_server()
                try:
                    resp = await client.get("/debug/slo")
                    assert resp.status == 200
                    doc = await resp.json()
                    assert doc["enabled"] is True
                    names = [o["name"] for o in doc["objectives"]]
                    assert "slo_unit_face" in names
                    assert doc["breaches"]
                finally:
                    await client.close()

            asyncio.run(body())
        finally:
            db.close()

    def test_rides_rule_engine_cadence(self):
        """RuleEngine.run_once ticks the attached evaluator — the SLO
        plane deliberately has no loop of its own."""
        from horaedb_tpu.rules import RuleEngine
        from horaedb_tpu.utils.config import RulesSection

        db = horaedb_tpu.connect(None)
        try:
            ev = self._eval(db, ["slo_unit_ride := 0 <= 1"])
            eng = RuleEngine(db, RulesSection(), node="unit", slo=ev)
            assert ev.rounds == 0
            eng.run_once()
            eng.run_once()
            assert ev.rounds == 2
            (row,) = ev.snapshot()
            assert row["rounds"] == 2
        finally:
            db.close()


class TestFaultInjectingStore:
    def test_latency_errors_and_determinism(self):
        from horaedb_tpu.utils.object_store import (
            FaultInjectingStore,
            InjectedFaultError,
            MemoryStore,
        )

        inner = MemoryStore()
        st = FaultInjectingStore(inner, seed=42, suffix=".sst")
        st.put("a/1.sst", b"x" * 10)
        assert st.get("a/1.sst") == b"x" * 10
        assert st.get_range("a/1.sst", 2, 5) == b"xxx"
        assert st.head("a/1.sst") == 10

        # suffix filter: non-matching paths are never injected
        st.error_rate = 1.0
        st.put("manifest/edit.json", b"{}")
        with pytest.raises(InjectedFaultError):
            st.put("a/2.sst", b"y")
        assert st.injected_errors == 1
        assert not inner.exists("a/2.sst")
        st.error_rate = 0.0

        # deterministic under a seed: same sequence, same failures
        def failures(seed):
            s = FaultInjectingStore(MemoryStore(), seed=seed, error_rate=0.5)
            out = []
            for i in range(30):
                try:
                    s.put(f"p/{i}.sst", b"z")
                    out.append(True)
                except InjectedFaultError:
                    out.append(False)
            return out

        assert failures(7) == failures(7)
        assert failures(7) != failures(8)

        # latency knob actually delays (and is adjustable live)
        st.put_latency_s = 0.05
        t0 = time.perf_counter()
        st.put("a/3.sst", b"z")
        assert time.perf_counter() - t0 >= 0.04
        assert st.delayed_ops >= 1

    def test_injection_is_a_metric(self):
        """The simulator's alerts/SLOs observe injected chaos through
        the database's own telemetry — the counter must tick."""
        from horaedb_tpu.utils.metrics import REGISTRY
        from horaedb_tpu.utils.object_store import (
            FaultInjectingStore,
            InjectedFaultError,
            MemoryStore,
        )

        c = REGISTRY.counter(
            "horaedb_object_store_injected_faults_total", ""
        )
        before = c.value
        st = FaultInjectingStore(MemoryStore(), seed=1, error_rate=1.0)
        with pytest.raises(InjectedFaultError):
            st.put("x.sst", b"d")
        assert c.value == before + 1


class TestEventRingAccounting:
    def test_overflow_accounted_and_contiguous(self):
        store = EventStore(maxlen=8)
        for i in range(20):
            store.record({"kind": "k", "n": i})
        stats = store.stats()
        assert stats["size"] == 8
        assert stats["dropped"] == 12
        seqs = [e["seq"] for e in store.list()]
        assert seqs == list(range(13, 21))  # contiguous retained window
        # the journal invariant the simulator asserts: every missing
        # leading seq is an accounted drop
        assert seqs[0] - 1 == stats["dropped"]

    def test_resize_accounts_shrink_keeps_grow(self):
        store = EventStore(maxlen=8)
        for i in range(8):
            store.record({"kind": "k", "n": i})
        store.resize(4)
        stats = store.stats()
        assert stats["capacity"] == 4 and stats["size"] == 4
        assert stats["dropped"] == 4
        store.resize(16)
        assert store.stats()["capacity"] == 16
        assert store.stats()["dropped"] == 4  # growing drops nothing
        for i in range(20):
            store.record({"kind": "k"})
        assert store.stats()["size"] == 16
        # 4 kept + 20 new through a 16-ring = 8 more drops on top of the
        # 4 the shrink accounted
        assert store.stats()["dropped"] == 12

    def test_global_store_resize_via_create_app(self):
        """[observability] event_ring reaches the process-global ring
        through create_app."""
        from horaedb_tpu.server import create_app
        from horaedb_tpu.utils.config import ObservabilitySection

        db = horaedb_tpu.connect(None)
        try:
            old_cap = EVENT_STORE.capacity
            try:
                app = create_app(
                    db,
                    observability=ObservabilitySection(
                        self_scrape=False, event_ring=old_cap + 64
                    ),
                )
                assert EVENT_STORE.capacity == old_cap + 64
                assert app["metrics_recorder"] is None
            finally:
                EVENT_STORE.resize(old_cap)
        finally:
            db.close()
