"""Shard Split/Merge/Migrate/Scatter + bounded-load hash-ring placement
(ref: horaemeta/server/coordinator/procedure/procedure.go:40-55 — the
procedure repertoire; scheduler/nodepicker/hash/consistent_uniform.go —
consistent hashing with bounded loads).

Three layers:
- ring unit tests (balance bound, stability, determinism);
- handler tests against a MetaServer with a patched event dispatcher
  (split/merge semantics, retry idempotency, topology invariants);
- one full-process e2e: split a shard cross-node, verify routing and
  data integrity, migrate it, merge it back.
"""

from __future__ import annotations

import pytest

from horaedb_tpu.meta.kv import MemoryKV
from horaedb_tpu.meta.scheduler import BoundedLoadRing
from horaedb_tpu.meta import service as meta_service
from horaedb_tpu.meta.service import MetaServer

# Reuse the real-process cluster harness.
from tests.test_cluster_meta import (  # noqa: F401
    DDL, cluster, http, shards_all_assigned, sql, wait_until,
)


class TestBoundedLoadRing:
    def test_balance_bound_holds(self):
        members = [f"node{i}:80" for i in range(5)]
        ring = BoundedLoadRing(members, load_factor=1.25)
        loads = {m: 0 for m in members}
        for k in range(100):
            m = ring.pick(f"shard/{k}", loads)
            loads[m] += 1
        # Google bounded-loads invariant: nobody exceeds ceil(avg * c).
        assert max(loads.values()) <= ring.max_load(loads)
        # ...and everyone got something at this key:member ratio.
        assert min(loads.values()) > 0

    def test_determinism_across_instances(self):
        members = ["a:1", "b:2", "c:3"]
        r1 = BoundedLoadRing(members)
        r2 = BoundedLoadRing(list(reversed(members)))
        loads = {m: 0 for m in members}
        for k in range(50):
            assert r1.pick(f"s/{k}", dict(loads)) == r2.pick(f"s/{k}", dict(loads))

    def test_stability_on_member_loss(self):
        members = [f"n{i}" for i in range(6)]
        big = BoundedLoadRing(members)
        small = BoundedLoadRing(members[:-1])
        keys = [f"shard/{k}" for k in range(120)]
        before, after = {}, {}
        loads_b = {m: 0 for m in members}
        loads_a = {m: 0 for m in members[:-1]}
        for k in keys:
            before[k] = big.pick(k, loads_b)
            loads_b[before[k]] += 1
            after[k] = small.pick(k, loads_a)
            loads_a[after[k]] += 1
        # Keys not on the removed member mostly stay put (bounded loads
        # shifts a few near the bound; consistent hashing caps the rest).
        stayed = sum(
            1 for k in keys if before[k] != members[-1] and before[k] == after[k]
        )
        not_on_lost = sum(1 for k in keys if before[k] != members[-1])
        assert stayed / not_on_lost > 0.6

    def test_rejects_degenerate_factor(self):
        with pytest.raises(ValueError):
            BoundedLoadRing(["a"], load_factor=1.0)


@pytest.fixture()
def meta(monkeypatch):
    """Single-process MetaServer with two fake online nodes; /meta_event
    dispatches are captured instead of sent."""
    calls: list[tuple[str, str, dict]] = []
    next_id = iter(range(1, 10_000))

    def fake_post(endpoint, path, payload, timeout=5.0):
        calls.append((endpoint, path, payload))
        return {"table_id": next(next_id), "sub_table_ids": []}

    monkeypatch.setattr(meta_service, "_post", fake_post)
    server = MetaServer(MemoryKV(), num_shards=4)
    for ep in ("127.0.0.1:11", "127.0.0.1:22"):
        server.topology.register_node(ep)
    server.tick()  # static scheduler assigns all shards
    assert all(s.node for s in server.topology.shards())
    return server, calls


def _place_tables(server, n):
    for i in range(n):
        server.handle_create_table(f"t{i}", f"CREATE TABLE t{i} (...)")


class TestSplitMergeHandlers:
    def test_split_moves_tables_and_opens_new_shard(self, meta):
        server, calls = meta
        _place_tables(server, 8)
        src = max(
            server.topology.shards(), key=lambda s: len(s.table_ids)
        )
        src_tables = {t.name for t in server.topology.tables_of_shard(src.shard_id)}
        assert len(src_tables) >= 2
        calls.clear()
        out = server.handle_split(src.shard_id)
        new_sid = out["new_shard_id"]
        assert new_sid not in {s.shard_id for s in server.topology.shards()[:0]}
        moved = set(out["tables_moved"])
        assert moved and moved < src_tables
        # Topology: moved tables now route to the new shard.
        for name in moved:
            assert server.topology.table(name).shard_id == new_sid
        remaining = {
            t.name for t in server.topology.tables_of_shard(src.shard_id)
        }
        assert remaining == src_tables - moved
        # Same-node default: new shard opened on the source's node, and
        # the source got its updated (pruned) order.
        new_view = server.topology.shard(new_sid)
        assert new_view.node == src.node
        opened = [(ep, pl["shard_id"]) for ep, path, pl in calls
                  if path == "/meta_event/open_shard"]
        assert (src.node, new_sid) in opened and (src.node, src.shard_id) in opened
        # The new shard carries a fencing lease.
        assert new_view.lease_id != 0

    def test_split_explicit_tables_cross_node(self, meta):
        server, calls = meta
        _place_tables(server, 4)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        name = server.topology.tables_of_shard(src.shard_id)[0].name
        other = next(
            n.endpoint for n in server.topology.online_nodes()
            if n.endpoint != src.node
        )
        calls.clear()
        out = server.handle_split(
            src.shard_id, table_names=[name], target_node=other
        )
        assert out["node"] == other
        assert out["tables_moved"] == [name]
        # Cross-node order: source updated BEFORE the target opens (the
        # old owner must release before the new one replays the WAL).
        order = [(ep, pl["shard_id"]) for ep, path, pl in calls
                 if path == "/meta_event/open_shard"]
        assert order.index((src.node, src.shard_id)) < order.index(
            (other, out["new_shard_id"])
        )

    def test_split_unknown_table_fails(self, meta):
        server, _ = meta
        _place_tables(server, 2)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        with pytest.raises(RuntimeError, match="not on shard"):
            server.handle_split(src.shard_id, table_names=["nope"])

    def test_merge_folds_and_retires(self, meta):
        server, calls = meta
        _place_tables(server, 6)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        out = server.handle_split(src.shard_id)
        new_sid = out["new_shard_id"]
        n_before = len(server.topology.shards())
        moved = set(out["tables_moved"])
        calls.clear()
        merged = server.handle_merge(new_sid, src.shard_id)
        assert merged["remaining_shards"] == n_before - 1
        assert server.topology.shard(new_sid) is None
        for name in moved:
            assert server.topology.table(name).shard_id == src.shard_id
        # Victim closed on its owner.
        closes = [pl["shard_id"] for ep, path, pl in calls
                  if path == "/meta_event/close_shard"]
        assert new_sid in closes

    def test_merge_into_self_rejected(self, meta):
        server, _ = meta
        n_procs = len(server.procedures.list())
        with pytest.raises(RuntimeError, match="itself"):
            server.handle_merge(0, 0)
        # Rejected up-front: no procedure submitted, nothing to retry.
        assert len(server.procedures.list()) == n_procs

    def test_remove_shard_refuses_nonempty(self, meta):
        server, _ = meta
        _place_tables(server, 4)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        with pytest.raises(ValueError, match="still holds"):
            server.topology.remove_shard(src.shard_id)

    def test_migrate_to_named_node(self, meta):
        server, _ = meta
        _place_tables(server, 2)
        s = server.topology.shards()[0]
        other = next(
            n.endpoint for n in server.topology.online_nodes()
            if n.endpoint != s.node
        )
        out = server.handle_migrate(s.shard_id, other)
        assert out["node"] == other
        assert server.topology.shard(s.shard_id).node == other
        with pytest.raises(RuntimeError, match="not online"):
            server.handle_migrate(s.shard_id, "127.0.0.1:9999")

    def test_scatter_converges_to_ring_placement(self, meta):
        server, _ = meta
        # Skew everything onto one node, then scatter.
        victim = server.topology.online_nodes()[0].endpoint
        for s in server.topology.shards():
            server.topology.assign_shard(s.shard_id, victim)
        out = server.handle_scatter()
        assert out["moves"] == out["planned"]
        # A second scatter finds nothing to do (ring placement is stable).
        again = server.handle_scatter()
        assert again["planned"] == 0

    def test_admin_split_failure_cancels_background_retry(self, meta, monkeypatch):
        """The admin RPC reported failure — the queued retry must NOT keep
        running in the background (the admin will re-issue; a background
        completion racing that would carve a second shard)."""
        server, calls = meta
        _place_tables(server, 6)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))

        def always_boom(shard_id, node, lease_id=0):
            raise RuntimeError("injected crash mid-split")

        monkeypatch.setattr(server.topology, "assign_shard", always_boom)
        with pytest.raises(RuntimeError, match="injected"):
            server.handle_split(src.shard_id)
        proc = next(p for p in server.procedures.list() if p.kind == "split_shard")
        assert proc.state.value == "cancelled"
        monkeypatch.undo()
        server.procedures.tick()  # must not resurrect it
        assert proc.state.value == "cancelled"

    def test_split_resume_reuses_allocated_shard(self, meta, monkeypatch):
        """Crash-resume path (meta restart with an unfinished procedure in
        the KV journal): the tick-driven re-execution must REUSE the
        already-allocated shard and the already-chosen table set instead
        of allocating/halving again."""
        server, calls = meta
        _place_tables(server, 6)
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        src_tables = {t.name for t in server.topology.tables_of_shard(src.shard_id)}
        n_shards_before = len(server.topology.shards())

        real_assign = server.topology.assign_shard
        boom = {"armed": True}

        def flaky_assign(shard_id, node, lease_id=0):
            if boom["armed"] and shard_id >= n_shards_before:
                boom["armed"] = False
                raise RuntimeError("injected crash mid-split")
            return real_assign(shard_id, node, lease_id=lease_id)

        monkeypatch.setattr(server.topology, "assign_shard", flaky_assign)
        proc = server.procedures.submit("split_shard", {"shard_id": src.shard_id})
        server.procedures.tick()  # attempt 1: crashes after the moves
        assert proc.state.value == "running" and "injected" in proc.error
        assert len(server.topology.shards()) == n_shards_before + 1
        new_sid = proc.params["new_shard_id"]
        chosen = set(proc.params["table_names"])
        # Bounded-backoff retry finishes the job.
        import time as _t

        deadline = _t.monotonic() + 10
        while proc.state.value != "finished" and _t.monotonic() < deadline:
            server.procedures.tick()
            _t.sleep(0.1)
        assert proc.state.value == "finished", proc.error
        # Same shard, same table set — nothing halved twice.
        assert len(server.topology.shards()) == n_shards_before + 1
        assert server.topology.shard(new_sid).node == src.node
        moved = {t.name for t in server.topology.tables_of_shard(new_sid)}
        assert moved == chosen and moved < src_tables


class TestSplitCrashResume:
    def test_hard_crash_mid_split_resumes_with_journaled_choices(
        self, tmp_path, monkeypatch
    ):
        """kill -9 simulation over a REAL (serializing) KV: a new
        MetaServer process resumes the unfinished split and must reuse
        the journaled table set + shard id — not re-halve the remaining
        tables into a second new shard (the bug a by-reference MemoryKV
        hides)."""
        from horaedb_tpu.meta.kv import FileKV

        next_id = iter(range(1, 100))
        monkeypatch.setattr(
            meta_service, "_post",
            lambda ep, path, payload, timeout=5.0: {
                "table_id": next(next_id), "sub_table_ids": [],
            },
        )
        kv_path = str(tmp_path / "meta.kv")
        server = MetaServer(FileKV(kv_path), num_shards=2)
        server.topology.register_node("127.0.0.1:11")
        server.tick()
        for i in range(4):
            server.handle_create_table(f"t{i}", f"CREATE TABLE t{i} (...)")
        src = max(server.topology.shards(), key=lambda s: len(s.table_ids))
        src_tables = {t.name for t in server.topology.tables_of_shard(src.shard_id)}

        # Crash AFTER the moves, before any further persist: the handler
        # raises SystemExit-like error right at assign time, and we then
        # abandon this server instance entirely (no cancel, no retry).
        def crash(shard_id, node, lease_id=0):
            raise RuntimeError("kill -9")

        real_assign = server.topology.assign_shard
        monkeypatch.setattr(server.topology, "assign_shard", crash)
        proc = server.procedures.submit("split_shard", {"shard_id": src.shard_id})
        server.procedures.tick()
        assert proc.state.value == "running"
        chosen = set(proc.params["table_names"])
        new_sid = proc.params["new_shard_id"]
        monkeypatch.setattr(server.topology, "assign_shard", real_assign)
        server.kv.close()

        # "Restart": fresh server over the same journal resumes the
        # procedure on its first ticks.
        server2 = MetaServer(FileKV(kv_path), num_shards=2)
        server2.topology.register_node("127.0.0.1:11")
        import time as _t

        deadline = _t.monotonic() + 10
        while _t.monotonic() < deadline:
            server2.tick()
            p2 = next(
                p for p in server2.procedures.list() if p.kind == "split_shard"
            )
            if p2.state.value == "finished":
                break
            _t.sleep(0.1)
        assert p2.state.value == "finished", p2.error
        # Journaled choices reused: same shard id, same table set, and no
        # third shard ever allocated.
        assert p2.params["new_shard_id"] == new_sid
        moved = {t.name for t in server2.topology.tables_of_shard(new_sid)}
        assert moved == chosen
        remaining = {
            t.name for t in server2.topology.tables_of_shard(src.shard_id)
        }
        assert remaining == src_tables - chosen and remaining
        assert len(server2.topology.shards()) == 3  # 2 initial + 1 split
        server2.kv.close()


class TestShardOpsE2E:
    def test_split_migrate_merge_lifecycle(self, cluster):
        meta_port, node_ports, procs, spawn_node = cluster
        shards = wait_until(
            lambda: shards_all_assigned(meta_port), desc="shards assigned"
        )
        # Enough tables that some shard holds >= 2.
        names = [f"sp{i}" for i in range(6)]
        for n in names:
            s, body = http(
                "POST", f"http://127.0.0.1:{meta_port}/meta/v1/table/create",
                {"name": n, "create_sql": DDL.format(name=n)},
            )
            assert s == 200, body
        for i, n in enumerate(names):
            s, body = sql(
                node_ports[0],
                f"INSERT INTO {n} (host, v, ts) VALUES "
                + ", ".join(f"('h{j}', {j}.5, {1000 + j})" for j in range(20)),
            )
            assert s == 200, (n, body)

        def counts():
            out = {}
            for n in names:
                s, body = sql(node_ports[1], f"SELECT count(1) AS c FROM {n}")
                assert s == 200, (n, body)
                out[n] = body["rows"][0]["c"]
            return out

        before = counts()
        assert all(v == 20 for v in before.values())

        # Pick the shard with the most tables; split half off CROSS-NODE.
        _, body = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/shards")
        shard_tables: dict[int, int] = {}
        for n in names:
            s, r = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/{n}")
            assert s == 200
            shard_tables[r["shard_id"]] = shard_tables.get(r["shard_id"], 0) + 1
        src_sid = max(shard_tables, key=shard_tables.get)
        assert shard_tables[src_sid] >= 2
        s, r = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/{names[0]}")
        # Target: whichever node does NOT own the source shard.
        _, sh = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/shards")
        src_node = next(
            x["node"] for x in sh["shards"] if x["shard_id"] == src_sid
        )
        target = next(
            f"127.0.0.1:{p}" for p in node_ports
            if f"127.0.0.1:{p}" != src_node
        )
        s, split_out = http(
            "POST", f"http://127.0.0.1:{meta_port}/meta/v1/shard/split",
            {"shard_id": src_sid, "target_node": target}, timeout=30,
        )
        assert s == 200, split_out
        new_sid = split_out["new_shard_id"]
        moved = split_out["tables_moved"]
        assert moved

        # Routing follows the split; data survives the cross-node move.
        for n in moved:
            s, r = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/{n}")
            assert s == 200 and r["shard_id"] == new_sid and r["node"] == target

        def all_counts_ok():
            return all(v == 20 for v in counts().values())

        wait_until(all_counts_ok, timeout=60, desc="post-split data integrity")

        # Migrate the new shard back onto the source node.
        s, mig = http(
            "POST", f"http://127.0.0.1:{meta_port}/meta/v1/shard/migrate",
            {"shard_id": new_sid, "to_node": src_node}, timeout=30,
        )
        assert s == 200, mig
        wait_until(all_counts_ok, timeout=60, desc="post-migrate data integrity")

        # Merge it back; shard retires, tables fold into the source shard.
        s, mg = http(
            "POST", f"http://127.0.0.1:{meta_port}/meta/v1/shard/merge",
            {"shard_id": new_sid, "into_shard_id": src_sid}, timeout=30,
        )
        assert s == 200, mg
        _, sh = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/shards")
        assert new_sid not in {x["shard_id"] for x in sh["shards"]}
        for n in moved:
            s, r = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/{n}")
            assert s == 200 and r["shard_id"] == src_sid
        wait_until(all_counts_ok, timeout=60, desc="post-merge data integrity")
