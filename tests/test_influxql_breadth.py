"""InfluxQL planner breadth: regex matchers, OR/parens, now()/RFC3339
time bounds, selector + statistic functions, fill(previous|linear),
derivative-family transforms, SLIMIT/SOFFSET, SHOW DATABASES/RETENTION
POLICIES, multi-statement queries
(ref: src/query_frontend/src/influxql/planner.rs — the forked-IOx
planner surface real v1 clients exercise)."""

from __future__ import annotations

import time

import pytest

import horaedb_tpu
from horaedb_tpu.proxy.influxql import InfluxQLError, evaluate


@pytest.fixture()
def conn():
    c = horaedb_tpu.connect(None)
    c.execute(
        "CREATE TABLE h2o (level string TAG, location string TAG, "
        "water_level double, time timestamp NOT NULL, "
        "TIMESTAMP KEY(time)) ENGINE=Analytic"
    )
    c.execute(
        "INSERT INTO h2o (level, location, water_level, time) VALUES "
        "('mid', 'coyote_creek', 8.0, 0), "
        "('mid', 'coyote_creek', 6.0, 60000), "
        "('mid', 'coyote_creek', 10.0, 120000), "
        "('mid', 'coyote_creek', 4.0, 180000), "
        "('low', 'santa_monica', 2.0, 0), "
        "('low', 'santa_monica', 3.0, 60000), "
        "('low', 'santa_monica', 7.0, 240000)"
    )
    yield c
    c.close()


def one_series(out, i=0):
    return out["results"][0]["series"][i]


class TestWhereBreadth:
    def test_or_and_parens(self, conn):
        out = evaluate(
            conn,
            "SELECT water_level FROM h2o WHERE "
            "(location = 'santa_monica' OR location = 'coyote_creek') "
            "AND time < 60000ms",
        )
        assert len(one_series(out)["values"]) == 2

    def test_regex_match_on_tag(self, conn):
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE location =~ /creek$/"
        )
        assert one_series(out)["values"][0][1] == 4

    def test_regex_negative_match(self, conn):
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE location !~ /creek$/"
        )
        assert one_series(out)["values"][0][1] == 3

    def test_regex_with_or_time_branches_keeps_all_rows(self, conn):
        """The DISTINCT probe must use only GUARANTEED time bounds —
        AND-joining bounds from OR branches yields an empty probe window
        and silently drops valid rows."""
        out = evaluate(
            conn,
            "SELECT water_level FROM h2o WHERE location =~ /creek/ "
            "AND (time < 70000ms OR time > 110000ms)",
        )
        vals = [v[1] for v in one_series(out)["values"]]
        # all four creek rows satisfy one branch or the other
        assert sorted(vals) == [4.0, 6.0, 8.0, 10.0]

    def test_regex_matching_nothing_is_empty_not_everything(self, conn):
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE location =~ /xyzzy/"
        )
        assert "series" not in out["results"][0]

    def test_now_arithmetic(self, conn):
        # everything is decades before now(): now() - 1h excludes all
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE time > now() - 1h"
        )
        assert "series" not in out["results"][0]
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE time < now()"
        )
        assert one_series(out)["values"][0][1] == 7

    def test_rfc3339_literal(self, conn):
        out = evaluate(
            conn,
            "SELECT count(water_level) FROM h2o "
            "WHERE time < '1970-01-01T00:02:00Z'",
        )
        assert one_series(out)["values"][0][1] == 4  # ts 0 and 60000 per loc


class TestHostFunctions:
    def test_first_last(self, conn):
        out = evaluate(
            conn,
            "SELECT first(water_level), last(water_level) FROM h2o "
            "GROUP BY location",
        )
        by = {s["tags"]["location"]: s["values"][0] for s in
              out["results"][0]["series"]}
        assert by["coyote_creek"][1:] == [8.0, 4.0]
        assert by["santa_monica"][1:] == [2.0, 7.0]

    def test_median_spread_stddev(self, conn):
        out = evaluate(
            conn,
            "SELECT median(water_level), spread(water_level), "
            "stddev(water_level) FROM h2o WHERE location = 'coyote_creek'",
        )
        t, median, spread, stddev = one_series(out)["values"][0]
        assert median == 7.0
        assert spread == 6.0
        assert round(stddev, 4) == round(2.581988897, 4)

    def test_percentile_nearest_rank(self, conn):
        out = evaluate(
            conn,
            "SELECT percentile(water_level, 50) FROM h2o "
            "WHERE location = 'coyote_creek'",
        )
        # sorted [4,6,8,10]; ceil(0.5*4)=2 -> 6.0
        assert one_series(out)["values"][0][1] == 6.0

    def test_distinct(self, conn):
        out = evaluate(conn, "SELECT distinct(level) FROM h2o")
        vals = [v[1] for v in one_series(out)["values"]]
        assert vals == ["low", "mid"]

    def test_distinct_rejects_combination(self, conn):
        with pytest.raises(InfluxQLError, match="distinct"):
            evaluate(conn, "SELECT distinct(level), count(level) FROM h2o")

    def test_host_funcs_with_time_buckets(self, conn):
        out = evaluate(
            conn,
            "SELECT last(water_level) FROM h2o WHERE location = 'coyote_creek' "
            "GROUP BY time(2m)",
        )
        vals = one_series(out)["values"]
        assert vals == [[0, 6.0], [120000, 4.0]]


class TestTransforms:
    def test_derivative_per_second(self, conn):
        out = evaluate(
            conn,
            "SELECT derivative(mean(water_level), 1m) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)",
        )
        vals = one_series(out)["values"]
        # means per 1m bucket: 8, 6, 10, 4 -> derivatives -2, +4, -6
        assert [v[1] for v in vals] == [-2.0, 4.0, -6.0]

    def test_non_negative_derivative_drops_negatives(self, conn):
        out = evaluate(
            conn,
            "SELECT non_negative_derivative(mean(water_level), 1m) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)",
        )
        vals = one_series(out)["values"]
        assert [v[1] for v in vals] == [None, 4.0, None]

    def test_difference(self, conn):
        out = evaluate(
            conn,
            "SELECT difference(max(water_level)) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)",
        )
        assert [v[1] for v in one_series(out)["values"]] == [-2.0, 4.0, -6.0]

    def test_moving_average(self, conn):
        out = evaluate(
            conn,
            "SELECT moving_average(mean(water_level), 2) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)",
        )
        assert [v[1] for v in one_series(out)["values"]] == [7.0, 8.0, 7.0]


class TestFillModes:
    def test_fill_previous(self, conn):
        out = evaluate(
            conn,
            "SELECT mean(water_level) FROM h2o WHERE location = 'santa_monica' "
            "GROUP BY time(1m) FILL(previous)",
        )
        vals = one_series(out)["values"]
        # buckets 0,1m have data; 2m,3m filled w/ previous; 4m has data
        assert [v[1] for v in vals] == [2.0, 3.0, 3.0, 3.0, 7.0]

    def test_fill_linear(self, conn):
        out = evaluate(
            conn,
            "SELECT mean(water_level) FROM h2o WHERE location = 'santa_monica' "
            "GROUP BY time(1m) FILL(linear)",
        )
        vals = one_series(out)["values"]
        assert [v[1] for v in vals] == [2.0, 3.0, pytest.approx(4.3333, rel=1e-3),
                                        pytest.approx(5.6667, rel=1e-3), 7.0]


class TestSeriesLimits:
    def test_slimit_soffset(self, conn):
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o GROUP BY location SLIMIT 1"
        )
        series = out["results"][0]["series"]
        assert len(series) == 1 and series[0]["tags"]["location"] == "coyote_creek"
        out = evaluate(
            conn,
            "SELECT count(water_level) FROM h2o GROUP BY location "
            "SLIMIT 1 SOFFSET 1",
        )
        series = out["results"][0]["series"]
        assert len(series) == 1 and series[0]["tags"]["location"] == "santa_monica"

    def test_aggregate_limit_offset_per_series(self, conn):
        out = evaluate(
            conn,
            "SELECT mean(water_level) FROM h2o WHERE location = 'coyote_creek' "
            "GROUP BY time(1m) LIMIT 2 OFFSET 1",
        )
        assert [v[0] for v in one_series(out)["values"]] == [60000, 120000]

    def test_group_by_star(self, conn):
        out = evaluate(conn, "SELECT count(water_level) FROM h2o GROUP BY *")
        series = out["results"][0]["series"]
        assert all({"level", "location"} <= set(s["tags"]) for s in series)


class TestShowAndMeta:
    def test_show_databases(self, conn):
        out = evaluate(conn, "SHOW DATABASES")
        assert one_series(out)["values"] == [["public"]]

    def test_show_retention_policies(self, conn):
        out = evaluate(conn, "SHOW RETENTION POLICIES")
        s = one_series(out)
        assert s["columns"][0] == "name" and s["values"][0][0] == "autogen"
        assert s["values"][0][-1] is True

    def test_multi_statement(self, conn):
        out = evaluate(conn, "SHOW DATABASES; SHOW MEASUREMENTS")
        assert len(out["results"]) == 2
        assert out["results"][0]["statement_id"] == 0
        assert out["results"][1]["statement_id"] == 1
        assert out["results"][1]["series"][0]["values"] == [["h2o"]]

    def test_subquery_max_of_means(self, conn):
        """The canonical influx subquery: max over bucketed means."""
        out = evaluate(
            conn,
            "SELECT max(mean) FROM (SELECT mean(water_level) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m))",
        )
        # bucket means: 8, 6, 10, 4 -> max 10
        assert one_series(out)["values"][0][1] == 10.0

    def test_subquery_outer_group_by_tag(self, conn):
        out = evaluate(
            conn,
            "SELECT max(mean) FROM (SELECT mean(water_level) FROM h2o "
            "GROUP BY location, time(1m)) GROUP BY location",
        )
        by = {s["tags"]["location"]: s["values"][0][1]
              for s in out["results"][0]["series"]}
        assert by == {"coyote_creek": 10.0, "santa_monica": 7.0}

    def test_subquery_outer_where_on_inner_column(self, conn):
        out = evaluate(
            conn,
            "SELECT count(mean) FROM (SELECT mean(water_level) FROM h2o "
            "GROUP BY location, time(1m)) WHERE mean > 5",
        )
        # creek means 8,6,10 qualify (not 4); monica 7 qualifies (not 2,3)
        assert one_series(out)["values"][0][1] == 4

    def test_subquery_raw_passthrough(self, conn):
        out = evaluate(
            conn,
            "SELECT mean FROM (SELECT mean(water_level) FROM h2o "
            "WHERE location = 'santa_monica' GROUP BY time(1m)) LIMIT 2",
        )
        assert [v[1] for v in one_series(out)["values"]] == [2.0, 3.0]

    def test_subquery_raw_with_outer_group_by_keeps_tags(self, conn):
        out = evaluate(
            conn,
            "SELECT mean FROM (SELECT mean(water_level) FROM h2o "
            "GROUP BY location, time(1m)) GROUP BY location",
        )
        series = out["results"][0]["series"]
        tags = {s["tags"]["location"] for s in series}
        assert tags == {"coyote_creek", "santa_monica"}
        creek = next(s for s in series if s["tags"]["location"] == "coyote_creek")
        assert [v[1] for v in creek["values"]] == [8.0, 6.0, 10.0, 4.0]

    def test_subquery_outer_time_bound_pushed_down(self, conn):
        out = evaluate(
            conn,
            "SELECT count(mean) FROM (SELECT mean(water_level) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)) "
            "WHERE time < 120000ms",
        )
        assert one_series(out)["values"][0][1] == 2  # buckets 0 and 1m only

    def test_subquery_select_star_expands_value_columns(self, conn):
        out = evaluate(
            conn,
            "SELECT * FROM (SELECT mean(water_level) FROM h2o "
            "WHERE location = 'santa_monica' GROUP BY time(1m))",
        )
        s = one_series(out)
        assert s["columns"] == ["time", "mean"]
        assert [v[1] for v in s["values"]] == [2.0, 3.0, 7.0]

    def test_subquery_time_bound_keeps_partial_first_bucket(self, conn):
        """The pushed outer bound applies to inner DATA; the epoch-
        aligned bucket label (< the bound) must not be re-filtered."""
        out = evaluate(
            conn,
            "SELECT count(mean) FROM (SELECT mean(water_level) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m)) "
            "WHERE time >= 30000ms",
        )
        # rows at 60000, 120000, 180000 remain -> 3 buckets (the 0-bucket
        # row at ts 0 is excluded by the data bound, not by its label)
        assert one_series(out)["values"][0][1] == 3

    def test_subquery_mixed_projection_rejected(self, conn):
        with pytest.raises(InfluxQLError, match="all aggregates or all raw"):
            evaluate(
                conn,
                "SELECT mean, max(mean) FROM (SELECT mean(water_level) "
                "FROM h2o GROUP BY time(1m))",
            )

    def test_subquery_selector_over_inner(self, conn):
        out = evaluate(
            conn,
            "SELECT percentile(mean, 50), spread(mean) FROM "
            "(SELECT mean(water_level) FROM h2o "
            "WHERE location = 'coyote_creek' GROUP BY time(1m))",
        )
        t, p50, spread = one_series(out)["values"][0]
        # means [4,6,8,10]: nearest-rank p50 = 6, spread = 6
        assert (p50, spread) == (6.0, 6.0)


class TestReviewRegressions:
    def test_unspaced_now_arithmetic(self, conn):
        """'now()-1h' (no spaces — the form v1 clients actually emit)
        fuses '-1h' into one token; the parser must split it."""
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE time > now()-1h"
        )
        assert "series" not in out["results"][0]
        out = evaluate(
            conn, "SELECT count(water_level) FROM h2o WHERE time < now()+1h"
        )
        assert one_series(out)["values"][0][1] == 7

    def test_transform_over_distinct_rejected(self, conn):
        with pytest.raises(InfluxQLError, match="scalar aggregate"):
            evaluate(
                conn,
                "SELECT derivative(distinct(water_level), 1s) FROM h2o "
                "GROUP BY time(1m)",
            )

    def test_distinct_with_fill_keeps_all_rows(self, conn):
        out = evaluate(
            conn,
            "SELECT distinct(level) FROM h2o GROUP BY time(5m) FILL(0)",
        )
        vals = one_series(out)["values"]
        assert sorted(v[1] for v in vals) == ["low", "mid"]

    def test_raw_offset_without_limit_unsupported_not_silent(self, conn):
        # raw OFFSET slices host-side even without LIMIT
        out_all = evaluate(conn, "SELECT water_level FROM h2o")
        out_off = evaluate(conn, "SELECT water_level FROM h2o OFFSET 2")
        assert (len(one_series(out_off)["values"])
                == len(one_series(out_all)["values"]) - 2)

    def test_raw_limit_offset(self, conn):
        out = evaluate(conn, "SELECT water_level FROM h2o LIMIT 2 OFFSET 1")
        all_vals = one_series(evaluate(conn, "SELECT water_level FROM h2o"))["values"]
        assert one_series(out)["values"] == all_vals[1:3]

    def test_raw_soffset_drops_only_series(self, conn):
        out = evaluate(conn, "SELECT water_level FROM h2o SOFFSET 1")
        assert "series" not in out["results"][0]

    def test_duplicate_agg_functions_get_distinct_columns(self, conn):
        conn.execute(
            "CREATE TABLE m2 (g string TAG, a double, b double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        conn.execute(
            "INSERT INTO m2 (g, a, b, ts) VALUES "
            "('x', 1.0, 100.0, 0), ('x', 3.0, 300.0, 1000)"
        )
        out = evaluate(conn, "SELECT mean(a), mean(b) FROM m2")
        s = one_series(out)
        assert s["columns"] == ["time", "mean", "mean_1"]
        assert s["values"][0][1:] == [2.0, 200.0]
        # host path too
        out = evaluate(conn, "SELECT last(a), last(b) FROM m2")
        s = one_series(out)
        assert s["columns"] == ["time", "last", "last_1"]
        assert s["values"][0][1:] == [3.0, 300.0]

    def test_count_star_on_host_path(self, conn):
        out = evaluate(conn, "SELECT count(*), last(water_level) FROM h2o")
        s = one_series(out)
        assert s["values"][0][1] == 7  # row count, not null

    def test_selector_star_rejected(self, conn):
        with pytest.raises(InfluxQLError, match="name a field"):
            evaluate(conn, "SELECT first(*) FROM h2o")


class TestSelectorWithFields:
    """InfluxDB 1.x selector semantics: SELECT max(usage), host returns
    the SELECTED ROW's companion values; aggregators like mean() stay an
    error in that mix (ref: the forked-IOx planner's selector handling,
    query_frontend/src/influxql/planner.rs)."""

    def _db(self):
        import horaedb_tpu

        db = horaedb_tpu.connect(None)
        db.execute(
            "CREATE TABLE cpu (host string TAG, usage double, idle double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute(
            "INSERT INTO cpu (host, usage, idle, ts) VALUES "
            "('a',1.0,9.0,1000),('b',5.0,7.0,2000),('a',3.0,8.0,61000)"
        )
        return db

    def test_selector_attaches_row_values(self):
        from horaedb_tpu.proxy.influxql import evaluate

        db = self._db()
        s = evaluate(db, 'SELECT max(usage), host FROM "cpu"')["results"][0]["series"][0]
        assert s["columns"] == ["time", "max", "host"]
        assert s["values"] == [[2000, 5.0, "b"]]
        s = evaluate(db, 'SELECT first(usage), host, idle FROM "cpu"')["results"][0]["series"][0]
        assert s["values"] == [[1000, 1.0, "a", 9.0]]
        s = evaluate(db, 'SELECT last(usage), host FROM "cpu"')["results"][0]["series"][0]
        assert s["values"] == [[61000, 3.0, "a"]]

    def test_selector_with_time_buckets_and_group_by(self):
        from horaedb_tpu.proxy.influxql import evaluate

        db = self._db()
        s = evaluate(db, 'SELECT max(usage), idle FROM "cpu" GROUP BY time(1m)')
        vals = s["results"][0]["series"][0]["values"]
        assert vals == [[0, 5.0, 7.0], [60000, 3.0, 8.0]]
        out = evaluate(db, 'SELECT min(usage), idle FROM "cpu" GROUP BY host')
        series = out["results"][0]["series"]
        assert {tuple(s["tags"].items()) for s in series} == {
            (("host", "a"),), (("host", "b"),)
        }

    def test_aggregator_mix_still_rejected(self):
        import pytest

        from horaedb_tpu.proxy.influxql import InfluxQLError, evaluate

        db = self._db()
        with pytest.raises(InfluxQLError, match="mixing"):
            evaluate(db, 'SELECT mean(usage), host FROM "cpu"')

    def test_fill_spares_companion_columns(self):
        from horaedb_tpu.proxy.influxql import evaluate

        db = self._db()
        # gap bucket at minute 1 (rows at 1s/2s and 61s)
        out = evaluate(
            db, 'SELECT max(usage), host FROM "cpu" '
                'WHERE time < 2h GROUP BY time(2m) fill(0)'
        )
        for row in out["results"][0]["series"][0]["values"]:
            # numeric fill never lands in the string companion column
            assert row[2] is None or isinstance(row[2], str), row

    def test_unknown_companion_column_errors(self):
        import pytest

        from horaedb_tpu.proxy.influxql import InfluxQLError, evaluate

        db = self._db()
        with pytest.raises(InfluxQLError, match="unknown column"):
            evaluate(db, 'SELECT max(usage), nosuch FROM "cpu"')


class TestTopBottom:
    """top/bottom(field, N): InfluxDB's shape-changing selectors — the N
    largest/smallest samples per (tag-set, bucket), each stamped with its
    own sample time."""

    def _db(self):
        import horaedb_tpu

        db = horaedb_tpu.connect(None)
        db.execute(
            "CREATE TABLE cpu (host string TAG, usage double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute(
            "INSERT INTO cpu (host, usage, ts) VALUES ('a',1.0,1000),"
            "('b',5.0,2000),('a',3.0,3000),('b',2.0,4000),('a',9.0,61000)"
        )
        return db

    def test_top_bottom_basic(self):
        from horaedb_tpu.proxy.influxql import evaluate

        db = self._db()
        s = evaluate(db, 'SELECT top(usage, 3) FROM "cpu"')["results"][0]["series"][0]
        assert s["columns"] == ["time", "top"]
        assert s["values"] == [[2000, 5.0], [3000, 3.0], [61000, 9.0]]
        s = evaluate(db, 'SELECT bottom(usage, 2) FROM "cpu"')["results"][0]["series"][0]
        assert s["values"] == [[1000, 1.0], [4000, 2.0]]

    def test_top_grouped_and_bucketed(self):
        from horaedb_tpu.proxy.influxql import evaluate

        db = self._db()
        out = evaluate(db, 'SELECT top(usage, 2) FROM "cpu" GROUP BY host')
        by_tag = {s["tags"]["host"]: s["values"] for s in out["results"][0]["series"]}
        assert by_tag["a"] == [[3000, 3.0], [61000, 9.0]]
        assert by_tag["b"] == [[2000, 5.0], [4000, 2.0]]
        s = evaluate(db, 'SELECT top(usage, 1) FROM "cpu" GROUP BY time(1m)')
        assert s["results"][0]["series"][0]["values"] == [[2000, 5.0], [61000, 9.0]]

    def test_top_rejects_combinations(self):
        import pytest

        from horaedb_tpu.proxy.influxql import InfluxQLError, evaluate

        db = self._db()
        with pytest.raises(InfluxQLError, match="cannot combine"):
            evaluate(db, 'SELECT top(usage, 2), host FROM "cpu"')

    def test_top_fill_and_argument_validation(self):
        import pytest

        from horaedb_tpu.proxy.influxql import InfluxQLError, evaluate

        db = self._db()
        # fill() must not drop shape-changing rows off the bucket lattice
        s = evaluate(
            db, 'SELECT top(usage, 1) FROM "cpu" GROUP BY time(1m) fill(0)'
        )["results"][0]["series"][0]
        assert s["values"] == [[2000, 5.0], [61000, 9.0]]
        with pytest.raises(InfluxQLError, match="numeric"):
            evaluate(db, 'SELECT top(host, 1) FROM "cpu"')
        for bad in ('SELECT top(usage, 2.5) FROM "cpu"',
                    "SELECT top(usage, 'x') FROM \"cpu\"",
                    'SELECT top(usage, 2m) FROM "cpu"'):
            with pytest.raises(InfluxQLError, match="integer"):
                evaluate(db, bad)
