"""TPU ops tests — run on the virtual CPU mesh; numerical ground truth is
plain numpy (the same data the CPU fallback executor would compute)."""

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.ops import (
    ScanAggSpec,
    encode_group_codes,
    merge_dedup_permutation,
    pad_to_bucket,
    scan_aggregate,
    shape_bucket,
)
from horaedb_tpu.ops.encoding import (
    build_padded_batch,
    split_i64_sortable,
    split_u64,
    time_buckets,
)


class TestShapeBuckets:
    def test_bucket_rounding(self):
        assert shape_bucket(1) == 4096
        assert shape_bucket(4096) == 4096
        assert shape_bucket(4097) == 8192
        assert shape_bucket(100_000) == 131072

    def test_pad(self):
        a = np.arange(10, dtype=np.int32)
        p = pad_to_bucket(a, 10, fill=-1)
        assert len(p) == 4096 and p[9] == 9 and p[10] == -1


class TestSplit64:
    def test_u64_round_order(self):
        xs = np.array([0, 1, 2**32 - 1, 2**32, 2**63, 2**64 - 1], dtype=np.uint64)
        hi, lo = split_u64(xs)
        pairs = list(zip(hi.tolist(), lo.tolist()))
        assert pairs == sorted(pairs)

    def test_i64_order_preserved(self):
        xs = np.array([-(2**62), -1, 0, 1, 2**62], dtype=np.int64)
        hi, lo = split_i64_sortable(xs)
        pairs = list(zip(hi.tolist(), lo.tolist()))
        assert pairs == sorted(pairs)


class TestGroupEncoding:
    def schema(self):
        return Schema.build(
            [
                ColumnSchema("host", DatumKind.STRING, is_tag=True),
                ColumnSchema("region", DatumKind.STRING, is_tag=True),
                ColumnSchema("v", DatumKind.DOUBLE),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
        )

    def rows(self, n=100):
        return RowGroup.from_rows(
            self.schema(),
            [
                {
                    "host": f"h{i % 5}",
                    "region": "east" if i % 2 else "west",
                    "v": float(i),
                    "t": i,
                }
                for i in range(n)
            ],
        )

    def test_single_tag_group(self):
        rows = self.rows()
        enc = encode_group_codes(rows, ["host"])
        assert enc.num_groups == 5
        # code consistency: same host -> same code
        hosts = rows.column("host")
        for c in range(5):
            vals = set(hosts[enc.codes == c])
            assert len(vals) == 1
        assert sorted(enc.key_values[0].tolist()) == [f"h{i}" for i in range(5)]

    def test_composite_tag_group(self):
        enc = encode_group_codes(self.rows(), ["host", "region"])
        assert enc.num_groups == 10
        assert len(enc.key_values) == 2

    def test_empty_group_by(self):
        enc = encode_group_codes(self.rows(), [])
        assert enc.num_groups == 1 and (enc.codes == 0).all()

    def test_time_buckets(self):
        ts = np.array([0, 999, 1000, 5500], dtype=np.int64)
        b, n = time_buckets(ts, 0, 1000)
        assert b.tolist() == [0, 0, 1, 5] and n == 6


def numpy_reference_agg(codes, buckets, mask, values, n_groups, n_buckets):
    """Ground truth with f64 numpy."""
    counts = np.zeros((n_groups, n_buckets), dtype=np.int64)
    sums = np.zeros((len(values), n_groups, n_buckets))
    mins = np.full((len(values), n_groups, n_buckets), np.inf)
    maxs = np.full((len(values), n_groups, n_buckets), -np.inf)
    for i in range(len(codes)):
        if not mask[i]:
            continue
        g, b = codes[i], buckets[i]
        counts[g, b] += 1
        for f in range(len(values)):
            v = values[f][i]
            sums[f, g, b] += v
            mins[f, g, b] = min(mins[f, g, b], v)
            maxs[f, g, b] = max(maxs[f, g, b], v)
    return counts, sums, mins, maxs


class TestScanAggregate:
    def test_matches_numpy_reference(self):
        rng = np.random.default_rng(0)
        n, g, b = 5000, 7, 3
        codes = rng.integers(0, g, n).astype(np.int32)
        buckets = rng.integers(0, b, n).astype(np.int32)
        mask = rng.random(n) > 0.2
        vals = [rng.normal(size=n).astype(np.float32)]

        batch = build_padded_batch(codes, buckets, mask, vals)
        spec = ScanAggSpec(n_groups=g, n_buckets=b, n_agg_fields=1).padded()
        out = scan_aggregate(batch, spec)

        rc, rs, rmin, rmax = numpy_reference_agg(
            codes, buckets, mask, [v.astype(np.float64) for v in vals], g, b
        )
        assert (out.counts[:g, :b] == rc).all()
        np.testing.assert_allclose(out.sums[:, :g, :b], rs, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(out.mins[:, :g, :b], rmin)
        np.testing.assert_allclose(out.maxs[:, :g, :b], rmax)

    def test_device_numeric_filter(self):
        n = 4096
        codes = np.zeros(n, dtype=np.int32)
        buckets = np.zeros(n, dtype=np.int32)
        mask = np.ones(n, dtype=bool)
        vals = [np.arange(n, dtype=np.float32)]
        batch = build_padded_batch(codes, buckets, mask, vals)
        spec = ScanAggSpec(
            n_groups=1, n_buckets=1, n_agg_fields=1,
            numeric_filters=((0, ">"),),
        ).padded()
        out = scan_aggregate(batch, spec, filter_literals=[4000.0])
        assert out.counts[0, 0] == n - 4001
        assert out.mins[0, 0, 0] == 4001.0

    def test_literal_change_no_recompile(self):
        import jax

        n = 4096
        batch = build_padded_batch(
            np.zeros(n, dtype=np.int32),
            np.zeros(n, dtype=np.int32),
            np.ones(n, dtype=bool),
            [np.arange(n, dtype=np.float32)],
        )
        spec = ScanAggSpec(
            n_groups=1, n_buckets=1, n_agg_fields=1, numeric_filters=((0, "<"),)
        ).padded()
        scan_aggregate(batch, spec, [10.0])
        from horaedb_tpu.ops.scan_agg import _fused_scan_agg

        misses_before = _fused_scan_agg._cache_size()
        out = scan_aggregate(batch, spec, [100.0])
        assert _fused_scan_agg._cache_size() == misses_before
        assert out.counts[0, 0] == 100

    def test_partial_combine_associative(self):
        rng = np.random.default_rng(1)
        n, g, b = 4096, 4, 2
        spec = ScanAggSpec(n_groups=g, n_buckets=b, n_agg_fields=1).padded()

        def batch():
            return build_padded_batch(
                rng.integers(0, g, n).astype(np.int32),
                rng.integers(0, b, n).astype(np.int32),
                np.ones(n, dtype=bool),
                [rng.normal(size=n).astype(np.float32)],
            )

        b1, b2 = batch(), batch()
        s1, s2 = scan_aggregate(b1, spec), scan_aggregate(b2, spec)
        combined = s1.combine(s2)

        both = build_padded_batch(
            np.concatenate([b1.group_codes[:n], b2.group_codes[:n]]),
            np.concatenate([b1.bucket_ids[:n], b2.bucket_ids[:n]]),
            np.ones(2 * n, dtype=bool),
            [np.concatenate([b1.values[0][:n], b2.values[0][:n]])],
        )
        s_both = scan_aggregate(both, spec)
        assert (combined.counts == s_both.counts).all()
        np.testing.assert_allclose(combined.sums, s_both.sums, rtol=1e-4, atol=1e-4)

    def test_no_agg_fields_count_only(self):
        n = 4096
        batch = build_padded_batch(
            np.zeros(n, dtype=np.int32), np.zeros(n, dtype=np.int32),
            np.ones(n, dtype=bool), [],
        )
        spec = ScanAggSpec(n_groups=1, n_buckets=1, n_agg_fields=0).padded()
        out = scan_aggregate(batch, spec)
        assert out.counts[0, 0] == n and out.sums.shape[0] == 0


class TestMergeDedup:
    def test_merges_sorted_runs(self):
        # Two sorted runs with overlapping keys; newest seq must win.
        tsid = np.array([1, 1, 2, 1, 2, 3], dtype=np.uint64)
        ts = np.array([10, 20, 10, 10, 10, 5], dtype=np.int64)
        seq = np.array([1, 1, 1, 2, 2, 2], dtype=np.uint64)
        perm, keep = merge_dedup_permutation(tsid, ts, seq)
        merged_idx = perm[keep]
        out = list(zip(tsid[merged_idx].tolist(), ts[merged_idx].tolist(), seq[merged_idx].tolist()))
        # keys (1,10) and (2,10) dedup to seq=2 versions
        assert out == [(1, 10, 2), (1, 20, 1), (2, 10, 2), (3, 5, 2)]

    def test_no_dedup_keeps_all(self):
        tsid = np.array([1, 1], dtype=np.uint64)
        ts = np.array([10, 10], dtype=np.int64)
        seq = np.array([1, 2], dtype=np.uint64)
        perm, keep = merge_dedup_permutation(tsid, ts, seq, dedup=False)
        assert keep.sum() == 2
        # newest still sorts first
        assert seq[perm[0]] == 2

    def test_matches_numpy_lexsort(self):
        rng = np.random.default_rng(7)
        n = 10_000
        tsid = rng.integers(0, 50, n).astype(np.uint64)
        ts = rng.integers(-1000, 1000, n).astype(np.int64)
        seq = rng.permutation(n).astype(np.uint64)
        perm, keep = merge_dedup_permutation(tsid, ts, seq)

        order = np.lexsort((-(seq.astype(np.int64)), ts, tsid.astype(np.int64)))
        key = np.stack([tsid[order].astype(np.int64), ts[order]])
        first = np.ones(n, dtype=bool)
        first[1:] = (key[:, 1:] != key[:, :-1]).any(axis=0)
        expected = order[first]
        np.testing.assert_array_equal(perm[keep], expected)

    def test_empty(self):
        perm, keep = merge_dedup_permutation(
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.uint64),
        )
        assert len(perm) == 0 and len(keep) == 0

    def test_extreme_values(self):
        tsid = np.array([0, 2**64 - 1, 2**63], dtype=np.uint64)
        ts = np.array([-(2**62), 2**62, 0], dtype=np.int64)
        seq = np.array([1, 2, 3], dtype=np.uint64)
        perm, keep = merge_dedup_permutation(tsid, ts, seq)
        assert keep.sum() == 3
        assert tsid[perm].tolist() == [0, 2**63, 2**64 - 1]


class TestMergeDedupReady:
    def test_background_compile_gate(self):
        """merge_dedup_ready returns False while compiling, True after;
        only one compile thread per shape bucket."""
        import time

        from horaedb_tpu.ops import merge_dedup as md

        n = 1024
        bucket = __import__("horaedb_tpu.ops.encoding", fromlist=["shape_bucket"]).shape_bucket(n)
        with md._compile_lock:
            md._ready.discard((bucket, True))
        ready = md.merge_dedup_ready(n)
        # either already-compiled jit cache made it instant on a second
        # call, or the background thread lands shortly (CPU compile is ms)
        deadline = time.time() + 30
        while not ready and time.time() < deadline:
            time.sleep(0.01)
            ready = md.merge_dedup_ready(n)
        assert ready

    def test_direct_call_marks_ready(self):
        import numpy as np

        from horaedb_tpu.ops import merge_dedup as md
        from horaedb_tpu.ops.encoding import shape_bucket

        n = 2048
        with md._compile_lock:
            md._ready.discard((shape_bucket(n), True))
        tsid = np.arange(n, dtype=np.uint64)
        ts = np.zeros(n, dtype=np.int64)
        seq = np.arange(n, dtype=np.uint64)
        md.merge_dedup_permutation(tsid, ts, seq)
        assert md.merge_dedup_ready(n)
        # dedup=False is a different kernel: not marked ready by the above
        with md._compile_lock:
            md._ready.discard((shape_bucket(n), False))
            ready_false = (shape_bucket(n), False) in md._ready
        assert not ready_false


class TestCohortKernels:
    """Multi-query fused serving: the vmapped cohort kernels must be
    row-for-row identical to dispatching the packed kernels per query."""

    def _resident(self, n_series=5, rows_per=40, n_fields=2, seed=3):
        rng = np.random.default_rng(seed)
        codes = np.repeat(np.arange(n_series, dtype=np.int32), rows_per)
        ts_rel = np.tile(
            np.arange(rows_per, dtype=np.int32) * 10, n_series
        )
        values = rng.random((n_fields, n_series * rows_per)).astype(
            np.float32
        ) * 100.0
        return codes, ts_rel, values

    def test_cached_agg_cohort_matches_per_query_packed(self):
        import jax
        import jax.numpy as jnp

        from horaedb_tpu.ops.scan_agg import (
            ScanAggSpec,
            cached_scan_agg_cohort,
            cached_scan_agg_packed,
            encode_filter_ops,
            pack_dyn,
            pack_session,
            unpack_packed_state,
        )

        codes, ts_rel, values = self._resident()
        S = 5
        gos = np.append(np.arange(S, dtype=np.int32) % 3, 0)
        spec = ScanAggSpec(
            n_groups=3, n_buckets=4, n_agg_fields=2,
            numeric_filters=((0, ">="),), need_minmax=True,
            segment_impl="scatter",
        ).padded()
        nf = encode_filter_ops(spec.numeric_filters)
        rng = np.random.default_rng(7)
        members = []
        for b in range(4):  # varied allow-lists, literals, time bounds
            allow = np.append(rng.random(S) > 0.3, False)
            lo, hi = 10 * b, 400 - 20 * b
            members.append(
                (
                    pack_session(gos, allow),
                    pack_dyn([float(5 * b)], lo, hi, 0, 100),
                )
            )
        sessions = jnp.asarray(np.stack([m[0] for m in members]))
        dyns = jnp.asarray(np.stack([m[1] for m in members]))
        statics = dict(
            n_groups=spec.n_groups, n_buckets=spec.n_buckets,
            n_agg_fields=spec.n_agg_fields, numeric_filters=nf,
            need_minmax=True, segment_impl="scatter",
        )
        batched = np.asarray(
            jax.device_get(
                cached_scan_agg_cohort(
                    jnp.asarray(codes), jnp.asarray(ts_rel),
                    jnp.asarray(values), sessions, dyns, **statics
                )
            )
        )
        for j, (sess, dyn) in enumerate(members):
            solo = cached_scan_agg_packed(
                jnp.asarray(codes), jnp.asarray(ts_rel),
                jnp.asarray(values), jnp.asarray(sess), jnp.asarray(dyn),
                selective=False, hash_slots=0, **statics
            )
            a = unpack_packed_state(batched[j], spec)
            b = unpack_packed_state(solo, spec)
            np.testing.assert_array_equal(a.counts, b.counts)
            np.testing.assert_allclose(a.sums, b.sums, rtol=1e-6)
            np.testing.assert_allclose(a.mins, b.mins, rtol=1e-6)
            np.testing.assert_allclose(a.maxs, b.maxs, rtol=1e-6)

    def test_raw_topk_cohort_matches_per_query_packed(self):
        import jax
        import jax.numpy as jnp

        from horaedb_tpu.ops.scan_agg import encode_filter_ops
        from horaedb_tpu.ops.scan_topk import (
            pack_raw_dyn,
            raw_topk_cohort,
            raw_topk_packed,
            topk_key_bounds,
        )

        codes, ts_rel, values = self._resident()
        S = 5
        nf = encode_filter_ops(((0, "<"),))
        rng = np.random.default_rng(11)
        members = []
        for b in range(4):
            allow = np.append(rng.random(S) > 0.25, False).astype(np.int32)
            lo, hi = 5 * b, 390 - 10 * b
            key_lo, key_hi = topk_key_bounds(True, True, lo, hi)
            members.append(
                (allow, pack_raw_dyn([80.0 - b], lo, hi, key_lo, key_hi))
            )
        sessions = jnp.asarray(np.stack([m[0] for m in members]))
        dyns = jnp.asarray(np.stack([m[1] for m in members]))
        statics = dict(
            k=16, descending=True, key_is_ts=True, key_field=0,
            numeric_filters=nf,
        )
        batched = np.asarray(
            jax.device_get(
                raw_topk_cohort(
                    jnp.asarray(codes), jnp.asarray(ts_rel),
                    jnp.asarray(values), sessions, dyns, **statics
                )
            )
        )
        for j, (allow, dyn) in enumerate(members):
            solo = np.asarray(
                jax.device_get(
                    raw_topk_packed(
                        jnp.asarray(codes), jnp.asarray(ts_rel),
                        jnp.asarray(values), jnp.asarray(allow),
                        jnp.asarray(dyn), **statics
                    )
                )
            )
            # slot order is unspecified within ties: compare as sets of
            # selected row ids (the executor re-sorts gathered rows)
            assert set(batched[j][batched[j] >= 0]) == set(solo[solo >= 0])
