"""Partitioned table tests
(ref model: partition_table_engine + table_engine/partition rule tests)."""

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.table_engine import ColumnFilter, FilterOp, Predicate
from horaedb_tpu.table_engine.partition import HashRule, KeyRule, make_rule


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("host", DatumKind.STRING, is_tag=True),
            ColumnSchema("v", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


class TestRules:
    def rows(self, n=100):
        return RowGroup.from_rows(
            demo_schema(),
            [{"host": f"h{i % 10}", "v": float(i), "t": i} for i in range(n)],
        )

    def test_key_rule_deterministic_and_balanced(self):
        rule = KeyRule(("host",), 4)
        p1 = rule.partition_of_rows(self.rows())
        p2 = rule.partition_of_rows(self.rows())
        np.testing.assert_array_equal(p1, p2)
        assert set(p1.tolist()) <= {0, 1, 2, 3}
        # same host -> same partition
        hosts = self.rows().column("host")
        for h in set(hosts):
            assert len(set(p1[hosts == h])) == 1

    def test_key_rule_prune_eq(self):
        rule = KeyRule(("host",), 4)
        pred = Predicate.all_time([ColumnFilter("host", FilterOp.EQ, "h3")])
        keep = rule.prune(pred)
        assert keep is not None and len(keep) == 1
        assert keep[0] == rule.partition_of_values(["h3"])

    def test_key_rule_prune_in_list(self):
        rule = KeyRule(("host",), 8)
        pred = Predicate.all_time([ColumnFilter("host", FilterOp.IN, ("h1", "h2"))])
        keep = rule.prune(pred)
        expected = {rule.partition_of_values(["h1"]), rule.partition_of_values(["h2"])}
        assert set(keep) == expected

    def test_key_rule_no_prune_without_eq(self):
        rule = KeyRule(("host",), 4)
        assert rule.prune(Predicate.all_time()) is None
        assert rule.prune(
            Predicate.all_time([ColumnFilter("host", FilterOp.GT, "h")])
        ) is None

    def test_hash_rule_negative_values(self):
        rule = HashRule(("t",), 4)
        rows = RowGroup.from_rows(
            demo_schema(), [{"host": "h", "v": 1.0, "t": -7}]
        )
        p = rule.partition_of_rows(rows)
        assert 0 <= p[0] < 4

    def test_make_rule_unknown(self):
        with pytest.raises(ValueError):
            make_rule("bogus", ("a",), 2)

    def test_integer_key_prune_matches_write_routing(self):
        """Typed int64 column (write path) and Python literal (prune path)
        must hash to the SAME partition — review regression."""
        schema = Schema.build(
            [
                ColumnSchema("rid", DatumKind.INT64, is_tag=True),
                ColumnSchema("v", DatumKind.DOUBLE),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
        )
        rule = KeyRule(("rid",), 4)
        rows = RowGroup.from_rows(
            schema, [{"rid": i, "v": 1.0, "t": 1} for i in range(20)]
        )
        write_parts = rule.partition_of_rows(rows)
        for i in range(20):
            assert rule.partition_of_values([i]) == write_parts[i], i

    def test_hash_rule_rejects_multi_column(self):
        with pytest.raises(ValueError):
            HashRule(("a", "b"), 2)


class TestPartitionedSQL:
    DDL = (
        "CREATE TABLE cpu (host string TAG, v double NOT NULL, "
        "t timestamp NOT NULL, TIMESTAMP KEY(t)) "
        "PARTITION BY KEY(host) PARTITIONS 4 ENGINE=Analytic"
    )

    @pytest.fixture()
    def db(self):
        conn = horaedb_tpu.connect(None)
        yield conn
        conn.close()

    def seed(self, db, n=200):
        vals = ", ".join(
            f"('h{i % 10}', {float(i)}, {i * 1000})" for i in range(n)
        )
        db.execute(f"INSERT INTO cpu (host, v, t) VALUES {vals}")

    def test_scatter_write_gather_read(self, db):
        db.execute(self.DDL)
        self.seed(db)
        rows = db.execute("SELECT count(*) AS c FROM cpu").to_pylist()
        assert rows == [{"c": 200}]
        # sub-tables actually hold disjoint shards
        subs = db.catalog.open("cpu").sub_tables
        counts = [len(s.read()) for s in subs]
        assert sum(counts) == 200 and all(c > 0 for c in counts)

    def test_agg_across_partitions(self, db):
        db.execute(self.DDL)
        self.seed(db)
        rows = db.execute(
            "SELECT host, sum(v) AS s FROM cpu GROUP BY host ORDER BY host"
        ).to_pylist()
        assert len(rows) == 10
        expect_h1 = sum(float(i) for i in range(200) if i % 10 == 1)
        got = {r["host"]: r["s"] for r in rows}
        assert got["h1"] == pytest.approx(expect_h1)

    def test_eq_filter_prunes_partitions(self, db):
        db.execute(self.DDL)
        self.seed(db)
        table = db.catalog.open("cpu")
        pred = Predicate.all_time([ColumnFilter("host", FilterOp.EQ, "h7")])
        keep = table.rule.prune(pred)
        assert keep is not None and len(keep) == 1
        rows = db.execute("SELECT count(*) AS c FROM cpu WHERE host = 'h7'").to_pylist()
        assert rows == [{"c": 20}]

    def test_overwrite_lands_same_partition(self, db):
        db.execute(self.DDL)
        db.execute("INSERT INTO cpu (host, v, t) VALUES ('a', 1.0, 500)")
        db.execute("INSERT INTO cpu (host, v, t) VALUES ('a', 9.0, 500)")
        rows = db.execute("SELECT v FROM cpu WHERE host = 'a'").to_pylist()
        assert rows == [{"v": 9.0}]

    def test_persistence_across_reconnect(self, tmp_path):
        path = str(tmp_path / "db")
        db1 = horaedb_tpu.connect(path)
        db1.execute(self.DDL)
        db1.execute("INSERT INTO cpu (host, v, t) VALUES ('a', 1.0, 500), ('b', 2.0, 600)")
        db1.flush_all()
        db1.close()
        db2 = horaedb_tpu.connect(path)
        assert db2.execute("SELECT count(*) AS c FROM cpu").to_pylist() == [{"c": 2}]
        # SHOW TABLES lists only the logical table, not __cpu_N
        assert db2.execute("SHOW TABLES").to_pylist() == [{"Tables": "cpu"}]
        db2.close()

    def test_drop_removes_all_partitions(self, db):
        db.execute(self.DDL)
        self.seed(db, 50)
        db.execute("DROP TABLE cpu")
        assert db.execute("SHOW TABLES").to_pylist() == []
        assert list(db.store.list("manifest/")) == []

    def test_alter_propagates_to_partitions(self, db):
        db.execute(self.DDL)
        db.execute("INSERT INTO cpu (host, v, t) VALUES ('a', 1.0, 500)")
        db.execute("ALTER TABLE cpu ADD COLUMN v2 double")
        db.execute("INSERT INTO cpu (host, v, v2, t) VALUES ('zz', 2.0, 3.0, 600)")
        rows = db.execute("SELECT host, v2 FROM cpu ORDER BY host").to_pylist()
        assert rows == [{"host": "a", "v2": None}, {"host": "zz", "v2": 3.0}]

    def test_partition_validation(self, db):
        with pytest.raises(ValueError, match="not defined"):
            db.execute(
                "CREATE TABLE bad (host string TAG, t timestamp KEY) "
                "PARTITION BY KEY(nope) PARTITIONS 2"
            )
        with pytest.raises(ValueError, match="key kind"):
            db.execute(
                "CREATE TABLE bad (host string TAG, v double, t timestamp KEY) "
                "PARTITION BY KEY(v) PARTITIONS 2"
            )
        with pytest.raises(ValueError, match="integer"):
            db.execute(
                "CREATE TABLE bad (host string TAG, t timestamp KEY) "
                "PARTITION BY HASH(host) PARTITIONS 2"
            )
        with pytest.raises(ValueError, match="one column"):
            db.execute(
                "CREATE TABLE bad (a bigint TAG, b bigint TAG, t timestamp KEY) "
                "PARTITION BY HASH(a, b) PARTITIONS 2"
            )
