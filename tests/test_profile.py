"""Profile plane (ISSUE 20): the streaming fold of finished span trees.

What is pinned here, in order of importance:

- the ACCOUNTING CONTRACT: per folded trace, root_ms == sum of non-root
  exclusive_ms (signed overlap and the first-class untracked row make
  the telescope exact), and fleetwide, live rows + the evicted ledger
  always reconcile to a naive refold of every tree ever folded — LRU
  eviction under the profile_keys bound loses rows, never milliseconds;
- COVERAGE: real dashboard + insert shapes served through the proxy must
  leave the untracked fraction of root wall under the 40% bound (a
  regression here means a serving stage lost its spans);
- the registry lint for the horaedb_profile_* families and the
  [observability] knobs (same contract as the decision-plane lint);
- TraceStore.get returns the NEWEST snapshot on trace-id reuse.
"""

from __future__ import annotations

import os
import random

import pytest

import horaedb_tpu
from horaedb_tpu.obs.profile import (
    PROFILE,
    UNTRACKED,
    ProfileAggregator,
    critical_path,
    flush as profile_flush,
)
from horaedb_tpu.proxy import Proxy


def _tree(name: str, dur: float, children=()) -> dict:
    return {
        "name": name,
        "duration_ms": dur,
        "children": [dict(c) for c in children],
    }


def _random_tree(rng: random.Random, depth: int = 0) -> dict:
    """A plausible span tree: durations positive, children's sum MAY
    exceed the parent (parallel spans) so signed exclusive is exercised."""
    dur = rng.uniform(0.1, 50.0)
    kids = []
    if depth < 3:
        for _ in range(rng.randrange(0, 4)):
            kids.append(_random_tree(rng, depth + 1))
    name = rng.choice(["parse", "execute", "scan", "kernel", "wal", "merge"])
    return _tree(name, dur, kids)


def _naive_rows(root: dict) -> list[tuple[str, float, float]]:
    """Reference refold: the same telescoping walk, written naively."""
    rows: list[tuple[str, float, float]] = []

    def walk(node, path):
        dur = float(node["duration_ms"])
        child_sum = 0.0
        for c in node.get("children") or ():
            child_sum += walk(c, f"{path}/{c['name']}")
        rows.append((path, dur, dur - child_sum))
        return dur

    name = root["name"]
    walk(root, name)
    path, total, excl = rows.pop()
    rows.append((path, total, 0.0))
    rows.append((f"{name}/{UNTRACKED}", excl, excl))
    return rows


class TestAccountingInvariant:
    def test_root_equals_exclusive_sum_plus_untracked(self):
        """The hard per-trace invariant, including signed overlap: two
        parallel children longer than their parent drive the parent's
        exclusive negative, and the telescope still closes exactly."""
        agg = ProfileAggregator()
        root = _tree("sql", 10.0, [
            _tree("parse", 1.0),
            _tree("execute", 8.0, [
                # 5 + 5 > 8: overlapping (threaded) children
                _tree("scan", 5.0),
                _tree("kernel", 5.0),
            ]),
        ])
        agg.fold("t1", root, route="query", shape="s")
        rows = {r["path"]: r for r in agg.list()}
        assert rows["sql"]["exclusive_ms"] == 0.0
        assert rows["sql/execute"]["exclusive_ms"] == pytest.approx(-2.0)
        assert rows[f"sql/{UNTRACKED}"]["exclusive_ms"] == pytest.approx(1.0)
        non_root = sum(
            r["exclusive_ms"] for p, r in rows.items() if "/" in p
        )
        assert non_root == pytest.approx(10.0)

    def test_untracked_is_first_class_and_ratio_tracked(self):
        agg = ProfileAggregator()
        agg.fold("t1", _tree("req", 10.0, [_tree("work", 6.0)]),
                 route="query", shape="s")
        rows = {r["path"]: r for r in agg.list()}
        assert rows[f"req/{UNTRACKED}"]["total_ms"] == pytest.approx(4.0)
        assert agg.stats()["untracked_ratio"] == pytest.approx(0.4)

    def test_random_ops_reconcile_with_naive_refold(self):
        """The reconciliation property: after folding random trees into
        a SMALL aggregator (so LRU eviction genuinely fires), live rows
        plus the evicted ledger equal a naive refold of everything —
        counts, total ms and exclusive ms, exactly accounted."""
        rng = random.Random(20)
        agg = ProfileAggregator(capacity=12)
        naive_count = 0
        naive_total = 0.0
        naive_excl = 0.0
        naive_spans = 0
        for i in range(300):
            root = _random_tree(rng)
            route = rng.choice(["query", "ingest", "flush"])
            agg.fold(f"t{i}", root, route=route, shape=f"s{i % 7}")
            for _, total, excl in _naive_rows(root):
                naive_count += 1
                naive_total += total
                naive_excl += excl
            naive_spans += len(_naive_rows(root))
        s = agg.stats()
        assert s["traces"] == 300
        assert s["spans"] == naive_spans
        assert s["dropped"] > 0, "capacity 12 must have evicted keys"
        assert s["keys"] <= 12
        got_count = s["live"]["count"] + s["evicted"]["count"]
        got_total = s["live"]["total_ms"] + s["evicted"]["total_ms"]
        got_excl = s["live"]["exclusive_ms"] + s["evicted"]["exclusive_ms"]
        assert got_count == naive_count
        assert got_total == pytest.approx(naive_total, rel=1e-6)
        assert got_excl == pytest.approx(naive_excl, rel=1e-6)

    def test_resize_shrink_evicts_and_accounts(self):
        agg = ProfileAggregator(capacity=64)
        for i in range(20):
            agg.fold(f"t{i}", _tree(f"req{i}", 5.0), route="query",
                     shape="s")
        before = agg.stats()
        agg.resize(4)
        after = agg.stats()
        assert after["capacity"] == 4
        assert after["keys"] <= 4
        assert after["dropped"] > before["dropped"]
        # nothing lost: the evicted ledger absorbed the shrink
        assert (after["live"]["total_ms"] + after["evicted"]["total_ms"]
                == pytest.approx(
                    before["live"]["total_ms"]
                    + before["evicted"]["total_ms"]))


class TestKillSwitch:
    def test_profile_env_disables_fold(self):
        from horaedb_tpu.obs.profile import fold_trace

        prior = os.environ.get("HORAEDB_PROFILE")
        try:
            profile_flush(5.0)  # drain strays before the clean-slate
            PROFILE.clear()
            os.environ["HORAEDB_PROFILE"] = "0"
            fold_trace("t1", _tree("req", 5.0), route="query", shape="s")
            profile_flush(5.0)
            assert PROFILE.stats()["traces"] == 0
            os.environ["HORAEDB_PROFILE"] = "1"
            fold_trace("t2", _tree("req", 5.0), route="query", shape="s")
            assert profile_flush(5.0)
            assert PROFILE.stats()["traces"] == 1
        finally:
            if prior is None:
                os.environ.pop("HORAEDB_PROFILE", None)
            else:
                os.environ["HORAEDB_PROFILE"] = prior


class TestCriticalPath:
    def test_descends_max_child(self):
        root = _tree("sql", 10.0, [
            _tree("parse", 1.0),
            _tree("execute", 8.0, [
                _tree("scan", 6.0), _tree("kernel", 1.0),
            ]),
        ])
        hops = critical_path(root)
        assert [h["name"] for h in hops] == ["sql", "execute", "scan"]
        assert hops[1]["self_ms"] == pytest.approx(1.0)

    def test_explain_analyze_emits_critical_path_line(self):
        db = horaedb_tpu.connect(None)
        try:
            db.execute(
                "CREATE TABLE cp (host string TAG, v double, "
                "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
            )
            db.execute(
                "INSERT INTO cp (host, v, ts) VALUES ('a', 1.0, 1000)"
            )
            text = "\n".join(
                r["plan"]
                for r in db.execute(
                    "EXPLAIN ANALYZE SELECT host, sum(v) FROM cp "
                    "GROUP BY host"
                ).to_pylist()
            )
            assert "Critical path:" in text
            assert "ms (self " in text
        finally:
            db.close()


class TestServingCoverage:
    """The coverage bound: REAL shapes through the proxy, then the
    untracked fraction of root wall per route must stay under 40% — the
    standing assertion that the serving stages keep their spans."""

    def test_dashboard_and_insert_shapes_under_untracked_bound(self):
        prior = os.environ.get("HORAEDB_PROFILE")
        db = horaedb_tpu.connect(None)
        try:
            os.environ["HORAEDB_PROFILE"] = "1"
            profile_flush(5.0)  # drain strays before the clean-slate
            PROFILE.clear()
            db.execute(
                "CREATE TABLE dash (host string TAG, v double, "
                "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
            )
            proxy = Proxy(db)
            t0 = 1_700_000_000_000
            for i in range(8):
                vals = ",".join(
                    f"('h{h}', {h}.5, {t0 + i * 1000})" for h in range(4)
                )
                proxy.handle_sql(
                    f"INSERT INTO dash (host, v, ts) VALUES {vals}"
                )
            db.flush_all()
            for q in range(12):
                proxy.handle_sql(
                    f"SELECT host, count(v), sum(v) FROM dash WHERE "
                    f"ts >= {t0 + (q % 4) * 1000} GROUP BY host"
                )
            assert profile_flush(10.0)
            rows = PROFILE.list()
            for route in ("query", "ingest"):
                roots = sum(
                    r["total_ms"] for r in rows
                    if r["route"] == route and "/" not in r["path"]
                )
                untracked = sum(
                    max(0.0, r["total_ms"]) for r in rows
                    if r["route"] == route
                    and r["path"].endswith("/" + UNTRACKED)
                )
                assert roots > 0, f"no {route} root rows: {rows}"
                frac = untracked / roots
                assert frac < 0.40, (
                    f"route={route} untracked {frac:.1%} >= 40% — a "
                    f"serving stage lost its spans: {rows}"
                )
            # exemplar linkage: rows point at a real stored trace
            from horaedb_tpu.utils.tracectx import TRACE_STORE

            top = [r for r in rows if r["route"] == "query"][0]
            assert TRACE_STORE.get(top["last_trace_id"]) is not None
        finally:
            if prior is None:
                os.environ.pop("HORAEDB_PROFILE", None)
            else:
                os.environ["HORAEDB_PROFILE"] = prior
            db.close()


class TestTraceStore:
    def test_get_returns_newest_on_trace_id_reuse(self):
        """Request ids recycle across restarts; /debug/trace/{id} and
        the profile exemplar link must resolve to the LATEST tree."""
        from horaedb_tpu.utils.tracectx import TraceStore

        store = TraceStore()
        store.record_snapshot(
            7, {"name": "old", "duration_ms": 1.0, "start_at": 1.0,
                "children": []}
        )
        store.record_snapshot(
            7, {"name": "new", "duration_ms": 2.0, "start_at": 2.0,
                "children": []}
        )
        got = store.get(7)
        assert got is not None
        assert got["root"]["name"] == "new"

    def test_resize_applies_ring_knobs(self):
        from horaedb_tpu.utils.tracectx import TraceStore

        store = TraceStore()
        for i in range(10):
            store.record_snapshot(
                i, {"name": "r", "duration_ms": 1.0, "start_at": float(i),
                    "children": []}
            )
        store.resize(recent=3, slow=5)
        assert store.sizes() == (3, 5)
        assert len(store.list()) <= 3


class TestSystemTables:
    def test_profile_and_traces_tables_registered(self):
        from horaedb_tpu.table_engine.system import (
            PROFILE_NAME,
            TRACES_NAME,
            open_system_table,
        )

        t = open_system_table(None, PROFILE_NAME)
        cols = {c.name for c in t.schema.columns}
        assert {"path", "route", "shape", "count", "total_ms",
                "exclusive_ms", "ewma_ms", "fast_ms", "slow_ms",
                "trace_id"} <= cols
        tr = open_system_table(None, TRACES_NAME)
        tcols = {c.name for c in tr.schema.columns}
        assert {"trace_id", "name", "duration_ms", "spans",
                "slow"} <= tcols

    def test_profile_rows_flow_to_table(self):
        profile_flush(5.0)
        PROFILE.clear()
        PROFILE.fold("tx", _tree("req", 4.0, [_tree("work", 3.0)]),
                     route="query", shape="s")
        from horaedb_tpu.table_engine.system import (
            PROFILE_NAME,
            open_system_table,
        )

        rg = open_system_table(None, PROFILE_NAME)._materialize()
        paths = set(rg.columns["path"])
        assert {"req", "req/work", f"req/{UNTRACKED}"} <= paths


class TestProfileRegistryLint:
    """Same contract as the decision-plane registry lint: every family
    in PROFILE_METRIC_FAMILIES live + convention-clean + documented in
    docs/OBSERVABILITY.md, no stray horaedb_profile_* family, and the
    plane's knobs pinned to docs/WORKLOAD.md."""

    def test_profile_families_declared_and_documented(self):
        import re

        from horaedb_tpu.obs.profile import PROFILE_METRIC_FAMILIES
        from horaedb_tpu.utils.metrics import REGISTRY

        here = os.path.dirname(__file__)
        docs = open(
            os.path.join(here, "..", "docs", "OBSERVABILITY.md")
        ).read()
        wdocs = open(os.path.join(here, "..", "docs", "WORKLOAD.md")).read()
        families = set(REGISTRY.families())
        pat = re.compile(r"^horaedb_[a-z0-9_]+$")
        from tests.test_observability import TestMetricsNameLint

        suffixes = TestMetricsNameLint.SUFFIXES
        missing = []
        for fam in PROFILE_METRIC_FAMILIES:
            if fam not in families:
                missing.append(f"{fam}: not registered")
            if not pat.match(fam) or not fam.endswith(suffixes):
                missing.append(f"{fam}: violates naming lint")
            if f"`{fam}`" not in docs:
                missing.append(f"{fam}: undocumented in OBSERVABILITY.md")
        for fam in families:
            if (fam.startswith("horaedb_profile_")
                    and fam not in PROFILE_METRIC_FAMILIES):
                missing.append(f"{fam}: live but undeclared in registry")
        for knob in ("profile_keys", "trace_ring", "trace_slow_ring",
                     "slow_threshold", "HORAEDB_PROFILE"):
            if f"`{knob}`" not in wdocs:
                missing.append(f"{knob}: undocumented in docs/WORKLOAD.md")
        assert not missing, missing
