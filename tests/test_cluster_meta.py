"""Coordinator-driven cluster e2e: 1 meta + 2 data nodes, real processes
(ref model: integration_tests/Makefile cluster target — HoraeMeta + 2
horaedb-server nodes on localhost; recovery/run.sh kill-and-check).

Covers the round-2 coordinator milestones end to end:
create table -> shard assigned -> cross-node forwarding -> node death ->
shards reassigned, data survives (shared object store) -> resumed node's
stale lease fences writes.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http(method: str, url: str, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}, method=method
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode() or "{}")
        except Exception:
            body = {}
        return e.code, body


def sql(port: int, query: str):
    return http("POST", f"http://127.0.0.1:{port}/sql", {"query": query})


def wait_until(fn, timeout=60.0, interval=0.2, desc="condition"):
    # 60s default: on this 1-core host a loaded run stretches process
    # startup and heartbeat cadence enough that 30s flaked ~1 in 20.
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = fn()
            if last:
                return last
        except Exception as e:
            last = e
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}: last={last}")


CPU_ENV = {
    **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


@pytest.fixture()
def cluster(tmp_path):
    """(meta_port, node_ports, procs, spawn_node) with fast failover knobs."""
    meta_port = free_port()
    node_ports = [free_port(), free_port()]
    data_dir = str(tmp_path / "shared-store")
    procs: dict[str, subprocess.Popen] = {}

    meta = subprocess.Popen(
        [
            sys.executable, "-m", "horaedb_tpu.meta",
            "--port", str(meta_port),
            "--data-dir", str(tmp_path / "meta"),
            "--num-shards", "4",
            "--lease-ttl", "1.5",
            "--heartbeat-timeout", "2.0",
            "--tick-interval", "0.25",
        ],
        env=CPU_ENV,
        stdout=open(tmp_path / "meta.log", "wb"),
        stderr=subprocess.STDOUT,
    )
    procs["meta"] = meta

    def spawn_node(idx: int) -> subprocess.Popen:
        port = node_ports[idx]
        cfg = tmp_path / f"node{idx}.toml"
        cfg.write_text(
            f"""
[server]
host = "127.0.0.1"
http_port = {port}

[engine]
data_dir = "{data_dir}"

[cluster]
self_endpoint = "127.0.0.1:{port}"
meta_endpoints = ["127.0.0.1:{meta_port}"]
"""
        )
        p = subprocess.Popen(
            [sys.executable, "-m", "horaedb_tpu.server", "--config", str(cfg)],
            env=CPU_ENV,
            stdout=open(tmp_path / f"node{idx}.log", "wb"),
            stderr=subprocess.STDOUT,
        )
        procs[f"node{idx}"] = p
        return p

    for i in range(2):
        spawn_node(i)

    def healthy(port):
        s, _ = http("GET", f"http://127.0.0.1:{port}/health", timeout=2)
        return s == 200

    wait_until(lambda: healthy(meta_port), desc="meta health")
    for p in node_ports:
        wait_until(lambda p=p: healthy(p), desc=f"node {p} health")

    yield meta_port, node_ports, procs, spawn_node

    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    for p in procs.values():
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def shards_all_assigned(meta_port):
    _, body = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/shards")
    shards = body["shards"]
    return shards if all(s["node"] for s in shards) else None


DDL = (
    "CREATE TABLE {name} (host string TAG, v double, "
    "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
)


class TestMetaCluster:
    def test_cluster_lifecycle_and_failover(self, cluster):
        meta_port, (port_a, port_b), procs, spawn_node = cluster

        # --- shards spread over both nodes ---------------------------------
        # "all assigned" converges before "spread": when one node
        # registers a beat earlier (common under full-suite load), the
        # static scheduler gives it EVERY shard and the rebalance loop
        # moves them over one tick at a time — so wait for the spread,
        # not just for assignment.
        expected = {f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"}

        def spread():
            shards = shards_all_assigned(meta_port)
            if shards and {s["node"] for s in shards} == expected:
                return shards
            return None

        shards = wait_until(spread, desc="shards spread over both nodes")
        nodes_used = {s["node"] for s in shards}
        assert nodes_used == expected

        # --- create tables through a data node (meta picks placement) ------
        for name in ("t0", "t1", "t2", "t3"):
            status, out = sql(port_a, DDL.format(name=name))
            assert status == 200, out
        _, routes = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/shards")
        owners = {
            name: next(
                s["node"] for s in routes["shards"]
                if http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/{name}")[1][
                    "shard_id"
                ]
                == s["shard_id"]
            )
            for name in ("t0", "t1", "t2", "t3")
        }
        # least-loaded placement spreads 4 tables over 4 shards on 2 nodes
        assert set(owners.values()) == {f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"}

        # --- writes + reads from EITHER node (forwarding) ------------------
        for i, name in enumerate(("t0", "t1", "t2", "t3")):
            status, out = sql(
                port_a, f"INSERT INTO {name} (host, v, ts) VALUES ('h', {i}.5, 1000)"
            )
            assert status == 200 and out.get("affected_rows") == 1, out
        for port in (port_a, port_b):
            for i, name in enumerate(("t0", "t1", "t2", "t3")):
                status, out = sql(port, f"SELECT host, v, ts FROM {name}")
                assert status == 200, out
                assert out["rows"] == [{"host": "h", "v": i + 0.5, "ts": 1000}], (
                    port, name, out,
                )

        # --- kill node B: shards move, data survives (shared store) --------
        victim = f"127.0.0.1:{port_b}"
        moved_tables = [n for n, owner in owners.items() if owner == victim]
        assert moved_tables, "placement should have put something on node B"
        procs["node1"].kill()
        procs["node1"].wait(timeout=10)

        def all_on_a():
            shards = shards_all_assigned(meta_port)
            if shards and all(s["node"] == f"127.0.0.1:{port_a}" for s in shards):
                return shards
            return None

        wait_until(all_on_a, timeout=60, desc="failover to node A")

        def survivors_serve():
            for i, name in enumerate(("t0", "t1", "t2", "t3")):
                status, out = sql(port_a, f"SELECT host, v, ts FROM {name}")
                if status != 200 or out.get("rows") != [
                    {"host": "h", "v": i + 0.5, "ts": 1000}
                ]:
                    return None
            return True

        wait_until(survivors_serve, timeout=20, desc="data served after failover")

        # writes to moved tables also work on the survivor
        status, out = sql(
            port_a, f"INSERT INTO {moved_tables[0]} (host, v, ts) VALUES ('h2', 9.0, 2000)"
        )
        assert status == 200 and out.get("affected_rows") == 1, out

    def test_stale_lease_write_fenced(self, cluster):
        meta_port, (port_a, port_b), procs, spawn_node = cluster
        wait_until(lambda: shards_all_assigned(meta_port), desc="assignment")
        status, _ = sql(port_b, DDL.format(name="fence_t"))
        assert status == 200
        # find the owner; make sure the table lands on node B for the test
        _, route = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/fence_t")
        owner_port = int(route["node"].rsplit(":", 1)[1])
        standby_port = port_a if owner_port == port_b else port_b
        owner_proc = procs["node1"] if owner_port == port_b else procs["node0"]

        status, out = sql(
            owner_port, "INSERT INTO fence_t (host, v, ts) VALUES ('h', 1.0, 1000)"
        )
        assert status == 200, out

        # Suspend the owner: it misses heartbeats, its lease expires, meta
        # reassigns. Resume it and write DIRECTLY to it: the write must be
        # fenced (503), not silently applied (split brain).
        owner_proc.send_signal(signal.SIGSTOP)

        def reassigned():
            _, r = http(
                "GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/fence_t"
            )
            return r if int(r["node"].rsplit(":", 1)[1]) == standby_port else None

        wait_until(reassigned, timeout=60, desc="reassignment away from owner")

        # Queue the write WHILE the owner is still stopped (the kernel
        # completes the handshake and buffers the request), then resume:
        # the handler sees shard-owned + lease-expired BEFORE the
        # heartbeat thread can reach the coordinator — deterministic
        # stale-lease window, and the write MUST be fenced with 503.
        import threading

        result: dict = {}

        def queued_write():
            result["resp"] = sql(
                owner_port,
                "INSERT INTO fence_t (host, v, ts) VALUES ('h', 666.0, 3000)",
            )

        t = threading.Thread(target=queued_write)
        t.start()
        time.sleep(0.3)  # let the request reach the socket queue
        owner_proc.send_signal(signal.SIGCONT)
        t.join(timeout=15)
        status, out = result["resp"]
        if status == 200:
            # Two legitimate 200 paths exist besides fencing:
            #  (a) the rebalance scheduler re-granted the shard to the
            #      resumed node before the write was handled (it is the
            #      rightful owner again; route points back at it), or
            #  (b) the resumed node processed the buffered close order
            #      first, so the write was FORWARDED to the current owner
            #      (single-writer discipline held; only the front door was
            #      the stale node).
            # Split brain — a LOCAL apply under a stale lease — is
            # neither: the node would still be serving the table locally
            # while meta routes it elsewhere.
            _, r = http(
                "GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/fence_t"
            )
            if int(r["node"].rsplit(":", 1)[1]) != owner_port:
                _, dbg = http(
                    "GET", f"http://127.0.0.1:{owner_port}/debug/shards"
                )
                assert not any(
                    "fence_t" in s.get("tables", ())
                    for s in dbg.get("shards", ())
                ), ("stale node applied a write locally while another node "
                    "owns the shard (split brain)", r, dbg)

            def visible_via_route():
                _, r = http(
                    "GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/fence_t"
                )
                port = int(r["node"].rsplit(":", 1)[1])
                st, res = sql(port, "SELECT v FROM fence_t WHERE ts = 3000")
                if st != 200:
                    return None
                rows = res.get("rows", [])
                return rows if rows and rows[0]["v"] == 666.0 else None

            wait_until(visible_via_route, timeout=15,
                       desc="accepted write visible via current route")
        else:
            assert status == 503, (status, out)
            assert "fence" in out.get("error", "") or "not served" in out.get("error", ""), out

        # The new owner serves reads and writes (the open_shard order may
        # land via the next heartbeat reconcile — eventually consistent).
        def standby_accepts_write():
            status, out = sql(
                standby_port,
                "INSERT INTO fence_t (host, v, ts) VALUES ('h', 2.0, 2000)",
            )
            return (status, out) if status == 200 else None

        wait_until(standby_accepts_write, timeout=15, desc="standby serving writes")

        # The resumed node rejoins and the rebalancer may move shards
        # again; during a transfer there is a brief routing window (same
        # as the reference's shard moves). The CLUSTER must converge to
        # serving the correct data: if the 666.0 write was fenced (503)
        # it must NOT appear; if it was legitimately accepted (rebalance
        # re-grant or forward to the owner) it MUST appear — silently
        # dropping an acknowledged write would be the opposite bug.
        expect = [1.0, 2.0] if status == 503 else [1.0, 2.0, 666.0]
        last_seen = {}

        def converged():
            st, out = sql(standby_port, "SELECT v FROM fence_t ORDER BY ts")
            last_seen["r"] = (st, out)
            if st == 200 and [r["v"] for r in out["rows"]] == expect:
                return True
            return None

        try:
            wait_until(converged, timeout=20, desc="cluster convergence after rejoin")
        except TimeoutError:
            raise AssertionError(f"no convergence; last={last_seen.get('r')}")


class TestFencingUnit:
    """Deterministic, in-process lease fencing (no cross-process timing)."""

    def test_expired_lease_fences_writes(self):
        import horaedb_tpu
        from horaedb_tpu.cluster import ClusterImpl, ShardError
        from horaedb_tpu.cluster.meta_client import MetaClient

        conn = horaedb_tpu.connect(None)
        cluster = ClusterImpl(conn, "127.0.0.1:1", MetaClient(["127.0.0.1:1"]))
        ddl = DDL.format(name="ft")
        cluster.apply_shard_order(
            {
                "shard_id": 0,
                "version": 1,
                "lease_ttl_s": 0.05,
                "tables": [{"name": "ft", "table_id": 1, "create_sql": ddl}],
            },
            granted_at=time.monotonic(),
        )
        cluster.ensure_table_writable("ft")  # fresh lease: fine
        time.sleep(0.08)
        with pytest.raises(ShardError, match="lease expired"):
            cluster.ensure_table_writable("ft")
        # a renewed order (next heartbeat) restores writability
        cluster.apply_shard_order(
            {
                "shard_id": 0,
                "version": 2,
                "lease_ttl_s": 5.0,
                "tables": [{"name": "ft", "table_id": 1, "create_sql": ddl}],
            },
            granted_at=time.monotonic(),
        )
        cluster.ensure_table_writable("ft")

    def test_stale_buffered_reply_does_not_reopen_fence(self):
        """A heartbeat reply that was in flight across a long stall (the
        SIGSTOP window in the e2e test) carries a grant the coordinator
        has since revoked. Lease deadlines measure from request-SEND time
        (granted_at), so applying the stale reply must leave the fence
        closed — and a stale grant must never shorten a newer lease."""
        import horaedb_tpu
        from horaedb_tpu.cluster import ClusterImpl, ShardError
        from horaedb_tpu.cluster.meta_client import MetaClient

        conn = horaedb_tpu.connect(None)
        cluster = ClusterImpl(conn, "127.0.0.1:1", MetaClient(["127.0.0.1:1"]))
        ddl = DDL.format(name="ft2")
        order = {
            "shard_id": 0,
            "version": 1,
            "lease_ttl_s": 5.0,
            "tables": [{"name": "ft2", "table_id": 1, "create_sql": ddl}],
        }
        # Reply sent (and suspension began) 60s ago: grant long lapsed.
        cluster.apply_shard_order(order, granted_at=time.monotonic() - 60.0)
        with pytest.raises(ShardError, match="lease expired"):
            cluster.ensure_table_writable("ft2")
        # A /meta_event push (granted_at=None) opens membership but grants
        # NO lease — a buffered push has no bounded age.
        cluster.apply_shard_order({**order, "version": 2})
        with pytest.raises(ShardError, match="lease expired"):
            cluster.ensure_table_writable("ft2")
        # The heartbeat the push kicks delivers the lease; a late stale
        # reply must not roll the deadline back.
        cluster.apply_shard_order(
            {**order, "version": 2}, granted_at=time.monotonic()
        )
        cluster.ensure_table_writable("ft2")
        cluster.apply_shard_order(
            {**order, "version": 2}, granted_at=time.monotonic() - 60.0
        )
        cluster.ensure_table_writable("ft2")

    def test_stale_version_rejected(self):
        import horaedb_tpu
        from horaedb_tpu.cluster import ClusterImpl, ShardError
        from horaedb_tpu.cluster.meta_client import MetaClient

        conn = horaedb_tpu.connect(None)
        cluster = ClusterImpl(conn, "127.0.0.1:1", MetaClient(["127.0.0.1:1"]))
        order = {
            "shard_id": 0,
            "version": 5,
            "lease_ttl_s": 5.0,
            "tables": [],
        }
        cluster.apply_shard_order(order)
        with pytest.raises(ShardError, match="stale"):
            cluster.apply_shard_order({**order, "version": 3})
        with pytest.raises(ShardError, match="stale"):
            cluster.close_shard(0, version=3)


class TestPartitionPlacement:
    """Coordinator-placed partitions: each sub-table lives on its own
    shard/node; queries and writes span the cluster transparently."""

    def test_partitioned_table_spreads_and_serves(self, cluster):
        meta_port, (port_a, port_b), procs, spawn_node = cluster

        def balanced():
            # placement is decided at CREATE time: both nodes must hold
            # shards BEFORE the DDL or the spread assertion can't pass
            # (a transient lease lapse under load parks all shards on one
            # node until the rebalancer runs)
            shards = shards_all_assigned(meta_port)
            if not shards:
                return None
            return shards if len({s["node"] for s in shards}) == 2 else None

        wait_until(balanced, timeout=60, desc="shards spread over both nodes")
        ddl = (
            "CREATE TABLE ppt (host string TAG, v double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) PARTITION BY KEY(host) PARTITIONS 4 ENGINE=Analytic"
        )
        status, out = sql(port_a, ddl)
        assert status == 200, out

        # the coordinator placed each partition on its own shard; with two
        # nodes and 4 shards the partitions span BOTH nodes
        owners = set()
        for i in range(4):
            s, r = http(
                "GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/__ppt_{i}"
            )
            assert s == 200, r
            owners.add(r["node"])
        assert len(owners) == 2, f"partitions on one node only: {owners}"

        rows = [f"('h{i % 8}', {float(i)}, {1000 + i})" for i in range(160)]

        def insert_lands():
            # partition orders propagate via heartbeat (<=2s): writes are
            # fenced until each owner has opened its sub-tables
            status, out = sql(
                port_b, "INSERT INTO ppt (host, v, ts) VALUES " + ", ".join(rows)
            )
            insert_lands.last = (status, out)
            return out if status == 200 and out.get("affected_rows") == 160 else None

        # generous: under full-suite CPU load heartbeat rounds stretch to
        # seconds and shard orders propagate slowly (passes in ~2s alone)
        try:
            wait_until(insert_lands, timeout=60, desc="scattered insert accepted")
        except TimeoutError:
            raise AssertionError(
                f"scattered insert never accepted; last response: "
                f"{getattr(insert_lands, 'last', None)}"
            )

        import numpy as np

        expect = {
            f"h{h}": {
                "c": len([i for i in range(160) if i % 8 == h]),
                "s": float(sum(i for i in range(160) if i % 8 == h)),
            }
            for h in range(8)
        }

        def both_nodes_agree():
            for port in (port_a, port_b):
                s, out = sql(
                    port,
                    "SELECT host, count(*) AS c, sum(v) AS s FROM ppt GROUP BY host",
                )
                if s != 200:
                    return None
                got = {r["host"]: r for r in out["rows"]}
                if set(got) != set(expect):
                    return None
                for h, e in expect.items():
                    if got[h]["c"] != e["c"] or abs(got[h]["s"] - e["s"]) > 1e-6:
                        return None
            return True

        wait_until(both_nodes_agree, timeout=60, desc="partitioned query both nodes")

        # drop cleans up every partition cluster-wide
        status, out = sql(port_a, "DROP TABLE ppt")
        assert status == 200, out
        s, r = http("GET", f"http://127.0.0.1:{meta_port}/meta/v1/route/__ppt_0")
        assert s == 404, r
