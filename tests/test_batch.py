"""Cohort batching (horaedb_tpu/wlm/batch + the executor's prepare/
dispatch split): shape-identical in-flight SELECTs with differing
literals gather in a micro-batching window and serve from ONE fused
device dispatch, with per-query demux, per-member error isolation,
epoch-fenced read-your-writes, and dedup of identical twins inside the
cohort."""

from __future__ import annotations

import threading
import time

import pytest

import horaedb_tpu
from horaedb_tpu.proxy import Proxy
from horaedb_tpu.utils.config import BatchSection
from horaedb_tpu.utils.metrics import REGISTRY
from horaedb_tpu.utils.querystats import STATS_STORE
from horaedb_tpu.wlm.quota import QuotaExceededError


def _counter(name: str, **labels) -> float:
    return REGISTRY.counter(name, "", labels=labels or None).value


def _dash_db(hosts: int = 6, rows: int = 40):
    db = horaedb_tpu.connect(None)
    db.execute(
        "CREATE TABLE dash (host string TAG, v double, "
        "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
    )
    values = []
    for h in range(hosts):
        for i in range(rows):
            values.append(f"('h{h}', {h + i * 0.25}, {1000 + i * 10})")
    db.execute("INSERT INTO dash (host, v, ts) VALUES " + ",".join(values))
    db.flush_all()
    return db


def _batch_proxy(db, window_s=0.25, max_cohort=8, **kw) -> Proxy:
    return Proxy(
        db,
        batch_cfg=BatchSection(
            enabled=True, window_s=window_s, max_cohort=max_cohort, **kw
        ),
    )


def _run_concurrent(proxy, sqls, tenants=None):
    """Fire the statements concurrently; returns {sql: result-or-error}."""
    out: dict = {}

    def worker(sql, tenant):
        try:
            out[sql] = proxy.handle_sql(sql, tenant=tenant)
        except BaseException as e:  # noqa: BLE001 — outcomes under test
            out[sql] = e

    threads = [
        threading.Thread(
            target=worker,
            args=(s, tenants[i] if tenants else "default"),
        )
        for i, s in enumerate(sqls)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def _rows(result) -> list:
    return sorted(tuple(r.values()) for r in result.to_pylist())


class TestCohortFusion:
    def test_flood_smoke_fused_and_correct(self):
        """Tier-1 flood smoke: a burst of param-varied dashboard queries
        through the batcher serves from ONE fused dispatch and every
        member's answer matches its solo execution."""
        db = _dash_db()
        proxy = _batch_proxy(db, max_cohort=8)
        try:
            sqls = [
                f"SELECT host, count(v), sum(v) FROM dash "
                f"WHERE ts >= {1000 + i * 10} AND ts < 1400 GROUP BY host"
                for i in range(8)
            ]
            expected = {s: _rows(proxy.handle_sql(s)) for s in sqls}
            fused0 = _counter("horaedb_batch_dispatch_total", kind="fused")
            out = _run_concurrent(proxy, sqls)
            for s in sqls:
                assert not isinstance(out[s], BaseException), out[s]
                assert _rows(out[s]) == expected[s]
            assert (
                _counter("horaedb_batch_dispatch_total", kind="fused")
                >= fused0 + 1
            )
            # ledger roles: one leader row carrying the cohort size,
            # members carrying batch_member, all carrying batch_cohort
            recent = [
                r for r in STATS_STORE.list() if r.get("batch_cohort")
            ]
            assert any(r["batch_leader"] >= 2 for r in recent)
            assert any(r["batch_member"] == 1 for r in recent)
        finally:
            proxy.close()
            db.close()

    def test_mixed_limits_demux_per_member(self):
        """Mixed LIMITs share one shape (LIMIT is masked in the cohort
        key) and one fused dispatch; each member's LIMIT applies to ITS
        demuxed result."""
        db = _dash_db(hosts=6)
        proxy = _batch_proxy(db, max_cohort=4)
        try:
            sqls = [
                f"SELECT host, sum(v) FROM dash GROUP BY host "
                f"ORDER BY host LIMIT {k}"
                for k in (1, 2, 3, 4)
            ]
            for s in sqls:  # warm cache + solo answers
                proxy.handle_sql(s)
            fused0 = _counter("horaedb_batch_dispatch_total", kind="fused")
            out = _run_concurrent(proxy, sqls)
            for k, s in zip((1, 2, 3, 4), sqls):
                assert not isinstance(out[s], BaseException), out[s]
                assert out[s].num_rows == k
                assert list(out[s].column("host")) == [
                    f"h{i}" for i in range(k)
                ]
            assert (
                _counter("horaedb_batch_dispatch_total", kind="fused")
                == fused0 + 1
            )
        finally:
            proxy.close()
            db.close()

    def test_cohort_of_one_degenerates_to_solo_path(self):
        """A window that gathers a single query runs today's dedup+
        admission path: solo dispatch accounting, no fused dispatch, no
        batch ledger roles."""
        db = _dash_db()
        proxy = _batch_proxy(db, window_s=0.01)
        try:
            sql = "SELECT host, count(v) FROM dash GROUP BY host"
            fused0 = _counter("horaedb_batch_dispatch_total", kind="fused")
            solo0 = _counter("horaedb_batch_dispatch_total", kind="solo")
            out = proxy.handle_sql(sql)
            assert out.num_rows == 6
            assert _counter("horaedb_batch_dispatch_total", kind="fused") == fused0
            assert _counter("horaedb_batch_dispatch_total", kind="solo") == solo0 + 1
            row = STATS_STORE.list()[-1]
            assert row["batch_cohort"] == 0 and row["batch_member"] == 0
        finally:
            proxy.close()
            db.close()

    def test_identical_twins_coalesce_inside_cohort(self):
        """Members with the SAME sql share one cohort slot (the dedup
        contract survives inside the batch layer)."""
        db = _dash_db()
        proxy = _batch_proxy(db, max_cohort=3)
        try:
            twin = "SELECT host, sum(v) FROM dash GROUP BY host"
            other = (
                "SELECT host, sum(v) FROM dash WHERE ts >= 1100 GROUP BY host"
            )
            expected_twin = _rows(proxy.handle_sql(twin))
            dedup0 = _counter(
                "horaedb_admission_dedup_total", role="follower"
            )
            out: dict = {}

            def worker(tag, sql):
                out[tag] = proxy.handle_sql(sql)

            threads = [
                threading.Thread(target=worker, args=("a", twin)),
                threading.Thread(target=worker, args=("b", twin)),
                threading.Thread(target=worker, args=("c", other)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert _rows(out["a"]) == expected_twin
            assert _rows(out["b"]) == expected_twin
            assert (
                _counter("horaedb_admission_dedup_total", role="follower")
                >= dedup0 + 1
            )
        finally:
            proxy.close()
            db.close()

    def test_disabled_batcher_is_inert(self):
        """[wlm.batch] enabled=false (the default) reproduces today's
        behavior: no batch metrics move, no batch ledger roles."""
        db = _dash_db()
        proxy = Proxy(db)  # no batch_cfg: disabled
        try:
            fused0 = _counter("horaedb_batch_dispatch_total", kind="fused")
            solo0 = _counter("horaedb_batch_dispatch_total", kind="solo")
            sqls = [
                f"SELECT host, count(v) FROM dash WHERE ts >= {1000 + i * 10} "
                "GROUP BY host"
                for i in range(4)
            ]
            out = _run_concurrent(proxy, sqls)
            assert all(not isinstance(v, BaseException) for v in out.values())
            assert _counter("horaedb_batch_dispatch_total", kind="fused") == fused0
            assert _counter("horaedb_batch_dispatch_total", kind="solo") == solo0
        finally:
            proxy.close()
            db.close()

    def test_shapes_filter_restricts_eligibility(self):
        db = _dash_db()
        proxy = _batch_proxy(db, shapes=["from other_table"])
        try:
            assert not proxy.wlm.batch.eligible(
                db._cached_plan("SELECT host, sum(v) FROM dash GROUP BY host"),
                "select host, sum(v) from dash group by host",
            )
        finally:
            proxy.close()
            db.close()


class TestCorrectnessRails:
    def test_write_mid_window_fences_fresh_cohort(self):
        """Regression (read-your-writes across the window): a write
        landing while a cohort is forming must fence later-arriving
        members into a FRESH cohort — two fused size-2 cohorts, never
        one of size 4 — and the post-write members must see the row."""
        db = _dash_db()
        proxy = _batch_proxy(db, window_s=0.6, max_cohort=2)
        try:
            pre = [
                "SELECT host, count(v) FROM dash WHERE ts < 9000 GROUP BY host",
                "SELECT host, count(v) FROM dash WHERE ts < 9100 GROUP BY host",
            ]
            post = [
                "SELECT host, count(v) FROM dash WHERE ts < 9200 GROUP BY host",
                "SELECT host, count(v) FROM dash WHERE ts < 9300 GROUP BY host",
            ]
            size2_0 = _counter("horaedb_batch_cohort_total", size="2")
            size4_0 = _counter("horaedb_batch_cohort_total", size="4")
            out: dict = {}

            def worker(sql):
                out[sql] = proxy.handle_sql(sql)

            pre_threads = [
                threading.Thread(target=worker, args=(s,)) for s in pre
            ]
            pre_threads[0].start()
            time.sleep(0.1)  # the leader is mid-window
            proxy.handle_sql(
                "INSERT INTO dash (host, v, ts) VALUES ('hNEW', 1.0, 5000)"
            )  # bumps the dedup epoch -> fences the forming key
            post_threads = [
                threading.Thread(target=worker, args=(s,)) for s in post
            ]
            pre_threads[1].start()  # joins whichever epoch is current
            for t in post_threads:
                t.start()
            for t in pre_threads + post_threads:
                t.join()
            for s in post:
                hosts = list(out[s].column("host"))
                assert "hNEW" in hosts, "post-write member missed the write"
            # fencing: the post-write members never shared the pre-write
            # cohort — cohorts stayed at size <= 2, never merged into 4
            assert _counter("horaedb_batch_cohort_total", size="4") == size4_0
            assert _counter("horaedb_batch_cohort_total", size="2") >= size2_0 + 1
        finally:
            proxy.close()
            db.close()

    def test_quota_exceeded_member_does_not_poison_cohort(self):
        """A member shed by its tenant quota mid-window fails alone; the
        rest of the cohort serves normally."""
        db = _dash_db()
        proxy = _batch_proxy(db, max_cohort=3)
        try:
            proxy.wlm.quota.set_quota("tenant", "starved", "read_qps", 0.001, burst=0)
            sqls = [
                f"SELECT host, sum(v) FROM dash WHERE ts >= {1000 + i * 10} "
                "GROUP BY host"
                for i in range(3)
            ]
            out = _run_concurrent(
                proxy, sqls, tenants=["default", "default", "starved"]
            )
            assert isinstance(out[sqls[2]], QuotaExceededError)
            for s in sqls[:2]:
                assert not isinstance(out[s], BaseException), out[s]
                assert out[s].num_rows == 6
        finally:
            proxy.close()
            db.close()

    def test_error_isolation_one_bad_member(self, monkeypatch):
        """A member whose demux/assembly fails inside the fused dispatch
        poisons only its own slot."""
        from horaedb_tpu.query.executor import Executor

        db = _dash_db()
        proxy = _batch_proxy(db, max_cohort=3)
        try:
            orig = Executor._assemble_agg_result

            def poisoned(self, plan, *args, **kw):
                if plan.select.limit == 13:
                    raise RuntimeError("injected member failure")
                return orig(self, plan, *args, **kw)

            monkeypatch.setattr(Executor, "_assemble_agg_result", poisoned)
            base = "SELECT host, sum(v) FROM dash GROUP BY host ORDER BY host"
            sqls = [f"{base} LIMIT {k}" for k in (2, 13, 4)]
            out = _run_concurrent(proxy, sqls)
            bad = out[sqls[1]]
            assert isinstance(bad, RuntimeError)
            assert "injected member failure" in str(bad)
            assert out[sqls[0]].num_rows == 2
            assert out[sqls[2]].num_rows == 4
        finally:
            proxy.close()
            db.close()

    def test_batch_config_section_parses(self, tmp_path):
        from horaedb_tpu.utils.config import Config, ConfigError

        p = tmp_path / "c.toml"
        p.write_text(
            "[wlm.batch]\nenabled = true\nwindow = \"5ms\"\n"
            "max_cohort = 16\nshapes = [\"from dash\"]\n"
        )
        cfg = Config.load(str(p))
        assert cfg.wlm.batch.enabled is True
        assert cfg.wlm.batch.window_s == pytest.approx(0.005)
        assert cfg.wlm.batch.max_cohort == 16
        assert cfg.wlm.batch.shapes == ["from dash"]
        bad = tmp_path / "bad.toml"
        bad.write_text("[wlm.batch]\nmax_cohort = 1\n")
        with pytest.raises(ConfigError):
            Config.load(str(bad))

    def test_workload_snapshot_carries_batch_state(self):
        db = horaedb_tpu.connect(None)
        proxy = _batch_proxy(db, window_s=0.002, max_cohort=4)
        try:
            snap = proxy.wlm.snapshot()["batch"]
            assert snap["enabled"] is True
            assert snap["max_cohort"] == 4
            assert snap["forming_cohorts"] == 0
        finally:
            proxy.close()
            db.close()
