"""Cluster groundwork tests: shard state machine, router, 2-node forwarding
(ref model: cluster shard_set tests + the 2-node sqlness cluster env)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from horaedb_tpu.cluster import Route, RuleBasedRouter, Shard, ShardSet, ShardState
from horaedb_tpu.cluster.router import LocalOnlyRouter
from horaedb_tpu.cluster.shard import ShardError, ShardInfo


class TestShardStateMachine:
    def test_lifecycle(self):
        s = Shard(ShardInfo(shard_id=1, version=1, table_ids=(10,)))
        assert s.state is ShardState.INIT
        s.begin_open()
        assert s.state is ShardState.OPENING
        s.finish_open()
        assert s.state is ShardState.READY
        s.ensure_writable()
        s.freeze()
        with pytest.raises(ShardError, match="write fenced"):
            s.ensure_writable()
        s.thaw()
        s.ensure_writable()
        s.freeze()
        s.close()
        assert s.state is ShardState.INIT

    def test_illegal_transitions(self):
        s = Shard(ShardInfo(shard_id=1))
        with pytest.raises(ShardError):
            s.finish_open()  # not opening
        s.begin_open()
        with pytest.raises(ShardError):
            s.begin_open()  # already opening
        with pytest.raises(ShardError):
            s.freeze()  # not ready

    def test_version_fencing(self):
        s = Shard(ShardInfo(shard_id=1, version=5, table_ids=(1,)))
        with pytest.raises(ShardError, match="stale"):
            s.apply_update(ShardInfo(shard_id=1, version=5, table_ids=(2,)))
        s.apply_update(ShardInfo(shard_id=1, version=6, table_ids=(2,)))
        assert s.table_ids == (2,)

    def test_shard_set(self):
        ss = ShardSet()
        s = Shard(ShardInfo(shard_id=7))
        ss.insert(s)
        with pytest.raises(ShardError):
            ss.insert(Shard(ShardInfo(shard_id=7)))
        assert ss.get(7) is s
        assert ss.ready_count() == 0
        s.begin_open(); s.finish_open()
        assert ss.ready_count() == 1
        assert ss.remove(7) is s
        assert ss.get(7) is None


class TestRouter:
    def test_rule_pins_win(self):
        r = RuleBasedRouter("a:1", ["a:1", "b:2"], {"pinned": "b:2"})
        assert r.route("pinned") == Route("pinned", "b:2", False)

    def test_hash_fallback_stable_and_covering(self):
        r1 = RuleBasedRouter("a:1", ["a:1", "b:2"])
        r2 = RuleBasedRouter("b:2", ["a:1", "b:2"])
        # same topology -> identical routing decisions on every node
        for t in ("t1", "t2", "zzz", "cpu"):
            assert r1.route(t).endpoint == r2.route(t).endpoint
        # both nodes get some tables (hash spreads)
        eps = {r1.route(f"table_{i}").endpoint for i in range(32)}
        assert eps == {"a:1", "b:2"}

    def test_self_must_be_in_topology(self):
        with pytest.raises(ValueError, match="not in topology"):
            RuleBasedRouter("c:3", ["a:1", "b:2"])
        with pytest.raises(ValueError, match="unknown endpoint"):
            RuleBasedRouter("a:1", ["a:1"], {"t": "b:2"})

    def test_local_only(self):
        assert LocalOnlyRouter().route("anything").is_local


# ---- two real nodes over HTTP ------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def write_config(tmp_path, name, port, peer_port, data_dir, rules):
    self_ep = f"127.0.0.1:{port}"
    peer_ep = f"127.0.0.1:{peer_port}"
    rules_lines = "\n".join(f'{t} = "{ep}"' for t, ep in rules.items())
    p = tmp_path / f"{name}.toml"
    p.write_text(f"""
[server]
http_port = {port}

[engine]
data_dir = "{data_dir}"

[cluster]
self_endpoint = "{self_ep}"
endpoints = ["127.0.0.1:{min(port, peer_port)}", "127.0.0.1:{max(port, peer_port)}"]

[cluster.rules]
{rules_lines}
""")
    return str(p)


def start_node(config_path) -> subprocess.Popen:
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "horaedb_tpu.server", "--config", config_path],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_healthy(port, proc, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            return
        except Exception:
            if proc.poll() is not None:
                raise RuntimeError("node died during startup")
            time.sleep(0.2)
    raise RuntimeError("node not healthy in time")


def post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@pytest.mark.slow
def test_two_node_forwarding(tmp_path):
    port_a, port_b = free_port(), free_port()
    # 'demo' pinned to node B; everything else hashes over both.
    rules = {"demo": f"127.0.0.1:{port_b}"}
    cfg_a = write_config(tmp_path, "a", port_a, port_b, tmp_path / "da", rules)
    cfg_b = write_config(tmp_path, "b", port_b, port_a, tmp_path / "db", rules)
    pa, pb = start_node(cfg_a), start_node(cfg_b)
    try:
        wait_healthy(port_a, pa)
        wait_healthy(port_b, pb)

        # DDL sent to node A forwards to owner B.
        status, out = post(port_a, "/sql", {"query": (
            "CREATE TABLE demo (h string TAG, v double NOT NULL, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts))"
        )})
        assert status == 200 and out == {"affected_rows": 0}

        # Writes via A land on B; query via A reads them back.
        status, out = post(port_a, "/write", {"table": "demo", "rows": [
            {"h": "x", "v": 1.5, "ts": 1000}, {"h": "y", "v": 2.5, "ts": 2000},
        ]})
        assert status == 200 and out == {"affected_rows": 2}
        status, out = post(port_a, "/sql", {"query": "SELECT count(*) AS c FROM demo"})
        assert out["rows"] == [{"c": 2}]

        # The data REALLY lives on B only: B answers locally,
        # and B's debug view has the table while A's doesn't.
        status, out = post(port_b, "/sql", {"query": "SELECT max(v) AS m FROM demo"})
        assert out["rows"] == [{"m": 2.5}]
        tables_a = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port_a}/debug/tables", timeout=5).read()
        )
        tables_b = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port_b}/debug/tables", timeout=5).read()
        )
        assert "demo" not in tables_a and "demo" in tables_b

        # /route reports the owner from both nodes.
        ra = json.loads(
            urllib.request.urlopen(f"http://127.0.0.1:{port_a}/route/demo", timeout=5).read()
        )
        assert ra["routes"][0]["endpoint"] == f"127.0.0.1:{port_b}"
        assert ra["routes"][0]["is_local"] is False
    finally:
        for p in (pa, pb):
            p.send_signal(signal.SIGKILL)
            p.wait()
