"""InfluxDB line protocol + OpenTSDB put tests
(ref model: proxy influxdb/opentsdb unit tests + protocol suites)."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

import horaedb_tpu
from horaedb_tpu.proxy.influxdb import LineProtocolError, parse_lines
from horaedb_tpu.proxy.opentsdb import OpenTsdbError, parse_put
from horaedb_tpu.server import create_app


class TestLineProtocolParser:
    def test_basic(self):
        pts = parse_lines("cpu,host=h1,region=west usage=0.5,idle=99i 1700000000000", "ms")
        p = pts[0]
        assert p.measurement == "cpu"
        assert p.tags == {"host": "h1", "region": "west"}
        assert p.fields == {"usage": 0.5, "idle": 99}
        assert p.timestamp_ms == 1700000000000

    def test_precision_conversion(self):
        assert parse_lines("m v=1 1700000000000000000", "ns")[0].timestamp_ms == 1700000000000
        assert parse_lines("m v=1 1700000000", "s")[0].timestamp_ms == 1700000000000

    def test_escapes_and_quotes(self):
        pts = parse_lines(r'my\ table,ta\,g=va\=l msg="hello, \"world\"",ok=t', "ns")
        p = pts[0]
        assert p.measurement == "my table"
        assert p.tags == {"ta,g": "va=l"}
        assert p.fields == {"msg": 'hello, "world"', "ok": True}
        assert p.timestamp_ms is None

    def test_multi_line_and_comments(self):
        body = "# comment\ncpu v=1\n\ncpu v=2 100\n"
        pts = parse_lines(body, "ms")
        assert len(pts) == 2 and pts[1].timestamp_ms == 100

    @pytest.mark.parametrize(
        "bad",
        [
            "cpu",  # no fields
            "cpu v=",  # empty value
            'cpu v="unterminated',  # quote
            "cpu, v=1",  # empty tag
            "cpu v=1 2 3",  # too many sections
            "cpu v=abc",  # bad value
        ],
    )
    def test_errors_located(self, bad):
        with pytest.raises(LineProtocolError, match="line 1"):
            parse_lines(bad, "ns")


class TestOpenTsdbParser:
    def test_single_and_batch(self):
        one = parse_put({"metric": "m", "timestamp": 1356998400, "value": 1.5, "tags": {"h": "a"}})
        assert one[0]["timestamp"] == 1356998400000  # seconds -> ms
        two = parse_put([
            {"metric": "m", "timestamp": 1700000000000, "value": 2, "tags": {}},
            {"metric": "m2", "timestamp": 1700000000, "value": 3, "tags": {"x": "y"}},
        ])
        assert two[0]["timestamp"] == 1700000000000  # already ms

    @pytest.mark.parametrize(
        "bad",
        [
            {"timestamp": 1, "value": 1},  # no metric
            {"metric": "m", "timestamp": 1},  # no value
            {"metric": "m", "timestamp": "x", "value": 1},  # bad ts
            {"metric": "m", "timestamp": 1, "value": True},  # bool value
            {"metric": "m", "timestamp": 1, "value": 1, "tags": {"a": 1}},  # non-str tag
        ],
    )
    def test_errors(self, bad):
        with pytest.raises(OpenTsdbError):
            parse_put(bad)


def with_client(coro_fn):
    async def runner():
        conn = horaedb_tpu.connect(None)
        app = create_app(conn)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await coro_fn(client, conn)
        finally:
            await client.close()
            conn.close()

    asyncio.run(runner())


class TestInfluxEndpoint:
    def test_write_auto_creates_and_queries(self):
        async def body(client, conn):
            lines = (
                "cpu,host=h1 usage=0.5,idle=10i 1700000000000\n"
                "cpu,host=h2 usage=0.7 1700000001000\n"
                "mem,host=h1 used=123.0 1700000000000\n"
            )
            resp = await client.post("/influxdb/v1/write?precision=ms", data=lines)
            assert resp.status == 204
            out = await client.post(
                "/sql", json={"query": "SELECT host, usage FROM cpu ORDER BY host"}
            )
            rows = (await out.json())["rows"]
            assert rows == [
                {"host": "h1", "usage": 0.5},
                {"host": "h2", "usage": 0.7},
            ]
            out = await client.post("/sql", json={"query": "SELECT count(*) AS c FROM mem"})
            assert (await out.json())["rows"] == [{"c": 1}]

        with_client(body)

    def test_schema_evolves_for_new_fields(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="m,h=a v=1 100")
            await client.post("/influxdb/v1/write?precision=ms", data="m,h=a v=2,extra=9 200")
            out = await client.post(
                "/sql", json={"query": "SELECT extra FROM m ORDER BY time"}
            )
            rows = (await out.json())["rows"]
            assert rows == [{"extra": None}, {"extra": 9.0}]

        with_client(body)

    def test_bad_lines_rejected(self):
        async def body(client, conn):
            resp = await client.post("/influxdb/v1/write", data="cpu nofields")
            assert resp.status == 400
            assert "line 1" in (await resp.json())["error"]

        with_client(body)

    def test_ns_precision_exact(self):
        # ns values exceed float53: must use integer floor-div (review regression)
        pts = parse_lines("m v=1 1700000000189000029", "ns")
        assert pts[0].timestamp_ms == 1700000000189
        assert parse_lines("m v=1 28333333", "m")[0].timestamp_ms == 28333333 * 60_000

    def test_reserved_time_name_rejected(self):
        with pytest.raises(LineProtocolError, match="reserved"):
            parse_lines("cpu time=5 100", "ms")
        with pytest.raises(LineProtocolError, match="reserved"):
            parse_lines("cpu,time=x v=5 100", "ms")

    def test_blocked_table_rejected_on_protocol_writes(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="cpu v=1 100")
            await client.post("/admin/block", json={"tables": ["cpu"]})
            resp = await client.post("/influxdb/v1/write?precision=ms", data="cpu v=2 200")
            assert resp.status == 403
            resp = await client.post(
                "/opentsdb/api/put",
                json={"metric": "cpu", "timestamp": 1, "value": 1.0, "tags": {}},
            )
            assert resp.status == 403

        with_client(body)

    def test_order_by_alias_still_works(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="m v=3 100\nm v=1 200\nm v=2 300")
            out = await client.post(
                "/sql", json={"query": "SELECT v AS x FROM m ORDER BY x DESC"}
            )
            rows = (await out.json())["rows"]
            assert [r["x"] for r in rows] == [3.0, 2.0, 1.0]

        with_client(body)


class TestOpenTsdbEndpoint:
    def test_put_and_query(self):
        async def body(client, conn):
            resp = await client.post(
                "/opentsdb/api/put",
                json=[
                    {"metric": "sys.cpu", "timestamp": 1356998400, "value": 42.5,
                     "tags": {"host": "web01"}},
                    {"metric": "sys.cpu", "timestamp": 1356998460, "value": 43.0,
                     "tags": {"host": "web01"}},
                ],
            )
            assert resp.status == 204
            out = await client.post(
                "/sql",
                json={"query": 'SELECT avg(value) AS a FROM "sys.cpu" GROUP BY host'},
            )
            assert (await out.json())["rows"] == [{"a": 42.75}]

        with_client(body)

    def test_bad_put(self):
        async def body(client, conn):
            resp = await client.post("/opentsdb/api/put", json={"metric": "m"})
            assert resp.status == 400

        with_client(body)


class TestInfluxQLQuery:
    """InfluxQL SELECT subset -> the v1 /query response shape
    (ref corpus: integration_tests/cases/env/local/influxql/basic.sql)."""

    def _seed(self):
        async def seed(client, conn):
            conn.execute(
                "CREATE TABLE h2o (level string TAG, location string TAG, "
                "water_level double, time timestamp NOT NULL, "
                "TIMESTAMP KEY(time)) ENGINE=Analytic"
            )
            conn.execute(
                "INSERT INTO h2o (level, location, water_level, time) VALUES "
                "('mid', 'coyote_creek', 8.12, 1439827200000), "
                "('low', 'santa_monica', 2.064, 1439827200000), "
                "('mid', 'coyote_creek', 8.005, 1439827560000), "
                "('low', 'santa_monica', 2.116, 1439827560000), "
                "('mid', 'coyote_creek', 7.887, 1439827620000), "
                "('low', 'santa_monica', 2.028, 1439827620000)"
            )
        return seed

    def test_select_star(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            resp = await client.get(
                "/influxdb/v1/query", params={"q": 'SELECT * FROM "h2o"'}
            )
            assert resp.status == 200
            series = (await resp.json())["results"][0]["series"][0]
            assert series["name"] == "h2o"
            assert series["columns"][0] == "time"
            assert len(series["values"]) == 6

        with_client(body)

    def test_filter_and_projection(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            resp = await client.get(
                "/influxdb/v1/query",
                params={"q": "SELECT water_level FROM h2o WHERE location = 'santa_monica'"},
            )
            series = (await resp.json())["results"][0]["series"][0]
            assert [v[1] for v in series["values"]] == [2.064, 2.116, 2.028]

        with_client(body)

    def test_group_by_tag_count(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            resp = await client.get(
                "/influxdb/v1/query",
                params={"q": "SELECT count(water_level) FROM h2o GROUP BY location"},
            )
            series = (await resp.json())["results"][0]["series"]
            got = {s["tags"]["location"]: s["values"][0][1] for s in series}
            assert got == {"coyote_creek": 3, "santa_monica": 3}

        with_client(body)

    def test_group_by_time_with_fill(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            q = (
                "SELECT count(water_level) FROM h2o "
                "WHERE time < 1439828400000ms GROUP BY location, time(5m) FILL(666)"
            )
            resp = await client.get("/influxdb/v1/query", params={"q": q})
            series = (await resp.json())["results"][0]["series"]
            by_loc = {s["tags"]["location"]: s["values"] for s in series}
            # window [floor(first bucket) .. bucket before 1439828400000)
            for loc in ("coyote_creek", "santa_monica"):
                vals = by_loc[loc]
                counts = {v[0]: v[1] for v in vals}
                assert counts[1439827200000] == 1  # 00:00
                assert counts[1439827500000] == 2  # 00:06 + 00:12
                assert counts[1439827800000] == 666  # filled
                assert counts[1439828100000] == 666  # filled

        with_client(body)

    def test_show_measurements(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            resp = await client.get(
                "/influxdb/v1/query", params={"q": "show measurements"}
            )
            series = (await resp.json())["results"][0]["series"][0]
            assert ["h2o"] in series["values"]

        with_client(body)

    def test_mean_alias_and_limit(self):
        async def body(client, conn):
            await self._seed()(client, conn)
            resp = await client.get(
                "/influxdb/v1/query",
                params={"q": "SELECT mean(water_level) FROM h2o GROUP BY location"},
            )
            series = (await resp.json())["results"][0]["series"]
            got = {s["tags"]["location"]: s["values"][0][1] for s in series}
            assert abs(got["santa_monica"] - (2.064 + 2.116 + 2.028) / 3) < 1e-5
            assert series[0]["columns"] == ["time", "mean"]

        with_client(body)

    def test_parse_errors(self):
        async def body(client, conn):
            resp = await client.get(
                "/influxdb/v1/query", params={"q": "SELEC nope"}
            )
            assert resp.status == 400

        with_client(body)


class TestOpenTsdbQuery:
    def test_downsample_and_aggregate(self):
        async def body(client, conn):
            # two series of metric m: h1 and h2
            put = [
                {"metric": "m", "timestamp": 1700000000, "value": 1.0, "tags": {"host": "h1"}},
                {"metric": "m", "timestamp": 1700000010, "value": 3.0, "tags": {"host": "h1"}},
                {"metric": "m", "timestamp": 1700000000, "value": 10.0, "tags": {"host": "h2"}},
                {"metric": "m", "timestamp": 1700000070, "value": 5.0, "tags": {"host": "h1"}},
            ]
            resp = await client.post("/opentsdb/api/put", json=put)
            assert resp.status == 204
            q = {
                "start": 1699999000,
                "end": 1700001000,
                "queries": [
                    {"metric": "m", "aggregator": "sum", "downsample": "60s-avg"}
                ],
            }
            resp = await client.post("/opentsdb/api/query", json=q)
            assert resp.status == 200
            out = (await resp.json())[0]
            # bucket 1700000000-: h1 avg(1,3)=2, h2 avg(10)=10 -> sum 12
            # bucket 1700000060-: h1 avg(5)=5
            b0 = str(1700000000 // 60 * 60)
            b1 = str(1700000060 // 60 * 60)
            assert out["dps"][b0] == 12.0
            assert out["dps"][b1] == 5.0
            assert out["aggregateTags"] == ["host"]

        with_client(body)

    def test_tag_filter(self):
        async def body(client, conn):
            put = [
                {"metric": "m2", "timestamp": 1700000000, "value": 1.0, "tags": {"host": "a"}},
                {"metric": "m2", "timestamp": 1700000000, "value": 9.0, "tags": {"host": "b"}},
            ]
            await client.post("/opentsdb/api/put", json=put)
            q = {
                "start": 1699999000,
                "queries": [{"metric": "m2", "aggregator": "sum", "tags": {"host": "a"}}],
            }
            resp = await client.post("/opentsdb/api/query", json=q)
            out = (await resp.json())[0]
            assert list(out["dps"].values()) == [1.0]
            assert out["tags"] == {"host": "a"}

        with_client(body)


class TestPromRemoteRead:
    def test_round_trip(self):
        from horaedb_tpu.proxy.prom_remote import (
            _emit_field,
            _emit_varint,
            decode_read_request,
        )
        from horaedb_tpu.utils.snappy import compress, decompress

        # build a ReadRequest: one query, __name__ = mm, host != b
        def matcher(op_code, name, value):
            return (
                _emit_field(1, 0, _emit_varint(op_code))
                + _emit_field(2, 2, name.encode())
                + _emit_field(3, 2, value.encode())
            )

        query = (
            _emit_field(1, 0, _emit_varint(1699999000000))
            + _emit_field(2, 0, _emit_varint(1700001000000))
            + _emit_field(3, 2, matcher(0, "__name__", "mm"))
            + _emit_field(3, 2, matcher(1, "host", "b"))
        )
        req = compress(_emit_field(1, 2, query))
        qs = decode_read_request(req)
        assert qs[0]["start_ms"] == 1699999000000
        assert ("!=", "host", "b") in qs[0]["matchers"]

        async def body(client, conn):
            conn.execute(
                "CREATE TABLE mm (host string TAG, value double, "
                "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
            )
            conn.execute(
                "INSERT INTO mm (host, value, ts) VALUES "
                "('a', 1.5, 1700000000000), ('b', 9.0, 1700000000000), "
                "('a', 2.5, 1700000060000)"
            )
            resp = await client.post("/prom/v1/read", data=req)
            assert resp.status == 200, await resp.text()
            raw = await resp.read()
            body_pb = decompress(raw)
            # decode response: results(1) -> timeseries(1) -> labels/samples
            from horaedb_tpu.proxy.prom_remote import _fields
            import struct as _struct

            series = []
            for f, wt, v in _fields(body_pb):
                assert f == 1
                for f2, _, ts_buf in _fields(v):
                    labels, samples = {}, []
                    for f3, _, item in _fields(ts_buf):
                        if f3 == 1:
                            kv = {}
                            for f4, _, x in _fields(item):
                                kv[f4] = x
                            labels[kv[1].decode()] = kv[2].decode()
                        else:
                            val = t = None
                            for f4, w4, x in _fields(item):
                                if f4 == 1:
                                    val = _struct.unpack("<d", x)[0]
                                else:
                                    t = x
                            samples.append((t, val))
                    series.append((labels, samples))
            assert len(series) == 1  # host 'b' excluded by !=
            labels, samples = series[0]
            assert labels["host"] == "a" and labels["__name__"] == "mm"
            assert [(t, v) for t, v in samples] == [
                (1700000000000, 1.5), (1700000060000, 2.5),
            ]

        with_client(body)

    def test_snappy_codec_round_trip(self):
        from horaedb_tpu.utils.snappy import compress, decompress

        for data in (b"", b"x", b"hello world" * 100, bytes(range(256)) * 50):
            assert decompress(compress(data)) == data

    def test_snappy_copy_ops(self):
        from horaedb_tpu.utils.snappy import decompress, _write_uvarint

        # hand-built stream using a 1-byte-offset overlapping copy:
        # literal "ab" then copy len 6 offset 2 -> "abababab"
        stream = _write_uvarint(8) + bytes([(2 - 1) << 2]) + b"ab" + bytes(
            [0b001 | ((6 - 4) << 2)]
        ) + bytes([2])
        assert decompress(stream) == b"abababab"


class TestProtocolReviewRegressions:
    def test_opentsdb_same_second_ms_points_aggregate(self):
        async def body(client, conn):
            put = [
                {"metric": "ms1", "timestamp": 1700000000100, "value": 1.0, "tags": {"h": "a"}},
                {"metric": "ms1", "timestamp": 1700000000900, "value": 2.0, "tags": {"h": "a"}},
            ]
            await client.post("/opentsdb/api/put", json=put)
            q = {"start": 1699999000, "queries": [{"metric": "ms1", "aggregator": "sum"}]}
            resp = await client.post("/opentsdb/api/query", json=q)
            out = (await resp.json())[0]
            assert out["dps"] == {"1700000000": 3.0}, out  # both points folded

        with_client(body)

    def test_opentsdb_quote_in_tag_value(self):
        async def body(client, conn):
            put = [{"metric": "qt", "timestamp": 1700000000, "value": 1.0, "tags": {"h": "o'brien"}}]
            await client.post("/opentsdb/api/put", json=put)
            q = {"start": 1699999000, "queries": [{"metric": "qt", "aggregator": "sum", "tags": {"h": "o'brien"}}]}
            resp = await client.post("/opentsdb/api/query", json=q)
            assert resp.status == 200, await resp.text()
            assert (await resp.json())[0]["dps"] == {"1700000000": 1.0}

        with_client(body)

    def test_prom_remote_read_missing_label_matcher(self):
        from horaedb_tpu.proxy.prom_remote import _run_query

        async def body(client, conn):
            conn.execute(
                "CREATE TABLE t3 (host string TAG, value double, "
                "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
            )
            conn.execute("INSERT INTO t3 (host, value, ts) VALUES ('a', 1.0, 1700000000000)")
            q = {
                "start_ms": 0, "end_ms": 2**42,
                "matchers": [("=", "__name__", "t3"), ("=", "env", "prod")],
            }
            assert _run_query(conn, q) == []  # missing label + non-empty value
            q["matchers"][1] = ("=", "env", "")
            assert len(_run_query(conn, q)) == 1  # empty value matches missing

        with_client(body)

    def test_influxql_order_desc_on_aggregate(self):
        async def body(client, conn):
            conn.execute(
                "CREATE TABLE od (h string TAG, v double, time timestamp NOT NULL, "
                "TIMESTAMP KEY(time)) ENGINE=Analytic"
            )
            conn.execute(
                "INSERT INTO od (h, v, time) VALUES ('a', 1.0, 0), ('a', 2.0, 60000)"
            )
            resp = await client.get(
                "/influxdb/v1/query",
                params={"q": "SELECT mean(v) FROM od GROUP BY time(1m) ORDER BY time DESC"},
            )
            series = (await resp.json())["results"][0]["series"][0]
            assert [v[0] for v in series["values"]] == [60000, 0]

        with_client(body)


class TestInfluxQLShow:
    def test_show_tag_and_field_keys(self):
        async def body(client, conn):
            conn.execute(
                "CREATE TABLE sm (host string TAG, region string TAG, usage double, "
                "idle bigint, time timestamp NOT NULL, TIMESTAMP KEY(time)) ENGINE=Analytic"
            )
            resp = await client.get(
                "/influxdb/v1/query", params={"q": "SHOW TAG KEYS FROM sm"}
            )
            s = (await resp.json())["results"][0]["series"][0]
            assert s["values"] == [["host"], ["region"]]
            resp = await client.get(
                "/influxdb/v1/query", params={"q": "SHOW FIELD KEYS FROM sm"}
            )
            s = (await resp.json())["results"][0]["series"][0]
            # influx fieldType vocabulary, not engine kind names
            assert ["usage", "float"] in s["values"]
            assert ["idle", "integer"] in s["values"]

        with_client(body)

    def test_show_tag_values(self):
        async def body(client, conn):
            conn.execute(
                "CREATE TABLE sv (host string TAG, v double, "
                "time timestamp NOT NULL, TIMESTAMP KEY(time)) ENGINE=Analytic"
            )
            conn.execute(
                "INSERT INTO sv (host, v, time) VALUES ('b', 1, 1), ('a', 2, 2), ('b', 3, 3)"
            )
            resp = await client.get(
                "/influxdb/v1/query",
                params={"q": 'SHOW TAG VALUES FROM sv WITH KEY = "host"'},
            )
            s = (await resp.json())["results"][0]["series"][0]
            assert s["values"] == [["host", "a"], ["host", "b"]]

        with_client(body)


class TestOpenTsdbSuggestLookup:
    """/api/suggest + /api/search/lookup (ref: the OpenTSDB surface the
    reference's opentsdb shim targets)."""

    def _seed(self, conn):
        conn.execute(
            "CREATE TABLE sys_cpu (host string TAG, dc string TAG, "
            "value double, ts timestamp NOT NULL, TIMESTAMP KEY(ts))"
        )
        conn.execute(
            "CREATE TABLE sys_mem (host string TAG, value double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts))"
        )
        conn.execute(
            "INSERT INTO sys_cpu (host, dc, value, ts) VALUES "
            "('a', 'east', 1.0, 1000), ('b', 'west', 2.0, 1000), "
            "('a', 'west', 3.0, 2000)"
        )

    def test_suggest_metrics_tagk_tagv(self):
        async def body(client, conn):
            self._seed(conn)
            resp = await client.get("/opentsdb/api/suggest?type=metrics&q=sys_")
            assert resp.status == 200
            assert await resp.json() == ["sys_cpu", "sys_mem"]
            resp = await client.get("/opentsdb/api/suggest?type=metrics&q=sys_c")
            assert await resp.json() == ["sys_cpu"]
            resp = await client.get("/opentsdb/api/suggest?type=tagk")
            assert set(await resp.json()) == {"host", "dc"}
            resp = await client.get("/opentsdb/api/suggest?type=tagv&q=e")
            assert "east" in await resp.json()
            resp = await client.get("/opentsdb/api/suggest?type=bogus")
            assert resp.status == 400

        with_client(body)

    def test_lookup_post_and_get(self):
        async def body(client, conn):
            self._seed(conn)
            resp = await client.post(
                "/opentsdb/api/search/lookup",
                json={"metric": "sys_cpu", "tags": [{"key": "dc", "value": "west"}]},
            )
            assert resp.status == 200
            out = await resp.json()
            assert out["metric"] == "sys_cpu" and out["totalResults"] == 2
            assert all(r["tags"]["dc"] == "west" for r in out["results"])
            # GET form with m=metric{k=v}
            resp = await client.get(
                "/opentsdb/api/search/lookup?m=sys_cpu{host=a}"
            )
            out = await resp.json()
            assert out["totalResults"] == 2
            assert all(r["tags"]["host"] == "a" for r in out["results"])
            # wildcard matches everything
            resp = await client.get("/opentsdb/api/search/lookup?m=sys_cpu{dc=*}")
            assert (await resp.json())["totalResults"] == 3
            # unknown metric / tag key -> clean 400
            resp = await client.get("/opentsdb/api/search/lookup?m=nope")
            assert resp.status == 400
            resp = await client.post(
                "/opentsdb/api/search/lookup",
                json={"metric": "sys_cpu", "tags": [{"key": "zz", "value": "x"}]},
            )
            assert resp.status == 400

        with_client(body)

    def test_dotted_metric_and_edge_cases(self):
        async def body(client, conn):
            # dotted metric names (the OpenTSDB convention) via /api/put
            resp = await client.post(
                "/opentsdb/api/put",
                json={"metric": "sys.cpu.user", "timestamp": 1,
                      "value": 1.5, "tags": {"host": "x"}},
            )
            assert resp.status == 204, await resp.text()
            resp = await client.get("/opentsdb/api/suggest?type=metrics&q=sys.")
            assert await resp.json() == ["sys.cpu.user"]
            resp = await client.get("/opentsdb/api/suggest?type=tagv&q=x")
            assert "x" in await resp.json()
            resp = await client.get(
                "/opentsdb/api/search/lookup?m=sys.cpu.user{host=x}"
            )
            assert (await resp.json())["totalResults"] == 1
            # tag-less metric is one series
            resp = await client.post(
                "/opentsdb/api/put",
                json={"metric": "bare", "timestamp": 1, "value": 2.0, "tags": {}},
            )
            assert resp.status == 204
            resp = await client.get("/opentsdb/api/search/lookup?m=bare")
            out = await resp.json()
            assert out["totalResults"] == 1 and out["results"][0]["tags"] == {}
            # malformed tag spec / bad limit -> clean 400s
            resp = await client.get("/opentsdb/api/search/lookup?m=bare{host=a")
            assert resp.status == 400
            resp = await client.get("/opentsdb/api/search/lookup?m=bare&limit=zz")
            assert resp.status == 400

        with_client(body)
