"""InfluxDB line protocol + OpenTSDB put tests
(ref model: proxy influxdb/opentsdb unit tests + protocol suites)."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

import horaedb_tpu
from horaedb_tpu.proxy.influxdb import LineProtocolError, parse_lines
from horaedb_tpu.proxy.opentsdb import OpenTsdbError, parse_put
from horaedb_tpu.server import create_app


class TestLineProtocolParser:
    def test_basic(self):
        pts = parse_lines("cpu,host=h1,region=west usage=0.5,idle=99i 1700000000000", "ms")
        p = pts[0]
        assert p.measurement == "cpu"
        assert p.tags == {"host": "h1", "region": "west"}
        assert p.fields == {"usage": 0.5, "idle": 99}
        assert p.timestamp_ms == 1700000000000

    def test_precision_conversion(self):
        assert parse_lines("m v=1 1700000000000000000", "ns")[0].timestamp_ms == 1700000000000
        assert parse_lines("m v=1 1700000000", "s")[0].timestamp_ms == 1700000000000

    def test_escapes_and_quotes(self):
        pts = parse_lines(r'my\ table,ta\,g=va\=l msg="hello, \"world\"",ok=t', "ns")
        p = pts[0]
        assert p.measurement == "my table"
        assert p.tags == {"ta,g": "va=l"}
        assert p.fields == {"msg": 'hello, "world"', "ok": True}
        assert p.timestamp_ms is None

    def test_multi_line_and_comments(self):
        body = "# comment\ncpu v=1\n\ncpu v=2 100\n"
        pts = parse_lines(body, "ms")
        assert len(pts) == 2 and pts[1].timestamp_ms == 100

    @pytest.mark.parametrize(
        "bad",
        [
            "cpu",  # no fields
            "cpu v=",  # empty value
            'cpu v="unterminated',  # quote
            "cpu, v=1",  # empty tag
            "cpu v=1 2 3",  # too many sections
            "cpu v=abc",  # bad value
        ],
    )
    def test_errors_located(self, bad):
        with pytest.raises(LineProtocolError, match="line 1"):
            parse_lines(bad, "ns")


class TestOpenTsdbParser:
    def test_single_and_batch(self):
        one = parse_put({"metric": "m", "timestamp": 1356998400, "value": 1.5, "tags": {"h": "a"}})
        assert one[0]["timestamp"] == 1356998400000  # seconds -> ms
        two = parse_put([
            {"metric": "m", "timestamp": 1700000000000, "value": 2, "tags": {}},
            {"metric": "m2", "timestamp": 1700000000, "value": 3, "tags": {"x": "y"}},
        ])
        assert two[0]["timestamp"] == 1700000000000  # already ms

    @pytest.mark.parametrize(
        "bad",
        [
            {"timestamp": 1, "value": 1},  # no metric
            {"metric": "m", "timestamp": 1},  # no value
            {"metric": "m", "timestamp": "x", "value": 1},  # bad ts
            {"metric": "m", "timestamp": 1, "value": True},  # bool value
            {"metric": "m", "timestamp": 1, "value": 1, "tags": {"a": 1}},  # non-str tag
        ],
    )
    def test_errors(self, bad):
        with pytest.raises(OpenTsdbError):
            parse_put(bad)


def with_client(coro_fn):
    async def runner():
        conn = horaedb_tpu.connect(None)
        app = create_app(conn)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await coro_fn(client, conn)
        finally:
            await client.close()
            conn.close()

    asyncio.run(runner())


class TestInfluxEndpoint:
    def test_write_auto_creates_and_queries(self):
        async def body(client, conn):
            lines = (
                "cpu,host=h1 usage=0.5,idle=10i 1700000000000\n"
                "cpu,host=h2 usage=0.7 1700000001000\n"
                "mem,host=h1 used=123.0 1700000000000\n"
            )
            resp = await client.post("/influxdb/v1/write?precision=ms", data=lines)
            assert resp.status == 204
            out = await client.post(
                "/sql", json={"query": "SELECT host, usage FROM cpu ORDER BY host"}
            )
            rows = (await out.json())["rows"]
            assert rows == [
                {"host": "h1", "usage": 0.5},
                {"host": "h2", "usage": 0.7},
            ]
            out = await client.post("/sql", json={"query": "SELECT count(*) AS c FROM mem"})
            assert (await out.json())["rows"] == [{"c": 1}]

        with_client(body)

    def test_schema_evolves_for_new_fields(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="m,h=a v=1 100")
            await client.post("/influxdb/v1/write?precision=ms", data="m,h=a v=2,extra=9 200")
            out = await client.post(
                "/sql", json={"query": "SELECT extra FROM m ORDER BY time"}
            )
            rows = (await out.json())["rows"]
            assert rows == [{"extra": None}, {"extra": 9.0}]

        with_client(body)

    def test_bad_lines_rejected(self):
        async def body(client, conn):
            resp = await client.post("/influxdb/v1/write", data="cpu nofields")
            assert resp.status == 400
            assert "line 1" in (await resp.json())["error"]

        with_client(body)

    def test_ns_precision_exact(self):
        # ns values exceed float53: must use integer floor-div (review regression)
        pts = parse_lines("m v=1 1700000000189000029", "ns")
        assert pts[0].timestamp_ms == 1700000000189
        assert parse_lines("m v=1 28333333", "m")[0].timestamp_ms == 28333333 * 60_000

    def test_reserved_time_name_rejected(self):
        with pytest.raises(LineProtocolError, match="reserved"):
            parse_lines("cpu time=5 100", "ms")
        with pytest.raises(LineProtocolError, match="reserved"):
            parse_lines("cpu,time=x v=5 100", "ms")

    def test_blocked_table_rejected_on_protocol_writes(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="cpu v=1 100")
            await client.post("/admin/block", json={"tables": ["cpu"]})
            resp = await client.post("/influxdb/v1/write?precision=ms", data="cpu v=2 200")
            assert resp.status == 403
            resp = await client.post(
                "/opentsdb/api/put",
                json={"metric": "cpu", "timestamp": 1, "value": 1.0, "tags": {}},
            )
            assert resp.status == 403

        with_client(body)

    def test_order_by_alias_still_works(self):
        async def body(client, conn):
            await client.post("/influxdb/v1/write?precision=ms", data="m v=3 100\nm v=1 200\nm v=2 300")
            out = await client.post(
                "/sql", json={"query": "SELECT v AS x FROM m ORDER BY x DESC"}
            )
            rows = (await out.json())["rows"]
            assert [r["x"] for r in rows] == [3.0, 2.0, 1.0]

        with_client(body)


class TestOpenTsdbEndpoint:
    def test_put_and_query(self):
        async def body(client, conn):
            resp = await client.post(
                "/opentsdb/api/put",
                json=[
                    {"metric": "sys.cpu", "timestamp": 1356998400, "value": 42.5,
                     "tags": {"host": "web01"}},
                    {"metric": "sys.cpu", "timestamp": 1356998460, "value": 43.0,
                     "tags": {"host": "web01"}},
                ],
            )
            assert resp.status == 204
            out = await client.post(
                "/sql",
                json={"query": 'SELECT avg(value) AS a FROM "sys.cpu" GROUP BY host'},
            )
            assert (await out.json())["rows"] == [{"a": 42.75}]

        with_client(body)

    def test_bad_put(self):
        async def body(client, conn):
            resp = await client.post("/opentsdb/api/put", json={"metric": "m"})
            assert resp.status == 400

        with_client(body)
