"""Kill-and-restart recovery test against real server processes
(ref: integration_tests/recovery/run.sh:30-45 — write, kill -9, restart,
verify; repeat after explicit flush)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def start_server(data_dir: str, port: int) -> subprocess.Popen:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the TPU tunnel untouched
    proc = subprocess.Popen(
        [sys.executable, "-m", "horaedb_tpu.server",
         "--data-dir", data_dir, "--port", str(port)],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=1)
            return proc
        except Exception:
            if proc.poll() is not None:
                raise RuntimeError("server died during startup")
            time.sleep(0.2)
    proc.kill()
    raise RuntimeError("server did not become healthy")


def post(port, path, payload) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read()
        return json.loads(body) if body else {}


@pytest.mark.slow
def test_kill9_recovery_cycle(tmp_path):
    data = str(tmp_path / "data")
    port = free_port()
    proc = start_server(data, port)
    try:
        post(port, "/sql", {"query": (
            "CREATE TABLE r (host string TAG, v double NOT NULL, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) WITH (segment_duration='1h')"
        )})
        post(port, "/write", {"table": "r", "rows": [
            {"host": f"h{i%3}", "v": float(i), "ts": i * 1000} for i in range(50)
        ]})
        # no flush: rows live in WAL + memtable only
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    # Restart 1: WAL replay must recover everything.
    port2 = free_port()
    proc = start_server(data, port2)
    try:
        out = post(port2, "/sql", {"query": "SELECT count(*) AS c, max(v) AS m FROM r"})
        assert out["rows"] == [{"c": 50, "m": 49.0}]
        # More writes + enough volume to reach SSTs via tiny buffer table.
        post(port2, "/write", {"table": "r", "rows": [
            {"host": "hX", "v": 999.0, "ts": 999_000}
        ]})
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    # Restart 2: both the old rows and the post-recovery write survive.
    port3 = free_port()
    proc = start_server(data, port3)
    try:
        out = post(port3, "/sql", {"query": "SELECT count(*) AS c, max(v) AS m FROM r"})
        assert out["rows"] == [{"c": 51, "m": 999.0}]
        out = post(port3, "/sql", {"query": "SELECT v FROM r WHERE host = 'hX'"})
        assert out["rows"] == [{"v": 999.0}]
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
