"""Pallas segment-reduce kernel vs jax.ops.segment_sum ground truth.

Runs in pallas interpret-equivalent mode on the CPU backend (the real-MXU
run needs the chip; see the module docstring's gating note).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horaedb_tpu.ops.pallas_segment import (
    ROW_TILE,
    pad_segments,
    segment_sum_matmul,
)


def reference(seg, mask, values, n_seg):
    seg = np.where(mask, seg, n_seg)
    counts = jax.ops.segment_sum(mask.astype(np.float32), seg, num_segments=n_seg + 1)[:n_seg]
    sums = jax.ops.segment_sum(
        (values * mask[None, :].astype(np.float32)).T, seg, num_segments=n_seg + 1
    )[:n_seg].T
    return np.asarray(counts), np.asarray(sums)


class TestSegmentSumMatmul:
    @pytest.mark.parametrize("n,f,s", [(ROW_TILE, 1, 128), (4 * ROW_TILE, 3, 256)])
    def test_matches_segment_sum(self, n, f, s):
        rng = np.random.default_rng(0)
        seg = rng.integers(0, s, n).astype(np.int32)
        mask = rng.random(n) > 0.25
        values = rng.normal(size=(f, n)).astype(np.float32)

        counts, sums = segment_sum_matmul(
            jnp.asarray(seg), jnp.asarray(mask), jnp.asarray(values), n_seg=s
        )
        rc, rs = reference(seg, mask, values, s)
        np.testing.assert_allclose(np.asarray(counts)[0], rc, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sums), rs, rtol=1e-4, atol=1e-4)

    def test_masked_nan_does_not_poison(self):
        # Review regression: NaN in a masked row must not reach the matmul.
        n, s = ROW_TILE, 128
        v = np.ones((1, n), dtype=np.float32)
        v[0, 5] = np.nan
        mask = np.ones(n, dtype=bool)
        mask[5] = False
        counts, sums = segment_sum_matmul(
            jnp.zeros(n, dtype=jnp.int32), jnp.asarray(mask), jnp.asarray(v), n_seg=s
        )
        assert np.isfinite(np.asarray(sums)).all()
        assert float(np.asarray(sums)[0, 0]) == n - 1

    def test_all_masked(self):
        n, s = ROW_TILE, 128
        counts, sums = segment_sum_matmul(
            jnp.zeros(n, dtype=jnp.int32),
            jnp.zeros(n, dtype=bool),
            jnp.ones((1, n), dtype=jnp.float32),
            n_seg=s,
        )
        assert float(np.asarray(counts).sum()) == 0.0
        assert float(np.asarray(sums).sum()) == 0.0

    def test_pad_segments(self):
        assert pad_segments(1) == 128
        assert pad_segments(128) == 128
        assert pad_segments(129) == 256
