"""S3 backend + disk cache tests.

The fake S3 server implements the protocol subset (GET/Range, PUT, HEAD,
DELETE, ListObjectsV2 with continuation, multipart upload) and VERIFIES
every request's Signature V4 by recomputing it with the known secret —
the tests prove the signing algorithm, not just request plumbing.
"""

from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from horaedb_tpu.utils.object_store import DiskCacheStore, MemoryStore
from horaedb_tpu.utils.s3 import S3Store, sigv4_headers

ACCESS, SECRET, REGION, BUCKET = "AKTEST", "s3cr3t", "us-test-1", "tsdb"


class FakeS3Handler(BaseHTTPRequestHandler):
    objects: dict[str, bytes] = {}
    uploads: dict[str, dict[int, bytes]] = {}
    lock = threading.Lock()
    list_page_size = 2  # force continuation in tests

    def log_message(self, *a):  # quiet
        pass

    # ---- sigv4 verification --------------------------------------------
    def _verify_auth(self, body: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        amz_date = self.headers.get("x-amz-date", "")
        payload_sha = self.headers.get("x-amz-content-sha256", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        # honor the client's SignedHeaders list (e.g. range on GETs)
        signed = ""
        for part in auth.split(", "):
            if part.startswith("SignedHeaders="):
                signed = part[len("SignedHeaders="):]
        extra = {
            name: self.headers.get(name, "")
            for name in signed.split(";")
            if name not in ("host", "x-amz-date", "x-amz-content-sha256")
        }
        url = f"http://{self.headers.get('host')}{self.path}"
        expected = sigv4_headers(
            self.command, url, REGION, ACCESS, SECRET, payload_sha,
            amz_date=amz_date, extra_headers=extra,
        )["Authorization"]
        return auth == expected

    def _deny(self):
        self.send_response(403)
        self.end_headers()
        self.wfile.write(b"<Error>SignatureDoesNotMatch</Error>")

    def _key(self) -> str:
        path = urllib.parse.urlsplit(self.path).path
        assert path.startswith(f"/{BUCKET}")
        return urllib.parse.unquote(path[len(BUCKET) + 2 :])

    # ---- verbs ----------------------------------------------------------
    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify_auth(body):
            return self._deny()
        q = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(self.path).query))
        key = self._key()
        if "partNumber" in q:
            with self.lock:
                self.uploads.setdefault(q["uploadId"], {})[int(q["partNumber"])] = body
            self.send_response(200)
            self.send_header("ETag", f'"part-{q["partNumber"]}"')
            self.end_headers()
            return
        with self.lock:
            self.objects[key] = body
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        if not self._verify_auth(b""):
            return self._deny()
        split = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(split.query))
        if split.path == f"/{BUCKET}" and q.get("list-type") == "2":
            return self._list(q)
        key = self._key()
        with self.lock:
            data = self.objects.get(key)
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        rng = self.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes="):
            lo, _, hi = rng[len("bytes="):].partition("-")
            data = data[int(lo) : int(hi) + 1]
            status = 206
        self.send_response(status)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _list(self, q):
        prefix = q.get("prefix", "")
        token = q.get("continuation-token")
        with self.lock:
            keys = sorted(k for k in self.objects if k.startswith(prefix))
        start = int(token) if token else 0
        page = keys[start : start + self.list_page_size]
        truncated = start + self.list_page_size < len(keys)
        contents = "".join(f"<Contents><Key>{k}</Key></Contents>" for k in page)
        nxt = (
            f"<NextContinuationToken>{start + self.list_page_size}</NextContinuationToken>"
            if truncated
            else ""
        )
        xml = (
            f"<ListBucketResult><IsTruncated>{str(truncated).lower()}</IsTruncated>"
            f"{nxt}{contents}</ListBucketResult>"
        ).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(xml)))
        self.end_headers()
        self.wfile.write(xml)

    def do_HEAD(self):
        if not self._verify_auth(b""):
            return self._deny()
        with self.lock:
            data = self.objects.get(self._key())
        if data is None:
            self.send_response(404)
        else:
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_DELETE(self):
        if not self._verify_auth(b""):
            return self._deny()
        with self.lock:
            self.objects.pop(self._key(), None)
        self.send_response(204)
        self.end_headers()

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify_auth(body):
            return self._deny()
        q = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(self.path).query, keep_blank_values=True))
        key = self._key()
        if "uploads" in q:
            upload_id = f"up-{len(self.uploads) + 1}"
            with self.lock:
                self.uploads[upload_id] = {}
            xml = f"<InitiateMultipartUploadResult><UploadId>{upload_id}</UploadId></InitiateMultipartUploadResult>".encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(xml)))
            self.end_headers()
            self.wfile.write(xml)
            return
        if "uploadId" in q:
            with self.lock:
                parts = self.uploads.pop(q["uploadId"], {})
                self.objects[key] = b"".join(parts[i] for i in sorted(parts))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(400)
        self.end_headers()


@pytest.fixture()
def fake_s3():
    FakeS3Handler.objects = {}
    FakeS3Handler.uploads = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), FakeS3Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def make_store(endpoint, **kw) -> S3Store:
    return S3Store(BUCKET, endpoint, ACCESS, SECRET, region=REGION, **kw)


class TestS3Store:
    def test_put_get_head_delete(self, fake_s3):
        s = make_store(fake_s3)
        s.put("a/b.sst", b"hello world")
        assert s.get("a/b.sst") == b"hello world"
        assert s.head("a/b.sst") == 11
        assert s.exists("a/b.sst")
        s.delete("a/b.sst")
        assert not s.exists("a/b.sst")
        with pytest.raises(FileNotFoundError):
            s.get("a/b.sst")

    def test_get_range(self, fake_s3):
        s = make_store(fake_s3)
        s.put("r", bytes(range(100)))
        assert s.get_range("r", 10, 20) == bytes(range(10, 20))

    def test_list_with_continuation(self, fake_s3):
        s = make_store(fake_s3)
        for i in range(5):
            s.put(f"t/{i}", b"x")
        assert list(s.list("t/")) == [f"t/{i}" for i in range(5)]

    def test_prefix_scoping(self, fake_s3):
        s = make_store(fake_s3, prefix="cluster1")
        s.put("x", b"1")
        assert FakeS3Handler.objects.get("cluster1/x") == b"1"
        assert list(s.list("")) == ["x"]

    def test_bad_secret_rejected(self, fake_s3):
        s = S3Store(BUCKET, fake_s3, ACCESS, "wrong", region=REGION)
        with pytest.raises(Exception):
            s.put("a", b"1")

    def test_multipart_upload(self, fake_s3):
        s = make_store(fake_s3, multipart_threshold=100, multipart_part_size=64)
        data = bytes(i % 251 for i in range(1000))
        s.put("big", data)
        assert s.get("big") == data

    def test_engine_runs_on_s3(self, fake_s3):
        from horaedb_tpu.db import Connection

        conn = Connection(make_store(fake_s3))
        conn.execute(
            "CREATE TABLE s3t (h string TAG, v double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        conn.execute("INSERT INTO s3t (h, v, ts) VALUES ('a', 1.5, 100), ('b', 2.5, 200)")
        conn.flush_all()
        out = conn.execute("SELECT h, v FROM s3t ORDER BY h").to_pylist()
        assert out == [{"h": "a", "v": 1.5}, {"h": "b", "v": 2.5}]
        # cold reopen straight from "cloud" storage
        conn2 = Connection(make_store(fake_s3))
        out = conn2.execute("SELECT count(*) AS c FROM s3t").to_pylist()
        assert out == [{"c": 2}]


class TestDiskCacheStore:
    def test_range_reads_cached_by_page(self, tmp_path):
        inner = MemoryStore()
        inner.put("obj", bytes(range(256)) * 16)  # 4096 bytes
        cache = DiskCacheStore(inner, str(tmp_path / "c"), page_size=1024)
        assert cache.get_range("obj", 100, 200) == (bytes(range(256)) * 16)[100:200]
        assert cache.misses == 1 and cache.hits == 0
        assert cache.get_range("obj", 0, 50) == (bytes(range(256)) * 16)[:50]
        assert cache.hits == 1  # same page
        assert cache.get_range("obj", 1000, 3000) == (bytes(range(256)) * 16)[1000:3000]

    def test_corrupt_page_refetches(self, tmp_path):
        import os

        inner = MemoryStore()
        inner.put("obj", b"A" * 2048)
        cache = DiskCacheStore(inner, str(tmp_path / "c"), page_size=1024)
        cache.get_range("obj", 0, 10)
        # corrupt the cached page on disk
        files = os.listdir(str(tmp_path / "c"))
        with open(str(tmp_path / "c" / files[0]), "r+b") as f:
            f.seek(8)
            f.write(b"\xff\xff")
        assert cache.get_range("obj", 0, 10) == b"A" * 10  # CRC miss -> refetch
        assert cache.misses == 2

    def test_eviction_under_capacity(self, tmp_path):
        inner = MemoryStore()
        inner.put("obj", b"B" * 8192)
        cache = DiskCacheStore(
            inner, str(tmp_path / "c"), page_size=1024, capacity_bytes=2100
        )
        cache.get_range("obj", 0, 8192)  # 8 pages, only ~2 fit
        assert cache._bytes <= 2100

    def test_put_invalidates(self, tmp_path):
        inner = MemoryStore()
        inner.put("obj", b"old" * 400)
        cache = DiskCacheStore(inner, str(tmp_path / "c"), page_size=256)
        assert cache.get_range("obj", 0, 3) == b"old"
        cache.put("obj", b"new" * 400)
        assert cache.get_range("obj", 0, 3) == b"new"

    def test_index_survives_restart(self, tmp_path):
        inner = MemoryStore()
        inner.put("obj", b"C" * 1024)
        cache = DiskCacheStore(inner, str(tmp_path / "c"), page_size=1024)
        cache.get_range("obj", 0, 100)
        cache2 = DiskCacheStore(inner, str(tmp_path / "c"), page_size=1024)
        assert cache2.get_range("obj", 0, 100) == b"C" * 100
        assert cache2.hits == 1 and cache2.misses == 0


class TestServerOnS3:
    def test_server_process_on_s3_with_cold_restart(self, fake_s3, tmp_path):
        """Full node on cloud storage: HTTP writes land in the fake S3,
        a fresh process serves them back (WAL + manifest + SSTs all in
        the bucket — diskless recovery)."""
        import json
        import os
        import socket
        import subprocess
        import sys
        import time
        import urllib.request

        port = _free_port()
        cfg = tmp_path / "s3node.toml"
        cfg.write_text(
            f"""
[server]
host = "127.0.0.1"
http_port = {port}

[s3]
bucket = "{BUCKET}"
endpoint = "{fake_s3}"
region = "{REGION}"
access_key = "{ACCESS}"
secret_key = "{SECRET}"
disk_cache_dir = "{tmp_path}/cache"
"""
        )
        env = {
            **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        }

        def spawn():
            return subprocess.Popen(
                [sys.executable, "-m", "horaedb_tpu.server", "--config", str(cfg)],
                env=env,
                stdout=open(tmp_path / "s3node.log", "wb"),
                stderr=subprocess.STDOUT,
            )

        def sql(q):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/sql",
                data=json.dumps({"query": q}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read().decode())

        def wait_health(deadline=60):
            end = time.monotonic() + deadline
            while time.monotonic() < end:
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/health", timeout=1
                    )
                    return
                except Exception:
                    time.sleep(0.3)
            raise TimeoutError(open(tmp_path / "s3node.log").read()[-2000:])

        p = spawn()
        try:
            wait_health()
            sql(
                "CREATE TABLE cloud (h string TAG, v double, ts timestamp NOT NULL, "
                "TIMESTAMP KEY(ts)) ENGINE=Analytic"
            )
            sql("INSERT INTO cloud (h, v, ts) VALUES ('a', 1.5, 100), ('b', 2.5, 200)")
            # unflushed rows live only in the S3-backed WAL now
        finally:
            p.kill()
            p.wait(timeout=10)
        assert any(k.startswith("wal/") for k in FakeS3Handler.objects), (
            "WAL pages should be in the bucket"
        )
        p = spawn()
        try:
            wait_health()
            out = sql("SELECT h, v FROM cloud ORDER BY h")
            assert out["rows"] == [{"h": "a", "v": 1.5}, {"h": "b", "v": 2.5}]
        finally:
            p.terminate()
            p.wait(timeout=10)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestRemoteConcurrentScan:
    def test_multi_sst_read_from_s3_parallel_and_correct(self, fake_s3):
        from horaedb_tpu.db import Connection
        from horaedb_tpu.engine.instance import EngineConfig

        conn = Connection(
            make_store(fake_s3), config=EngineConfig(compaction_l0_trigger=1000)
        )
        conn.execute(
            "CREATE TABLE par (h string TAG, v double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic WITH (update_mode='append')"
        )
        t = conn.catalog.open("par")
        # several flushes -> several SSTs in the bucket
        for run in range(4):
            conn.execute(
                "INSERT INTO par (h, v, ts) VALUES "
                + ", ".join(f"('h{i%3}', {run * 100 + i}, {1000 + i})" for i in range(50))
            )
            t.flush()
        assert len(t.physical_datas()[0].version.levels.all_files()) >= 4
        out = conn.execute("SELECT count(*) AS c, sum(v) AS s FROM par").to_pylist()
        expect_sum = float(sum(run * 100 + i for run in range(4) for i in range(50)))
        assert out == [{"c": 200, "s": expect_sum}]
