"""Device-resident raw reads (PR 7).

Covers: the randomized device-vs-host equivalence property for
non-aggregate queries (NULL masks, DESC + tie ordering, LIMIT/OFFSET,
empty allow-list, delta-only tables, the HORAEDB_RAW_MAX_ROWS
boundary), the sharded (shard_map) variant, the HORAEDB_RAW_DEVICE
kill switch, ledger/query_stats coverage, the presorted-ORDER-BY
lexsort skip, and the partial-agg kernel-routing satellite.
"""

import numpy as np
import pytest

import horaedb_tpu


@pytest.fixture()
def db():
    conn = horaedb_tpu.connect(None)
    yield conn
    conn.close()


@pytest.fixture(autouse=True)
def _deterministic_raw(monkeypatch):
    """Pin routing off: the learned PathRouter would interleave host
    probes between device servings — correct in production, flaky to
    assert on. Eligibility, budget, and kill-switch fallbacks still
    apply; dedicated tests re-enable routing explicitly."""
    monkeypatch.setenv("HORAEDB_ADAPTIVE_PATH", "0")
    from horaedb_tpu.query.path_router import KERNEL_ROUTER

    KERNEL_ROUTER.reset()
    yield
    KERNEL_ROUTER.reset()


DDL = (
    "CREATE TABLE rd (host string TAG, v double, w double, "
    "ts timestamp NOT NULL, TIMESTAMP KEY(ts))"
)


def _seed(db, n=400, hosts=8, null_w_every=0, ts_step=1000, rng=None):
    db.execute(DDL)
    parts = []
    for i in range(n):
        w = (
            "NULL"
            if null_w_every and i % null_w_every == 0
            else f"{float(3 * i)}"
        )
        v = float(i if rng is None else rng.integers(0, 10 * n))
        parts.append(
            f"('h{i % hosts}', {v}, {w}, {1_700_000_000_000 + i * ts_step})"
        )
    db.execute(f"INSERT INTO rd (host, v, w, ts) VALUES {', '.join(parts)}")


def _warm(db, sql, times=3):
    out = None
    for _ in range(times):
        out = db.execute(sql)
    return out


def _host_ref(db, sql, monkeypatch):
    monkeypatch.setenv("HORAEDB_RAW_DEVICE", "0")
    try:
        return db.execute(sql)
    finally:
        monkeypatch.delenv("HORAEDB_RAW_DEVICE", raising=False)


class TestRawEquivalence:
    """The property: the device raw path must be indistinguishable from
    the host projection path on every eligible query."""

    def test_randomized_topk_and_selection(self, db, monkeypatch):
        rng = np.random.default_rng(42)
        _seed(db, n=500, hosts=10, null_w_every=7)
        filters = ["", "WHERE v < 250", "WHERE v >= 100 AND host IN ('h1', 'h3', 'h5')",
                   "WHERE host = 'h2'", "WHERE v != 123"]
        orders = ["ts DESC", "ts ASC", "v DESC", "v ASC"]
        for trial in range(16):
            where = filters[trial % len(filters)]
            order = orders[trial % len(orders)]
            limit = int(rng.integers(1, 60))
            offset = int(rng.integers(0, 20)) if trial % 3 == 0 else 0
            sql = (
                f"SELECT host, v, w, ts FROM rd {where} ORDER BY {order} "
                f"LIMIT {limit}"
                + (f" OFFSET {offset}" if offset else "")
            )
            got = _warm(db, sql)
            assert got.metrics.get("path") == "raw_device", sql
            assert got.metrics.get("raw_kernel") == "topk", sql
            ref = _host_ref(db, sql, monkeypatch)
            assert ref.metrics.get("path") == "host"
            assert got.to_pylist() == ref.to_pylist(), sql

    def test_selection_multikey_and_no_limit(self, db, monkeypatch):
        _seed(db, n=300, hosts=6, null_w_every=11)
        for sql in (
            "SELECT host, v, w FROM rd WHERE v < 120 ORDER BY host ASC, v DESC",
            "SELECT host, v FROM rd WHERE v >= 250 ORDER BY v ASC, host DESC LIMIT 20 OFFSET 5",
            "SELECT DISTINCT host FROM rd WHERE v < 50 ORDER BY host",
        ):
            got = _warm(db, sql)
            assert got.metrics.get("path") == "raw_device", sql
            assert got.metrics.get("raw_kernel") == "select", sql
            assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist(), sql

    def test_desc_ties_select_equivalent_keys(self, db, monkeypatch):
        """Duplicate ORDER BY keys: which tied rows cross the LIMIT
        boundary is unspecified SQL, and host read order differs from
        the resident layout — assert on the KEY multiset and the
        predicate instead of exact row identity."""
        db.execute(DDL)
        rows = ", ".join(
            f"('h{i % 4}', {float(i % 5)}, {float(i)}, "
            f"{1_700_000_000_000 + i * 1000})"
            for i in range(200)
        )
        db.execute(f"INSERT INTO rd (host, v, w, ts) VALUES {rows}")
        sql = "SELECT v, w FROM rd WHERE w < 150 ORDER BY v DESC LIMIT 30"
        got = _warm(db, sql)
        assert got.metrics.get("path") == "raw_device"
        ref = _host_ref(db, sql, monkeypatch)
        g, r = got.to_pylist(), ref.to_pylist()
        assert [x["v"] for x in g] == [x["v"] for x in r]
        assert all(x["w"] < 150 for x in g)
        assert len(set((x["v"], x["w"]) for x in g)) == len(g)

    def test_null_in_order_column_falls_back(self, db, monkeypatch):
        """NULLs in the ORDER BY / filter column: resident columns hold
        fill values there — the device path must refuse and the host
        path must serve the 3-valued semantics."""
        db.execute(DDL)
        rows = ", ".join(
            f"('h{i % 3}', {float(i)}, "
            + ("NULL" if i % 2 else f"{float(i)}")
            + f", {1_700_000_000_000 + i * 1000})"
            for i in range(60)
        )
        db.execute(f"INSERT INTO rd (host, v, w, ts) VALUES {rows}")
        sql = "SELECT host, w FROM rd ORDER BY w DESC LIMIT 10"
        got = _warm(db, sql)
        assert got.metrics.get("path") == "host"
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()

    def test_empty_allow_list(self, db, monkeypatch):
        _seed(db, n=100)
        sql = "SELECT host, v FROM rd WHERE host = 'nope' ORDER BY ts DESC LIMIT 5"
        got = _warm(db, sql)
        assert got.metrics.get("path") == "raw_device"
        assert got.num_rows == 0
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()

    def test_time_range_and_empty_range(self, db, monkeypatch):
        _seed(db, n=200)
        base = 1_700_000_000_000
        for sql in (
            f"SELECT v, ts FROM rd WHERE ts >= {base + 50_000} AND "
            f"ts < {base + 150_000} ORDER BY ts DESC LIMIT 20",
            f"SELECT v, ts FROM rd WHERE ts >= {base + 10_000_000} "
            "ORDER BY ts DESC LIMIT 20",
        ):
            got = _warm(db, sql)
            assert got.metrics.get("path") == "raw_device", sql
            assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist(), sql

    def test_delta_rows_including_new_series(self, db, monkeypatch):
        """Writes after the cache build fold in exactly — including a
        series the base has never seen."""
        _seed(db, n=120, ts_step=1000)
        sql = "SELECT host, v, ts FROM rd ORDER BY ts DESC LIMIT 10"
        out = _warm(db, sql)
        assert out.metrics.get("cache") in ("build", "hit")
        newer = 1_700_000_000_000 + 500 * 1000
        db.execute(
            f"INSERT INTO rd (host, v, w, ts) VALUES "
            f"('brand_new', 9001.0, 1.0, {newer}), "
            f"('h1', 9002.0, 2.0, {newer + 1000})"
        )
        got = db.execute(sql)
        assert got.metrics.get("path") == "raw_device"
        assert got.metrics.get("delta_rows") == 2
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()
        hosts = [r["host"] for r in got.to_pylist()]
        assert hosts[:2] == ["h1", "brand_new"]

    def test_overwrite_shadowing_delta_falls_back(self, db, monkeypatch):
        """An OVERWRITE-mode delta row that could shadow a cached base
        row makes the union unsound — the device path must refuse."""
        _seed(db, n=80)
        sql = "SELECT host, v, ts FROM rd ORDER BY ts DESC LIMIT 5"
        _warm(db, sql)
        # same (series, ts) key as an existing base row -> overwrite
        db.execute(
            "INSERT INTO rd (host, v, w, ts) VALUES "
            f"('h1', 7777.0, 1.0, {1_700_000_000_000 + 1 * 1000})"
        )
        got = db.execute(sql)
        assert got.metrics.get("path") == "host"
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()

    def test_raw_max_rows_boundary(self, db, monkeypatch):
        """Selection shapes estimate their exact candidate bound; over
        the budget the host serves, at/under it the device does."""
        _seed(db, n=200, hosts=4)
        sql = "SELECT host, v FROM rd ORDER BY host ASC, v ASC"  # multikey: selection
        monkeypatch.setenv("HORAEDB_RAW_MAX_ROWS", "10")  # 200 > 10
        got = _warm(db, sql)
        assert got.metrics.get("path") == "host"
        monkeypatch.setenv("HORAEDB_RAW_MAX_ROWS", "200")  # exactly at bound
        got = _warm(db, sql)
        assert got.metrics.get("path") == "raw_device"
        assert got.metrics.get("raw_kernel") == "select"
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()

    def test_limit_pushdown_shape_stays_host(self, db):
        """LIMIT with no ORDER BY and no residual stops the host scan at
        LIMIT rows — the device path must not claim it."""
        _seed(db, n=100)
        sql = "SELECT host, v FROM rd LIMIT 5"
        out = _warm(db, sql)
        assert out.metrics.get("path") == "host"
        assert "raw_kernel" not in out.metrics
        assert out.num_rows == 5


class TestRawSharded:
    """The shard_map variant: entries sharded over the (8-device CPU)
    mesh serve raw reads with per-shard kernels + host combine."""

    @pytest.fixture(autouse=True)
    def _small_dist_floor(self, monkeypatch):
        monkeypatch.setenv("HORAEDB_DIST_MIN_ROWS", "1")

    def test_sharded_topk_and_selection(self, db, monkeypatch):
        _seed(db, n=400, hosts=8)
        for sql, kind in (
            ("SELECT host, v, ts FROM rd WHERE v < 333 ORDER BY ts DESC LIMIT 17", "topk"),
            ("SELECT host, v, ts FROM rd WHERE v >= 100 ORDER BY v ASC LIMIT 23 OFFSET 3", "topk"),
            ("SELECT host, v FROM rd WHERE v < 150 ORDER BY host ASC, v DESC", "select"),
        ):
            got = _warm(db, sql)
            assert got.metrics.get("path") == "raw_device", sql
            assert got.metrics.get("raw_kernel") == kind, sql
            assert got.metrics.get("mesh_devices") == 8, sql
            ref = _host_ref(db, sql, monkeypatch)
            assert got.to_pylist() == ref.to_pylist(), sql
        entry = db.interpreters.executor.scan_cache._entries.get("rd")
        assert entry is not None and entry.mesh is not None

    def test_limit_exceeding_shard_length_loses_no_rows(self, db, monkeypatch):
        """Review regression: per-shard k clamps to the shard length, so
        the merged union must be cut at the REQUESTED limit+offset — the
        old cut at the clamped k silently dropped rows whenever
        limit+offset exceeded one shard's row count."""
        _seed(db, n=2000, hosts=8)  # pads to 4096 -> 512 rows/shard
        sql = "SELECT v, ts FROM rd WHERE v < 1900 ORDER BY ts DESC LIMIT 700"
        got = _warm(db, sql)
        assert got.metrics.get("path") == "raw_device"
        assert got.metrics.get("raw_kernel") == "topk"
        assert got.metrics.get("mesh_devices") == 8
        assert got.num_rows == 700
        assert got.to_pylist() == _host_ref(db, sql, monkeypatch).to_pylist()

    def test_sharded_matches_single_device(self, db, monkeypatch):
        """Same query, sharded vs single-device entry: identical rows."""
        _seed(db, n=300, hosts=6)
        sql = "SELECT host, v, ts FROM rd WHERE v < 222 ORDER BY ts DESC LIMIT 11"
        sharded = _warm(db, sql).to_pylist()
        db.interpreters.executor.scan_cache.invalidate("rd")
        monkeypatch.setenv("HORAEDB_DIST_MIN_ROWS", "1000000")
        single = _warm(db, sql)
        assert single.metrics.get("path") == "raw_device"
        assert "mesh_devices" not in single.metrics
        assert sharded == single.to_pylist()


class TestFloatKeyNaN:
    def _seed_with_nan(self, db, n=60, nan_every=4):
        from horaedb_tpu.common_types import RowGroup
        from horaedb_tpu.common_types.schema import compute_tsid

        db.execute(DDL)
        hosts = np.array([f"h{i % 4}" for i in range(n)], dtype=object)
        v = np.arange(n, dtype=np.float64)
        v[::nan_every] = np.nan
        schema = db.catalog.open("rd").schema
        rows = RowGroup(
            schema,
            {
                "tsid": compute_tsid([hosts]),
                "host": hosts,
                "v": v,
                "w": np.ones(n),
                "ts": (1_700_000_000_000 + np.arange(n) * 1000).astype(np.int64),
            },
        )
        db.catalog.open("rd").write(rows)

    def test_nan_sorts_last_both_directions(self, db, monkeypatch):
        """Review regression: the f32->int32 bit transform ranks NaN
        above +inf, but np.lexsort (the host reference) places NaN LAST
        in both directions — the device key must pin NaN to the bottom
        or a DESC top-k returns NaN rows instead of the real maxima."""
        self._seed_with_nan(db)
        for sql in (
            "SELECT v, ts FROM rd ORDER BY v DESC LIMIT 8",
            "SELECT v, ts FROM rd ORDER BY v ASC LIMIT 8",
        ):
            got = _warm(db, sql)
            assert got.metrics.get("path") == "raw_device", sql
            vals = [r["v"] for r in got.to_pylist()]
            assert not any(np.isnan(x) for x in vals), (sql, vals)
            ref = [r["v"] for r in _host_ref(db, sql, monkeypatch).to_pylist()]
            assert vals == ref, sql

    def test_limit_past_real_values_includes_nans_like_host(
        self, db, monkeypatch
    ):
        self._seed_with_nan(db, n=20, nan_every=2)  # 10 real, 10 NaN
        sql = "SELECT v FROM rd ORDER BY v DESC LIMIT 15"
        got = [r["v"] for r in _warm(db, sql).to_pylist()]
        ref = [
            r["v"] for r in _host_ref(db, sql, monkeypatch).to_pylist()
        ]
        assert [np.isnan(x) for x in got] == [np.isnan(x) for x in ref]
        assert [x for x in got if not np.isnan(x)] == [
            x for x in ref if not np.isnan(x)
        ]


class TestRawKillSwitchAndRouting:
    def test_kill_switch_pins_host(self, db, monkeypatch):
        _seed(db, n=100)
        monkeypatch.setenv("HORAEDB_RAW_DEVICE", "0")
        sql = "SELECT host, v FROM rd WHERE v < 50 ORDER BY ts DESC LIMIT 5"
        out = _warm(db, sql)
        assert out.metrics.get("path") == "host"
        assert db.interpreters.executor.last_path == "host"
        assert "raw_kernel" not in out.metrics

    def test_raw_scan_counters_move(self, db):
        from horaedb_tpu.utils.metrics import REGISTRY

        _seed(db, n=100)
        sql = "SELECT host, v FROM rd WHERE v < 50 ORDER BY ts DESC LIMIT 5"
        _warm(db, sql)
        text = REGISTRY.expose()
        assert "horaedb_raw_scan_total" in text

    def test_learned_routing_probes_then_serves(self, db, monkeypatch):
        """With routing enabled the PathRouter warms device (2 probes),
        samples host once, then serves the measured winner."""
        monkeypatch.setenv("HORAEDB_ADAPTIVE_PATH", "1")
        _seed(db, n=150)
        sql = "SELECT host, v FROM rd WHERE v < 60 ORDER BY ts DESC LIMIT 5"
        paths = []
        for _ in range(6):
            out = db.execute(sql)
            paths.append(out.metrics.get("path"))
        assert "host" in paths  # the host probe happened
        from horaedb_tpu.query.path_router import plan_shape_key

        plan = db.frontend.statement_to_plan(db.frontend.parse_sql(sql))
        st = db.interpreters.executor.path_router.stats(plan_shape_key(plan))
        assert st.get("device_n", 0) >= 2 and "host" in st

    def test_persistent_fallback_converges_to_host(self, db, monkeypatch):
        """Review regression: a shape whose device attempt always
        bounces (NULLs in the ORDER BY column) must charge the DEVICE
        arm — recording it as host left the router in its probe phase,
        re-paying the failed attempt on every query forever."""
        monkeypatch.setenv("HORAEDB_ADAPTIVE_PATH", "1")
        db.execute(DDL)
        rows = ", ".join(
            f"('h{i % 3}', {float(i)}, "
            + ("NULL" if i % 2 else f"{float(i)}")
            + f", {1_700_000_000_000 + i * 1000})"
            for i in range(60)
        )
        db.execute(f"INSERT INTO rd (host, v, w, ts) VALUES {rows}")
        sql = "SELECT host, w FROM rd ORDER BY w DESC LIMIT 5"
        for _ in range(6):
            out = db.execute(sql)
            assert out.metrics.get("path") == "host"
        from horaedb_tpu.query.path_router import plan_shape_key

        plan = db.frontend.statement_to_plan(db.frontend.parse_sql(sql))
        st = db.interpreters.executor.path_router.stats(plan_shape_key(plan))
        # both arms sampled -> the router can judge instead of probing
        # device-first forever (timing RATIOS are host jitter — the
        # convergence property is that both estimates exist)
        assert st.get("device_n", 0) >= 2 and "host" in st

    def test_ledger_and_query_stats_cover_raw(self, db):
        from horaedb_tpu.proxy import Proxy

        proxy = Proxy(db)
        try:
            _seed(db, n=120)
            sql = "SELECT host, v, ts FROM rd WHERE v < 90 ORDER BY ts DESC LIMIT 7"
            out = None
            for _ in range(3):
                out = proxy.handle_sql(sql)
            assert out.metrics.get("path") == "raw_device"
            stats = proxy.handle_sql(
                "SELECT kernel, raw_rows_returned, route FROM "
                "system.public.query_stats"
            ).to_pylist()
            mine = [r for r in stats if r["route"] == "raw_device"]
            assert mine, stats
            assert mine[-1]["kernel"] == "raw_topk"
            assert mine[-1]["raw_rows_returned"] == 7
        finally:
            proxy.close()

    def test_explain_names_raw_execution(self, db):
        _seed(db, n=50)
        out = db.execute(
            "EXPLAIN SELECT host, v FROM rd WHERE v < 10 "
            "ORDER BY ts DESC LIMIT 5"
        )
        plan_text = "\n".join(out.column("plan"))
        assert "raw device" in plan_text and "top-k" in plan_text


class TestLexsortSkip:
    def test_presorted_helper(self):
        from horaedb_tpu.query.executor import _lex_presorted

        a = np.array([1, 2, 2, 3])
        assert _lex_presorted([a])
        assert not _lex_presorted([a[::-1].copy()])
        # two keys, np.lexsort order: LAST is primary
        primary = np.array([1, 1, 2, 2])
        secondary = np.array([0, 1, 0, 1])
        assert _lex_presorted([secondary, primary])
        assert not _lex_presorted([secondary[::-1].copy(), primary])
        # ties in the primary defer to the secondary
        assert _lex_presorted([np.array([0, 1, 0, 1]), np.array([1, 1, 2, 2])])
        # NaN pairs are conservative: fall through to the real sort
        assert not _lex_presorted([np.array([1.0, np.nan, 2.0])])
        # object keys compare fine; incomparable mixes bail out
        assert _lex_presorted([np.array(["a", "b"], dtype=object)])
        assert not _lex_presorted([np.array(["b", 1], dtype=object)])
        assert _lex_presorted([np.array([5])]) and _lex_presorted([np.array([])])

    def test_single_series_order_by_ts_skips_sort(self, db, monkeypatch):
        """The dashboard shape: one series, ORDER BY ts — storage hands
        over (key, ts)-sorted rows, so the host projection's lexsort is
        the identity and must be skipped."""
        monkeypatch.setenv("HORAEDB_RAW_DEVICE", "0")  # host projection path
        _seed(db, n=120, hosts=3)
        sql = "SELECT v, ts FROM rd WHERE host = 'h1' ORDER BY ts ASC"
        out = db.execute(sql)
        assert out.metrics.get("path") == "host"
        assert out.metrics.get("sort_skipped") is True
        ts = [r["ts"] for r in out.to_pylist()]
        assert ts == sorted(ts)
        # DESC over ascending storage order must NOT skip (and stays right)
        out = db.execute("SELECT v, ts FROM rd WHERE host = 'h1' ORDER BY ts DESC")
        assert out.metrics.get("sort_skipped") is None
        ts = [r["ts"] for r in out.to_pylist()]
        assert ts == sorted(ts, reverse=True)


class TestPartialKernelRouting:
    """Satellite: the partial-agg path now routes its segment impl
    through the shared KernelRouter instead of the static heuristic."""

    def test_bounded_partial_routes_and_matches(self, db, monkeypatch):
        _seed(db, n=400, hosts=20)
        sql = "SELECT host, count(1) AS c, sum(v) AS s FROM rd GROUP BY host"
        expect = db.execute(sql).to_pylist()
        monkeypatch.setenv("HORAEDB_AGG_MEMORY_MB", "0.0001")
        out = db.execute(sql)
        assert out.metrics.get("path") == "device-partial"
        assert sorted(tuple(r.values()) for r in out.to_pylist()) == sorted(
            tuple(r.values()) for r in expect
        )
        from horaedb_tpu.query.path_router import KERNEL_ROUTER

        partial_keys = [
            k for k in KERNEL_ROUTER._stats
            if isinstance(k, tuple) and k and isinstance(k[0], tuple)
            and k[0] and k[0][0] == "partial"
        ]
        assert partial_keys, "partial path never consulted the KernelRouter"

    def test_partial_respects_pin(self, db, monkeypatch):
        monkeypatch.setenv("HORAEDB_SEGMENT_IMPL", "scatter")
        monkeypatch.setenv("HORAEDB_AGG_MEMORY_MB", "0.0001")
        _seed(db, n=300, hosts=10)
        sql = "SELECT host, count(1) AS c FROM rd GROUP BY host"
        out = db.execute(sql)
        assert out.metrics.get("path") == "device-partial"
        from horaedb_tpu.query.path_router import KERNEL_ROUTER

        assert not [
            k for k in KERNEL_ROUTER._stats
            if isinstance(k, tuple) and k and isinstance(k[0], tuple)
            and k[0] and k[0][0] == "partial"
        ]
