"""DictColumn tests: code/vocab semantics, concat union, fast-path equivalences."""

import numpy as np
import pytest

from horaedb_tpu.common_types.dict_column import (
    DictColumn,
    as_values,
    concat_columns,
    unique_inverse,
)


def dc(values, codes):
    return DictColumn(np.asarray(codes, dtype=np.int32), np.asarray(values, dtype=object))


class TestDictColumn:
    def test_basic_semantics(self):
        c = dc(["a", "b", "c"], [2, 0, 1, 0])
        assert len(c) == 4
        assert c[0] == "c" and c[3] == "a"
        np.testing.assert_array_equal(c.decode(), np.array(["c", "a", "b", "a"], dtype=object))
        sub = c[np.array([1, 2])]
        assert isinstance(sub, DictColumn)
        np.testing.assert_array_equal(as_values(sub), np.array(["a", "b"], dtype=object))

    def test_encode_round_trip(self):
        arr = np.array(["x", "y", "x", "z"], dtype=object)
        c = DictColumn.encode(arr)
        np.testing.assert_array_equal(c.decode(), arr)
        assert len(c.values) == 3

    def test_map_values_matches_decoded(self):
        c = dc(["aa", "b", "cc"], [0, 1, 2, 1, 0])
        fast = c.map_values(lambda vs: vs == "b")
        slow = c.decode() == "b"
        np.testing.assert_array_equal(fast, slow)

    def test_sort_ranks_order_like_values(self):
        c = dc(["m", "a", "z"], [0, 1, 2, 1])
        order_fast = np.argsort(c.sort_ranks(), kind="stable")
        order_slow = np.argsort(c.decode(), kind="stable")
        np.testing.assert_array_equal(order_fast, order_slow)

    def test_min_max_respects_mask(self):
        c = dc(["a", "b", "z"], [2, 0, 1])
        assert c.min_max() == ("a", "z")
        assert c.min_max(np.array([True, False, True])) == ("b", "z")
        assert c.min_max(np.zeros(3, dtype=bool)) == (None, None)

    def test_concat_union_vocab(self):
        a = dc(["a", "b"], [0, 1])
        b = dc(["b", "c"], [1, 0])
        out = concat_columns([a, b])
        assert isinstance(out, DictColumn)
        np.testing.assert_array_equal(
            out.decode(), np.array(["a", "b", "c", "b"], dtype=object)
        )
        assert sorted(out.values.tolist()) == ["a", "b", "c"]

    def test_concat_mixed_plain_and_dict(self):
        a = dc(["a", "b"], [0, 1])
        b = np.array(["c", "a"], dtype=object)
        out = concat_columns([a, b])
        np.testing.assert_array_equal(
            out.decode(), np.array(["a", "b", "c", "a"], dtype=object)
        )

    def test_concat_all_plain_stays_plain(self):
        out = concat_columns([np.array([1, 2]), np.array([3])])
        assert isinstance(out, np.ndarray)

    def test_concat_single_part_unsorted_vocab_unchanged(self):
        # Review regression: first-occurrence (unsorted) vocabularies from
        # Parquet must NOT be remapped via searchsorted for single parts.
        c = dc(["host_0", "host_1", "host_2", "host_10"], [3, 0, 1, 2, 3])
        out = concat_columns([c])
        np.testing.assert_array_equal(
            out.decode(),
            np.array(["host_10", "host_0", "host_1", "host_2", "host_10"], dtype=object),
        )

    def test_concat_multi_part_unsorted_vocabs(self):
        a = dc(["host_2", "host_10"], [0, 1])
        b = dc(["host_10", "host_1"], [0, 1])
        out = concat_columns([a, b])
        np.testing.assert_array_equal(
            out.decode(),
            np.array(["host_2", "host_10", "host_10", "host_1"], dtype=object),
        )

    def test_unique_inverse_equivalence(self):
        # Unused vocab entry 'z' (code 2 never appears): uniques cover only
        # PRESENT values; reconstruction must equal the decoded column.
        c = dc(["b", "a", "z"], [0, 1, 0, 0])
        u_fast, inv_fast = unique_inverse(c)
        assert sorted(u_fast.tolist()) == ["a", "b"]
        np.testing.assert_array_equal(u_fast[inv_fast], c.decode())

    def test_tsid_hash_equivalence(self):
        from horaedb_tpu.common_types.schema import compute_tsid

        vals = np.array(["h1", "h2", "h3"], dtype=object)
        codes = np.array([2, 0, 1, 0], dtype=np.int32)
        via_dict = compute_tsid([DictColumn(codes, vals)])
        via_plain = compute_tsid([vals[codes]])
        np.testing.assert_array_equal(via_dict, via_plain)


class TestDictColumnThroughEngine:
    def test_sst_round_trip_stays_encoded_and_queries_match(self):
        import horaedb_tpu

        db = horaedb_tpu.connect(None)
        db.execute(
            "CREATE TABLE t (host string TAG, v double, ts timestamp KEY) "
            "WITH (segment_duration='1h')"
        )
        vals = ", ".join(f"('h{i % 5}', {float(i)}, {i})" for i in range(100))
        db.execute(f"INSERT INTO t (host, v, ts) VALUES {vals}")
        db.flush_all()
        table = db.catalog.open("t")
        rows = table.read()
        assert isinstance(rows.column("host"), DictColumn)
        # filters, group-by, order-by on the encoded column
        out = db.execute(
            "SELECT host, count(*) AS c FROM t WHERE host != 'h0' GROUP BY host ORDER BY host DESC"
        ).to_pylist()
        assert [r["host"] for r in out] == ["h4", "h3", "h2", "h1"]
        assert all(r["c"] == 20 for r in out)
        db.close()

    def test_memtable_sst_mixed_scan(self):
        import horaedb_tpu

        db = horaedb_tpu.connect(None)
        db.execute("CREATE TABLE t (host string TAG, v double, ts timestamp KEY)")
        db.execute("INSERT INTO t (host, v, ts) VALUES ('a', 1.0, 1)")
        db.flush_all()
        db.execute("INSERT INTO t (host, v, ts) VALUES ('b', 2.0, 2)")
        out = db.execute("SELECT host, v FROM t ORDER BY ts").to_pylist()
        assert out == [{"host": "a", "v": 1.0}, {"host": "b", "v": 2.0}]
        db.close()
