"""Continuous-query subsystem tests (PR-8 acceptance): PromQL recording
rules materialize as real tables, tiered rollups stay exactly equivalent
to raw recomputation (including restart/WAL-replay watermark catch-up
and TTL-boundary reads), step-compatible dashboard queries transparently
serve from the rollup (``route=rollup`` in the ledger + EXPLAIN), and
the alert evaluator drives pending -> firing -> resolved with typed
trace-linked events and ``system.public.alerts`` on all three wires."""

from __future__ import annotations

import asyncio
import json
import os
import socket
import time

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.proxy import Proxy
from horaedb_tpu.proxy.promql import (
    evaluate_expr_instant,
    evaluate_expr_range,
    parse_promql,
)
from horaedb_tpu.rules import (
    ROLLUPS,
    RuleEngine,
    RuleError,
    parse_rule_line,
    rollup_table_name,
    rule_from_dict,
)
from horaedb_tpu.server import create_app
from horaedb_tpu.server.mysql import MysqlServer
from horaedb_tpu.server.postgres import PostgresServer
from horaedb_tpu.utils.config import Config, ConfigError, RulesSection
from horaedb_tpu.utils.events import EVENT_STORE
from horaedb_tpu.utils.querystats import STATS_STORE

# raw byte-level protocol clients + subprocess-node helpers
from test_remote_engine import CPU_ENV, free_port, http, sql  # noqa: F401
from test_wire_protocols import MyClient, PgClient

HOUR = 3_600_000
MIN = 60_000


@pytest.fixture(autouse=True)
def _fresh_rollup_registry():
    """The rollup registry is process-global (like STATS_STORE): tests
    must not see another module's — or test's — registrations."""
    ROLLUPS.reset()
    yield
    ROLLUPS.reset()


def _mk_source(db, name: str, n_hosts=3, hours=3, step_s=20, seed=11,
               end=1_786_000_000_000):
    """A dashboard-shaped source table: host TAG, value double, dense
    samples over `hours` ending at the hour-aligned `end`."""
    db.execute(
        f"CREATE TABLE {name} (host string TAG, value double, ts timestamp "
        "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic "
        "WITH (segment_duration='2h', update_mode='append')"
    )
    end = (end // HOUR) * HOUR
    start = end - hours * HOUR
    rng = np.random.default_rng(seed)
    vals = []
    for t in range(start, end, step_s * 1000):
        for h in range(n_hosts):
            vals.append(f"('h{h}', {rng.normal(10, 3):.6f}, {t})")
    for i in range(0, len(vals), 1000):
        db.execute(
            f"INSERT INTO {name} (host, value, ts) VALUES "
            + ",".join(vals[i:i + 1000])
        )
    return start, end


def _rows_close(a: list, b: list, rtol=2e-3, atol=1e-3) -> bool:
    """Order-insensitive approximate row comparison (the raw path rides
    f32 device kernels; the rollup partials are f64)."""
    if len(a) != len(b):
        return False

    def key(row):
        return tuple(
            (k, v if not isinstance(v, float) else round(v, 3))
            for k, v in sorted(row.items())
        )

    for ra, rb in zip(sorted(a, key=key), sorted(b, key=key)):
        if set(ra) != set(rb):
            return False
        for k in ra:
            va, vb = ra[k], rb[k]
            if isinstance(va, float) or isinstance(vb, float):
                if not np.isclose(
                    float(va), float(vb), rtol=rtol, atol=atol, equal_nan=True
                ):
                    return False
            elif va != vb:
                return False
    return True


def _raw_forced(db, sql_text):
    os.environ["HORAEDB_ROLLUP"] = "0"
    try:
        return db.execute(sql_text).to_pylist()
    finally:
        os.environ.pop("HORAEDB_ROLLUP", None)


class TestRuleModel:
    def test_parse_forms(self):
        r = parse_rule_line("req_rate := rate(reqs[1m])", "recording")
        assert (r.name, r.kind, r.for_s) == ("req_rate", "recording", 0.0)
        a = parse_rule_line(
            "HighRate := rate(reqs[1m]) > 5 for 30s", "alert"
        )
        assert (a.name, a.for_s) == ("HighRate", 30.0)
        assert a.expr == "rate(reqs[1m]) > 5"
        # for is optional on alerts
        a0 = parse_rule_line("Now := reqs > 1", "alert")
        assert a0.for_s == 0.0

    def test_validation_errors(self):
        with pytest.raises(RuleError, match="NAME := EXPR"):
            parse_rule_line("no separator", "recording")
        with pytest.raises(RuleError, match="bad expr"):
            parse_rule_line("x := rate(", "recording")
        with pytest.raises(RuleError, match="must match"):
            parse_rule_line("bad-name := reqs", "recording")
        with pytest.raises(RuleError, match="no for duration"):
            rule_from_dict(
                {"name": "x", "expr": "reqs", "kind": "recording",
                 "for": "5s"}
            )
        r = rule_from_dict(
            {"name": "x", "expr": "reqs > 1", "kind": "alert", "for": "2m"}
        )
        assert r.for_s == 120.0

    def test_config_section_parses_and_validates(self, tmp_path):
        cfg = tmp_path / "c.toml"
        cfg.write_text(
            """
[rules]
eval_interval = "1s"
grace = "0s"
recording = ["r1 := avg(cpu)"]
alerts = ["A1 := cpu > 5 for 10s"]
rollup_tables = ["cpu"]
rollup_raw_ttl = "12h"
"""
        )
        c = Config.load(str(cfg))
        assert c.rules.eval_interval_s == 1.0
        assert c.rules.rollup_tables == ["cpu"]
        assert c.rules.rollup_raw_ttl_s == 12 * 3600.0
        bad = tmp_path / "bad.toml"
        bad.write_text('[rules]\nalerts = ["A1 := rate("]\n')
        with pytest.raises(ConfigError, match="bad expr"):
            Config.load(str(bad))
        unk = tmp_path / "unk.toml"
        unk.write_text("[rules]\nnope = 1\n")
        with pytest.raises(ConfigError, match="unknown key"):
            Config.load(str(unk))


class TestPromqlComparisons:
    """The alert evaluator's threshold surface: prom filter semantics."""

    @pytest.fixture()
    def db(self):
        conn = horaedb_tpu.connect(None)
        conn.execute(
            "CREATE TABLE cmp (host string TAG, value double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        now = int(time.time() * 1000)
        for i in range(10):
            conn.execute(
                f"INSERT INTO cmp (host, value, ts) VALUES "
                f"('a', 100.0, {now - 60000 + i * 5000}), "
                f"('b', 1.0, {now - 60000 + i * 5000})"
            )
        yield conn, now
        conn.close()

    def test_vector_scalar_filters(self, db):
        conn, now = db
        out = evaluate_expr_instant(conn, parse_promql("cmp > 50"), now)
        assert [s["metric"]["host"] for s in out] == ["a"]
        assert float(out[0]["value"][1]) == 100.0
        out = evaluate_expr_instant(conn, parse_promql("cmp <= 50"), now)
        assert [s["metric"]["host"] for s in out] == ["b"]
        # scalar OP vector keeps the vector's values
        out = evaluate_expr_instant(conn, parse_promql("50 < cmp"), now)
        assert [s["metric"]["host"] for s in out] == ["a"]

    def test_scalar_scalar_and_vector_vector(self, db):
        conn, now = db
        out = evaluate_expr_instant(conn, parse_promql("3 > 2"), now)
        assert float(out[0]["value"][1]) == 1.0
        out = evaluate_expr_instant(conn, parse_promql("2 > 3"), now)
        assert float(out[0]["value"][1]) == 0.0
        # vector/vector: lhs survives where both exist and cmp holds
        out = evaluate_expr_instant(
            conn, parse_promql("cmp >= cmp"), now
        )
        assert {s["metric"]["host"] for s in out} == {"a", "b"}

    def test_range_filter_and_precedence(self, db):
        conn, now = db
        out = evaluate_expr_range(
            conn, parse_promql("cmp > 2 + 40"), now - 30000, now, 10000
        )
        # + binds tighter than >: threshold is 42 -> only host a
        assert {s["metric"]["host"] for s in out} == {"a"}
        out = evaluate_expr_range(
            conn, parse_promql("avg_over_time(cmp[1m]) == 1"),
            now - 60000, now, 10000,
        )
        assert {s["metric"]["host"] for s in out} == {"b"}


class TestRollupMaintenance:
    def test_rollup_matches_exact_recompute_and_is_idempotent(self):
        db = horaedb_tpu.connect(None)
        start, end = _mk_source(db, "rm_src", hours=2)
        eng = RuleEngine(
            db,
            RulesSection(rollup_tables=["rm_src"], grace_s=0,
                         rollup_raw_ttl_s=0),
        ).load()
        eng.run_once(now_ms=end)
        got = db.execute(
            "SELECT ts, host, agg_sum, agg_count, agg_min, agg_max "
            "FROM rm_src_rollup_1m"
        ).to_pylist()
        want = db.execute(
            "SELECT time_bucket(ts, '1m') AS ts, host, sum(value) AS agg_sum, "
            "count(value) AS agg_count, min(value) AS agg_min, "
            "max(value) AS agg_max FROM rm_src "
            f"WHERE ts < {end} GROUP BY time_bucket(ts, '1m'), host"
        ).to_pylist()
        assert len(got) == len(want) > 0
        assert _rows_close(got, want)
        # 1h tier folds the 1m tier
        got_h = db.execute(
            "SELECT ts, host, agg_sum, agg_count FROM rm_src_rollup_1h"
        ).to_pylist()
        assert len(got_h) == 2 * 3  # 2 hours x 3 hosts
        # replaying the round cannot double-count (overwrite semantics +
        # watermark): totals stay identical
        st = ROLLUPS.get("rm_src")
        st.set_watermark("1m", start)  # simulate a lost watermark
        st.set_watermark("1h", start)
        eng.run_once(now_ms=end)
        again = db.execute(
            "SELECT sum(agg_count) AS n FROM rm_src_rollup_1m"
        ).to_pylist()
        before = sum(r["agg_count"] for r in want)
        assert again[0]["n"] == pytest.approx(before)
        db.close()

    def test_grace_keeps_open_buckets_out(self):
        db = horaedb_tpu.connect(None)
        start, end = _mk_source(db, "gr_src", hours=1)
        eng = RuleEngine(
            db,
            RulesSection(rollup_tables=["gr_src"], grace_s=120.0,
                         rollup_raw_ttl_s=0),
        ).load()
        eng.run_once(now_ms=end)
        wm = ROLLUPS.get("gr_src").watermark("1m")
        assert wm == ((end - 120_000) // MIN) * MIN
        got = db.execute(
            "SELECT max(ts) AS m FROM gr_src_rollup_1m"
        ).to_pylist()
        assert got[0]["m"] < wm
        db.close()

    def test_restart_and_wal_replay_catch_up(self, tmp_path):
        """Kill the engine (and the process state: fresh registry), write
        more rows, restart: catch-up recomputes forward from the persisted
        watermark — no gaps, no double counts."""
        path = str(tmp_path / "rr")
        db = horaedb_tpu.connect(path)
        start, end = _mk_source(db, "rs_src", hours=2)
        sec = RulesSection(rollup_tables=["rs_src"], grace_s=0,
                           rollup_raw_ttl_s=0)
        eng = RuleEngine(db, sec).load()
        eng.run_once(now_ms=end - HOUR)  # roll only the first hour
        assert os.path.exists(os.path.join(path, "rules_state.json"))
        db.close()
        ROLLUPS.reset()  # process restart: registry is empty

        db2 = horaedb_tpu.connect(path)  # WAL replay path
        # late-arriving rows land in the UNROLLED tail (after the
        # persisted watermark) — catch-up must include them
        for t in range(end - HOUR, end, MIN // 2):
            db2.execute(
                f"INSERT INTO rs_src (host, value, ts) VALUES "
                f"('late', 5.0, {t})"
            )
        eng2 = RuleEngine(db2, sec).load()
        eng2.run_once(now_ms=end)
        got = db2.execute(
            "SELECT ts, host, agg_sum, agg_count, agg_min, agg_max "
            "FROM rs_src_rollup_1m"
        ).to_pylist()
        want = db2.execute(
            "SELECT time_bucket(ts, '1m') AS ts, host, sum(value) AS agg_sum, "
            "count(value) AS agg_count, min(value) AS agg_min, "
            "max(value) AS agg_max FROM rs_src "
            f"WHERE ts < {end} GROUP BY time_bucket(ts, '1m'), host"
        ).to_pylist()
        assert _rows_close(got, want)
        assert any(r["host"] == "late" for r in got)
        # the multi-bucket advance journaled a catch-up event
        assert any(
            e["kind"] == "rollup_catchup" for e in EVENT_STORE.list()
        )
        db2.close()

    def test_ttl_ladder_applied_to_source_and_tiers(self):
        db = horaedb_tpu.connect(None)
        _mk_source(db, "tt_src", hours=1)
        eng = RuleEngine(
            db,
            RulesSection(
                rollup_tables=["tt_src"], grace_s=0,
                rollup_raw_ttl_s=24 * 3600.0,
                rollup_1m_ttl_s=30 * 24 * 3600.0,
                rollup_1h_ttl_s=0.0,
            ),
        ).load()
        eng.run_once()
        src_opts = db.catalog.open("tt_src").physical_datas()[0].options
        assert src_opts.enable_ttl and src_opts.ttl_ms == 24 * 3600 * 1000
        m_opts = db.catalog.open("tt_src_rollup_1m").physical_datas()[0].options
        assert m_opts.enable_ttl and m_opts.ttl_ms == 30 * 24 * 3600 * 1000
        h_opts = db.catalog.open("tt_src_rollup_1h").physical_datas()[0].options
        assert not h_opts.enable_ttl  # kept forever
        from horaedb_tpu.engine.options import UpdateMode

        assert m_opts.update_mode is UpdateMode.OVERWRITE
        db.close()


class TestRollupRewrite:
    @pytest.fixture()
    def served(self):
        db = horaedb_tpu.connect(None)
        start, end = _mk_source(db, "rw_src", hours=3)
        eng = RuleEngine(
            db,
            RulesSection(rollup_tables=["rw_src"], grace_s=0,
                         rollup_raw_ttl_s=0),
        ).load()
        eng.run_once(now_ms=end)
        yield db, start, end, eng
        db.close()

    def test_randomized_equivalence_property(self, served):
        """THE acceptance property, end-to-end via Proxy.handle_sql (so
        ledger rows populate): for random step/agg/filter/order shapes,
        the rollup-served answer equals the exact raw recomputation."""
        db, start, end, _ = served
        proxy = Proxy(db)
        rng = np.random.default_rng(3)
        steps = ["1m", "5m", "15m", "1h"]
        aggs = [
            "sum(value) AS v", "count(value) AS v", "min(value) AS v",
            "max(value) AS v", "avg(value) AS v",
            "min(value) AS lo, max(value) AS hi, avg(value) AS v",
        ]
        checked_rollup = 0
        for trial in range(12):
            step = steps[rng.integers(0, len(steps))]
            agg = aggs[rng.integers(0, len(aggs))]
            where = [f"ts >= {start + int(rng.integers(0, 2 * HOUR))}"]
            if rng.random() < 0.5:
                where.append(f"ts < {end - int(rng.integers(0, HOUR))}")
            if rng.random() < 0.4:
                where.append("host != 'h1'")
            tail = ""
            if rng.random() < 0.4:
                tail = " ORDER BY b, host LIMIT 40"
            q = (
                f"SELECT time_bucket(ts, '{step}') AS b, host, {agg} "
                f"FROM rw_src WHERE {' AND '.join(where)} "
                f"GROUP BY time_bucket(ts, '{step}'), host{tail}"
            )
            got = proxy.handle_sql(q).to_pylist()
            path = db.interpreters.executor.last_path
            want = _raw_forced(db, q)
            assert _rows_close(got, want), f"trial {trial}: {q}"
            if path == "rollup":
                checked_rollup += 1
        assert checked_rollup >= 8, "rollup route should serve most shapes"
        # the ledger recorded the rewrite: route=rollup rows in
        # query_stats for the proxied statements
        routes = {
            e["route"] for e in STATS_STORE.list()
            if "rw_src" in e.get("sql", "")
        }
        assert "rollup" in routes

    def test_open_tail_is_served_fresh(self, served):
        """Rows newer than the watermark (the still-open bucket) must be
        included via the raw tail — a dashboard's 'now' edge is never
        stale."""
        db, start, end, eng = served
        for t in range(end, end + 90_000, 10_000):
            db.execute(
                f"INSERT INTO rw_src (host, value, ts) VALUES ('h0', 42.0, {t})"
            )
        q = (
            "SELECT time_bucket(ts, '1m') AS b, host, sum(value) AS v "
            f"FROM rw_src WHERE ts >= {start} GROUP BY "
            "time_bucket(ts, '1m'), host"
        )
        got = db.execute(q)
        assert db.interpreters.executor.last_path == "rollup"
        m = got.metrics
        assert m["raw_tail_rows"] > 0
        assert _rows_close(got.to_pylist(), _raw_forced(db, q))

    def test_explain_and_ledger_visibility(self, served):
        db, start, end, _ = served
        q = (
            "SELECT time_bucket(ts, '5m') AS b, host, avg(value) AS v "
            f"FROM rw_src WHERE ts >= {start} GROUP BY "
            "time_bucket(ts, '5m'), host"
        )
        plan = "\n".join(
            r["plan"] for r in db.execute(f"EXPLAIN {q}").to_pylist()
        )
        assert "Rollup: table=rw_src_rollup_1m" in plan
        assert "route=rollup" in plan
        analyzed = "\n".join(
            r["plan"] for r in db.execute(f"EXPLAIN ANALYZE {q}").to_pylist()
        )
        assert "path=rollup" in analyzed
        assert "route=rollup" in analyzed
        # the kill switch pins the raw path AND removes the EXPLAIN claim
        os.environ["HORAEDB_ROLLUP"] = "0"
        try:
            plan_off = "\n".join(
                r["plan"] for r in db.execute(f"EXPLAIN {q}").to_pylist()
            )
            assert "Rollup:" not in plan_off
        finally:
            os.environ.pop("HORAEDB_ROLLUP", None)

    def test_incompatible_shapes_refuse(self, served):
        db, start, end, _ = served
        compatible = (
            "SELECT time_bucket(ts, '5m') AS b, host, avg(value) AS v "
            f"FROM rw_src WHERE ts >= {start} "
            "GROUP BY time_bucket(ts, '5m'), host"
        )
        db.execute(compatible)
        assert db.interpreters.executor.last_path == "rollup"
        refusals = [
            # count(*) differs from count(value) under NULLs
            "SELECT time_bucket(ts, '5m') AS b, count(1) AS v FROM rw_src "
            "GROUP BY time_bucket(ts, '5m')",
            # step not a multiple of any tier
            "SELECT time_bucket(ts, '90s') AS b, avg(value) AS v FROM rw_src "
            "GROUP BY time_bucket(ts, '90s')",
            # residual WHERE on the value column
            "SELECT time_bucket(ts, '5m') AS b, avg(value) AS v FROM rw_src "
            "WHERE value > 5 GROUP BY time_bucket(ts, '5m')",
            # HAVING
            "SELECT time_bucket(ts, '5m') AS b, avg(value) AS v FROM rw_src "
            "GROUP BY time_bucket(ts, '5m') HAVING avg(value) > 0",
            # no time_bucket key at all
            "SELECT host, avg(value) AS v FROM rw_src GROUP BY host",
        ]
        for q in refusals:
            db.execute(q)
            assert db.interpreters.executor.last_path != "rollup", q

    def test_promql_range_query_rides_the_rewrite(self, served):
        db, start, end, _ = served
        pq = parse_promql("rw_src")
        got = evaluate_expr_range(db, pq, start, end - 1, 5 * MIN)
        assert db.interpreters.executor.last_path == "rollup"
        os.environ["HORAEDB_ROLLUP"] = "0"
        try:
            want = evaluate_expr_range(db, pq, start, end - 1, 5 * MIN)
        finally:
            os.environ.pop("HORAEDB_ROLLUP", None)
        assert len(got) == len(want) > 0
        for gs, ws in zip(got, want):
            assert gs["metric"] == ws["metric"]
            assert len(gs["values"]) == len(ws["values"])
            for (tb, gv), (_, wv) in zip(gs["values"], ws["values"]):
                assert float(gv) == pytest.approx(float(wv), rel=2e-3)

    def test_ttl_boundary_reads_serve_from_rollup(self):
        """Raw SSTs older than the ladder's raw TTL drop WHOLE; the
        rollup keeps answering for that range, equal to what raw said
        before the drop.

        The source range must sit AHEAD of the wall clock: background
        flush-triggered compactions cut TTL at real `now`, and once the
        calendar catches the fixed test epoch they race this test's
        explicit `compact(now_ms=end)` for the expired files (observed
        as a ~50% flake the week the epoch went stale)."""
        db = horaedb_tpu.connect(None)
        fresh = ((int(time.time() * 1000) + 48 * HOUR) // HOUR) * HOUR
        start, end = _mk_source(
            db, "tb_src", hours=3, end=max(1_786_000_000_000, fresh)
        )
        eng = RuleEngine(
            db,
            RulesSection(rollup_tables=["tb_src"], grace_s=0,
                         # raw keeps only the last hour
                         rollup_raw_ttl_s=3600.0),
        ).load()
        eng.run_once(now_ms=end)
        old_q = (
            "SELECT time_bucket(ts, '5m') AS b, host, sum(value) AS v, "
            "count(value) AS n FROM tb_src "
            f"WHERE ts >= {start} AND ts < {start + HOUR} "
            "GROUP BY time_bucket(ts, '5m'), host"
        )
        before = _raw_forced(db, old_q)  # raw truth before the drop
        # flush + TTL compaction drops the expired SSTs whole
        table = db.catalog.open("tb_src")
        table.flush()
        from horaedb_tpu.engine.compaction import Compactor

        td = table.physical_datas()[0]
        result = Compactor(td).compact(now_ms=end)
        assert result.expired_dropped > 0
        # raw can no longer answer the old range...
        gone = _raw_forced(db, old_q)
        assert len(gone) < len(before)
        # ...but the rollup-served path still does, exactly
        after = db.execute(old_q)
        assert db.interpreters.executor.last_path == "rollup"
        assert _rows_close(after.to_pylist(), before)
        db.close()

    def test_coarse_steps_use_the_1h_tier(self, served):
        db, start, end, _ = served
        q = (
            "SELECT time_bucket(ts, '1h') AS b, host, max(value) AS v "
            f"FROM rw_src WHERE ts >= {start} GROUP BY "
            "time_bucket(ts, '1h'), host"
        )
        out = db.execute(q)
        assert db.interpreters.executor.last_path == "rollup"
        assert out.metrics["tier"] == "1h"
        assert _rows_close(out.to_pylist(), _raw_forced(db, q))


class TestRecordingRules:
    def test_recording_writes_real_table_and_promql_reads_back(self):
        db = horaedb_tpu.connect(None)
        now = int(time.time() * 1000)
        db.execute(
            "CREATE TABLE reqs (host string TAG, value double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        for i in range(30):
            db.execute(
                f"INSERT INTO reqs (host, value, ts) VALUES "
                f"('a', {float(i)}, {now - 30000 + i * 1000}), "
                f"('b', 7.0, {now - 30000 + i * 1000})"
            )
        eng = RuleEngine(
            db,
            RulesSection(
                recording=["req_avg := avg_over_time(reqs[1m])"],
            ),
        ).load()
        eng.run_once(now_ms=now)
        rows = db.execute(
            "SELECT labels, node, value FROM req_avg"
        ).to_pylist()
        assert {r["labels"] for r in rows} == {'{host="a"}', '{host="b"}'}
        assert all(r["node"] == "standalone" for r in rows)
        # PromQL selector + matcher on the LIFTED label
        out = evaluate_expr_instant(
            db, parse_promql('req_avg{host="b"}'), now + 1000
        )
        assert len(out) == 1
        assert out[0]["metric"]["host"] == "b"
        assert float(out[0]["value"][1]) == pytest.approx(7.0)
        db.close()

    def test_user_table_with_labels_tag_keeps_plain_semantics(self):
        """Only the EXACT samples shape gets folded-label lifting: a
        user table that merely has a tag called 'labels' beside its own
        tags must keep plain-tag series identity (lifting would parse
        the values and collapse distinct series)."""
        db = horaedb_tpu.connect(None)
        now = int(time.time() * 1000)
        db.execute(
            "CREATE TABLE lbl (labels string TAG, region string TAG, "
            "value double, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) "
            "ENGINE=Analytic"
        )
        db.execute(
            f"INSERT INTO lbl (labels, region, value, ts) VALUES "
            f"('critical', 'eu', 1.0, {now - 1000}), "
            f"('warning', 'eu', 2.0, {now - 1000})"
        )
        out = evaluate_expr_instant(db, parse_promql("lbl"), now)
        assert {s["metric"]["labels"] for s in out} == {
            "critical", "warning"
        }
        # an unknown label still errors (not silently post-filtered)
        from horaedb_tpu.proxy.promql import PromQLError

        with pytest.raises(PromQLError, match="unknown label"):
            evaluate_expr_instant(
                db, parse_promql('lbl{nope="x"}'), now
            )
        db.close()

    def test_per_rule_eval_interval_line_form_and_gating(self):
        """PR-10 satellite: ``NAME := EXPR [for 30s] [every 15s]`` — a
        rule with ``every`` evaluates once per interval, not once per
        engine round (effective cadence max(eval_interval, every))."""
        from horaedb_tpu.rules.model import RuleError, parse_rule_line

        r = parse_rule_line("foo := avg(reqs) every 5m", "recording")
        assert r.every_s == 300.0
        r = parse_rule_line("bar := avg(reqs) > 1 for 30s every 10s", "alert")
        assert r.for_s == 30.0 and r.every_s == 10.0
        from horaedb_tpu.rules.model import Rule, validate_rule

        with pytest.raises(RuleError, match="negative every"):
            validate_rule(Rule("neg", "avg(reqs)", every_s=-1))

        db = horaedb_tpu.connect(None)
        now = int(time.time() * 1000)
        db.execute(
            "CREATE TABLE reqs (host string TAG, value double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute(
            f"INSERT INTO reqs (host, value, ts) VALUES ('a', 5.0, {now - 5000})"
        )
        eng = RuleEngine(
            db,
            RulesSection(
                recording=[
                    "every_round := avg_over_time(reqs[1m])",
                    "hourly := avg_over_time(reqs[1m]) every 1h",
                ],
            ),
        ).load()
        assert eng.rules["hourly"].every_s == 3600.0
        eng.run_once(now_ms=now)
        eng.run_once(now_ms=now + 15_000)
        eng.run_once(now_ms=now + 30_000)
        n_every = len(db.execute("SELECT value FROM every_round").to_pylist())
        n_hourly = len(db.execute("SELECT value FROM hourly").to_pylist())
        assert n_every == 3  # every round
        assert n_hourly == 1  # gated until the hour elapses
        assert eng._rule_last_eval_ms["hourly"] == now
        # once the interval elapses it evaluates again (fresh source rows
        # so the 1m lookback window is non-empty at the new eval time)
        later = now + 3_600_000 + 15_000
        db.execute(
            f"INSERT INTO reqs (host, value, ts) VALUES ('a', 9.0, {later - 5000})"
        )
        eng.run_once(now_ms=later)
        assert eng._rule_last_eval_ms["hourly"] == later
        assert len(db.execute("SELECT value FROM hourly").to_pylist()) == 2
        db.close()

    def test_every_field_on_admin_rules_roundtrip(self):
        db = horaedb_tpu.connect(None)
        eng = RuleEngine(db, RulesSection()).load()
        rule = eng.add_rule(
            {"name": "r_every", "expr": "avg(missing_metric)",
             "kind": "recording", "every": "2m"}
        )
        assert rule.every_s == 120.0
        assert eng.rules["r_every"].to_dict()["every_s"] == 120.0
        listed = [r for r in eng.list_rules() if r["name"] == "r_every"]
        assert listed and listed[0]["every_s"] == 120.0
        db.close()

    def test_runtime_rules_persist_across_restart(self, tmp_path):
        path = str(tmp_path / "rp")
        db = horaedb_tpu.connect(path)
        eng = RuleEngine(db, RulesSection()).load()
        eng.add_rule(
            {"name": "r_runtime", "expr": "avg(missing_metric)",
             "kind": "recording"}
        )
        assert eng.rules["r_runtime"].source == "runtime"
        db.close()
        db2 = horaedb_tpu.connect(path)
        eng2 = RuleEngine(db2, RulesSection()).load()
        assert "r_runtime" in eng2.rules
        assert eng2.remove_rule("r_runtime")
        eng3 = RuleEngine(db2, RulesSection()).load()
        assert "r_runtime" not in eng3.rules
        db2.close()

    def test_config_rules_cannot_be_removed_at_runtime(self):
        db = horaedb_tpu.connect(None)
        eng = RuleEngine(
            db, RulesSection(recording=["cfg_rule := avg(x)"])
        ).load()
        with pytest.raises(RuleError, match="config-defined"):
            eng.remove_rule("cfg_rule")
        with pytest.raises(RuleError, match="config-defined"):
            eng.add_rule(
                {"name": "cfg_rule", "expr": "avg(y)", "kind": "recording"}
            )
        db.close()

    def test_per_rule_errors_are_isolated(self):
        """One broken rule (bad column shape) must not starve the rest."""
        db = horaedb_tpu.connect(None)
        now = int(time.time() * 1000)
        db.execute(
            "CREATE TABLE ok_src (value double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute(
            f"INSERT INTO ok_src (value, ts) VALUES (1.0, {now - 1000})"
        )
        # two-double-field table: _value_column raises at eval time
        db.execute(
            "CREATE TABLE bad_src (v1 double, v2 double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute(
            f"INSERT INTO bad_src (v1, v2, ts) VALUES (1.0, 2.0, {now})"
        )
        eng = RuleEngine(
            db,
            RulesSection(recording=[
                "r_bad := avg_over_time(bad_src[1m])",
                "r_ok := avg_over_time(ok_src[1m])",
            ]),
        ).load()
        eng.run_once(now_ms=now)
        assert "r_bad" in eng.stats()["last_errors"]
        assert db.execute("SELECT value FROM r_ok").to_pylist() == [
            {"value": 1.0}
        ]
        assert any(
            e["kind"] == "rule_eval_failed"
            and e["attrs"].get("rule") == "r_bad"
            for e in EVENT_STORE.list()
        )
        db.close()


class TestAlertLifecycle:
    def _mk_alert_db(self):
        db = horaedb_tpu.connect(None)
        db.execute(
            "CREATE TABLE errs (host string TAG, value double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        return db

    def _burst(self, db, now, value=99.0):
        for i in range(12):
            db.execute(
                f"INSERT INTO errs (host, value, ts) VALUES "
                f"('a', {value}, {now - 55000 + i * 5000})"
            )

    def test_pending_firing_resolved_with_events(self):
        EVENT_STORE.clear()
        db = self._mk_alert_db()
        now = int(time.time() * 1000)
        self._burst(db, now)
        eng = RuleEngine(
            db,
            RulesSection(
                alerts=["HotErrs := avg_over_time(errs[1m]) > 50 for 20s"],
            ),
        ).load()
        eng.run_once(now_ms=now)
        snap = eng.alerts_snapshot()
        assert [a["state"] for a in snap] == ["pending"]
        assert snap[0]["labels"]["alertname"] == "HotErrs"
        # still matching at +21s: fires
        self._burst(db, now + 21000)
        eng.run_once(now_ms=now + 21000)
        snap = eng.alerts_snapshot()
        assert [a["state"] for a in snap] == ["firing"]
        fired = [e for e in EVENT_STORE.list() if e["kind"] == "alert_fired"]
        assert len(fired) == 1
        assert fired[0]["attrs"]["rule"] == "HotErrs"
        assert fired[0]["trace_id"]  # trace-linked
        # the window drains -> no samples -> resolved
        eng.run_once(now_ms=now + 600_000)
        snap = eng.alerts_snapshot()
        assert [a["state"] for a in snap] == ["resolved"]
        resolved = [
            e for e in EVENT_STORE.list() if e["kind"] == "alert_resolved"
        ]
        assert len(resolved) == 1
        db.close()

    def test_pending_resets_without_firing(self):
        EVENT_STORE.clear()
        db = self._mk_alert_db()
        now = int(time.time() * 1000)
        self._burst(db, now)
        eng = RuleEngine(
            db,
            RulesSection(
                alerts=["Flap := avg_over_time(errs[1m]) > 50 for 5m"],
            ),
        ).load()
        eng.run_once(now_ms=now)
        assert [a["state"] for a in eng.alerts_snapshot()] == ["pending"]
        eng.run_once(now_ms=now + 600_000)  # window empty before for_s
        assert eng.alerts_snapshot() == []
        assert not any(
            e["kind"].startswith("alert_") for e in EVENT_STORE.list()
        )
        db.close()

    def test_for_zero_fires_immediately(self):
        db = self._mk_alert_db()
        now = int(time.time() * 1000)
        self._burst(db, now)
        eng = RuleEngine(
            db,
            RulesSection(alerts=["Now := avg_over_time(errs[1m]) > 50"]),
        ).load()
        eng.run_once(now_ms=now)
        assert [a["state"] for a in eng.alerts_snapshot()] == ["firing"]
        db.close()

    def test_alerts_table_on_http_mysql_and_pg(self):
        """system.public.alerts serves the live lifecycle on all three
        wires (the acceptance's three-protocol face)."""
        db = self._mk_alert_db()
        now = int(time.time() * 1000)
        self._burst(db, now)
        sec = RulesSection(
            alerts=["WireHot := avg_over_time(errs[1m]) > 50"],
            eval_interval_s=3600,
        )
        ALERTS_SQL = (
            "SELECT rule, state, value, labels FROM system.public.alerts"
        )

        def check(dicts):
            rows = [r for r in dicts if r["rule"] == "WireHot"]
            assert len(rows) == 1, dicts
            assert rows[0]["state"] == "firing"
            assert float(rows[0]["value"]) == pytest.approx(99.0)
            assert 'host="a"' in rows[0]["labels"]

        def my_client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyClient(s)
            c.handshake()
            kind, names, rows = c.query(ALERTS_SQL)
            s.close()
            assert kind == "rows", rows
            check([dict(zip(names, r)) for r in rows])

        def pg_client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgClient(s)
            c.startup()
            names, rows, _complete, err = c.query(ALERTS_SQL)
            s.close()
            assert err is None, err
            check([dict(zip(names, r)) for r in rows])

        async def body():
            from aiohttp.test_utils import TestClient, TestServer

            app = create_app(db, rules_cfg=sec)
            client = TestClient(TestServer(app))
            await client.start_server()
            eng = app["rule_engine"]
            eng.run_once(now_ms=now)
            gw = app["sql_gateway"]
            my = MysqlServer(gw, port=0)
            pg = PostgresServer(gw, port=0)
            await my.start()
            await pg.start()
            loop = asyncio.get_running_loop()
            try:
                out = await client.post("/sql", json={"query": ALERTS_SQL})
                assert out.status == 200
                check((await out.json())["rows"])
                out = await client.get("/debug/alerts")
                data = await out.json()
                assert data["enabled"]
                assert [a["state"] for a in data["alerts"]] == ["firing"]
                assert data["alerts"][0]["labels"]["host"] == "a"
                await loop.run_in_executor(None, my_client, my.port)
                await loop.run_in_executor(None, pg_client, pg.port)
            finally:
                await my.stop()
                await pg.stop()
                await client.close()

        asyncio.run(body())
        db.close()


class TestAlertsThroughLivewindow:
    """Satellite: eligible open-tail alert rules evaluate through the
    live-window ring partials (``route=livewindow``) with second-level
    freshness — memtable-only rows move the alert on the next round."""

    def test_bare_selector_alert_promotes_then_serves_from_state(self):
        from horaedb_tpu.state.livewindow import STORE, promote_reads

        STORE.clear()
        db = horaedb_tpu.connect(None)
        try:
            db.execute(
                "CREATE TABLE lw_alert (host string TAG, value double NOT "
                "NULL, ts timestamp NOT NULL, TIMESTAMP KEY(ts)) "
                "ENGINE=Analytic WITH (segment_duration='2h', "
                "update_mode='append')"
            )
            now = int(time.time() * 1000)
            rows = ",".join(
                f"('h{h}', 10.0, {now - k * 20000})"
                for k in range(12) for h in range(2)
            )
            db.execute(f"INSERT INTO lw_alert (host, value, ts) VALUES {rows}")

            eng = RuleEngine(
                db, RulesSection(alerts=["LwHot := lw_alert > 50"])
            ).load()
            # A bare gauge selector is the livewindow-eligible shape: the
            # promql range path lowers it to ONE time_bucket GROUP BY
            # (``avg_over_time`` at instant eval takes the exact-window
            # raw fold instead and never promotes). The eval instant sits
            # a bucket ahead of the seed rows so the promoted state's
            # valid_from bucket falls inside the query window; the
            # open-tail predicate compares the range END against the real
            # wall clock, so it must stay within two steps of now.
            eval_at = now + 90_000
            for i in range(promote_reads()):
                eng.run_once(now_ms=eval_at + i)
            states = STORE.stats()["states"]
            assert [s["table"] for s in states] == ["lw_alert"], \
                "alert evals did not promote the shape to live state"
            assert eng.alerts_snapshot() == []  # baseline far below 50

            # Freshness: an over-threshold burst into the first servable
            # bucket, memtable-only (never flushed), must fire on the
            # NEXT round — served from the ring partials, not a rescan.
            burst_ts = (now // MIN + 1) * MIN + 1000
            db.execute(
                "INSERT INTO lw_alert (host, value, ts) VALUES "
                f"('h0', 100.0, {burst_ts}), ('h1', 100.0, {burst_ts})"
            )
            eng.run_once(now_ms=eval_at + promote_reads())
            assert db.interpreters.executor.last_path == "livewindow"
            served = [s["reads_served"] for s in STORE.stats()["states"]]
            assert served and served[0] >= 1, served
            snap = eng.alerts_snapshot()
            assert sorted(a["labels"]["host"] for a in snap) == ["h0", "h1"]
            assert all(a["state"] == "firing" for a in snap)
            assert all(float(a["value"]) == 100.0 for a in snap)
        finally:
            STORE.clear()
            db.close()


class TestAdminSurfaceAndStatus:
    def test_admin_rules_debug_status_and_readiness(self):
        db = horaedb_tpu.connect(None)
        sec = RulesSection(
            recording=["adm_r := avg(missing)"], eval_interval_s=3600
        )

        async def body():
            from aiohttp.test_utils import TestClient, TestServer

            app = create_app(db, rules_cfg=sec)
            eng = app["rule_engine"]
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                # started -> loaded -> ready
                r = await client.get("/health", params={"ready": "1"})
                assert r.status == 200
                r = await client.get("/debug/status")
                doc = await r.json()
                assert doc["rules"]["rules_loaded"] == 1
                assert doc["rules"]["loaded"] is True
                # add / list / rm
                r = await client.post(
                    "/admin/rules",
                    json={"name": "adm_added", "expr": "avg(x)",
                          "kind": "recording"},
                )
                assert r.status == 200, await r.text()
                r = await client.get("/admin/rules")
                names = [x["name"] for x in (await r.json())["rules"]]
                assert names == ["adm_added", "adm_r"]
                r = await client.post(
                    "/admin/rules", json={"name": "bad(", "expr": "x"}
                )
                assert r.status == 400
                r = await client.delete(
                    "/admin/rules", json={"name": "adm_added"}
                )
                assert (await r.json())["removed"] is True
                r = await client.delete(
                    "/admin/rules", json={"name": "adm_r"}
                )
                assert r.status == 400  # config rule
                # ctl subcommands against the live server
                from horaedb_tpu.tools import ctl

                ep = f"127.0.0.1:{client.server.port}"
                loop = asyncio.get_running_loop()
                assert await loop.run_in_executor(
                    None, ctl.main, ["--endpoint", ep, "rules", "list"]
                ) == 0
                assert await loop.run_in_executor(
                    None, ctl.main,
                    ["--endpoint", ep, "rules", "add", "ctl_rule",
                     "avg(x)"],
                ) == 0
                assert "ctl_rule" in eng.rules
                assert await loop.run_in_executor(
                    None, ctl.main,
                    ["--endpoint", ep, "rules", "rm", "ctl_rule"],
                ) == 0
                assert "ctl_rule" not in eng.rules
                assert await loop.run_in_executor(
                    None, ctl.main, ["--endpoint", ep, "alerts"]
                ) == 0
            finally:
                await client.close()

        asyncio.run(body())
        db.close()

    def test_readiness_gates_on_rule_state_load(self):
        """A node whose rule engine exists but has not loaded its state
        is NOT ready (it would evaluate a stale rule set)."""
        db = horaedb_tpu.connect(None)
        sec = RulesSection(eval_interval_s=3600)

        async def body():
            from aiohttp.test_utils import TestClient, TestServer

            app = create_app(db, rules_cfg=sec)
            eng = app["rule_engine"]
            # simulate the pre-startup window: engine exists, not loaded
            app.on_startup.clear()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                assert not eng.loaded
                r = await client.get("/health", params={"ready": "1"})
                assert r.status == 503
                r = await client.get("/health")
                assert r.status == 200  # liveness unaffected
                eng.load()
                r = await client.get("/health", params={"ready": "1"})
                assert r.status == 200
            finally:
                await client.close()

        asyncio.run(body())
        db.close()


@pytest.fixture(scope="module")
def rules_cluster(tmp_path_factory):
    """Two static-mode nodes sharing a store; the rules config is
    IDENTICAL on both (fleet-config discipline) and pins the source
    table to node 1 — eval-on-owner means exactly one node evaluates."""
    import subprocess
    import sys

    tmp_path = tmp_path_factory.mktemp("rulescluster")
    ports = [free_port(), free_port()]
    endpoints = [f"127.0.0.1:{p}" for p in ports]
    data_dir = str(tmp_path / "shared")
    procs = []
    for i, port in enumerate(ports):
        cfg = tmp_path / f"n{i}.toml"
        cfg.write_text(
            f"""
[server]
host = "127.0.0.1"
http_port = {port}

[engine]
data_dir = "{data_dir}"

[observability]
self_scrape = false

[rules]
eval_interval = "500ms"
grace = "0s"
recording = ["clus_rate := avg_over_time(clus_src[5m])"]

[cluster]
self_endpoint = "{endpoints[i]}"
endpoints = {json.dumps(endpoints)}

[cluster.rules]
clus_src = "{endpoints[1]}"
"""
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "horaedb_tpu.server",
                 "--config", str(cfg)],
                env=CPU_ENV,
                stdout=open(tmp_path / f"n{i}.log", "wb"),
                stderr=subprocess.STDOUT,
            )
        )
    deadline = time.monotonic() + 60
    for port in ports:
        while True:
            try:
                if http("GET", f"http://127.0.0.1:{port}/health?ready=1",
                        timeout=2)[0] == 200:
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"node {port} never became ready")
            time.sleep(0.3)
    yield ports, endpoints
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestClusterEvalOnOwner:
    def test_rule_evaluates_only_on_owner(self, rules_cluster):
        ports, endpoints = rules_cluster
        status, _ = sql(
            ports[0],
            "CREATE TABLE clus_src (host string TAG, value double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic",
        )
        assert status == 200
        now = int(time.time() * 1000)
        values = ", ".join(
            f"('a', 3.0, {now - 60000 + i * 5000})" for i in range(12)
        )
        status, _ = sql(
            ports[0],
            f"INSERT INTO clus_src (host, value, ts) VALUES {values}",
        )
        assert status == 200
        # the owner's engine picks the rule up on its next rounds
        deadline = time.monotonic() + 45
        rows = []
        while time.monotonic() < deadline:
            status, out = sql(
                ports[0], "SELECT node, value FROM clus_rate"
            )
            if status == 200 and out.get("rows"):
                rows = out["rows"]
                break
            time.sleep(0.5)
        assert rows, "recording rule output never appeared"
        # eval-on-owner: every row was evaluated by the pinned owner
        assert {r["node"] for r in rows} == {endpoints[1]}
        assert all(r["value"] == pytest.approx(3.0) for r in rows)
        # both nodes agree (distributed read path), and both report the
        # rule loaded while only the owner accumulates evaluations
        status, out = sql(ports[1], "SELECT node FROM clus_rate")
        assert status == 200 and out["rows"]
        for port in ports:
            status, doc = http(
                "GET", f"http://127.0.0.1:{port}/debug/status"
            )
            assert status == 200
            assert doc["rules"]["rules_loaded"] == 1
