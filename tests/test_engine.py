"""Engine integration tests, in-process
(ref model: analytic_engine/src/tests/{read_write_test,alter_test,drop_test,open_test}.rs
driven by the TestEnv fixture in tests/util.rs).
"""

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema, TimeRange
from horaedb_tpu.engine.instance import Instance
from horaedb_tpu.engine.options import TableOptions, UpdateMode
from horaedb_tpu.table_engine import Predicate
from horaedb_tpu.utils.object_store import MemoryStore


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


class TestEnv:
    """Reusable engine fixture (ref: tests/util.rs TestEnv/TestContext)."""

    def __init__(self, store=None):
        self.store = store or MemoryStore()
        self.instance = Instance(self.store)

    def create_demo(self, table_id=1, **opt_kv):
        opts = TableOptions.from_kv(opt_kv) if opt_kv else TableOptions()
        return self.instance.create_table(0, table_id, "demo", demo_schema(), opts)

    def write_rows(self, table, rows):
        return self.instance.write(table, RowGroup.from_rows(table.schema, rows))

    def reopen(self):
        """Simulate restart: fresh Instance over the same store."""
        self.instance = Instance(self.store)
        return self.instance


def rows_named(table, result):
    return sorted((r["name"], r["t"], r["value"]) for r in result.to_pylist())


class TestWriteRead:
    def test_write_read_memtable_only(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [
            {"name": "h1", "value": 1.0, "t": 1000},
            {"name": "h2", "value": 2.0, "t": 1000},
        ])
        out = env.instance.read(t)
        assert rows_named(t, out) == [("h1", 1000, 1.0), ("h2", 1000, 2.0)]

    def test_flush_then_read(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        res = env.instance.flush_table(t)
        assert res.files_added == 1 and res.rows_flushed == 1
        assert t.version.immutables() == []
        env.write_rows(t, [{"name": "h1", "value": 2.0, "t": 2000}])
        out = env.instance.read(t)
        assert rows_named(t, out) == [("h1", 1000, 1.0), ("h1", 2000, 2.0)]

    def test_overwrite_dedup_across_flush(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.instance.flush_table(t)
        # Same primary key (same series, same timestamp) -> newest wins.
        env.write_rows(t, [{"name": "h1", "value": 9.0, "t": 1000}])
        out = env.instance.read(t)
        assert rows_named(t, out) == [("h1", 1000, 9.0)]
        # ...even after the newer version is flushed into its own SST.
        env.instance.flush_table(t)
        out = env.instance.read(t)
        assert rows_named(t, out) == [("h1", 1000, 9.0)]

    def test_overwrite_dedup_within_memtable(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.write_rows(t, [{"name": "h1", "value": 2.0, "t": 1000}])
        out = env.instance.read(t)
        assert rows_named(t, out) == [("h1", 1000, 2.0)]

    def test_append_mode_keeps_duplicates(self):
        env = TestEnv()
        t = env.create_demo(update_mode="append")
        assert t.options.update_mode is UpdateMode.APPEND
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.write_rows(t, [{"name": "h1", "value": 2.0, "t": 1000}])
        out = env.instance.read(t)
        assert len(out) == 2

    def test_time_range_read(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [
            {"name": "h1", "value": float(i), "t": i * 1000} for i in range(10)
        ])
        env.instance.flush_table(t)
        out = env.instance.read(t, Predicate(time_range=TimeRange(3000, 6000)))
        assert sorted(r["t"] for r in out.to_pylist()) == [3000, 4000, 5000]

    def test_projection(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.instance.flush_table(t)
        out = env.instance.read(t, projection=["value"])
        assert "value" in out.columns and "name" not in out.columns

    def test_write_buffer_triggers_flush(self):
        env = TestEnv()
        t = env.create_demo(write_buffer_size="1kb")
        for i in range(20):
            env.write_rows(t, [
                {"name": f"h{j}", "value": float(j), "t": i * 1000} for j in range(10)
            ])
        # The tripped buffer REQUESTS a flush; the dump runs on the
        # background scheduler — poll for its completion instead of
        # asserting the L0 file into existence at write-return time.
        import time as _time

        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline and not t.version.levels.files_at(0):
            _time.sleep(0.02)
        assert len(t.version.levels.files_at(0)) > 0


class TestEdgeSchemas:
    def test_tagless_table_single_series(self):
        s = Schema.build(
            [ColumnSchema("v", DatumKind.DOUBLE), ColumnSchema("t", DatumKind.TIMESTAMP)],
            timestamp_column="t",
        )
        env = TestEnv()
        t = env.instance.create_table(0, 5, "tagless", s)
        env.write_rows(t, [{"v": 1.0, "t": 1}, {"v": 2.0, "t": 2}])
        env.instance.flush_table(t)
        out = env.instance.read(t)
        assert sorted(r["v"] for r in out.to_pylist()) == [1.0, 2.0]

    def test_varbinary_column_flush(self):
        s = Schema.build(
            [
                ColumnSchema("k", DatumKind.STRING, is_tag=True),
                ColumnSchema("payload", DatumKind.VARBINARY),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
        )
        env = TestEnv()
        t = env.instance.create_table(0, 6, "bin", s)
        env.instance.write(t, RowGroup.from_rows(s, [{"k": "a", "payload": b"\x00\xff", "t": 1}]))
        assert env.instance.flush_table(t).files_added == 1
        assert env.instance.read(t).to_pylist()[0]["payload"] == b"\x00\xff"


class TestSegmentSplit:
    def test_flush_splits_by_segment_and_sets_duration(self):
        env = TestEnv()
        t = env.create_demo(segment_duration="1h")
        hour = 3_600_000
        env.write_rows(t, [
            {"name": "h1", "value": 1.0, "t": 100},
            {"name": "h1", "value": 2.0, "t": hour + 100},
            {"name": "h1", "value": 3.0, "t": 2 * hour + 100},
        ])
        res = env.instance.flush_table(t)
        assert res.files_added == 3
        files = t.version.levels.files_at(0)
        assert all(
            f.time_range.exclusive_end - f.time_range.inclusive_start <= hour
            for f in files
        )

    def test_auto_segment_duration_sampled(self):
        env = TestEnv()
        t = env.create_demo()
        assert t.options.segment_duration_ms is None
        env.write_rows(t, [
            {"name": "h1", "value": 1.0, "t": 0},
            {"name": "h1", "value": 2.0, "t": 3 * 3_600_000},
        ])
        env.instance.flush_table(t)
        assert t.options.segment_duration_ms == 4 * 3_600_000


class TestRecovery:
    def test_reopen_reads_flushed_data(self):
        env = TestEnv()
        t = env.create_demo(segment_duration="2h")
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.instance.flush_table(t)
        inst = env.reopen()
        t2 = inst.open_table(0, 1, "demo")
        assert t2 is not None
        assert t2.schema == t.schema
        assert t2.options.segment_duration_ms == 2 * 3_600_000
        out = inst.read(t2)
        assert rows_named(t2, out) == [("h1", 1000, 1.0)]

    def test_unflushed_data_lost_without_wal(self):
        # disable_data_wal semantics (ref: setup.rs:122-127 warning).
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        inst = env.reopen()
        t2 = inst.open_table(0, 1, "demo")
        assert len(inst.read(t2)) == 0

    def test_open_missing_table_returns_none(self):
        env = TestEnv()
        assert env.instance.open_table(0, 99, "nope") is None

    def test_sequence_continues_after_reopen(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.instance.flush_table(t)
        last = t.last_sequence
        inst = env.reopen()
        t2 = inst.open_table(0, 1, "demo")
        seq = inst.write(t2, RowGroup.from_rows(t2.schema, [
            {"name": "h1", "value": 2.0, "t": 2000}
        ]))
        assert seq > last


class TestDDL:
    def test_create_duplicate_rejected(self):
        env = TestEnv()
        env.create_demo()
        with pytest.raises(ValueError):
            env.create_demo()

    def test_drop_table_removes_storage(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        env.instance.flush_table(t)
        env.instance.drop_table(t)
        assert list(env.store.list()) == []
        assert env.reopen().open_table(0, 1, "demo") is None

    def test_alter_schema_add_column(self):
        env = TestEnv()
        t = env.create_demo()
        env.write_rows(t, [{"name": "h1", "value": 1.0, "t": 1000}])
        new_schema = t.schema.with_added_column(
            ColumnSchema("v2", DatumKind.DOUBLE)
        )
        env.instance.alter_schema(t, new_schema)
        env.write_rows(t, [{"name": "h1", "value": 2.0, "v2": 7.0, "t": 2000}])
        out = env.instance.read(t)
        by_t = {r["t"]: r for r in out.to_pylist()}
        assert by_t[2000]["v2"] == 7.0
        # Row flushed under schema v1 reads back with NULL for the new column.
        assert by_t[1000]["v2"] is None
        # Old rows surface NULL for the new column after reopen too.
        env.instance.flush_table(t)
        inst = env.reopen()
        t2 = inst.open_table(0, 1, "demo")
        assert t2.schema.version == new_schema.version

    def test_write_with_stale_schema_rejected(self):
        env = TestEnv()
        t = env.create_demo()
        old_schema = t.schema
        env.instance.alter_schema(
            t, t.schema.with_added_column(ColumnSchema("v2", DatumKind.DOUBLE))
        )
        with pytest.raises(ValueError):
            env.instance.write(t, RowGroup.from_rows(old_schema, [
                {"name": "h1", "value": 1.0, "t": 1000}
            ]))


class TestDeviceMergeRead:
    def test_device_merge_matches_host(self, monkeypatch):
        # Force the device merge path (off by default on the CPU backend)
        # and diff it against the host merge on an overwrite-heavy view.
        import numpy as np

        from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
        from horaedb_tpu.engine.instance import EngineConfig, Instance
        from horaedb_tpu.engine.options import TableOptions
        from horaedb_tpu.engine.flush import Flusher
        from horaedb_tpu.utils.object_store import MemoryStore

        schema = Schema.build(
            [
                ColumnSchema("name", DatumKind.STRING, is_tag=True),
                ColumnSchema("value", DatumKind.DOUBLE),
                ColumnSchema("t", DatumKind.TIMESTAMP),
            ],
            timestamp_column="t",
        )
        inst = Instance(MemoryStore(), EngineConfig(compaction_l0_trigger=1000))
        t = inst.create_table(0, 1, "dm", schema, TableOptions.from_kv({}))
        rng = np.random.default_rng(3)
        expect = {}
        for run in range(4):
            rows = []
            for _ in range(400):
                ts = int(rng.integers(0, 50_000))
                name = f"h{rng.integers(0, 6)}"
                v = float(rng.random())
                rows.append({"name": name, "value": v, "t": ts})
                expect[(name, ts)] = v
            inst.write(t, RowGroup.from_rows(schema, rows))
            if run < 3:
                Flusher(t).flush()  # 3 SSTs + 1 live memtable

        host_out = inst.read(t)
        monkeypatch.setenv("HORAEDB_DEVICE_MERGE_MIN_ROWS", "1")
        dev_out = inst.read(t)
        def as_map(rg):
            return {(r["name"], r["t"]): r["value"] for r in rg.to_pylist()}
        assert as_map(host_out) == expect
        assert as_map(dev_out) == expect


class TestLayeredMemtable:
    """memtable_type='layered': mutable head + frozen immutable segments
    (ref: analytic_engine/src/memtable/layered/, table_options.rs:416)."""

    def _mt(self, threshold=1):
        from horaedb_tpu.engine.memtable import LayeredMemTable

        return LayeredMemTable(demo_schema(), 1, switch_threshold=threshold)

    def _rows(self, n, base_ts=1000, base_v=0.0):
        sch = demo_schema()
        return RowGroup.from_rows(
            sch,
            [
                {"name": f"s{i % 3}", "value": base_v + i, "t": base_ts + i}
                for i in range(n)
            ],
        )

    def test_freeze_and_scan_equivalence(self):
        mt = self._mt(threshold=1)  # freeze after every put
        for k in range(4):
            mt.put(self._rows(5, base_ts=1000 + 100 * k, base_v=10.0 * k), k + 1)
        assert len(mt.frozen_segments()) == 4
        rows, seqs = mt.scan(None)
        assert len(rows) == 20 and mt.num_rows == 20
        # insertion order preserved: sequences ascend across segments
        assert list(np.unique(seqs)) == [1, 2, 3, 4]
        assert seqs.tolist() == sorted(seqs.tolist())
        assert mt.last_sequence == 4
        tr = mt.time_range()
        assert tr.inclusive_start == 1000 and tr.exclusive_end == 1305

    def test_head_not_frozen_below_threshold(self):
        mt = self._mt(threshold=1 << 30)
        mt.put(self._rows(5), 1)
        assert mt.frozen_segments() == []
        rows, seqs = mt.scan(None)
        assert len(rows) == 5

    def test_time_pruned_scan(self):
        mt = self._mt(threshold=1)
        mt.put(self._rows(5, base_ts=1000), 1)
        mt.put(self._rows(5, base_ts=9000), 2)
        pred = Predicate(TimeRange(9000, 9100))
        rows, seqs = mt.scan(pred)
        assert len(rows) == 5 and set(seqs.tolist()) == {2}

    def test_frozen_segments_are_stable_objects(self):
        mt = self._mt(threshold=1)
        mt.put(self._rows(5), 1)
        seg_a = mt.frozen_segments()[0]
        mt.put(self._rows(5, base_ts=2000), 2)
        seg_b = mt.frozen_segments()[0]
        assert seg_a is seg_b  # identity stable -> cacheable downstream

    def test_engine_end_to_end_with_layered_option(self):
        env = TestEnv()
        t = env.create_demo(
            memtable_type="layered", mutable_segment_switch_threshold="1b"
        )
        for k in range(3):
            env.write_rows(
                t,
                [
                    {"name": "a", "value": float(k), "t": 1000 + k},
                ],
            )
        rows = env.instance.read(t)
        assert len(rows) == 3
        assert t.options.memtable_type == "layered"
        # overwrite semantics survive the layered layout: same key+ts wins
        env.write_rows(t, [{"name": "a", "value": 99.0, "t": 1000}])
        rows = env.instance.read(t)
        vals = {int(ts): v for ts, v in zip(rows.timestamps, rows.columns["value"])}
        assert vals[1000] == 99.0

    def test_skiplist_alias_and_bad_type(self):
        opts = TableOptions.from_kv({"memtable_type": "skiplist"})
        assert opts.memtable_type == "columnar"
        with pytest.raises(ValueError):
            TableOptions.from_kv({"memtable_type": "btree"})

    def test_segment_ids_unique_across_memtables(self):
        a, b = self._mt(1), self._mt(1)
        a.put(self._rows(2), 1)
        b.put(self._rows(2), 1)
        a.put(self._rows(2, base_ts=2000), 2)
        ids = [s.segment_id for s in a.frozen_segments() + b.frozen_segments()]
        assert len(ids) == len(set(ids)) == 3  # (table, id) safe cache key
