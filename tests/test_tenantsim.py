"""Multi-tenant production-simulator gates (PR 11, ROADMAP item 5).

The tier-1 smoke drives a small but complete configuration — a real
in-process 1-meta + 2-node cluster, tens of tenants, one latency burst,
one store-error burst, one leader kill — and asserts the acceptance
invariants FROM THE DATABASE'S OWN TABLES:

- SLO verdicts read back from ``system.public.slo`` (evaluated over the
  node's own ``system_metrics.samples`` history, not harness timing),
  with the cheap-class p99 objective never burning;
- zero wrong answers on any served read (frozen-range references);
- a contiguous event-journal seq window with every drop accounted;
- at least one alert observed firing AND resolving under the injected
  faults;
- acknowledged writes (incl. rows acked by the killed leader) readable
  after recovery.

The full-scale run (hundreds of tenants, 3 nodes, lease flap + rolling
shard migration) is ``slow`` and also wired as ``BENCH_CONFIG=tenantsim``.
"""

import pytest

from horaedb_tpu.tools.tenantsim import SimConfig, run_sim


def _smoke_config() -> SimConfig:
    return SimConfig(
        nodes=2,
        tenants=12,
        tables=2,
        duration_s=14.0,
        seed=7,
        workers=3,
        ingest_workers=1,
        rows_per_table=3000,
        read_replicas=1,
        scrape_interval_s=0.3,
        eval_interval_s=0.3,
        fast_window_s=3.0,
        slow_window_s=10.0,
        lease_ttl_s=2.0,
        heartbeat_timeout_s=3.0,
        storm_window=(0.15, 0.45),
        latency_burst=(0.2, 0.4),
        error_burst=(0.25, 0.5),
        # slow-storm-with-tight-deadlines (ISSUE 14): overlaps the
        # store-latency burst so the expensive scans genuinely cannot
        # fit the budget — the typed 504s, the journal evidence, and
        # the admission-slot drain are all gated in violations()
        deadline_phase=(0.2, 0.45),
        deadline_budget_ms=150.0,
        # decision plane (ISSUE 16): run the learned per-column dtype
        # mode plus the min/max-only panel table so the dtype tuner's
        # graded promotion joins the kernel-router / admission /
        # deadline loops in system.public.decisions
        dtype_auto=True,
        kill_at=0.65,
        lease_flap_at=None,
        shard_move_at=None,
        settle_timeout_s=25.0,
    )


class TestTenantSimSmoke:
    def test_smoke_invariants_hold(self):
        report = run_sim(_smoke_config())
        violations = report.violations()
        detail = {
            k: v
            for k, v in report.to_dict().items()
            if k not in ("config", "slo_rows")
        }
        assert not violations, f"{violations}\nreport: {detail}"
        # beyond the gate: the run actually exercised the machinery
        assert report.served > 100, detail
        assert report.ingest_acked_rows > 0, detail
        assert report.killed_node, detail
        assert report.kill_recovered, detail
        assert "StoreFaults" in report.alerts_fired, detail
        # the SLO table carried every declared objective
        names = {r["objective"] for r in report.slo_rows}
        assert {"cheap_p99", "store_faults", "shed_ratio"} <= names, detail
        # the deadline storm ran and the gates (typed 504 within
        # budget + slack, journal evidence, slot drain, cheap p99
        # flat) all held — violations() already enforced them; pin
        # the concrete expectations here too
        assert report.deadline_sent > 0, detail
        assert report.deadline_expired >= 1, detail
        assert report.deadline_overdue == 0, detail
        assert report.deadline_timeout_events >= 1, detail
        assert report.admission_units_after in (0, 1), detail
        # the decision plane's standing gate (ISSUE 16): every loop the
        # smoke activates shows resolved decisions + a finite calibration
        # verdict in the database's own tables, with exact accounting —
        # violations() enforced it; pin the active-loop set here too
        assert set(report.decision_active_loops) == {
            "kernel_router", "admission", "deadline", "layout_tuner",
            "livewindow",
        }, detail
        for loop in report.decision_active_loops:
            assert report.decision_resolved_counts.get(loop, 0) >= 1, detail
            assert report.calibration_verdicts.get(loop), detail
        assert report.decision_unaccounted == 0, detail


def _elastic_config() -> SimConfig:
    """The elastic standing gate (ISSUE 12): a 2-node cluster under a
    hot-tenant skew phase with the [cluster.elastic] loop driving — no
    other injected fault, so every transition is the CONTROLLER's doing.
    Three tables over four shards on two nodes: by pigeonhole one node
    co-owns >= 2 hot shards, so a skew-REDUCING pre-warmed move is
    possible (and therefore demanded) by construction."""
    return SimConfig(
        nodes=2,
        tenants=12,
        tables=3,
        duration_s=18.0,
        seed=7,
        workers=3,
        ingest_workers=1,
        rows_per_table=3000,
        read_replicas=0,  # the elastic policy owns replica counts
        elastic=True,
        hot_phase=(0.1, 0.6),
        storm_window=None,
        latency_burst=None,
        error_burst=None,
        kill_at=None,
        scrape_interval_s=0.3,
        eval_interval_s=0.3,
        fast_window_s=3.0,
        slow_window_s=10.0,
        lease_ttl_s=2.0,
        heartbeat_timeout_s=3.0,
        settle_timeout_s=35.0,
    )


class TestTenantSimElastic:
    def test_elastic_scales_out_moves_and_scales_in(self):
        report = run_sim(_elastic_config())
        violations = report.violations()
        detail = {
            k: v
            for k, v in report.to_dict().items()
            if k not in ("config", "slo_rows")
        }
        assert not violations, f"{violations}\nreport: {detail}"
        # asserted from the database's own tables/journal (the
        # violations() gate already requires >=1 scale-up, >=1 scale-in,
        # follower serving, and — when hot shards were co-owned — a
        # pre-warmed move); pin the concrete expectations here too
        assert report.elastic_scale_ups >= 1, detail
        assert report.elastic_scale_downs >= 1, detail
        assert report.follower_served >= 1, detail
        # 3 tables / 2 nodes: co-ownership is guaranteed by pigeonhole
        assert report.elastic_move_expected, detail
        assert report.elastic_moves >= 1, detail
        assert report.elastic_prewarmed_moves >= 1, detail
        # zero wrong answers and a flat cheap p99 THROUGH the moves
        assert report.wrong_answers == 0, detail
        assert report.cheap_objective_breaches == 0, detail
        # the elastic loop's forecasts are journaled and graded: each
        # round's persistence forecast of hot-shard pressure resolves
        # against the NEXT round's realized qps (ISSUE 16 unified the
        # controller's private ring onto the decision journal)
        assert "elastic" in report.decision_active_loops, detail
        assert report.decision_resolved_counts.get("elastic", 0) >= 1, detail
        assert report.calibration_verdicts.get("elastic"), detail
        assert report.decision_unaccounted == 0, detail


@pytest.mark.slow
class TestTenantSimFullScale:
    def test_full_scale(self):
        cfg = SimConfig(
            nodes=3,
            tenants=200,
            tables=3,
            duration_s=45.0,
            workers=6,
            ingest_workers=2,
            rows_per_table=30_000,
            read_replicas=1,
            elastic=True,
            hot_phase=(0.1, 0.45),
            lease_flap_at=0.72,
            shard_move_at=0.8,
            settle_timeout_s=45.0,
        )
        report = run_sim(cfg)
        violations = report.violations()
        detail = {
            k: v
            for k, v in report.to_dict().items()
            if k not in ("config", "slo_rows")
        }
        assert not violations, f"{violations}\nreport: {detail}"
        # at full scale the fault objective must complete a full
        # burn -> recover cycle and followers must actually serve
        assert "store_faults" in report.slo_burned_objectives, detail
        assert "store_faults" in report.slo_recovered_objectives, detail
        assert report.kill_recovered, detail
