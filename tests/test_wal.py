"""WAL tests (ref model: wal read_write suite, src/wal/tests/read_write.rs)."""

import os

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
from horaedb_tpu.engine.instance import Instance
from horaedb_tpu.engine.wal import LocalDiskWal, NoopWal, WalCorruption
from horaedb_tpu.utils.object_store import LocalDiskStore


def demo_schema():
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def rows(schema, *vals):
    return RowGroup.from_rows(
        schema, [{"name": n, "value": v, "t": t} for n, v, t in vals]
    )


class TestLocalDiskWal:
    def test_append_read_round_trip(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20), ("c", 3.0, 30)))
        got = list(wal.read_from(1, 1))
        assert [seq for seq, _ in got] == [1, 2]
        batch = got[1][1]
        back = RowGroup.from_arrow(s, batch)
        assert sorted(back.column("value").tolist()) == [2.0, 3.0]

    def test_read_from_skips_older(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        for i in range(1, 6):
            wal.append(1, i, rows(s, ("a", float(i), i)))
        assert [seq for seq, _ in wal.read_from(1, 4)] == [4, 5]

    def test_mark_flushed_partial_then_full(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        for i in range(1, 4):
            wal.append(1, i, rows(s, ("a", float(i), i)))
        wal.mark_flushed(1, 2)
        assert [seq for seq, _ in wal.read_from(1, 1)] == [3]
        wal.mark_flushed(1, 3)  # everything flushed -> log removed
        assert list(wal.read_from(1, 1)) == []
        assert not os.path.exists(os.path.join(str(tmp_path), "1.wal"))

    def test_torn_tail_tolerated(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20)))
        wal.close()
        path = os.path.join(str(tmp_path), "1.wal")
        size = os.path.getsize(path)
        with open(path, "r+b") as f:  # cut the last record in half
            f.truncate(size - 17)
        wal2 = LocalDiskWal(str(tmp_path))
        got = [seq for seq, _ in wal2.read_from(1, 1)]
        assert got == [1]

    def test_mid_log_corruption_raises(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20)))
        wal.close()
        path = os.path.join(str(tmp_path), "1.wal")
        with open(path, "r+b") as f:  # flip a byte inside the first record
            f.seek(12)
            b = f.read(1)
            f.seek(12)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(WalCorruption, match="CRC"):
            list(LocalDiskWal(str(tmp_path)).read_from(1, 1))

    def test_delete_table(self, tmp_path):
        wal = LocalDiskWal(str(tmp_path))
        s = demo_schema()
        wal.append(7, 1, rows(s, ("a", 1.0, 10)))
        wal.delete_table(7)
        assert list(wal.read_from(7, 1)) == []

    def test_noop_wal(self):
        wal = NoopWal()
        wal.append(1, 1, rows(demo_schema(), ("a", 1.0, 10)))
        assert list(wal.read_from(1, 1)) == []


class TestEngineWithWal:
    def test_crash_replay_then_flush_truncates(self, tmp_path):
        store = LocalDiskStore(str(tmp_path / "store"))
        s = demo_schema()

        inst = Instance(store, wal=LocalDiskWal(str(tmp_path / "wal")))
        t = inst.create_table(0, 1, "demo", s)
        inst.write(t, rows(s, ("a", 1.0, 10), ("b", 2.0, 20)))
        inst.write(t, rows(s, ("a", 3.0, 30)))
        # crash: no flush, no close

        inst2 = Instance(store, wal=LocalDiskWal(str(tmp_path / "wal")))
        t2 = inst2.open_table(0, 1, "demo")
        out = inst2.read(t2)
        assert len(out) == 3
        assert t2.last_sequence == 2

        inst2.flush_table(t2)
        assert not os.path.exists(str(tmp_path / "wal" / "1.wal"))
        # replay after flush: nothing comes back twice
        inst3 = Instance(store, wal=LocalDiskWal(str(tmp_path / "wal")))
        t3 = inst3.open_table(0, 1, "demo")
        assert len(inst3.read(t3)) == 3

    def test_replay_after_alter_fills_nulls(self, tmp_path):
        store = LocalDiskStore(str(tmp_path / "store"))
        s = demo_schema()
        inst = Instance(store, wal=LocalDiskWal(str(tmp_path / "wal")))
        t = inst.create_table(0, 1, "demo", s)
        inst.write(t, rows(s, ("a", 1.0, 10)))
        # ALTER flushes old rows first (engine invariant), so WAL replay with
        # the new schema only ever sees post-ALTER entries... unless the
        # flush itself was lost. Simulate that worst case: alter the schema
        # in the manifest but keep the WAL entry.
        new_schema = s.with_added_column(ColumnSchema("v2", DatumKind.DOUBLE))
        from horaedb_tpu.engine.manifest import AlterSchema

        t.manifest.append_edits([AlterSchema(new_schema)])
        inst2 = Instance(store, wal=LocalDiskWal(str(tmp_path / "wal")))
        t2 = inst2.open_table(0, 1, "demo")
        out = inst2.read(t2)
        assert out.to_pylist()[0]["v2"] is None


class TestObjectStoreWal:
    """Backend-parity suite (ref: wal read_write.rs runs one suite over
    every backend) — same behaviors as the disk WAL, over the store."""

    def make(self, tmp_path=None):
        from horaedb_tpu.engine.wal import ObjectStoreWal
        from horaedb_tpu.utils.object_store import MemoryStore

        store = MemoryStore()
        return ObjectStoreWal(store), store

    def test_append_read_round_trip(self):
        wal, _ = self.make()
        schema = demo_schema()
        wal.append(1, 1, rows(schema, ("a", 1.0, 100)))
        wal.append(1, 2, rows(schema, ("b", 2.0, 200)))
        got = [(seq, b.num_rows) for seq, b in wal.read_from(1, 1)]
        assert got == [(1, 1), (2, 1)]

    def test_read_from_skips_older(self):
        wal, _ = self.make()
        schema = demo_schema()
        for s in (1, 2, 3):
            wal.append(1, s, rows(schema, ("a", float(s), s * 100)))
        assert [s for s, _ in wal.read_from(1, 3)] == [3]

    def test_mark_flushed_partial_then_full(self):
        wal, store = self.make()
        schema = demo_schema()
        for s in (1, 2, 3):
            wal.append(1, s, rows(schema, ("a", float(s), s * 100)))
        wal.mark_flushed(1, 2)
        assert [s for s, _ in wal.read_from(1, 1)] == [3]
        # pages 1 and 2 physically gone
        assert len([p for p in store.list("wal/1/") if p.endswith(".page")]) == 1
        wal.mark_flushed(1, 3)
        assert [s for s, _ in wal.read_from(1, 1)] == []
        assert list(store.list("wal/1/")) == []

    def test_tables_isolated(self):
        wal, _ = self.make()
        schema = demo_schema()
        wal.append(1, 1, rows(schema, ("a", 1.0, 100)))
        wal.append(2, 1, rows(schema, ("b", 2.0, 100)))
        wal.delete_table(1)
        assert list(wal.read_from(1, 1)) == []
        assert [s for s, _ in wal.read_from(2, 1)] == [1]

    def test_survives_reopen_from_shared_store(self):
        from horaedb_tpu.engine.wal import ObjectStoreWal
        from horaedb_tpu.utils.object_store import MemoryStore

        store = MemoryStore()
        schema = demo_schema()
        wal = ObjectStoreWal(store)
        wal.append(1, 5, rows(schema, ("a", 1.0, 100)))
        # a different WAL instance over the same store sees everything
        wal2 = ObjectStoreWal(store)
        assert [s for s, _ in wal2.read_from(1, 1)] == [5]

    def test_engine_crash_replay(self, tmp_path):
        from horaedb_tpu.engine.instance import Instance
        from horaedb_tpu.engine.options import TableOptions
        from horaedb_tpu.engine.wal import ObjectStoreWal
        from horaedb_tpu.utils.object_store import LocalDiskStore

        store = LocalDiskStore(str(tmp_path / "store"))
        schema = demo_schema()
        inst = Instance(store, wal=ObjectStoreWal(store))
        t = inst.create_table(0, 1, "w", schema, TableOptions())
        inst.write(t, rows(schema, ("a", 1.0, 100), ("b", 2.0, 200)))
        # crash: new instance over the SAME store replays from the wal
        inst2 = Instance(store, wal=ObjectStoreWal(store))
        t2 = inst2.open_table(0, 1, "w")
        out = inst2.read(t2)
        assert sorted(r["value"] for r in out.to_pylist()) == [1.0, 2.0]
        inst2.flush_table(t2)
        # flushed -> wal truncated in the store
        assert not [p for p in store.list("wal/1/") if p.endswith(".page")]


class TestSharedLogWal:
    """Backend-parity suite for the region-based shared log (ref: the
    message-queue WAL, wal/src/message_queue_impl/region.rs — one log per
    region multiplexing its tables; RegionBased replay scans once)."""

    def make(self, tmp_path, **kw):
        from horaedb_tpu.engine.wal import SharedLogWal

        return SharedLogWal(str(tmp_path), **kw)

    def test_append_read_round_trip(self, tmp_path):
        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(2, 1, rows(s, ("x", 9.0, 15)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20), ("c", 3.0, 30)))
        got = list(wal.read_from(1, 1))
        assert [seq for seq, _ in got] == [1, 2]
        back = RowGroup.from_arrow(s, got[1][1])
        assert sorted(back.column("value").tolist()) == [2.0, 3.0]
        assert [seq for seq, _ in wal.read_from(2, 1)] == [1]

    def test_read_from_skips_older(self, tmp_path):
        wal = self.make(tmp_path)
        s = demo_schema()
        for i in range(1, 6):
            wal.append(1, i, rows(s, ("a", float(i), i)))
        assert [seq for seq, _ in wal.read_from(1, 4)] == [4, 5]

    def test_mark_flushed_and_truncation(self, tmp_path):
        import os as _os

        wal = self.make(tmp_path, segment_bytes=1)  # one record per segment
        s = demo_schema()
        for i in range(1, 4):
            wal.append(1, i, rows(s, ("a", float(i), i)))
        region = str(tmp_path) + "/region_0"
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 3
        wal.mark_flushed(1, 2)
        assert [seq for seq, _ in wal.read_from(1, 1)] == [3]
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 1
        wal.mark_flushed(1, 3)
        assert list(wal.read_from(1, 1)) == []
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 0

    def test_segment_held_by_unflushed_table(self, tmp_path):
        """A segment mixing two tables' records survives until BOTH are
        flushed — the region log's defining property."""
        import os as _os

        wal = self.make(tmp_path)  # one big segment
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(2, 1, rows(s, ("x", 9.0, 15)))
        wal.mark_flushed(1, 1)
        region = str(tmp_path) + "/region_0"
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 1
        assert list(wal.read_from(1, 1)) == []  # watermark hides table 1
        assert [seq for seq, _ in wal.read_from(2, 1)] == [1]
        wal.mark_flushed(2, 1)
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 0

    def test_replay_region_single_scan(self, tmp_path):
        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(2, 1, rows(s, ("x", 9.0, 15)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20)))
        got = [(tid, seq) for tid, seq, _ in wal.replay_region(0)]
        assert got == [(1, 1), (2, 1), (1, 2)]  # append order preserved

    def test_region_of_partitions_tables(self, tmp_path):
        import os as _os

        wal = self.make(tmp_path, region_of=lambda tid: tid % 2)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(2, 1, rows(s, ("x", 9.0, 15)))
        dirs = sorted(
            d for d in _os.listdir(str(tmp_path)) if d.startswith("region_")
        )
        assert dirs == ["region_0", "region_1"]
        assert [seq for seq, _ in wal.read_from(1, 1)] == [1]
        assert [seq for seq, _ in wal.read_from(2, 1)] == [1]

    def test_delete_table_releases_segments(self, tmp_path):
        import os as _os

        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(2, 1, rows(s, ("x", 9.0, 15)))
        wal.delete_table(2)
        assert list(wal.read_from(2, 1)) == []
        assert [seq for seq, _ in wal.read_from(1, 1)] == [1]
        wal.mark_flushed(1, 1)
        region = str(tmp_path) + "/region_0"
        assert len([f for f in _os.listdir(region) if f.endswith(".seg")]) == 0

    def test_survives_reopen(self, tmp_path):
        wal = self.make(tmp_path, segment_bytes=1)
        s = demo_schema()
        for i in range(1, 4):
            wal.append(1, i, rows(s, ("a", float(i), i)))
        wal.mark_flushed(1, 1)
        wal.close()
        wal2 = self.make(tmp_path, segment_bytes=1)
        assert [seq for seq, _ in wal2.read_from(1, 1)] == [2, 3]
        # appends after reopen don't collide with existing segment names
        wal2.append(1, 4, rows(s, ("d", 4.0, 40)))
        assert [seq for seq, _ in wal2.read_from(1, 1)] == [2, 3, 4]

    def test_torn_tail_ignored(self, tmp_path):
        import os as _os

        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20)))
        wal.close()
        region = str(tmp_path) + "/region_0"
        seg = [f for f in _os.listdir(region) if f.endswith(".seg")][0]
        p = _os.path.join(region, seg)
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[:-5])  # tear the tail record
        wal2 = self.make(tmp_path)
        assert [seq for seq, _ in wal2.read_from(1, 1)] == [1]

    def test_engine_end_to_end_recovery(self, tmp_path):
        """Full engine crash/replay over the shared log backend."""
        import numpy as np

        import horaedb_tpu

        db = horaedb_tpu.connect(str(tmp_path), wal_backend="shared_log")
        db.execute(
            "CREATE TABLE t1 (h string TAG, v double, ts timestamp KEY) ENGINE=Analytic"
        )
        db.execute(
            "CREATE TABLE t2 (h string TAG, v double, ts timestamp KEY) ENGINE=Analytic"
        )
        db.execute("INSERT INTO t1 (h, v, ts) VALUES ('a', 1.0, 1000)")
        db.execute("INSERT INTO t2 (h, v, ts) VALUES ('b', 2.0, 2000)")
        db.execute("INSERT INTO t1 (h, v, ts) VALUES ('c', 3.0, 3000)")
        # crash: no flush, no close — a second connection replays the WAL

        db2 = horaedb_tpu.connect(str(tmp_path), wal_backend="shared_log")
        r1 = db2.execute("SELECT h, v FROM t1 ORDER BY ts").to_pylist()
        r2 = db2.execute("SELECT h, v FROM t2").to_pylist()
        assert r1 == [{"h": "a", "v": 1.0}, {"h": "c", "v": 3.0}]
        assert r2 == [{"h": "b", "v": 2.0}]
        db2.close()

    def test_torn_tail_then_append_stays_replayable(self, tmp_path):
        """Appends after a torn-tail crash must not bury the tear mid-file
        (the torn segment is truncated on open; rotation never reuses a
        crashed segment's name)."""
        import os as _os

        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.append(1, 2, rows(s, ("b", 2.0, 20)))
        wal.close()
        region = str(tmp_path) + "/region_0"
        seg = [f for f in _os.listdir(region) if f.endswith(".seg")][0]
        p = _os.path.join(region, seg)
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[:-5])  # crash mid-write of record 2

        wal2 = self.make(tmp_path)
        wal2.append(1, 2, rows(s, ("b2", 2.5, 25)))  # re-log the lost write
        wal2.close()
        wal3 = self.make(tmp_path)
        got = [(seq, RowGroup.from_arrow(s, b).column("name")[0])
               for seq, b in wal3.read_from(1, 1)]
        assert got == [(1, "a"), (2, "b2")]

    def test_append_after_delete_rejected(self, tmp_path):
        import pytest as _pytest

        wal = self.make(tmp_path)
        s = demo_schema()
        wal.append(1, 1, rows(s, ("a", 1.0, 10)))
        wal.delete_table(1)
        with _pytest.raises(ValueError, match="deleted"):
            wal.append(1, 2, rows(s, ("b", 2.0, 20)))
