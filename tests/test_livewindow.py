"""Live window state tests (ISSUE 18): device-resident ring-buffer
partial aggregates for O(1) open-tail dashboard reads. The acceptance
property is exact state==rescan equivalence under randomized interleaved
ingest — in-order, late-but-in-ring, and older-than-tail rows — plus
ring rollover, eviction mid-query, the ``HORAEDB_LIVEWINDOW=0`` kill
switch, the PromQL counter fold over adjacent-bucket partials, the
promote/evict decision-journal loop, and ledger/EXPLAIN parity
(``route=livewindow`` + ``state_buckets`` from the ONE executor
predicate)."""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.proxy import Proxy
from horaedb_tpu.proxy.promql import evaluate_range, parse_promql
from horaedb_tpu.state.livewindow import (
    _M_READS_PROMQL,
    STORE,
    livewindow_enabled,
)
from horaedb_tpu.utils.querystats import STATS_STORE

from test_rules import _rows_close

MIN = 60_000
END = (1_786_000_000_000 // MIN) * MIN


@pytest.fixture(autouse=True)
def _fresh_store():
    """The live-window store is process-global (like STATS_STORE):
    every test starts and ends with no resident states."""
    STORE.clear()
    yield
    STORE.clear()


def _create(db, name):
    db.execute(
        f"CREATE TABLE {name} (host string TAG, value double NOT NULL, "
        "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic "
        "WITH (segment_duration='2h', update_mode='append')"
    )


def _insert(db, name, rows):
    vals = ",".join(f"('{h}', {v!r}, {t})" for h, v, t in rows)
    db.execute(f"INSERT INTO {name} (host, value, ts) VALUES {vals}")


def _seed(db, name, minutes=200, step_s=20, n_hosts=3, seed=5):
    _create(db, name)
    rng = np.random.default_rng(seed)
    start = END - minutes * MIN
    rows = []
    for t in range(start, END, step_s * 1000):
        for h in range(n_hosts):
            rows.append((f"h{h}", float(rng.normal(10, 3)), t))
    _insert(db, name, rows)
    return start


def _panel(name, where=""):
    w = f"WHERE {where} " if where else ""
    return (
        "SELECT time_bucket(ts, '1m') AS b, host, sum(value) AS s, "
        f"count(value) AS c, min(value) AS mn, max(value) AS mx "
        f"FROM {name} {w}GROUP BY time_bucket(ts, '1m'), host"
    )


def _promote(db, name, q=None):
    q = q or _panel(name)
    for _ in range(3):
        db.execute(q)
    keys = [s["key"] for s in STORE.stats()["states"]]
    assert keys, "promotion did not fire after 3 eligible reads"
    return keys[0]


def _raw(db, q):
    """The kill switch forces the raw rescan; flipping it between two
    reads is safe as long as nothing writes in between."""
    os.environ["HORAEDB_LIVEWINDOW"] = "0"
    try:
        return db.execute(q).to_pylist()
    finally:
        os.environ.pop("HORAEDB_LIVEWINDOW", None)


class TestEquivalence:
    """state == rescan, always — the answers contract."""

    def test_randomized_interleaved_ingest(self):
        """THE acceptance property: randomized rounds of ingest (fresh,
        late-but-in-ring, older-than-tail) interleaved with panel reads
        under random pushdown matchers; every answer must equal the
        kill-switch rescan, and the state path must actually serve."""
        db = horaedb_tpu.connect(None)
        try:
            _seed(db, "lw_rand")
            key = _promote(db, "lw_rand")
            rng = np.random.default_rng(17)
            cursor = END
            served = 0
            for trial in range(24):
                batch = []
                for _ in range(int(rng.integers(5, 40))):
                    cursor += int(rng.integers(1_000, 30_000))
                    batch.append(
                        (f"h{int(rng.integers(0, 3))}",
                         float(rng.normal(10, 3)), cursor)
                    )
                if rng.random() < 0.5:
                    # late but in-ring (depth 128 -> ~128 min span)
                    batch.append(
                        ("h1", float(rng.normal(10, 3)),
                         cursor - int(rng.integers(2, 100)) * MIN)
                    )
                if rng.random() < 0.3:
                    # older than the ring tail: poisons the bucket for
                    # rescan, must never poison the answer
                    batch.append(
                        ("h0", float(rng.normal(10, 3)), cursor - 160 * MIN)
                    )
                _insert(db, "lw_rand", batch)
                where = ["", "host = 'h1'", "host != 'h2'"][
                    int(rng.integers(0, 3))
                ]
                q = _panel("lw_rand", where)
                got = db.execute(q)
                path = db.interpreters.executor.last_path
                want = _raw(db, q)
                assert _rows_close(got.to_pylist(), want), (
                    f"trial {trial}: state != rescan for {q!r}"
                )
                if path == "livewindow":
                    served += 1
            assert served >= 8, f"state served only {served}/24 reads"
            assert key in [s["key"] for s in STORE.stats()["states"]]
        finally:
            db.close()

    def test_ring_rollover(self, monkeypatch):
        """A tiny ring (depth 8) rolls over quickly: reused slots must
        reset cleanly and older-than-tail late rows must rescan."""
        monkeypatch.setenv("HORAEDB_LIVEWINDOW_DEPTH", "8")
        db = horaedb_tpu.connect(None)
        try:
            _seed(db, "lw_roll", minutes=30)
            _promote(db, "lw_roll")
            cursor = END
            for _ in range(30):  # ~30 buckets >> depth 8
                cursor += MIN
                _insert(db, "lw_roll", [("h0", 1.5, cursor),
                                        ("h1", 2.5, cursor + 900)])
            q = _panel("lw_roll")
            got = db.execute(q)
            assert db.interpreters.executor.last_path == "livewindow"
            assert _rows_close(got.to_pylist(), _raw(db, q))
            # now a late row that fell off the tail of the small ring
            _insert(db, "lw_roll", [("h0", 99.0, cursor - 20 * MIN)])
            got2 = db.execute(q)
            assert _rows_close(got2.to_pylist(), _raw(db, q))
        finally:
            db.close()

    def test_eviction_mid_query(self):
        """A dropper thread evicts states continuously while the panel
        is read: any individual read may fall back to raw, but no read
        may ever answer wrong, and re-promotion must still work."""
        db = horaedb_tpu.connect(None)
        try:
            _seed(db, "lw_evict", minutes=60)
            _promote(db, "lw_evict")
            _insert(db, "lw_evict", [("h0", 3.0, END + MIN),
                                     ("h1", 4.0, END + 2 * MIN)])
            q = _panel("lw_evict")
            stop = threading.Event()

            def dropper():
                while not stop.is_set():
                    for s in STORE.stats()["states"]:
                        STORE.drop(s["key"], outcome="evict")
                    time.sleep(0.001)

            th = threading.Thread(target=dropper, daemon=True)
            th.start()
            try:
                for _ in range(30):
                    got = db.execute(q).to_pylist()
                    assert _rows_close(got, _raw(db, q))
            finally:
                stop.set()
                th.join(timeout=5)
            # with the dropper gone, the shape re-promotes and serves
            key = _promote(db, "lw_evict")
            _insert(db, "lw_evict", [("h2", 5.0, END + 3 * MIN)])
            got = db.execute(q)
            assert db.interpreters.executor.last_path == "livewindow"
            assert _rows_close(got.to_pylist(), _raw(db, q))
            assert key in [s["key"] for s in STORE.stats()["states"]]
        finally:
            db.close()

    def test_kill_switch(self):
        """HORAEDB_LIVEWINDOW=0 pins the raw path, removes the EXPLAIN
        claim, and a write under the kill switch drops the table's
        states (a re-enabled state can never backfill the fold gap)."""
        db = horaedb_tpu.connect(None)
        try:
            _seed(db, "lw_kill", minutes=60)
            _promote(db, "lw_kill")
            _insert(db, "lw_kill", [("h0", 1.0, END + MIN)])
            q = _panel("lw_kill")
            db.execute(q)
            assert db.interpreters.executor.last_path == "livewindow"
            os.environ["HORAEDB_LIVEWINDOW"] = "0"
            try:
                assert not livewindow_enabled()
                db.execute(q)
                assert db.interpreters.executor.last_path != "livewindow"
                plan = "\n".join(
                    r["plan"]
                    for r in db.execute(f"EXPLAIN {q}").to_pylist()
                )
                assert "LiveWindow:" not in plan
                # the documented drop-on-write contract
                _insert(db, "lw_kill", [("h0", 2.0, END + 2 * MIN)])
                assert not STORE.stats()["states"]
            finally:
                os.environ.pop("HORAEDB_LIVEWINDOW", None)
        finally:
            db.close()


class TestPromqlCounterFold:
    def test_increase_and_rate_from_partials(self):
        """rate()/increase() fold adjacent-bucket firsts/lasts + the
        in-bucket increment ring instead of raw samples, bit-agreeing
        with the kill-switch fold across counter resets."""
        db = horaedb_tpu.connect(None)
        try:
            _create(db, "lw_ctr")
            rows = []
            t = END - 30 * MIN
            while t < END:
                for h, slope in (("h0", 2.0), ("h1", 5.0)):
                    v = 100.0 + slope * ((t - (END - 30 * MIN)) // 10_000)
                    if h == "h0" and t == END - 10 * MIN:
                        v = 1.0  # counter reset
                    rows.append((h, v, t))
                t += 10_000
            _insert(db, "lw_ctr", rows)
            # the counter fold requires the all-tags grouped state
            _promote(
                db, "lw_ctr",
                "SELECT time_bucket(ts, '1m') AS b, host, sum(value) AS s, "
                "count(value) AS c FROM lw_ctr "
                "GROUP BY time_bucket(ts, '1m'), host",
            )
            more = []
            t = END
            while t < END + 10 * MIN:
                for h, slope in (("h0", 2.0), ("h1", 5.0)):
                    more.append((h, 500.0 + slope * ((t - END) // 10_000), t))
                t += 10_000
            _insert(db, "lw_ctr", more)

            def matrix(promql):
                out = evaluate_range(
                    db, parse_promql(promql), END - 20 * MIN,
                    END + 10 * MIN, 2 * MIN,
                )
                return {
                    tuple(sorted(s["metric"].items())):
                        [(ts, float(v)) for ts, v in s["values"]]
                    for s in out
                }

            for expr in ("increase(lw_ctr[2m])", "rate(lw_ctr[2m])",
                         'increase(lw_ctr{host="h0"}[2m])'):
                before = _M_READS_PROMQL.value
                got = matrix(expr)
                assert _M_READS_PROMQL.value > before, (
                    f"{expr}: not served from state partials"
                )
                os.environ["HORAEDB_LIVEWINDOW"] = "0"
                try:
                    ref = matrix(expr)
                finally:
                    os.environ.pop("HORAEDB_LIVEWINDOW", None)
                assert set(got) == set(ref)
                for k in ref:
                    assert len(got[k]) == len(ref[k]), (expr, k)
                    for (t1, v1), (t2, v2) in zip(got[k], ref[k]):
                        assert t1 == t2
                        assert abs(v1 - v2) <= 1e-4 * max(1.0, abs(v2)), (
                            expr, k, t1, v1, v2
                        )
        finally:
            db.close()


class TestDecisionJournal:
    def test_promote_and_evict_are_journaled_and_graded(self):
        """Promotion records a loop=livewindow decision with a predicted
        hit count; eviction resolves it against realized hits, so the
        calibration table grades the loop (PR-16 discipline)."""
        db = horaedb_tpu.connect(None)
        try:
            _seed(db, "lw_jrnl", minutes=30)
            key = _promote(db, "lw_jrnl")
            rows = db.execute(
                "SELECT loop, choice, resolved, outcome "
                "FROM system.public.decisions"
            ).to_pylist()
            mine = [r for r in rows if r["loop"] == "livewindow"]
            assert mine, "no livewindow decision journaled at promote"
            assert any(r["choice"] == "promote" and not r["resolved"]
                       for r in mine)
            # serve a few reads, then evict: the decision resolves with
            # the realized hit count
            _insert(db, "lw_jrnl", [("h0", 1.0, END + MIN)])
            q = _panel("lw_jrnl")
            for _ in range(3):
                db.execute(q)
            STORE.drop(key, outcome="evict")
            rows = db.execute(
                "SELECT loop, choice, resolved, outcome "
                "FROM system.public.decisions"
            ).to_pylist()
            done = [r for r in rows
                    if r["loop"] == "livewindow" and r["resolved"]]
            assert any(r["outcome"] == "evict" for r in done)
            cal = db.execute(
                "SELECT loop, samples FROM system.public.calibration"
            ).to_pylist()
            g = [r for r in cal if r["loop"] == "livewindow"]
            assert g and int(g[0]["samples"]) >= 1, (
                "livewindow eviction did not grade the calibration loop"
            )
        finally:
            db.close()


class TestLedgerAndExplain:
    def test_route_and_state_buckets_parity(self):
        """The ONE eligibility predicate drives EXPLAIN's promise and
        the serve: ``LiveWindow:`` + route=livewindow in the plan text,
        route=livewindow + state_buckets in the query_stats ledger."""
        db = horaedb_tpu.connect(None)
        proxy = Proxy(db)
        try:
            _seed(db, "lw_ledger", minutes=60)
            for _ in range(3):
                proxy.handle_sql(_panel("lw_ledger"))
            _insert(db, "lw_ledger", [("h0", 7.0, END + MIN),
                                      ("h1", 8.0, END + MIN + 500)])
            q = _panel("lw_ledger")
            plan = "\n".join(
                r["plan"] for r in db.execute(f"EXPLAIN {q}").to_pylist()
            )
            assert "LiveWindow:" in plan
            assert "route=livewindow" in plan
            proxy.handle_sql(q)
            assert db.interpreters.executor.last_path == "livewindow"
            mine = [e for e in STATS_STORE.list()
                    if "lw_ledger" in e.get("sql", "")]
            assert any(e.get("route") == "livewindow" for e in mine)
            served = [e for e in mine if e.get("route") == "livewindow"]
            assert any(int(e.get("state_buckets") or 0) > 0
                       for e in served), (
                "route=livewindow row carries no state_buckets"
            )
        finally:
            db.close()
