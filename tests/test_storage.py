"""Storage slice tests: object store, Parquet SSTs, levels, manifest.

Mirrors the reference's test strategy: round-trips (parquet writer tests,
sst/parquet/writer.rs:653-964), manifest recovery with in-memory stores
(manifest/details.rs:926-1389).
"""

import numpy as np
import pytest

from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema, TimeRange
from horaedb_tpu.engine.manifest import (
    AddFile,
    AlterSchema,
    Flushed,
    Manifest,
    RemoveFile,
    TableManifestState,
)
from horaedb_tpu.engine.sst import FileHandle, LevelsController, SstReader, SstWriter
from horaedb_tpu.engine.sst.meta import SstMeta, sst_path
from horaedb_tpu.engine.sst.writer import WriteOptions
from horaedb_tpu.table_engine import ColumnFilter, FilterOp, Predicate
from horaedb_tpu.utils.object_store import LocalDiskStore, MemCacheStore, MemoryStore


def demo_schema() -> Schema:
    return Schema.build(
        [
            ColumnSchema("name", DatumKind.STRING, is_tag=True),
            ColumnSchema("value", DatumKind.DOUBLE),
            ColumnSchema("t", DatumKind.TIMESTAMP),
        ],
        timestamp_column="t",
    )


def make_rows(n, t0=0, step=1000, hosts=("h1", "h2")):
    return [
        {"name": hosts[i % len(hosts)], "value": float(i), "t": t0 + i * step}
        for i in range(n)
    ]


class TestObjectStores:
    @pytest.mark.parametrize("kind", ["memory", "disk", "cache"])
    def test_basic_ops(self, kind, tmp_path):
        if kind == "memory":
            store = MemoryStore()
        elif kind == "disk":
            store = LocalDiskStore(str(tmp_path))
        else:
            store = MemCacheStore(MemoryStore(), capacity_bytes=1 << 20)
        store.put("a/b/one", b"hello world")
        store.put("a/two", b"xy")
        assert store.get("a/b/one") == b"hello world"
        assert store.get_range("a/b/one", 6, 11) == b"world"
        assert store.head("a/two") == 2
        assert list(store.list("a/")) == ["a/b/one", "a/two"]
        assert store.exists("a/two")
        store.delete("a/two")
        assert not store.exists("a/two")
        with pytest.raises(FileNotFoundError):
            store.get("a/two")

    def test_disk_put_is_atomic_no_tmp_listed(self, tmp_path):
        store = LocalDiskStore(str(tmp_path))
        store.put("x", b"1" * 1024)
        assert list(store.list()) == ["x"]

    def test_disk_path_escape_rejected(self, tmp_path):
        store = LocalDiskStore(str(tmp_path))
        with pytest.raises(ValueError):
            store.put("../evil", b"x")

    def test_cache_hits(self):
        inner = MemoryStore()
        store = MemCacheStore(inner, capacity_bytes=1 << 20)
        store.put("k", b"v" * 100)
        store.get("k")
        store.get("k")
        assert store.hits >= 1


class TestSstRoundTrip:
    def test_write_read_meta(self, tmp_store):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, make_rows(100)).sorted_by_key()
        writer = SstWriter(tmp_store, WriteOptions(num_rows_per_row_group=32))
        path = sst_path(0, 1, 7)
        meta = writer.write(path, 7, rg, max_sequence=42)
        assert meta.num_rows == 100
        assert meta.max_sequence == 42
        assert meta.size_bytes > 0
        assert meta.time_range == TimeRange(0, 99_001)

        reader = SstReader(tmp_store, path)
        assert reader.read_meta().to_dict() == meta.to_dict()
        back = reader.read(schema)
        assert len(back) == 100
        assert sorted(back.to_pylist(), key=lambda r: r["t"]) == sorted(
            rg.to_pylist(), key=lambda r: r["t"]
        )

    def test_row_group_pruning_by_time(self, tmp_store):
        schema = demo_schema()
        # 4 row groups of 25 rows each, times 0..99_000
        rg = RowGroup.from_rows(schema, make_rows(100)).sorted_by_key()
        # sort by key interleaves hosts; re-sort by time for deterministic
        # row-group time spans in this test
        order = np.argsort(rg.timestamps, kind="stable")
        rg = rg.take(order)
        writer = SstWriter(tmp_store, WriteOptions(num_rows_per_row_group=25))
        path = sst_path(0, 1, 1)
        writer.write(path, 1, rg, max_sequence=1)
        reader = SstReader(tmp_store, path)
        pred = Predicate(time_range=TimeRange(0, 25_000))
        kept = reader.prune_row_groups(schema, pred)
        assert kept == [0]
        out = reader.read(schema, pred)
        assert len(out) == 25

    def test_row_group_pruning_by_filter(self, tmp_store):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, make_rows(100)).sorted_by_key()
        order = np.argsort(rg.column("value"), kind="stable")
        rg = rg.take(order)
        writer = SstWriter(tmp_store, WriteOptions(num_rows_per_row_group=50))
        path = sst_path(0, 1, 2)
        writer.write(path, 2, rg, max_sequence=1)
        reader = SstReader(tmp_store, path)
        pred = Predicate.all_time([ColumnFilter("value", FilterOp.GT, 80.0)])
        kept = reader.prune_row_groups(schema, pred)
        assert kept == [1]

    def test_projection_keeps_keys(self, tmp_store):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, make_rows(10)).sorted_by_key()
        writer = SstWriter(tmp_store)
        path = sst_path(0, 1, 3)
        writer.write(path, 3, rg, max_sequence=1)
        out = SstReader(tmp_store, path).read(schema, projection=["value"])
        # tsid + t force-included
        assert set(out.schema.names()) == {"tsid", "t", "value"}

    def test_empty_result_when_fully_pruned(self, tmp_store):
        schema = demo_schema()
        rg = RowGroup.from_rows(schema, make_rows(10)).sorted_by_key()
        writer = SstWriter(tmp_store)
        path = sst_path(0, 1, 4)
        writer.write(path, 4, rg, max_sequence=1)
        out = SstReader(tmp_store, path).read(
            schema, Predicate(time_range=TimeRange(1_000_000, 2_000_000))
        )
        assert len(out) == 0


def mk_meta(fid, lo, hi, seq=1, rows=10):
    return SstMeta(
        file_id=fid,
        time_range=TimeRange(lo, hi),
        max_sequence=seq,
        num_rows=rows,
        size_bytes=100,
        schema_version=1,
        column_ranges={},
    )


class TestLevelsController:
    def test_add_pick_remove(self):
        lc = LevelsController()
        lc.add_file(0, FileHandle(mk_meta(1, 0, 100), "p1", 0))
        lc.add_file(0, FileHandle(mk_meta(2, 50, 150), "p2", 0))
        lc.add_file(1, FileHandle(mk_meta(3, 0, 200, seq=0), "p3", 1))
        assert [h.file_id for h in lc.pick_overlapping(TimeRange(120, 130))] == [2, 3]
        assert lc.max_sequence() == 1
        lc.remove_files(0, [1])
        assert [h.file_id for h in lc.all_files()] == [2, 3]
        purged = lc.drain_purge_queue()
        assert [h.file_id for h in purged] == [1]
        assert lc.drain_purge_queue() == []

    def test_expired_files(self):
        lc = LevelsController()
        lc.add_file(0, FileHandle(mk_meta(1, 0, 100), "p1", 0))
        lc.add_file(0, FileHandle(mk_meta(2, 5000, 6000), "p2", 0))
        expired = lc.expired_files(now_ms=10_000, ttl_ms=5_000)
        assert [h.file_id for h in expired] == [1]

    def test_invalid_level(self):
        with pytest.raises(ValueError):
            LevelsController().add_file(5, FileHandle(mk_meta(1, 0, 1), "p", 5))


class TestManifest:
    def edits(self):
        return [
            AlterSchema(demo_schema()),
            AddFile(0, mk_meta(1, 0, 100, seq=10), "0/1/1.sst"),
            Flushed(10),
        ]

    @pytest.mark.parametrize("store_kind", ["memory", "disk"])
    def test_append_and_recover(self, store_kind, tmp_path):
        store = MemoryStore() if store_kind == "memory" else LocalDiskStore(str(tmp_path))
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        m.append_edits([AddFile(0, mk_meta(2, 100, 200, seq=20), "0/1/2.sst"), Flushed(20)])

        # Fresh Manifest object = process restart.
        m2 = Manifest(store, 0, 1)
        st = m2.load()
        assert st.schema == demo_schema()
        assert [h.file_id for h in st.levels.files_at(0)] == [1, 2]
        assert st.flushed_sequence == 20
        assert st.next_file_id == 3

    def test_snapshot_compacts_logs(self):
        store = MemoryStore()
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        for i in range(2, 40):
            m.append_edits([AddFile(0, mk_meta(i, 0, 100), f"0/1/{i}.sst")])
        logs = [p for p in store.list("manifest/0/1/") if "log." in p]
        assert len(logs) < 39  # snapshots pruned covered logs
        st = Manifest(store, 0, 1).load()
        assert len(st.levels.files_at(0)) == 39

    def test_remove_file_after_snapshot(self):
        store = MemoryStore()
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        m.snapshot()
        m.append_edits([RemoveFile(0, 1)])
        st = Manifest(store, 0, 1).load()
        assert st.levels.files_at(0) == []

    def test_destroy(self):
        store = MemoryStore()
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        assert m.exists()
        m.destroy()
        assert not m.exists()

    def test_reopen_after_truncating_snapshot_keeps_new_edits(self):
        """Silent-data-loss regression (found by tools/fuzz.py seed 2):
        a snapshot that truncated EVERY log left a fresh handle thinking
        the next log seq was 0; its appends landed at seqs <= the
        snapshot watermark and every future load SKIPPED them — recovery
        reverted to the snapshot and the orphan sweep then deleted the
        SSTs those invisible edits added."""
        store = MemoryStore()
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        for i in range(2, 20):  # crosses SNAPSHOT_EVERY_N_LOGS
            m.append_edits([AddFile(0, mk_meta(i, 0, 100), f"0/1/{i}.sst")])
        m.snapshot()  # truncates ALL logs; watermark > 0

        # Process restart: new handle must append PAST the watermark.
        m2 = Manifest(store, 0, 1)
        st = m2.load()
        n_before = len(st.levels.files_at(0))
        m2.append_edits([AddFile(0, mk_meta(100, 0, 100), "0/1/100.sst")])
        m2.append_edits([RemoveFile(0, 2)])

        # Same handle sees them...
        st2 = m2.load()
        assert {h.file_id for h in st2.levels.files_at(0)} == (
            {h.file_id for h in st.levels.files_at(0)} | {100}
        ) - {2}
        # ...and so does the NEXT restart (the bug: these were skipped).
        st3 = Manifest(store, 0, 1).load()
        assert len(st3.levels.files_at(0)) == n_before  # +1 added, -1 removed
        assert 100 in {h.file_id for h in st3.levels.files_at(0)}
        assert 2 not in {h.file_id for h in st3.levels.files_at(0)}

    def test_snapshot_then_more_snapshots_round_trip(self):
        """Repeated append/snapshot/reopen cycles never lose edits."""
        store = MemoryStore()
        expected: set[int] = set()
        fid = 1
        for cycle in range(6):
            m = Manifest(store, 0, 1)
            for _ in range(10):
                m.append_edits([AddFile(0, mk_meta(fid, 0, 100), f"0/1/{fid}.sst")])
                expected.add(fid)
                fid += 1
            if cycle % 2:
                m.snapshot()
        st = Manifest(store, 0, 1).load()
        assert {h.file_id for h in st.levels.files_at(0)} == expected
        assert Manifest(store, 0, 1).exists()  # snapshot persists

    def test_append_after_recover_no_collision(self):
        """Log seq must continue after the highest recovered seq."""
        store = MemoryStore()
        m = Manifest(store, 0, 1)
        m.append_edits(self.edits())
        m2 = Manifest(store, 0, 1)
        m2.load()
        m2.append_edits([Flushed(30)])
        st = Manifest(store, 0, 1).load()
        assert st.flushed_sequence == 30


class TestRowGroupBloomFilters:
    """Tag point lookups prune row groups min/max stats can't
    (ref: the xor filters of row_group_pruner.rs:283-288)."""

    def test_filter_unit(self):
        from horaedb_tpu.engine.sst.filters import build_filter, might_contain

        f = build_filter([f"host_{i}" for i in range(100)])
        assert all(might_contain(f, f"host_{i}") for i in range(100))
        misses = sum(might_contain(f, f"absent_{i}") for i in range(1000))
        assert misses < 60  # ~1-2% FP target, generous bound
        assert might_contain(b"", "anything")  # absent filter never prunes

    def test_prunes_groups_minmax_cannot(self, tmp_path):
        import numpy as np

        from horaedb_tpu.common_types import ColumnSchema, DatumKind, RowGroup, Schema
        from horaedb_tpu.common_types.schema import compute_tsid
        from horaedb_tpu.engine.sst.reader import SstReader
        from horaedb_tpu.engine.sst.writer import SstWriter, WriteOptions
        from horaedb_tpu.table_engine.predicate import ColumnFilter, FilterOp, Predicate
        from horaedb_tpu.utils.object_store import MemoryStore

        schema = Schema.build(
            [
                ColumnSchema("host", DatumKind.STRING, is_tag=True),
                ColumnSchema("v", DatumKind.DOUBLE),
                ColumnSchema("ts", DatumKind.TIMESTAMP),
            ],
            timestamp_column="ts",
        )
        # Each 64-row group holds DISJOINT hosts, but with names chosen so
        # min/max ranges OVERLAP across groups (a_/z_ mix in every group).
        n_groups_written = 4
        rows_per = 64
        hosts, ts = [], []
        for g in range(n_groups_written):
            for i in range(rows_per):
                prefix = "a" if i % 2 == 0 else "z"
                hosts.append(f"{prefix}_{g}_{i}")
                ts.append(g * rows_per + i)
        hosts = np.array(hosts, dtype=object)
        data = RowGroup(
            schema,
            {
                "tsid": compute_tsid([hosts]),
                "host": hosts,
                "v": np.arange(len(hosts), dtype=np.float64),
                "ts": np.array(ts, dtype=np.int64),
            },
        )
        store = MemoryStore()
        writer = SstWriter(store, WriteOptions(num_rows_per_row_group=rows_per))
        meta = writer.write("t.sst", 1, data, max_sequence=1)
        assert len(meta.row_group_filters) == n_groups_written

        reader = SstReader(store, "t.sst")
        target = "a_2_10"  # lives only in group 2
        pred = Predicate.all_time([ColumnFilter("host", FilterOp.EQ, target)])
        keep = reader.prune_row_groups(schema, pred)
        assert keep == [2], f"bloom should prune to group 2, kept {keep}"
        out = reader.read(schema, pred)
        assert target in set(out.column("host"))

        # IN across two groups keeps both; absent value prunes everything
        pred = Predicate.all_time(
            [ColumnFilter("host", FilterOp.IN, ("a_0_0", "a_3_2"))]
        )
        assert set(reader.prune_row_groups(schema, pred)) == {0, 3}
        pred = Predicate.all_time([ColumnFilter("host", FilterOp.EQ, "nope")])
        assert reader.prune_row_groups(schema, pred) == []
