"""Meta HA: leader election + follower redirect + leader failover
(ref model: horaemeta member election, member.go:41-283)."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from horaedb_tpu.meta.election import FileLease

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestFileLease:
    def test_single_acquire_and_renew(self, tmp_path):
        l1 = FileLease(str(tmp_path / "lock"), "m1:1", ttl_s=5)
        assert l1.try_acquire()
        assert l1.leader() == "m1:1"
        assert l1.renew()

    def test_second_candidate_stands_down(self, tmp_path):
        l1 = FileLease(str(tmp_path / "lock"), "m1:1", ttl_s=5)
        l2 = FileLease(str(tmp_path / "lock"), "m2:2", ttl_s=5)
        assert l1.try_acquire()
        assert not l2.try_acquire()
        assert l2.leader() == "m1:1"
        assert not l2.renew()

    def test_takeover_after_expiry(self, tmp_path):
        l1 = FileLease(str(tmp_path / "lock"), "m1:1", ttl_s=0.2)
        l2 = FileLease(str(tmp_path / "lock"), "m2:2", ttl_s=5)
        assert l1.try_acquire()
        time.sleep(0.3)
        assert l2.try_acquire()
        assert not l1.renew()  # old leader sees it lost

    def test_resign_frees_lock(self, tmp_path):
        l1 = FileLease(str(tmp_path / "lock"), "m1:1", ttl_s=5)
        l2 = FileLease(str(tmp_path / "lock"), "m2:2", ttl_s=5)
        assert l1.try_acquire()
        l1.resign()
        assert l2.try_acquire()


# ---- two-meta process e2e --------------------------------------------------


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http(method, url, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}, method=method
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except Exception:
            return e.code, {}


def wait_until(fn, timeout=60.0, desc=""):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = fn()
            if last:
                return last
        except Exception as e:
            last = e
        time.sleep(0.3)
    raise TimeoutError(f"{desc}: last={last}")


CPU_ENV = {
    **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


class TestTwoMetaFailover:
    def test_leader_failover_preserves_state(self, tmp_path):
        ha_dir = str(tmp_path / "ha")
        ports = [free_port(), free_port()]
        procs = []
        for port in ports:
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "horaedb_tpu.meta",
                        "--port", str(port),
                        "--ha-dir", ha_dir,
                        "--advertise", f"127.0.0.1:{port}",
                        "--num-shards", "2",
                        "--lease-ttl", "1.0",
                        "--tick-interval", "0.2",
                    ],
                    env=CPU_ENV,
                    stdout=open(tmp_path / f"meta{port}.log", "wb"),
                    stderr=subprocess.STDOUT,
                )
            )
        try:
            for port in ports:
                wait_until(
                    lambda p=port: http("GET", f"http://127.0.0.1:{p}/health")[0] == 200,
                    desc=f"meta {port} health",
                )

            def leader_port():
                leaders = [
                    p for p in ports
                    if http("GET", f"http://127.0.0.1:{p}/health")[1].get("leader")
                ]
                return leaders[0] if len(leaders) == 1 else None

            lp = wait_until(leader_port, desc="exactly one leader")
            follower = next(p for p in ports if p != lp)

            # follower redirects mutations with a leader hint (421)
            status, body = http(
                "POST",
                f"http://127.0.0.1:{follower}/meta/v1/node/heartbeat",
                {"endpoint": "127.0.0.1:59999"},
            )
            assert status == 421 and body.get("leader") == f"127.0.0.1:{lp}", body

            # MetaClient follows the hint transparently
            from horaedb_tpu.cluster.meta_client import MetaClient

            client = MetaClient([f"127.0.0.1:{follower}", f"127.0.0.1:{lp}"])
            out = client.heartbeat("127.0.0.1:59999")
            assert "desired" in out

            # kill the leader: the follower takes over and RELOADS state
            # (the registered node survives in the shared journal)
            victim = procs[ports.index(lp)]
            victim.kill()
            victim.wait(timeout=10)

            def new_leader():
                s, b = http("GET", f"http://127.0.0.1:{follower}/health")
                return s == 200 and b.get("leader")

            wait_until(new_leader, desc="follower takes leadership")
            s, nodes = http("GET", f"http://127.0.0.1:{follower}/meta/v1/nodes")
            assert s == 200
            assert any(
                n["endpoint"] == "127.0.0.1:59999" for n in nodes["nodes"]
            ), nodes
            out = client.heartbeat("127.0.0.1:59999")
            assert "desired" in out
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
