"""Remote engine + gRPC storage service tests
(ref model: remote_engine_client tests + integration_tests/dist_query —
a 2-node cluster answering a group-by over a partitioned table where each
node only scans its own partitions, results identical to single-node).

Two layers:
- in-process gRPC round trips (server + client in one process);
- 2-process static cluster: partitioned table with sub-tables hashed over
  both nodes, distributed partial-agg push-down over the wire.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import horaedb_tpu
from horaedb_tpu.remote import GrpcServer, RemoteEngineClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


DDL = (
    "CREATE TABLE rt (host string TAG, v double, "
    "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
)


@pytest.fixture()
def grpc_env():
    conn = horaedb_tpu.connect(None)
    conn.execute(DDL)
    server = GrpcServer(conn, port=0)  # ephemeral port
    server.start()
    endpoint = f"127.0.0.1:{server.bound_port}"
    yield conn, endpoint
    server.stop()
    conn.close()


class TestGrpcRoundTrip:
    def test_write_read(self, grpc_env):
        conn, ep = grpc_env
        client = RemoteEngineClient(ep)
        from horaedb_tpu.common_types import RowGroup

        t = conn.catalog.open("rt")
        rows = RowGroup.from_rows(
            t.schema,
            [{"host": "a", "v": 1.0, "ts": 1000}, {"host": "b", "v": 2.0, "ts": 2000}],
        )
        assert client.write("rt", rows) == 2
        out = client.read("rt", t.schema, None)
        got = sorted((r["host"], r["v"]) for r in out.to_pylist())
        assert got == [("a", 1.0), ("b", 2.0)]

    def test_read_with_predicate_and_projection(self, grpc_env):
        conn, ep = grpc_env
        client = RemoteEngineClient(ep)
        from horaedb_tpu.common_types import RowGroup, TimeRange
        from horaedb_tpu.table_engine.predicate import Predicate

        t = conn.catalog.open("rt")
        t.write(RowGroup.from_rows(
            t.schema,
            [{"host": "a", "v": 1.0, "ts": 1000}, {"host": "a", "v": 2.0, "ts": 5000}],
        ))
        out = client.read("rt", t.schema, Predicate(TimeRange(0, 2000)), projection=["v", "ts"])
        got = out.to_pylist()
        # projection keeps key columns (tsid) — dedup needs them
        assert len(got) == 1 and got[0]["v"] == 1.0 and got[0]["ts"] == 1000

    def test_paged_read_streams_windows(self):
        """ReadPage: one segment window per RPC, stateless continuation
        tokens, union of pages == one-shot read (VERDICT r4 missing #3 —
        the remote engine no longer needs one giant envelope)."""
        conn = horaedb_tpu.connect(None)
        conn.execute(
            "CREATE TABLE pg (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic "
            "WITH (segment_duration='1h')"
        )
        server = GrpcServer(conn, port=0)
        server.start()
        try:
            hour = 3_600_000
            rows = []
            for w in range(4):
                rows += [
                    f"('h{i % 3}', {float(w * 100 + i)}, {w * hour + i * 1000})"
                    for i in range(50)
                ]
            conn.execute("INSERT INTO pg (host, v, ts) VALUES " + ", ".join(rows))
            conn.flush_all()
            t = conn.catalog.open("pg")
            client = RemoteEngineClient(f"127.0.0.1:{server.bound_port}")
            pages = list(client.read_pages("pg", t.schema, None))
            assert len(pages) == 4, [len(p) for p in pages]
            assert all(len(p) == 50 for p in pages)
            streamed = sorted(
                (r["host"], r["v"], r["ts"])
                for p in pages
                for r in p.to_pylist()
            )
            oneshot = sorted(
                (r["host"], r["v"], r["ts"])
                for r in client.read("pg", t.schema, None).to_pylist()
            )
            assert streamed == oneshot
            # time-pruned stream touches only matching windows
            from horaedb_tpu.common_types import TimeRange
            from horaedb_tpu.table_engine.predicate import Predicate

            pages = list(
                client.read_pages(
                    "pg", t.schema, Predicate(TimeRange(hour, 3 * hour))
                )
            )
            assert len(pages) == 2
        finally:
            server.stop()
            conn.close()

    def test_partial_agg_over_wire(self, grpc_env):
        conn, ep = grpc_env
        client = RemoteEngineClient(ep)
        from horaedb_tpu.common_types import RowGroup

        t = conn.catalog.open("rt")
        t.write(RowGroup.from_rows(
            t.schema,
            [{"host": "a", "v": float(i), "ts": 1000 + i} for i in range(10)],
        ))
        spec = {
            "predicate": {"time_range": [0, 10**15], "filters": []},
            "exact_filters": [],
            "device_filters": [["v", ">", 3.0]],
            "group_tags": ["host"],
            "bucket_ms": 0,
            "agg_cols": ["v"],
        }
        names, arrays, metrics = client.partial_agg("rt", spec)
        assert metrics.get("elapsed_ms") is not None  # stage metrics ride home
        d = dict(zip(names, arrays))
        assert list(d["__k0"]) == ["a"]
        assert d["__count_rows"][0] == 6  # v in 4..9
        assert d["__sum_0"][0] == sum(range(4, 10))
        assert d["__min_0"][0] == 4.0 and d["__max_0"][0] == 9.0

    def test_trace_id_and_substage_metrics_propagate(self, grpc_env):
        """The coordinator's request id rides the wire spec; the owner
        records a correlatable span and returns sub-stage metrics
        (ref: RemoteTaskContext.remote_metrics)."""
        conn, ep = grpc_env
        client = RemoteEngineClient(ep)
        from horaedb_tpu.common_types import RowGroup

        t = conn.catalog.open("rt")
        t.write(RowGroup.from_rows(
            t.schema,
            [{"host": "a", "v": float(i), "ts": 5000 + i} for i in range(4)],
        ))
        spec = {
            "predicate": {"time_range": [0, 10**15], "filters": []},
            "exact_filters": [],
            "device_filters": [],
            "group_tags": ["host"],
            "bucket_ms": 0,
            "agg_cols": ["v"],
            "trace": {"request_id": 4242},
        }
        _, _, metrics = client.partial_agg("rt", spec)
        # sub-stage spans came home
        assert metrics["path"] in ("kernel", "host")
        assert "scan_ms" in metrics and "agg_ms" in metrics
        assert metrics["rows_scanned"] >= 4
        # the owner's span ring carries the origin's request id
        spans = [sp for sp in conn.remote_spans if sp.get("request_id") == 4242]
        assert spans and spans[-1]["table"] == "rt"

    def test_table_info_and_not_found(self, grpc_env):
        conn, ep = grpc_env
        client = RemoteEngineClient(ep)
        info = client.get_table_info("rt")
        assert any(c["name"] == "host" for c in info["schema"]["columns"])
        import grpc as grpc_mod

        with pytest.raises(grpc_mod.RpcError) as ei:
            client.get_table_info("nope")
        assert ei.value.code() == grpc_mod.StatusCode.NOT_FOUND

    def test_storage_service_sql(self, grpc_env):
        conn, ep = grpc_env
        import grpc as grpc_mod

        from horaedb_tpu.remote.codec import pack, unpack

        ch = grpc_mod.insecure_channel(ep)
        call = ch.unary_unary("/horaedb.storage/SqlQuery")
        out = unpack(call(pack({"query": "INSERT INTO rt (host, v, ts) VALUES ('x', 5.0, 100)"}), timeout=10))
        assert out == {"affected": 1}
        out = unpack(call(pack({"query": "SELECT host, v FROM rt WHERE host = 'x'"}), timeout=10))
        assert out == {"rows": [{"host": "x", "v": 5.0}]}


# ---- 2-process distributed partition test --------------------------------


def http(method: str, url: str, payload=None, timeout=10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}, method=method
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def sql(port: int, query: str):
    return http("POST", f"http://127.0.0.1:{port}/sql", {"query": query})


CPU_ENV = {
    **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": REPO,
}


@pytest.fixture()
def static_cluster(tmp_path):
    """Two static-mode nodes over a shared store, gRPC enabled."""
    ports = [free_port(), free_port()]
    endpoints = [f"127.0.0.1:{p}" for p in ports]
    data_dir = str(tmp_path / "shared")
    procs = []
    for i, port in enumerate(ports):
        cfg = tmp_path / f"n{i}.toml"
        cfg.write_text(
            f"""
[server]
host = "127.0.0.1"
http_port = {port}
grpc_port = {port + 1000}

[engine]
data_dir = "{data_dir}"

[cluster]
self_endpoint = "{endpoints[i]}"
endpoints = {json.dumps(endpoints)}
"""
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "horaedb_tpu.server", "--config", str(cfg)],
                env=CPU_ENV,
                stdout=open(tmp_path / f"n{i}.log", "wb"),
                stderr=subprocess.STDOUT,
            )
        )
    deadline = time.monotonic() + 60
    for port in ports:
        while True:
            try:
                if http("GET", f"http://127.0.0.1:{port}/health", timeout=2)[0] == 200:
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"node {port} never became healthy")
            time.sleep(0.3)
    yield ports
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestDistributedPartitions:
    def test_partitioned_groupby_spans_nodes(self, static_cluster):
        port_a, port_b = static_cluster
        # The logical table routes to ONE node; its partitions hash over
        # BOTH via sub-table names — a true cross-node partitioned table.
        ddl = (
            "CREATE TABLE dpt (host string TAG, v double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 8 ENGINE=Analytic"
        )
        status, out = sql(port_a, ddl)
        assert status == 200, out
        rows = [f"('h{i % 16}', {float(i)}, {1000 + i})" for i in range(800)]
        status, out = sql(
            port_a, "INSERT INTO dpt (host, v, ts) VALUES " + ", ".join(rows)
        )
        assert status == 200 and out["affected_rows"] == 800, out

        expect = {}
        for h in range(16):
            vals = [float(i) for i in range(800) if i % 16 == h]
            expect[f"h{h}"] = {
                "c": len(vals), "a": float(np.mean(vals)),
                "lo": min(vals), "hi": max(vals),
            }
        q = (
            "SELECT host, count(v) AS c, avg(v) AS a, min(v) AS lo, "
            "max(v) AS hi FROM dpt GROUP BY host"
        )
        for port in (port_a, port_b):
            status, out = sql(port, q)
            assert status == 200, out
            got = {r["host"]: r for r in out["rows"]}
            assert set(got) == set(expect), (port, sorted(got))
            for h, e in expect.items():
                assert got[h]["c"] == e["c"], (port, h)
                np.testing.assert_allclose(got[h]["a"], e["a"], rtol=1e-9)
                assert got[h]["lo"] == e["lo"] and got[h]["hi"] == e["hi"]

    def test_shipped_plan_subtrees_span_nodes(self, static_cluster):
        """VERDICT r4 item 3: window/topk/distinct/full-agg/filter shapes
        execute REMOTELY on partition owners (ExecutePlan RPC) over a
        2-node partitioned table, results matching a numpy oracle, with
        the peer's /debug/remote_spans proving remote execution."""
        port_a, port_b = static_cluster
        ddl = (
            "CREATE TABLE wt (host string TAG, v double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 8 ENGINE=Analytic"
        )
        assert sql(port_a, ddl)[0] == 200
        rows = [
            f"('h{i % 12}', {float((i * 7) % 101)}, {1000 + i})"
            for i in range(600)
        ]
        assert sql(
            port_a, "INSERT INTO wt (host, v, ts) VALUES " + ", ".join(rows)
        )[0] == 200
        data = [
            (f"h{i % 12}", float((i * 7) % 101), 1000 + i) for i in range(600)
        ]

        # EXPLAIN shows the distributed stage.
        status, out = sql(
            port_a,
            "EXPLAIN SELECT host, ts, v, row_number() OVER "
            "(PARTITION BY host ORDER BY ts) AS rn FROM wt",
        )
        assert status == 200
        text = "\n".join(r[next(iter(r))] for r in out["rows"])
        assert "mode=window" in text and "ExecutePlan" in text, text

        # Window over the rule column: per-owner execution is exact.
        status, out = sql(
            port_a,
            "SELECT host, ts, v, row_number() OVER "
            "(PARTITION BY host ORDER BY ts) AS rn FROM wt "
            "ORDER BY host, ts LIMIT 30",
        )
        assert status == 200, out
        per_host: dict = {}
        oracle = []
        for h, v, ts in sorted(data, key=lambda r: (r[0], r[2])):
            per_host[h] = per_host.get(h, 0) + 1
            oracle.append({"host": h, "ts": ts, "v": v, "rn": per_host[h]})
        assert out["rows"] == oracle[:30]

        # Top-k: owners return local top rows, coordinator re-limits.
        status, out = sql(
            port_a, "SELECT host, v, ts FROM wt ORDER BY v DESC, ts LIMIT 7"
        )
        assert status == 200, out
        topk = sorted(data, key=lambda r: (-r[1], r[2]))[:7]
        assert out["rows"] == [
            {"host": h, "v": v, "ts": ts} for h, v, ts in topk
        ]

        # DISTINCT dedups per owner then at the coordinator.
        status, out = sql(
            port_a, "SELECT DISTINCT host FROM wt ORDER BY host"
        )
        assert status == 200, out
        assert [r["host"] for r in out["rows"]] == sorted(
            {h for h, _, _ in data}
        )

        # Full aggregate with FILTER (not kernel-pushable) whose GROUP BY
        # covers the rule column: owners run the whole aggregate.
        status, out = sql(
            port_a,
            "SELECT host, count(v) FILTER (WHERE v > 50) AS big "
            "FROM wt GROUP BY host ORDER BY host",
        )
        assert status == 200, out
        agg: dict = {}
        for h, v, _ in data:
            agg[h] = agg.get(h, 0) + (1 if v > 50 else 0)
        assert out["rows"] == [
            {"host": h, "big": agg[h]} for h in sorted(agg)
        ]

        # Residual WHERE evaluated on the owner (v*2 > 150 can't ride the
        # storage predicate).
        status, out = sql(
            port_a, "SELECT host, v FROM wt WHERE v * 2 > 150 AND ts < 1300"
        )
        assert status == 200, out
        expect_rows = sorted(
            (h, v) for h, v, ts in data if v * 2 > 150 and ts < 1300
        )
        assert sorted((r["host"], r["v"]) for r in out["rows"]) == expect_rows

        # Proof of REMOTE execution: the peer node recorded ExecutePlan
        # spans (partitions hash over both nodes).
        spans = []
        for port in (port_a, port_b):
            st, body = http(
                "GET", f"http://127.0.0.1:{port}/debug/remote_spans"
            )
            assert st == 200
            spans.append([
                s for s in body.get("spans", body if isinstance(body, list) else [])
                if s.get("op") == "execute_plan"
            ])
        assert spans[0] or spans[1], "no ExecutePlan ran on either node"

        # EXPLAIN ANALYZE on the routed query renders the span tree with
        # at least one remote-origin span, and /debug/trace/{request_id}
        # on the executing node returns the same tree as JSON.
        status, out = sql(
            port_a,
            "EXPLAIN ANALYZE SELECT host, v, ts FROM wt "
            "ORDER BY v DESC, ts LIMIT 7",
        )
        assert status == 200, out
        text = "\n".join(r[next(iter(r))] for r in out["rows"])
        assert "Trace: request_id=" in text, text
        assert "[remote " in text, text  # remote-origin span rendered
        rid = text.split("Trace: request_id=")[1].splitlines()[0].strip()

        def walk(node):
            yield node
            for c in node.get("children", ()):
                yield from walk(c)

        found_remote = False
        for port in (port_a, port_b):  # the statement may have forwarded
            st, body = http(
                "GET", f"http://127.0.0.1:{port}/debug/trace/{rid}"
            )
            if st != 200:
                continue
            remote_nodes = [
                n for n in walk(body["root"])
                if (n.get("attrs") or {}).get("origin") == "remote"
            ]
            if remote_nodes and all(
                isinstance(n.get("duration_ms"), (int, float))
                for n in remote_nodes
            ):
                found_remote = True
        assert found_remote, "no stored trace with remote spans found"

    def test_each_node_owns_some_partitions(self, static_cluster, tmp_path):
        port_a, port_b = static_cluster
        ddl = (
            "CREATE TABLE spread (host string TAG, v double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 8 ENGINE=Analytic"
        )
        assert sql(port_a, ddl)[0] == 200
        # Sub-table names hash over both endpoints: with 8 partitions the
        # chance both land on one node is (1/2)^7 per side; assert spread.
        from horaedb_tpu.cluster import RuleBasedRouter
        from horaedb_tpu.table_engine.partition import sub_table_name

        eps = [f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"]
        router = RuleBasedRouter(eps[0], eps)
        owners = {router.route(sub_table_name("spread", i)).endpoint for i in range(8)}
        assert len(owners) == 2, "partitions all hashed onto one node"


class TestRoutedSubTable:
    """Dynamic partition handles: re-resolve ownership through the router
    on every operation, follow moves, refuse non-authoritative local
    routes (ref: remote_engine_client/src/cached_router.rs eviction)."""

    class _FakeRouter:
        def __init__(self, route):
            self._route = route
            self.invalidated = []

        def set(self, route):
            self._route = route

        def route(self, table):
            return self._route

        def invalidate(self, table):
            self.invalidated.append(table)

    def _mk(self, router, conn=None, sub="__rst_0"):
        from horaedb_tpu.remote.client import RoutedSubTable

        if conn is None:
            conn = horaedb_tpu.connect(None)
        conn.execute(
            "CREATE TABLE rst (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        t = conn.catalog.open("rst")
        data = t.physical_datas()[0]
        return (
            RoutedSubTable(
                sub,
                t.schema,
                t.options,
                router=router,
                instance=conn.instance,
                local_open=lambda: data,
            ),
            conn,
        )

    def test_read_windows_streams_local_and_remote(self):
        """RoutedSubTable.read_windows pages through _call (route + close
        guards per page) for BOTH resolutions; union == one-shot read."""
        from horaedb_tpu.cluster.router import Route
        from horaedb_tpu.common_types.row_group import RowGroup

        router = self._FakeRouter(Route("__rst_0", "local", True, source="owned"))
        rst, conn = self._mk(router)
        hour = 3_600_000
        rows = RowGroup.from_rows(rst.schema, [
            {"host": f"h{i % 2}", "v": float(w * 10 + i), "ts": w * hour + i * 1000}
            for w in range(3)
            for i in range(5)
        ])
        assert rst.write(rows) == 15
        conn.flush_all()
        local_pages = list(rst.read_windows())
        assert sum(len(p) for p in local_pages) == 15
        oneshot = sorted(
            (r["host"], r["v"]) for r in rst.read().to_pylist()
        )
        assert sorted(
            (r["host"], r["v"]) for p in local_pages for r in p.to_pylist()
        ) == oneshot
        # remote resolution: a separate OWNER node holds __rst_0 (a real
        # partitioned sub-table, as in test_follows_move_to_remote_owner)
        owner = horaedb_tpu.connect(None)
        owner.execute(
            "CREATE TABLE rst (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 1 ENGINE=Analytic "
            "WITH (segment_duration='1h')"
        )
        owner_rows = [
            f"('h{i % 2}', {float(w * 100 + i)}, {w * hour + i * 1000})"
            for w in range(3)
            for i in range(4)
        ]
        owner.execute(
            "INSERT INTO rst (host, v, ts) VALUES " + ", ".join(owner_rows)
        )
        owner.flush_all()
        server = GrpcServer(owner, port=0)
        server.start()
        try:
            from horaedb_tpu.remote.client import GRPC_PORT_OFFSET

            http_port = server.bound_port - GRPC_PORT_OFFSET
            router.set(Route(
                "__rst_0", f"127.0.0.1:{http_port}", False, source="meta"
            ))
            remote_pages = list(rst.read_windows())
            assert len(remote_pages) >= 2, "not paged by window"
            got = sorted(
                (r["host"], r["v"]) for p in remote_pages for r in p.to_pylist()
            )
            expect = sorted(
                (f"h{i % 2}", float(w * 100 + i))
                for w in range(3)
                for i in range(4)
            )
            assert got == expect
        finally:
            server.stop()
            owner.close()
            conn.close()

    def test_read_pages_spans_graft_under_one_trace(self):
        """Satellite: a routed read_pages stream over multiple windows
        produces one remote span PER PAGE, all grafted under the ONE
        coordinator trace id (span context rides every ReadPage RPC)."""
        from horaedb_tpu.cluster.router import Route
        from horaedb_tpu.utils.tracectx import (
            TRACE_STORE, finish_trace, start_trace,
        )

        router = self._FakeRouter(Route("__rst_0", "local", True, source="owned"))
        rst, conn = self._mk(router)
        hour = 3_600_000
        owner = horaedb_tpu.connect(None)
        owner.execute(
            "CREATE TABLE rst (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 1 ENGINE=Analytic "
            "WITH (segment_duration='1h')"
        )
        owner_rows = [
            f"('h{i % 2}', {float(w * 100 + i)}, {w * hour + i * 1000})"
            for w in range(3)
            for i in range(4)
        ]
        owner.execute(
            "INSERT INTO rst (host, v, ts) VALUES " + ", ".join(owner_rows)
        )
        owner.flush_all()
        server = GrpcServer(owner, port=0)
        server.start()
        try:
            from horaedb_tpu.remote.client import GRPC_PORT_OFFSET

            http_port = server.bound_port - GRPC_PORT_OFFSET
            router.set(Route(
                "__rst_0", f"127.0.0.1:{http_port}", False, source="meta"
            ))
            trace, handle = start_trace(31337, "sql")
            pages = list(rst.read_windows())
            finish_trace(handle)
            assert len(pages) >= 2, "not paged by window"
            entry = TRACE_STORE.get(31337)
            assert entry is not None

            def walk(node):
                yield node
                for c in node.get("children", ()):
                    yield from walk(c)

            remote = [
                n for n in walk(entry["root"])
                if (n.get("attrs") or {}).get("origin") == "remote"
                and n["name"] == "remote_read_page"
            ]
            # one remote span per page, each with a measured duration,
            # all inside the single coordinator tree
            assert len(remote) >= len(pages)
            assert all(
                isinstance(n["duration_ms"], (int, float)) for n in remote
            )
            eps = {n["attrs"].get("endpoint") for n in remote}
            assert eps == {f"127.0.0.1:{server.bound_port}"}
        finally:
            server.stop()
            owner.close()
            conn.close()

    def test_local_route_serves_and_nonauthoritative_refused(self):
        from horaedb_tpu.cluster.router import Route
        from horaedb_tpu.common_types.row_group import RowGroup

        router = self._FakeRouter(Route("__rst_0", "local", True, source="owned"))
        rst, conn = self._mk(router)
        rows = RowGroup.from_rows(
            rst.schema, [{"host": "a", "v": 1.0, "ts": 1000}]
        )
        assert rst.write(rows) == 1
        assert len(rst.read()) == 1
        # Coordinator-down fallback must NOT open shared storage locally.
        router.set(Route("__rst_0", "local", True, source="fallback"))
        with pytest.raises(RuntimeError, match="non-authoritative"):
            rst.read()
        conn.close()

    def test_follows_move_to_remote_owner(self):
        """Handle starts local, route flips to a live remote owner: the
        next op crosses the wire instead of touching stale local state."""
        from horaedb_tpu.cluster.router import Route
        from horaedb_tpu.common_types.row_group import RowGroup

        # Remote owner: a real in-process gRPC server over its own conn.
        owner = horaedb_tpu.connect(None)
        owner.execute(
            "CREATE TABLE rst (host string TAG, v double, ts timestamp "
            "NOT NULL, TIMESTAMP KEY(ts)) "
            "PARTITION BY KEY(host) PARTITIONS 1 ENGINE=Analytic"
        )
        server = GrpcServer(owner, port=0)
        server.start()
        try:
            router = self._FakeRouter(
                Route("__rst_0", "local", True, source="owned")
            )
            rst, conn = self._mk(router)
            rows = RowGroup.from_rows(
                rst.schema, [{"host": "a", "v": 1.0, "ts": 1000}]
            )
            rst.write(rows)
            # Shard moves: route now names the remote owner's HTTP
            # endpoint; gRPC port derives via the +1000 convention.
            http_ep = f"127.0.0.1:{server.bound_port - 1000}"
            router.set(Route("__rst_0", http_ep, False, source="meta"))
            rows2 = RowGroup.from_rows(
                rst.schema, [{"host": "b", "v": 2.0, "ts": 2000}]
            )
            assert rst.write(rows2) == 1
            # The write landed on the OWNER, not the stale local table.
            got = owner.execute("SELECT v FROM rst")
            assert [r["v"] for r in got.to_pylist()] == [2.0]
            conn.close()
        finally:
            server.stop()
            owner.close()

    def test_write_not_retried_on_unavailable(self):
        """UNAVAILABLE is ambiguous for writes (may have applied before
        the connection died) — the write must surface the error, not
        silently double-apply; reads may retry."""
        from horaedb_tpu.cluster.router import Route
        from horaedb_tpu.common_types.row_group import RowGroup
        import grpc as _grpc

        # Remote route to a port nobody listens on -> UNAVAILABLE.
        router = self._FakeRouter(
            Route("__rst_0", "127.0.0.1:9", False, source="meta")
        )
        rst, conn = self._mk(router)
        rows = RowGroup.from_rows(
            rst.schema, [{"host": "a", "v": 1.0, "ts": 1000}]
        )
        with pytest.raises(_grpc.RpcError):
            rst.write(rows)
        assert router.invalidated == []  # no retry attempted for the write
        with pytest.raises(_grpc.RpcError):
            rst.read()
        assert router.invalidated == ["__rst_0"]  # read DID retry once
        conn.close()
