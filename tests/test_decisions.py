"""Decision-journal semantics (ISSUE 16 tentpole).

The journal's accounting contract is the whole point — a decision is
always in exactly one of {resolved, expired, unresolved}, late resolves
are counted misses, ring evictions of unresolved entries are counted
expiries, and the incremental calibration windows must agree with a
naive refold. These tests pin that contract on private journal
instances (the process-global DECISION_JOURNAL is exercised end-to-end
by the tenantsim gates)."""

import math
import random

import pytest

from horaedb_tpu.obs.decisions import (
    DECISION_LOOPS,
    DecisionJournal,
    _ErrWindow,
    _LoopCalibration,
)


def _reconciles(j: DecisionJournal) -> list:
    """issued == resolved + expired + unresolved, per loop."""
    bad = []
    s = j.stats()
    for loop, c in s["loops"].items():
        if c["issued"] != c["resolved"] + c["expired"] + c["unresolved"]:
            bad.append((loop, c))
    return bad


class TestJournalAccounting:
    def test_record_resolve_roundtrip(self):
        j = DecisionJournal(maxlen=8)
        i = j.record("admission", key="shape-a", choice="cheap",
                     features={"est_ms": 12.0}, predicted=0.012)
        assert i > 0
        assert j.resolve(i, actual=0.018, outcome="ok", loop="admission")
        (e,) = j.list(loop="admission")
        assert e["resolved"] and e["outcome"] == "ok"
        assert e["error"] == pytest.approx((0.018 - 0.012) / 0.012)
        assert not _reconciles(j)

    def test_undeclared_loop_refused(self):
        j = DecisionJournal(maxlen=8)
        with pytest.raises(ValueError, match="undeclared decision loop"):
            j.record("mystery", key="k", choice="c")

    def test_ring_rollover_exact_drop_accounting(self):
        """Overflow evicts oldest-first; every eviction ticks dropped,
        and an UNRESOLVED victim is additionally counted expired — the
        ledger reconciles exactly through the rollover."""
        j = DecisionJournal(maxlen=4)
        ids = [j.record("elastic", key=f"s{i}", choice="hold")
               for i in range(10)]
        # resolve two of the still-live tail so both kinds of victims
        # (resolved and unresolved) roll off in later overflow
        assert j.resolve(ids[6], actual=1.0, loop="elastic")
        assert j.resolve(ids[7], actual=1.0, loop="elastic")
        for i in range(10, 16):
            j.record("elastic", key=f"s{i}", choice="hold")
        s = j.stats()
        assert s["size"] == 4
        assert s["dropped"] == 12  # 16 issued, capacity 4
        c = s["loops"]["elastic"]
        assert c["issued"] == 16
        assert c["resolved"] == 2
        # every unresolved entry that rolled off is a counted expiry
        assert c["expired"] == 10
        assert c["unresolved"] == 4
        assert not _reconciles(j)

    def test_resolve_after_rollover_is_counted_miss(self):
        """A resolve whose id already rolled off must be a counted miss
        attributed to the caller's loop — never a KeyError, never a
        silent nothing."""
        j = DecisionJournal(maxlen=2)
        first = j.record("deadline", key="shape", choice="shed")
        for i in range(4):  # roll `first` off the ring
            j.record("deadline", key=f"k{i}", choice="shed")
        assert j.resolve(first, actual=1.0, loop="deadline") is False
        assert j.stats()["loops"]["deadline"]["missed"] == 1
        # a miss with no loop attribution is tolerated but unattributed
        assert j.resolve(999_999) is False
        assert j.stats()["loops"]["deadline"]["missed"] == 1
        assert not _reconciles(j)

    def test_unresolved_expiry_accounting(self, monkeypatch):
        """Unresolved decisions past HORAEDB_DECISION_EXPIRE_MS are lazily
        counted expired; their late resolve is then a miss."""
        j = DecisionJournal(maxlen=8)
        i = j.record("layout_tuner", key="t:c", choice="promote_f32",
                     predicted=100.0)
        monkeypatch.setenv("HORAEDB_DECISION_EXPIRE_MS", "0.0001")
        # any verb triggers the lazy head-expiry scan
        s = j.stats()
        c = s["loops"]["layout_tuner"]
        assert c["expired"] == 1 and c["unresolved"] == 0
        monkeypatch.delenv("HORAEDB_DECISION_EXPIRE_MS")
        assert j.resolve(i, actual=200.0, loop="layout_tuner") is False
        assert j.stats()["loops"]["layout_tuner"]["missed"] == 1
        (e,) = j.list(loop="layout_tuner")
        assert e["outcome"] == "expired" and not e["resolved"]
        assert not _reconciles(j)

    def test_resolve_matching_oldest_first_and_zero_match_is_not_miss(self):
        j = DecisionJournal(maxlen=8)
        a = j.record("deadline", key="shape", choice="shed", predicted=0.5,
                     features={"remaining_s": 0.1})
        b = j.record("deadline", key="shape", choice="shed", predicted=0.5,
                     features={"remaining_s": 0.4})
        n = j.resolve_matching(
            "deadline", "shape", actual=0.2,
            outcome=lambda e: (
                "doomed" if 0.2 >= e["features"]["remaining_s"]
                else "premature"
            ),
        )
        assert n == 2
        by_id = {e["id"]: e for e in j.list(loop="deadline")}
        assert by_id[a]["outcome"] == "doomed"
        assert by_id[b]["outcome"] == "premature"
        # a completion with nothing pending resolves nothing and counts
        # no miss — nothing was issued for it
        assert j.resolve_matching("deadline", "shape", actual=0.2) == 0
        assert j.stats()["loops"]["deadline"]["missed"] == 0
        assert not _reconciles(j)

    def test_resize_shrink_accounts_like_overflow(self):
        j = DecisionJournal(maxlen=8)
        ids = [j.record("admission", key=f"k{i}", choice="cheap")
               for i in range(8)]
        j.resolve(ids[0], actual=1.0, loop="admission")
        j.resize(3)
        s = j.stats()
        assert s["capacity"] == 3 and s["size"] == 3
        assert s["dropped"] == 5
        c = s["loops"]["admission"]
        assert c["expired"] == 4  # 5 discarded, 1 of them was resolved
        assert not _reconciles(j)

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("HORAEDB_DECISIONS", "0")
        j = DecisionJournal(maxlen=8)
        assert j.record("admission", key="k", choice="c") == 0
        assert j.resolve(0) is False
        s = j.stats()
        assert s["issued"] == 0 and s["size"] == 0
        assert s["loops"]["admission"]["missed"] == 0

    def test_list_limit_zero_means_zero(self):
        j = DecisionJournal(maxlen=8)
        j.record("elastic", key="k", choice="hold")
        assert j.list(limit=0) == []
        assert len(j.list(limit=1)) == 1

    def test_reconciliation_under_random_ops(self):
        """Property: whatever interleaving of record / resolve /
        resolve_matching / resize hits the journal, the per-loop ledger
        reconciles exactly at every step."""
        rng = random.Random(11)
        j = DecisionJournal(maxlen=16)
        live: list = []
        for step in range(400):
            op = rng.random()
            if op < 0.55:
                loop = rng.choice(DECISION_LOOPS)
                live.append(
                    (loop, j.record(loop, key=f"k{rng.randrange(6)}",
                                    choice="c", predicted=rng.random()))
                )
            elif op < 0.8 and live:
                loop, did = live.pop(rng.randrange(len(live)))
                j.resolve(did, actual=rng.random(), loop=loop)
            elif op < 0.9:
                j.resolve_matching(
                    rng.choice(DECISION_LOOPS), f"k{rng.randrange(6)}",
                    actual=rng.random(),
                )
            else:
                j.resize(rng.choice((4, 8, 16)))
            assert not _reconciles(j), f"step {step}"


class TestCalibrationWindows:
    def test_incremental_window_matches_naive_refold(self):
        """Property: the running-sums window equals a from-scratch refold
        over the retained span at every push — no drift, no stale sums."""
        rng = random.Random(7)
        w = _ErrWindow(span_ms=1000.0)
        pushed: list = []
        now = 0.0
        for _ in range(500):
            now += rng.random() * 120.0
            signed = rng.uniform(-3.0, 3.0)
            w.push(now, signed, abs(signed))
            pushed.append((now, signed))
            got_signed, got_abs, got_n = w.means(now)
            keep = [(t, s) for t, s in pushed if t > now - 1000.0]
            assert got_n == len(keep)
            naive_signed = sum(s for _, s in keep) / len(keep)
            naive_abs = sum(abs(s) for _, s in keep) / len(keep)
            assert got_signed == pytest.approx(naive_signed, abs=1e-9)
            assert got_abs == pytest.approx(naive_abs, abs=1e-9)

    def test_empty_window_means_none(self):
        w = _ErrWindow(span_ms=10.0)
        w.push(0.0, 1.0, 1.0)
        assert w.means(1e6) == (None, None, 0)

    def test_miscalibration_transition_and_recovery(self):
        """loop_miscalibrated fires exactly on the transition into the
        state (fast_n >= 8, both windows over 0.5 abs error) and the
        state clears when the fast window does."""
        cal = _LoopCalibration("admission", fast_ms=100.0, slow_ms=1e9)
        now = 0.0
        fired = []
        for i in range(12):
            now += 1.0
            r = cal.push(now, 2.0)  # 200% error every sample
            if r is not None:
                fired.append((i, r))
        assert len(fired) == 1, fired
        assert fired[0][0] == 7  # the 8th sample crosses MIN_SAMPLES
        assert fired[0][1]["loop"] == "admission"
        assert cal.miscalibrated
        # fast window drains past its span with good samples -> recover
        now += 1000.0
        assert cal.push(now, 0.0) is None
        assert not cal.miscalibrated
        # re-entering the state fires again
        for i in range(10):
            now += 1.0
            cal.push(now, 2.0)
        assert cal.miscalibrated

    def test_calibration_rows_carry_ledger(self):
        j = DecisionJournal(maxlen=8)
        i = j.record("elastic", key="s", choice="hold", predicted=2.0)
        j.resolve(i, actual=3.0, loop="elastic")
        row = {r["loop"]: r for r in j.calibration()}["elastic"]
        assert row["samples"] == 1
        assert row["ewma_signed"] == pytest.approx(0.5)
        assert row["ewma_abs"] == pytest.approx(0.5)
        assert row["issued"] == 1 and row["resolved"] == 1
        assert row["unresolved"] == 0 and row["expired"] == 0
        assert math.isfinite(row["fast_abs"])

    def test_uncalibrated_resolve_not_graded(self):
        j = DecisionJournal(maxlen=8)
        i = j.record("kernel_router", key="k", choice="mxu", predicted=0.1)
        j.resolve(i, actual=9.9, outcome="degenerate", loop="kernel_router",
                  calibrate=False)
        row = {r["loop"]: r for r in j.calibration()}["kernel_router"]
        assert row["samples"] == 0 and row["ewma_abs"] is None
        (e,) = j.list(loop="kernel_router")
        assert e["resolved"] and e["error"] is None
