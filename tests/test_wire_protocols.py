"""MySQL + PostgreSQL wire protocol tests with raw byte-level clients
(ref model: integration_tests mysql/ and postgresql/ client-driven suites
— no client libraries ship in this image, so the tests implement the
client half of each protocol, which also pins the wire format)."""

from __future__ import annotations

import asyncio
import socket
import struct

import pytest

import horaedb_tpu
from horaedb_tpu.server import create_app
from horaedb_tpu.server.mysql import MysqlServer
from horaedb_tpu.server.postgres import PostgresServer


def run(coro):
    return asyncio.run(coro)


def gateway_for(conn):
    return create_app(conn)["sql_gateway"]


@pytest.fixture()
def db():
    conn = horaedb_tpu.connect(None)
    conn.execute(
        "CREATE TABLE wt (host string TAG, v double, ts timestamp NOT NULL, "
        "TIMESTAMP KEY(ts)) ENGINE=Analytic"
    )
    conn.execute(
        "INSERT INTO wt (host, v, ts) VALUES ('a', 1.5, 1000), ('b', 2.5, 2000)"
    )
    yield conn
    conn.close()


# ---- minimal MySQL client -------------------------------------------------


class MyClient:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.seq = 0

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("closed")
            out += chunk
        return out

    def read_packet(self) -> bytes:
        head = self._recv_exact(4)
        length = int.from_bytes(head[:3], "little")
        self.seq = (head[3] + 1) & 0xFF
        return self._recv_exact(length)

    def send_packet(self, payload: bytes) -> None:
        self.sock.sendall(len(payload).to_bytes(3, "little") + bytes([self.seq]) + payload)
        self.seq = (self.seq + 1) & 0xFF

    def handshake(self) -> None:
        greeting = self.read_packet()
        assert greeting[0] == 0x0A  # protocol 10
        assert b"horaedb_tpu" in greeting
        # HandshakeResponse41: caps, max packet, charset, filler, user
        resp = struct.pack("<IIB23x", 0x200 | 0x8000, 1 << 24, 33) + b"root\x00" + b"\x00"
        self.send_packet(resp)
        ok = self.read_packet()
        assert ok[0] == 0x00, ok

    def query(self, sql: str):
        self.seq = 0
        self.send_packet(b"\x03" + sql.encode())
        first = self.read_packet()
        if first[0] == 0x00:  # OK
            i = 1
            affected, _ = _lenenc(first, i)
            return ("ok", affected)
        if first[0] == 0xFF:
            return ("err", first[9:].decode())
        ncols, _ = _lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self.read_packet()
            # parse 5 lenenc strings; the 5th is the column name
            i = 0
            vals = []
            for _ in range(6):
                if col[i] == 0xFB:
                    vals.append(None); i += 1; continue
                ln, i = _lenenc(col, i)
                vals.append(col[i : i + ln]); i += ln
            names.append(vals[4].decode())
        eof = self.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            i = 0
            row = []
            for _ in range(ncols):
                if pkt[i] == 0xFB:
                    row.append(None); i += 1; continue
                ln, i = _lenenc(pkt, i)
                row.append(pkt[i : i + ln].decode()); i += ln
            rows.append(row)
        return ("rows", names, rows)


def _lenenc(buf: bytes, i: int):
    b = buf[i]
    if b < 0xFB:
        return b, i + 1
    if b == 0xFC:
        return int.from_bytes(buf[i + 1 : i + 3], "little"), i + 3
    if b == 0xFD:
        return int.from_bytes(buf[i + 1 : i + 4], "little"), i + 4
    return int.from_bytes(buf[i + 1 : i + 9], "little"), i + 9


class TestMysqlProtocol:
    def _with_server(self, db, fn):
        async def body():
            server = MysqlServer(gateway_for(db), port=0)
            await server.start()
            try:
                return await asyncio.get_running_loop().run_in_executor(
                    None, fn, server.port
                )
            finally:
                await server.stop()

        return run(body())

    def test_handshake_and_select(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyClient(s)
            c.handshake()
            kind, names, rows = c.query("SELECT host, v FROM wt ORDER BY host")
            assert kind == "rows" and names == ["host", "v"]
            assert rows == [["a", "1.5"], ["b", "2.5"]]
            kind, affected = c.query(
                "INSERT INTO wt (host, v, ts) VALUES ('c', 3.5, 3000)"
            )
            assert (kind, affected) == ("ok", 1)
            kind, msg = c.query("SELECT nope FROM wt")
            assert kind == "err" and "nope" in msg
            # session chatter answered locally
            assert c.query("SET NAMES utf8")[0] == "ok"
            kind, names, rows = c.query("select @@version_comment limit 1")
            assert kind == "rows" and "horaedb_tpu" in rows[0][0]
            s.close()

        self._with_server(db, client)

    def test_null_rendering(self, db):
        db.execute(
            "CREATE TABLE wn (h string TAG, x double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute("INSERT INTO wn (h, x, ts) VALUES ('a', NULL, 1)")

        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyClient(s)
            c.handshake()
            kind, names, rows = c.query("SELECT x FROM wn")
            assert rows == [[None]]
            s.close()

        self._with_server(db, client)


# ---- minimal PostgreSQL client --------------------------------------------


class PgClient:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock

    def _recv_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self.sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("closed")
            out += chunk
        return out

    def startup(self, ssl_probe: bool = False) -> None:
        if ssl_probe:
            self.sock.sendall(struct.pack("!II", 8, 80877103))
            assert self._recv_exact(1) == b"N"
        params = b"user\x00test\x00database\x00public\x00\x00"
        payload = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        ready = False
        while not ready:
            tag, body = self.read_msg()
            if tag == b"R":
                assert int.from_bytes(body[:4], "big") == 0  # AuthenticationOk
            elif tag == b"Z":
                ready = True

    def read_msg(self):
        tag = self._recv_exact(1)
        length = int.from_bytes(self._recv_exact(4), "big")
        return tag, self._recv_exact(length - 4)

    def query(self, sql: str):
        payload = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(payload) + 4) + payload)
        names, rows, complete, err = [], [], None, None
        while True:
            tag, body = self.read_msg()
            if tag == b"T":
                n = int.from_bytes(body[:2], "big")
                i = 2
                for _ in range(n):
                    end = body.index(b"\x00", i)
                    names.append(body[i:end].decode())
                    i = end + 1 + 18
            elif tag == b"D":
                n = int.from_bytes(body[:2], "big")
                i = 2
                row = []
                for _ in range(n):
                    ln = int.from_bytes(body[i : i + 4], "big", signed=True)
                    i += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[i : i + ln].decode())
                        i += ln
                rows.append(row)
            elif tag == b"C":
                complete = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body.decode("utf-8", "replace")
            elif tag == b"Z":
                return names, rows, complete, err


class TestPostgresProtocol:
    def _with_server(self, db, fn):
        async def body():
            server = PostgresServer(gateway_for(db), port=0)
            await server.start()
            try:
                return await asyncio.get_running_loop().run_in_executor(
                    None, fn, server.port
                )
            finally:
                await server.stop()

        return run(body())

    def test_startup_and_query(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgClient(s)
            c.startup(ssl_probe=True)  # SSLRequest answered 'N', then plain
            names, rows, complete, err = c.query("SELECT host, v FROM wt ORDER BY host")
            assert err is None
            assert names == ["host", "v"]
            assert rows == [["a", "1.5"], ["b", "2.5"]]
            assert complete == "SELECT 2"
            names, rows, complete, err = c.query(
                "INSERT INTO wt (host, v, ts) VALUES ('c', 9.0, 9000)"
            )
            assert err is None and complete == "INSERT 0 1"
            _, _, _, err = c.query("SELECT nope FROM wt")
            assert err is not None and "nope" in err
            # error recovery: the session keeps working
            names, rows, _, err = c.query("SELECT count(*) AS c FROM wt")
            assert err is None and rows == [["3"]]
            s.close()

        self._with_server(db, client)

    def test_null_and_set(self, db):
        db.execute(
            "CREATE TABLE pn (h string TAG, x double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )
        db.execute("INSERT INTO pn (h, x, ts) VALUES ('a', NULL, 1)")

        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgClient(s)
            c.startup()
            _, _, complete, err = c.query("SET client_encoding TO 'UTF8'")
            assert err is None and complete == "SET"
            names, rows, _, err = c.query("SELECT x FROM pn")
            assert err is None and rows == [[None]]
            s.close()

        self._with_server(db, client)


class PgExtClient(PgClient):
    """Extended-protocol pipelining client (psycopg3-style Parse..Sync)."""

    def _send(self, tag: bytes, payload: bytes) -> None:
        self.sock.sendall(tag + struct.pack("!I", len(payload) + 4) + payload)

    def parse(self, stmt: str, sql: str) -> None:
        self._send(b"P", stmt.encode() + b"\x00" + sql.encode() + b"\x00" + b"\x00\x00")

    def bind(self, portal: str, stmt: str, params: list) -> None:
        p = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
        p += struct.pack("!h", 0)  # no param format codes (default text)
        p += struct.pack("!h", len(params))
        for v in params:
            if v is None:
                p += struct.pack("!i", -1)
            else:
                b = str(v).encode()
                p += struct.pack("!i", len(b)) + b
        p += struct.pack("!h", 0)  # default (text) result formats
        self._send(b"B", p)

    def describe(self, what: str, name: str) -> None:
        self._send(b"D", what.encode() + name.encode() + b"\x00")

    def execute(self, portal: str) -> None:
        self._send(b"E", portal.encode() + b"\x00" + struct.pack("!i", 0))

    def close_stmt(self, what: str, name: str) -> None:
        self._send(b"C", what.encode() + name.encode() + b"\x00")

    def sync(self) -> None:
        self._send(b"S", b"")

    def collect_until_ready(self) -> list:
        """Drain messages until ReadyForQuery; returns [(tag, body)...]."""
        out = []
        while True:
            tag, body = self.read_msg()
            out.append((tag, body))
            if tag == b"Z":
                return out


class TestPostgresExtendedProtocol:
    def _with_server(self, db, fn):
        return TestPostgresProtocol._with_server(self, db, fn)

    def test_parse_bind_describe_execute(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgExtClient(s)
            c.startup()
            # full pipeline in one flush, like a real driver
            c.parse("s1", "SELECT host, v FROM wt WHERE host = $1 ORDER BY v")
            c.bind("", "s1", ["a"])
            c.describe("P", "")
            c.execute("")
            c.sync()
            msgs = c.collect_until_ready()
            tags = [t for t, _ in msgs]
            assert tags[:2] == [b"1", b"2"]          # ParseComplete, BindComplete
            assert b"T" in tags and b"D" in tags      # RowDescription + DataRow
            dr = [b for t, b in msgs if t == b"D"][0]
            assert b"a" in dr and b"1.5" in dr
            cc = [b for t, b in msgs if t == b"C"][0]
            assert cc.rstrip(b"\x00") == b"SELECT 1"
            s.close()

        self._with_server(db, client)

    def test_params_quoting_null_and_insert(self, db):
        db.execute(
            "CREATE TABLE pe (h string TAG, x double, ts timestamp NOT NULL, "
            "TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )

        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgExtClient(s)
            c.startup()
            c.parse("ins", "INSERT INTO pe (h, x, ts) VALUES ($1, $2, $3)")
            c.bind("", "ins", ["o'brien", None, "1000"])
            c.execute("")
            c.sync()
            msgs = c.collect_until_ready()
            cc = [b for t, b in msgs if t == b"C"][0]
            assert cc.rstrip(b"\x00") == b"INSERT 0 1"
            # read it back: quoted value round-trips, NULL stays NULL
            c.parse("", "SELECT h, x FROM pe WHERE h = $1")
            c.bind("", "", ["o'brien"])
            c.describe("P", "")
            c.execute("")
            c.sync()
            msgs = c.collect_until_ready()
            dr = [b for t, b in msgs if t == b"D"][0]
            assert b"o'brien" in dr
            assert struct.pack("!i", -1) in dr  # NULL x
            s.close()

        self._with_server(db, client)

    def test_error_discards_until_sync(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgExtClient(s)
            c.startup()
            c.bind("", "missing", [])    # errors: unknown statement
            c.execute("")                # must be discarded
            c.sync()
            msgs = c.collect_until_ready()
            tags = [t for t, _ in msgs]
            assert tags == [b"E", b"Z"]  # one error, then ReadyForQuery only
            # session recovers
            c.parse("", "SELECT count(*) AS c FROM wt")
            c.bind("", "", [])
            c.execute("")
            c.sync()
            msgs = c.collect_until_ready()
            assert [t for t, _ in msgs if t == b"D"]
            s.close()

        self._with_server(db, client)

    def test_describe_statement_and_close(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = PgExtClient(s)
            c.startup()
            c.parse("ds", "SELECT v FROM wt WHERE host = $1 AND v > $2")
            c.describe("S", "ds")
            c.close_stmt("S", "ds")
            c.sync()
            msgs = c.collect_until_ready()
            tags = [t for t, _ in msgs]
            # ParseComplete, ParameterDescription, RowDescription (probed
            # with NULL params — PgJDBC-style describe-before-bind),
            # CloseComplete, ReadyForQuery
            assert tags == [b"1", b"t", b"T", b"3", b"Z"], tags
            pd = [b for t, b in msgs if t == b"t"][0]
            assert int.from_bytes(pd[:2], "big") == 2  # two parameters
            rd = [b for t, b in msgs if t == b"T"][0]
            assert b"v" in rd
            # a side-effecting statement still describes as NoData
            c.parse("di", "INSERT INTO wt (host, v, ts) VALUES ($1, $2, $3)")
            c.describe("S", "di")
            c.sync()
            msgs = c.collect_until_ready()
            assert [t for t, _ in msgs] == [b"1", b"t", b"n", b"Z"]
            s.close()

        self._with_server(db, client)


class MyPsClient(MyClient):
    """Prepared-statement (binary protocol) client."""

    def prepare(self, sql: str):
        self.seq = 0
        self.send_packet(b"\x16" + sql.encode())
        first = self.read_packet()
        if first[0] == 0xFF:
            return ("err", first[9:].decode())
        assert first[0] == 0x00
        stmt_id = int.from_bytes(first[1:5], "little")
        ncols = int.from_bytes(first[5:7], "little")
        nparams = int.from_bytes(first[7:9], "little")
        for _ in range(nparams):
            self.read_packet()
        if nparams:
            assert self.read_packet()[0] == 0xFE  # EOF after param defs
        for _ in range(ncols):
            self.read_packet()
        if ncols:
            assert self.read_packet()[0] == 0xFE
        return ("ok", stmt_id, nparams)

    def execute(self, stmt_id: int, params: list):
        """params: list of (type_byte, python_value_or_None)."""
        self.seq = 0
        p = b"\x17" + stmt_id.to_bytes(4, "little") + b"\x00" + (1).to_bytes(4, "little")
        n = len(params)
        if n:
            bitmap = bytearray((n + 7) // 8)
            for i, (_, v) in enumerate(params):
                if v is None:
                    bitmap[i // 8] |= 1 << (i % 8)
            p += bytes(bitmap) + b"\x01"  # new_params_bound
            for t, _ in params:
                p += bytes([t, 0])
            for t, v in params:
                if v is None:
                    continue
                if t == 0x08:
                    p += int(v).to_bytes(8, "little", signed=v >= 0)
                elif t == 0x05:
                    p += struct.pack("<d", v)
                elif t == 0xFD:
                    b = str(v).encode()
                    p += bytes([len(b)]) + b  # lenenc (short strings)
                else:
                    raise AssertionError(f"test client can't encode {t:#x}")
        self.send_packet(p)
        first = self.read_packet()
        if first[0] == 0x00:
            affected, _ = _lenenc(first, 1)
            return ("ok", affected)
        if first[0] == 0xFF:
            return ("err", first[9:].decode())
        ncols, _ = _lenenc(first, 0)
        names = []
        types = []
        for _ in range(ncols):
            col = self.read_packet()
            i = 0
            vals = []
            for _ in range(6):
                ln, i = _lenenc(col, i)
                vals.append(col[i : i + ln]); i += ln
            names.append(vals[4].decode())
            # fixed tail: 0x0c filler, charset(2), length(4), TYPE(1)
            types.append(col[i + 1 + 2 + 4])
        assert self.read_packet()[0] == 0xFE
        rows = []
        nbm = (ncols + 9) // 8
        while True:
            pkt = self.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            assert pkt[0] == 0x00
            bitmap = pkt[1 : 1 + nbm]
            i = 1 + nbm
            row = []
            for c in range(ncols):
                if bitmap[(c + 2) // 8] & (1 << ((c + 2) % 8)):
                    row.append(None)
                    continue
                t = types[c]
                if t == 0x08:  # LONGLONG, 8-byte LE
                    row.append(int.from_bytes(pkt[i : i + 8], "little", signed=True))
                    i += 8
                elif t == 0x05:  # DOUBLE, 8-byte LE ieee754
                    row.append(struct.unpack("<d", pkt[i : i + 8])[0])
                    i += 8
                else:
                    ln, i = _lenenc(pkt, i)
                    row.append(pkt[i : i + ln].decode()); i += ln
            rows.append(row)
        return ("rows", names, rows)


class TestMysqlPreparedStatements:
    def _with_server(self, db, fn):
        return TestMysqlProtocol._with_server(self, db, fn)

    def test_prepare_execute_select(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyPsClient(s)
            c.handshake()
            st = c.prepare("SELECT host, v FROM wt WHERE host = ? AND v < ?")
            assert st[0] == "ok" and st[2] == 2, st
            out = c.execute(st[1], [(0xFD, "a"), (0x05, 99.5)])
            assert out[0] == "rows" and out[1] == ["host", "v"]
            assert out[2] == [["a", 1.5]]  # v is a typed DOUBLE now
            # re-execute with different params, same statement
            out = c.execute(st[1], [(0xFD, "b"), (0x05, 99.5)])
            assert out[2] == [["b", 2.5]]
            s.close()

        self._with_server(db, client)

    def test_typed_binary_columns(self, db):
        """Column defs declare real types; numeric values travel binary
        (LONGLONG/DOUBLE), not as strings."""
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyPsClient(s)
            c.handshake()
            st = c.prepare("SELECT host, v, count(*) AS c FROM wt GROUP BY host, v")
            out = c.execute(st[1], [])
            assert out[0] == "rows"
            byhost = {r[0]: r for r in out[2]}
            assert byhost["a"] == ["a", 1.5, 1]  # str, float, int — typed
            assert isinstance(byhost["a"][1], float)
            assert isinstance(byhost["a"][2], int)
            s.close()

        self._with_server(db, client)

    def test_insert_with_nulls_and_quotes(self, db):
        db.execute(
            "CREATE TABLE mp (h string TAG, note string, x double, "
            "ts timestamp NOT NULL, TIMESTAMP KEY(ts)) ENGINE=Analytic"
        )

        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyPsClient(s)
            c.handshake()
            st = c.prepare("INSERT INTO mp (h, note, x, ts) VALUES (?, ?, ?, ?)")
            assert st[0] == "ok" and st[2] == 4
            out = c.execute(
                st[1],
                [(0xFD, "o'hara"), (0xFD, None), (0x05, None), (0x08, 1000)],
            )
            assert out == ("ok", 1), out
            st2 = c.prepare("SELECT h, note, x FROM mp WHERE h = ?")
            out = c.execute(st2[1], [(0xFD, "o'hara")])
            assert out[0] == "rows"
            assert out[2] == [["o'hara", None, None]], out[2]
            s.close()

        self._with_server(db, client)

    def test_placeholder_in_literal_and_close(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyPsClient(s)
            c.handshake()
            # the ? inside the string literal is NOT a parameter
            st = c.prepare("SELECT host, 'a?b' AS tag FROM wt WHERE host = ?")
            assert st[0] == "ok" and st[2] == 1, st
            out = c.execute(st[1], [(0xFD, "a")])
            assert out[0] == "rows" and out[2] == [["a", "a?b"]]
            # close, then execute must error (not crash)
            c.seq = 0
            c.send_packet(b"\x19" + st[1].to_bytes(4, "little"))  # no response
            out = c.execute(st[1], [(0xFD, "a")])
            assert out[0] == "err" and "unknown statement" in out[1]
            # plain text query still works on the same session
            out = c.query("SELECT count(*) AS c FROM wt")
            assert out[0] == "rows" and out[2] == [["2"]]
            s.close()

        self._with_server(db, client)

    def test_unsigned_param_and_comment_scan(self, db):
        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            c = MyPsClient(s)
            c.handshake()
            # a ? inside a -- comment is NOT a parameter
            st = c.prepare(
                "SELECT host FROM wt WHERE ts = ? -- by time?\n ORDER BY host"
            )
            assert st[0] == "ok" and st[2] == 1, st
            # unsigned LONGLONG above int64 range must not wrap negative:
            # send flag 0x80 with a top-bit-set value; splicing -1 would
            # error or match nothing differently than the true value
            big = 2**63 + 5
            c.seq = 0
            p = b"\x17" + st[1].to_bytes(4, "little") + b"\x00" + (1).to_bytes(4, "little")
            p += b"\x00"          # null bitmap (1 param)
            p += b"\x01"          # new_params_bound
            p += bytes([0x08, 0x80])  # LONGLONG, unsigned flag
            p += big.to_bytes(8, "little")
            c.send_packet(p)
            first = c.read_packet()
            # no row has that ts: a clean empty resultset or OK — never a
            # decode error or negative-wrap match
            assert first[0] != 0xFF, first
            if first[0] != 0x00:
                ncols, _ = _lenenc(first, 0)
                for _ in range(ncols):
                    c.read_packet()
                assert c.read_packet()[0] == 0xFE
                pkt = c.read_packet()
                assert pkt[0] == 0xFE and len(pkt) < 9  # zero rows
            s.close()

        self._with_server(db, client)


class TestReadDedup:
    """Identical in-flight SELECTs share one execution (single-flight)."""

    def test_concurrent_identical_selects_deduped(self, db):
        import threading

        gw = gateway_for(db)
        calls = []
        gate = threading.Event()
        orig = type(gw.app["proxy"]).handle_sql

        def slow_handle(self_, sql):
            calls.append(sql)
            gate.wait(5)  # park the leader so followers pile up
            return orig(self_, sql)

        async def body():
            p = gw.app["proxy"]
            p.handle_sql = slow_handle.__get__(p)
            tasks = [
                asyncio.ensure_future(gw.execute("SELECT count(*) AS c FROM wt"))
                for _ in range(5)
            ]
            await asyncio.sleep(0.3)  # all five enter; one leader executes
            gate.set()
            return await asyncio.gather(*tasks)

        results = run(body())
        assert len(calls) == 1, calls  # one real execution
        assert all(r == results[0] for r in results)
        kind, (names, rows) = results[0]
        assert kind == "rows" and rows[0]["c"] == 2

    def test_writes_never_deduped(self, db):
        gw = gateway_for(db)

        async def body():
            outs = await asyncio.gather(
                gw.execute("INSERT INTO wt (host, v, ts) VALUES ('x', 1.0, 5000)"),
                gw.execute("INSERT INTO wt (host, v, ts) VALUES ('x', 2.0, 6000)"),
            )
            return outs

        outs = run(body())
        assert all(k == "affected" and n == 1 for k, n in outs)
        kind, (_, rows) = run(gw.execute("SELECT count(*) AS c FROM wt"))
        assert rows[0]["c"] == 4  # both writes landed

    def test_sequential_selects_not_shared_after_done(self, db):
        gw = gateway_for(db)
        run(gw.execute("INSERT INTO wt (host, v, ts) VALUES ('y', 9.0, 7000)"))
        k1, (_, r1) = run(gw.execute("SELECT count(*) AS c FROM wt"))
        run(gw.execute("INSERT INTO wt (host, v, ts) VALUES ('y', 9.5, 8000)"))
        k2, (_, r2) = run(gw.execute("SELECT count(*) AS c FROM wt"))
        assert r1[0]["c"] == 3 and r2[0]["c"] == 4  # fresh execution each time

    def test_read_your_writes_after_interleaved_write(self, db):
        """A SELECT issued after a write never joins a pre-write in-flight
        execution (the dedup key carries a write epoch)."""
        import threading

        gw = gateway_for(db)
        calls = []
        gate = threading.Event()
        orig = type(gw.app["proxy"]).handle_sql

        def slow_select(self_, sql):
            if sql.lstrip().lower().startswith("select"):
                calls.append(sql)
                gate.wait(5)
            return orig(self_, sql)

        async def body():
            p = gw.app["proxy"]
            p.handle_sql = slow_select.__get__(p)
            stale = asyncio.ensure_future(
                gw.execute("SELECT count(*) AS c FROM wt")
            )
            await asyncio.sleep(0.2)  # leader is parked pre-write
            k, n = await gw.execute(
                "INSERT INTO wt (host, v, ts) VALUES ('z', 7.0, 9000)"
            )
            assert (k, n) == ("affected", 1)
            fresh = asyncio.ensure_future(
                gw.execute("SELECT count(*) AS c FROM wt")
            )
            await asyncio.sleep(0.2)
            gate.set()
            return await stale, await fresh

        (k1, (_, r1)), (k2, (_, r2)) = run(body())
        assert len(calls) == 2, calls  # post-write SELECT ran fresh
        assert r2[0]["c"] == 3  # sees its own write


class TestPostgresPartialExecute:
    """Execute with max_rows suspends the portal (cursor-style fetch)."""

    def _with_server(self, db, fn):
        return TestPostgresProtocol._with_server(self, db, fn)

    def test_portal_suspend_and_resume(self, db):
        db.execute(
            "INSERT INTO wt (host, v, ts) VALUES ('c', 3.5, 3000), ('d', 4.5, 4000)"
        )

        def client(port):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            try:
                # close even on assertion failure: a leaked socket keeps
                # the server handler alive and wait_closed hangs forever,
                # masking the real failure
                c = PgExtClient(s)
                c.startup()
                c.parse("", "SELECT host FROM wt ORDER BY host")
                c.bind("", "", [])
                # fetch 3 rows, then the rest
                c._send(b"E", b"\x00" + struct.pack("!i", 3))
                c._send(b"E", b"\x00" + struct.pack("!i", 0))
                c.sync()
                msgs = c.collect_until_ready()
                tags = [t for t, _ in msgs]
                # 3 DataRows, PortalSuspended, remaining 1 DataRow, Complete
                assert tags == [b"1", b"2", b"D", b"D", b"D", b"s", b"D", b"C", b"Z"], tags
                cc = [b for t, b in msgs if t == b"C"][0]
                assert cc.rstrip(b"\x00") == b"SELECT 1"
                # DataRow: int16 ncols + int32 len + utf8 value
                hosts = [b[6:].decode() for t, b in msgs if t == b"D"]
                assert hosts == ["a", "b", "c", "d"], hosts
            finally:
                s.close()

        self._with_server(db, client)
